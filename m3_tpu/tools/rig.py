"""Production traffic rig: recorded-shape load + process-level chaos.

The role of the reference's dtest harness driven to production shape
(/root/reference/src/cmd/tools/dtest + the m3em agents): a seeded load
generator replays recorded-shape traffic — zipf-distributed tenants,
bursty batched writes through ``session.write_many``, mixed query sizes
through the coordinator API — against REAL spawned service processes
(tools/em.py agents), while a seeded, replayable chaos schedule SIGKILLs
processes (dbnode, kvd replica, aggregator) and partitions them
(restart with env-injected ``M3_TPU_FAULTS`` network-fault rules). The
rig then proves the contracts the platform claims:

- **zero acked-write loss**: every entry the client session acked at
  the write consistency level is readable after the schedule heals
  (the WriteLedger records acks, ``verify`` replays them);
- **partial-result reads**: during an outage window reads SUCCEED with
  the PR-2 ReadWarning contract (warnings in the response envelope),
  never silently drop data;
- **SLO-bounded p99**: latency quantiles come from the PR-4 request
  histograms scraped off /metrics (per-tenant families), compared
  pair-median-style across interleaved windows so a noisy host cannot
  fake a regression or mask one;
- **tenant isolation**: the noisy-tenant phase saturates one tenant
  until admission control sheds it with 429s while a steady tenant's
  p99 holds — proven WHILE nodes are being killed;
- **anti-entropy convergence**: after the schedule heals, the replica
  that slept through its outage window converges via the nodes' OWN
  repair daemons — every replica pair reaches per-(shard, block)
  rollup-digest equality within the configured cycle budget
  (``convergence_audit``; nothing in the rig invokes repair directly).

Determinism: the traffic sequence (tenant choice, batch sizes, series,
query shapes) and the chaos schedule derive from one seed — the same
seed replays the same run shape. Timestamps and wall-clock interleaving
are the only nondeterminism, which is exactly the part production owns.

CLI (the ops surface; `run_tests.sh rig` drives the pytest wrapper):

    python -m m3_tpu.tools.rig --workdir /tmp/rig --seconds 20 --seed 7
"""

from __future__ import annotations

import argparse
import json
import math
import random
import statistics
import threading
import time
import urllib.error
import urllib.request

NS = 1_000_000_000


# ---------------------------------------------------------------------------
# traffic generation (seeded, recorded-shape)


def zipf_weights(n: int, s: float) -> list[float]:
    """Normalized zipf(s) weights over n ranks — the tenant/series skew
    every production metrics platform sees (a few namespaces dominate)."""
    w = [1.0 / (k ** s) for k in range(1, n + 1)]
    total = sum(w)
    return [x / total for x in w]


class RigConfig:
    """Knobs for one rig run; everything defaults to a shape small
    enough for CI and scales up by multiplying rates/duration."""

    def __init__(self, seed: int = 0, tenants: tuple = ("tenant0", "tenant1"),
                 zipf_s: float = 1.2, series_per_tenant: int = 32,
                 batch_size: int = 24, burst_every: int = 8,
                 burst_mult: int = 4, write_interval_s: float = 0.05,
                 query_interval_s: float = 0.08, duration_s: float = 10.0,
                 slo_p99_ms: float = 2000.0, churn_per_batch: int = 0):
        self.seed = seed
        self.tenants = tuple(tenants)
        self.zipf_s = zipf_s
        self.series_per_tenant = series_per_tenant
        self.batch_size = batch_size
        self.burst_every = burst_every
        self.burst_mult = burst_mult
        self.write_interval_s = write_interval_s
        self.query_interval_s = query_interval_s
        self.duration_s = duration_s
        self.slo_p99_ms = slo_p99_ms
        # cardinality-explosion shape: this many entries of every batch
        # carry a monotonically-unique `churn` tag, so each one mints a
        # brand-NEW series (continuous index ingest + segment churn — the
        # episode that must not blow up read latency)
        self.churn_per_batch = churn_per_batch


class TrafficGen:
    """Seeded recorded-shape traffic. The SEQUENCE (tenants, batch
    sizes, series ids, values, query shapes) is fully determined by the
    seed; timestamps are assigned by the caller at send time."""

    QUERY_WINDOWS_S = (60, 600, 3600)  # mixed query sizes: S / M / L

    def __init__(self, cfg: RigConfig):
        self.cfg = cfg
        self.rng = random.Random(f"rig-traffic:{cfg.seed}")
        self._weights = zipf_weights(len(cfg.tenants), cfg.zipf_s)
        self._batches = 0
        self._minted = 0  # monotonic: a churn tag value never repeats

    def pick_tenant(self) -> str:
        i = self.rng.choices(range(len(self.cfg.tenants)),
                             weights=self._weights)[0]
        return self.cfg.tenants[i]

    def next_batch(self, t_ns: int):
        """(tenant, entries) for session.write_many/db.write_batch:
        entries are (metric_name, tags, t_ns, value). Bursty: every
        burst_every-th batch is burst_mult times the base size."""
        tenant = self.pick_tenant()
        self._batches += 1
        n = self.cfg.batch_size
        if self.cfg.burst_every and self._batches % self.cfg.burst_every == 0:
            n *= self.cfg.burst_mult
        jitter = n // 4
        if jitter:
            n += self.rng.randrange(-jitter, jitter + 1)
        n = max(1, n)
        entries = []
        for k in range(n):
            sid = self.rng.randrange(self.cfg.series_per_tenant)
            name = f"rig_metric_{sid}".encode()
            tags = ((b"tenant", tenant.encode()),
                    (b"sid", str(sid).encode()))
            if k < self.cfg.churn_per_batch:
                # cardinality explosion: a never-repeating tag value
                # makes this entry a brand-new series every time
                tags += ((b"churn", b"c%08d" % self._minted),)
                self._minted += 1
            # 1us spacing keeps timestamps unique inside one batch (LWW
            # dedup must never collapse two ledgered datapoints)
            entries.append((name, tags, t_ns + k * 1000,
                            round(self.rng.random() * 100.0, 6)))
        return tenant, entries

    def next_query(self, now_s: float):
        """(tenant, expr, start_s, end_s, step_s) — mixed window sizes,
        selector and aggregation shapes."""
        tenant = self.pick_tenant()
        window = self.rng.choice(self.QUERY_WINDOWS_S)
        sid = self.rng.randrange(self.cfg.series_per_tenant)
        if self.rng.random() < 0.5:
            expr = f"rig_metric_{sid}"
        else:
            expr = f"sum(rig_metric_{sid})"
        step = max(1, window // 30)
        return tenant, expr, int(now_s - window), int(now_s), step


# ---------------------------------------------------------------------------
# acked-write ledger


class WriteLedger:
    """Thread-safe record of every ACKED write: the zero-loss contract
    is 'everything in here is readable after the schedule heals'."""

    def __init__(self):
        self._lock = threading.Lock()
        # (tenant, name, tags) -> list[(t_ns, value)]
        self._acked: dict[tuple, list] = {}
        self.acked_count = 0
        self.failed_count = 0

    def record(self, tenant: str, entries, results) -> None:
        """results: per-entry None (acked) or error string (not acked) —
        the session.write_many / Database.write_batch contract."""
        with self._lock:
            for (name, tags, t_ns, value), err in zip(entries, results):
                if err is None:
                    key = (tenant, bytes(name), tuple(tags))
                    self._acked.setdefault(key, []).append((int(t_ns),
                                                            float(value)))
                    self.acked_count += 1
                else:
                    self.failed_count += 1

    def series(self) -> list[tuple]:
        with self._lock:
            return list(self._acked)

    def verify(self, fetch_fn, max_missing: int = 20) -> dict:
        """Replay every acked datapoint against `fetch_fn(tenant, name,
        tags, start_ns, end_ns) -> [(t_ns, value)]`. Returns a report;
        an empty `missing` list IS the zero-acked-write-loss proof."""
        with self._lock:
            acked = {k: list(v) for k, v in self._acked.items()}
        checked = 0
        missing = []
        for (tenant, name, tags), points in acked.items():
            lo = min(t for t, _ in points)
            hi = max(t for t, _ in points)
            have = {}
            for t, v in fetch_fn(tenant, name, tags, lo, hi + 1):
                have[int(t)] = float(v)
            for t, v in points:
                checked += 1
                got = have.get(t)
                if got is None or abs(got - v) > 1e-9:
                    if len(missing) < max_missing:
                        missing.append({"tenant": tenant,
                                        "name": name.decode(),
                                        "t_ns": t, "want": v, "got": got})
        return {"checked": checked, "missing": missing,
                "acked": self.acked_count, "failed": self.failed_count}


# ---------------------------------------------------------------------------
# chaos schedule (seeded, replayable)


class ChaosEvent:
    """One scheduled action against a managed service process."""

    __slots__ = ("t_s", "action", "agent", "service", "fault_spec")

    def __init__(self, t_s: float, action: str, agent: str, service: str,
                 fault_spec: str = ""):
        self.t_s = round(float(t_s), 3)
        self.action = action  # kill | restart | partition | heal
        self.agent = agent
        self.service = service
        self.fault_spec = fault_spec

    def __eq__(self, other):
        return isinstance(other, ChaosEvent) and self.to_doc() == other.to_doc()

    def __repr__(self):
        return f"ChaosEvent({self.to_doc()})"

    def to_doc(self) -> dict:
        return {"t_s": self.t_s, "action": self.action, "agent": self.agent,
                "service": self.service, "fault_spec": self.fault_spec}


# per-service-kind partition rules: env-injected network faults that make
# a live process drop most requests (the reachable-but-sick half of the
# failure space SIGKILL doesn't cover). Each plan also wedges the
# service's periodic loop with a delay fault — a partitioned process is
# typically also a STUCK process (blocked RPCs, wedged ticks), and the
# stall watchdog must observe exactly that from inside
PARTITION_SPECS = {
    "dbnode": "dbnode.handle=error:p0.7;dbnode.tick=delay:d2.0",
    "kvd": "consensus.append=error:p0.5;kvd.rpc=error:p0.3;"
           "kvd.tick=delay:d2.0",
    "aggregator": "msg.consumer.recv=error:p0.5;"
                  "aggregator.flush=delay:d4.0",
}

# the stall drill's fault plan: a live (serving) dbnode whose tick loop
# is wedged hard — /debug/profile is fault-exempt, so the watchdog's
# stall verdict is observable from outside WHILE the loop is stuck
STALL_DRILL_SPEC = "dbnode.tick=delay:d6.0"


class ChaosSchedule:
    """Seeded kill/partition schedule. Windows never overlap across
    targets — one failure domain at a time, so a majority/consistency
    claim is actually testable (two dead replicas of an RF=2 shard is an
    availability loss by design, not a bug the rig should manufacture)."""

    @staticmethod
    def generate(seed: int, duration_s: float, targets: list[tuple],
                 outage_s: float = 3.0,
                 partition_frac: float = 0.5) -> list[ChaosEvent]:
        """targets: [(agent, service, kind)] with kind in
        PARTITION_SPECS. Produces kill->restart / partition->heal pairs
        laid out in non-overlapping windows across [10%, 85%] of the
        run. Same (seed, args) -> identical schedule (replayable)."""
        rng = random.Random(f"rig-schedule:{seed}")
        n = len(targets)
        if n == 0 or duration_s <= 0:
            return []
        lo, hi = 0.10 * duration_s, 0.85 * duration_s
        slot = (hi - lo) / n
        outage = min(outage_s, max(0.5, slot * 0.6))
        events: list[ChaosEvent] = []
        order = list(targets)
        rng.shuffle(order)
        for i, (agent, service, kind) in enumerate(order):
            start = lo + i * slot + rng.uniform(0, max(slot - outage, 0.01))
            if rng.random() < partition_frac:
                spec = PARTITION_SPECS.get(kind, "dbnode.handle=error:p0.5")
                events.append(ChaosEvent(start, "partition", agent, service,
                                         spec))
                events.append(ChaosEvent(start + outage, "heal", agent,
                                         service))
            else:
                events.append(ChaosEvent(start, "kill", agent, service))
                events.append(ChaosEvent(start + outage, "restart", agent,
                                         service))
        events.sort(key=lambda e: (e.t_s, e.agent, e.service))
        return events


class ChaosRunner:
    """Executes a schedule against em agents on a background thread.
    `base_env` maps service name -> the env it was originally started
    with, so `heal` restores a partitioned process to clean faults."""

    def __init__(self, agents: dict, schedule: list[ChaosEvent],
                 base_env: dict[str, dict], seed: int = 0):
        self.agents = agents
        self.schedule = list(schedule)
        self.base_env = base_env
        self.seed = seed
        self.executed: list[dict] = []
        self.errors: list[str] = []
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def join(self, timeout_s: float = 120.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout_s)

    def _run(self) -> None:
        t0 = time.monotonic()
        for ev in self.schedule:
            delay = ev.t_s - (time.monotonic() - t0)
            if delay > 0:
                time.sleep(delay)
            try:
                self._execute(ev)
                self.executed.append({**ev.to_doc(),
                                      "at_s": round(time.monotonic() - t0, 3)})
            except Exception as e:  # noqa: BLE001 - a failed action is
                # part of the report, not a rig crash
                self.errors.append(f"{ev!r}: {e}")

    def _execute(self, ev: ChaosEvent) -> None:
        agent = self.agents[ev.agent]
        env = self.base_env.get(ev.service, {})
        if ev.action == "kill":
            agent.kill(ev.service)
        elif ev.action == "restart":
            agent.start(ev.service, grace_s=0.5)
        elif ev.action == "partition":
            # env is process-start state: a partition is a graceful stop
            # + relaunch under a fault plan that drops most requests
            agent.stop(ev.service)
            agent.start(ev.service, env={
                **env,
                "M3_TPU_FAULTS": ev.fault_spec,
                "M3_TPU_FAULTS_SEED": str(self.seed),
            }, grace_s=0.5)
        elif ev.action == "heal":
            agent.stop(ev.service)
            agent.start(ev.service, env=env, grace_s=0.5)
        else:
            raise ValueError(f"unknown chaos action {ev.action!r}")


# ---------------------------------------------------------------------------
# the rig: load loops + collection


class Rig:
    """Drives seeded write/query load through pluggable transports and
    collects per-tenant outcomes. `write_fn(tenant, entries)` returns
    per-entry results (None = acked); `query_fn(tenant, expr, start_s,
    end_s, step_s)` returns (status, doc_or_None, headers)."""

    MAX_LATENCIES = 20_000

    def __init__(self, cfg: RigConfig, write_fn, query_fn,
                 ledger: WriteLedger | None = None):
        self.cfg = cfg
        self.write_fn = write_fn
        self.query_fn = query_fn
        self.ledger = ledger if ledger is not None else WriteLedger()
        self.gen = TrafficGen(cfg)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.tenant_stats: dict[str, dict] = {
            t: {"writes_acked": 0, "writes_failed": 0, "write_errors": 0,
                "queries_ok": 0, "queries_shed": 0, "query_errors": 0,
                "warnings": 0, "latencies_ms": []}
            for t in cfg.tenants
        }
        self.retry_after_seen = 0

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            t_ns = time.time_ns()
            tenant, entries = self.gen.next_batch(t_ns)
            st = self.tenant_stats[tenant]
            try:
                results = self.write_fn(tenant, entries)
            except Exception:  # noqa: BLE001 - whole batch failed
                with self._lock:
                    st["write_errors"] += 1
                    st["writes_failed"] += len(entries)
            else:
                self.ledger.record(tenant, entries, results)
                acked = sum(1 for r in results if r is None)
                with self._lock:
                    st["writes_acked"] += acked
                    st["writes_failed"] += len(entries) - acked
            self._stop.wait(self.cfg.write_interval_s)

    def _query_loop(self) -> None:
        while not self._stop.is_set():
            tenant, expr, start_s, end_s, step_s = \
                self.gen.next_query(time.time())
            st = self.tenant_stats[tenant]
            t0 = time.perf_counter()
            try:
                status, doc, headers = self.query_fn(tenant, expr, start_s,
                                                     end_s, step_s)
            except Exception:  # noqa: BLE001 - transport failure
                with self._lock:
                    st["query_errors"] += 1
            else:
                ms = (time.perf_counter() - t0) * 1e3
                with self._lock:
                    if status == 200:
                        st["queries_ok"] += 1
                        if len(st["latencies_ms"]) < self.MAX_LATENCIES:
                            st["latencies_ms"].append(round(ms, 3))
                        if doc and doc.get("warnings"):
                            st["warnings"] += 1
                    elif status == 429:
                        st["queries_shed"] += 1
                        if headers and _header(headers, "Retry-After"):
                            self.retry_after_seen += 1
                    else:
                        st["query_errors"] += 1
            self._stop.wait(self.cfg.query_interval_s)

    def run(self, duration_s: float | None = None) -> dict:
        """Run the load loops for the configured duration; returns the
        per-tenant report (the chaos runner, if any, is driven by the
        caller alongside this)."""
        duration = duration_s if duration_s is not None else self.cfg.duration_s
        writer = threading.Thread(target=self._writer_loop, daemon=True)
        querier = threading.Thread(target=self._query_loop, daemon=True)
        writer.start()
        querier.start()
        time.sleep(duration)
        self._stop.set()
        writer.join(10.0)
        querier.join(10.0)
        return self.report()

    def report(self) -> dict:
        with self._lock:
            tenants = {
                t: {**{k: v for k, v in st.items() if k != "latencies_ms"},
                    "client_p99_ms": _p99(st["latencies_ms"])}
                for t, st in self.tenant_stats.items()
            }
        return {
            "seed": self.cfg.seed,
            "tenants": tenants,
            "acked_total": self.ledger.acked_count,
            "failed_total": self.ledger.failed_count,
            "retry_after_seen": self.retry_after_seen,
        }


def _p99(values: list[float]) -> float | None:
    if not values:
        return None
    ordered = sorted(values)
    return round(ordered[min(len(ordered) - 1,
                             int(math.ceil(0.99 * len(ordered))) - 1)], 3)


def _header(headers, name: str):
    get = getattr(headers, "get", None)
    if get is None:
        return None
    val = get(name)
    if val is None and isinstance(headers, dict):
        for k, v in headers.items():
            if str(k).lower() == name.lower():
                return v
    return val


# ---------------------------------------------------------------------------
# transports


def api_query_fn(api):
    """Query transport over an IN-PROCESS CoordinatorAPI (the tier-1
    smoke path: same handle() code, no sockets)."""

    def query(tenant, expr, start_s, end_s, step_s):
        status, _ctype, payload, headers = api.handle(
            "GET", "/api/v1/query_range",
            {"query": [expr], "start": [str(start_s)], "end": [str(end_s)],
             "step": [str(step_s)], "namespace": [tenant]}, b"")
        doc = json.loads(payload) if payload else None
        return status, doc, headers

    return query


def http_query_fn(port: int, timeout_s: float = 15.0):
    """Query transport over a real coordinator's HTTP API."""

    def query(tenant, expr, start_s, end_s, step_s):
        from urllib.parse import urlencode

        qs = urlencode({"query": expr, "start": start_s, "end": end_s,
                        "step": step_s, "namespace": tenant})
        url = f"http://127.0.0.1:{port}/api/v1/query_range?{qs}"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as r:
                return r.status, json.loads(r.read().decode()), dict(r.headers)
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                doc = json.loads(body.decode())
            except ValueError:
                doc = None
            return e.code, doc, dict(e.headers)

    return query


def db_write_fn(db):
    """Write transport over an in-process Database (smoke path)."""
    return lambda tenant, entries: db.write_batch(tenant, entries)


def session_write_fn(session):
    """Write transport over the cluster client session — the bursty
    batched `write_many` path the tentpole names."""
    return lambda tenant, entries: session.write_many(tenant, entries)


def session_fetch_fn(session):
    """Ledger-verification reader over the same session."""
    from m3_tpu.utils.ident import tags_to_id

    def fetch(tenant, name, tags, start_ns, end_ns):
        sid = tags_to_id(name, list(tags))
        return session.fetch(tenant, sid, start_ns, end_ns)

    return fetch


def db_fetch_fn(db):
    from m3_tpu.utils.ident import tags_to_id

    def fetch(tenant, name, tags, start_ns, end_ns):
        sid = tags_to_id(name, list(tags))
        return [(d.timestamp_ns, d.value)
                for d in db.read(tenant, sid, start_ns, end_ns)]

    return fetch


# ---------------------------------------------------------------------------
# histogram scraping: p99 from the PR-4 /metrics families


def parse_histogram(text: str, family: str,
                    labels: dict | None = None):
    """(bounds, bucket_counts) from a Prometheus text exposition:
    cumulative `_bucket` lines of `family` whose labels are a superset
    of `labels`, converted to per-bucket counts (last slot = +Inf)."""
    import re as _re

    want = dict(labels or {})
    rows = []
    for line in text.splitlines():
        if not line.startswith(family + "_bucket"):
            continue
        m = _re.match(r"^[\w:]+\{(.*)\}\s+(\S+)$", line)
        if not m:
            continue
        labelstr, value = m.groups()
        parsed = dict(_re.findall(r'([\w.]+)="((?:[^"\\]|\\.)*)"', labelstr))
        if any(parsed.get(k) != str(v) for k, v in want.items()):
            continue
        le = parsed.get("le")
        if le is None:
            continue
        ub = math.inf if le == "+Inf" else float(le)
        rows.append((ub, float(value)))
    rows.sort(key=lambda r: r[0])
    bounds = [ub for ub, _ in rows if not math.isinf(ub)]
    cum = [c for _, c in rows]
    counts = [cum[0] if cum else 0.0] + [cum[i] - cum[i - 1]
                                         for i in range(1, len(cum))]
    return bounds, counts


def hist_delta(prev, cur):
    """Per-bucket counts accrued between two scrapes of one histogram."""
    bounds, prev_counts = prev
    _, cur_counts = cur
    n = max(len(prev_counts), len(cur_counts))
    prev_counts = list(prev_counts) + [0.0] * (n - len(prev_counts))
    cur_counts = list(cur_counts) + [0.0] * (n - len(cur_counts))
    return bounds, [max(0.0, c - p) for p, c in zip(prev_counts, cur_counts)]


def hist_p99_ms(hist, q: float = 0.99) -> float | None:
    """Interpolated quantile over (bounds, per-bucket counts), in ms —
    the same math utils/instrument._Histogram.quantile runs in-process."""
    bounds, counts = hist
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    running = 0.0
    prev_ub = 0.0
    for ub, c in zip(bounds, counts):
        if running + c >= rank:
            if c == 0:
                return ub * 1e3
            return (prev_ub + (ub - prev_ub) * (rank - running) / c) * 1e3
        running += c
        prev_ub = ub
    # rank lands in the +Inf bucket: report the top finite bound
    return (bounds[-1] if bounds else 0.0) * 1e3


def scrape_metrics(port: int, timeout_s: float = 10.0) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=timeout_s) as r:
        return r.read().decode()


def parse_counters(text: str, family: str) -> dict:
    """{sorted-label-tuple: value} for one Prometheus counter/gauge
    family (un-labelled samples key on the empty tuple)."""
    import re as _re

    out: dict = {}
    for line in text.splitlines():
        if not line.startswith(family):
            continue
        m = _re.match(r"^([\w:]+)(?:\{(.*)\})?\s+(\S+)$", line)
        if not m or m.group(1) != family:
            continue
        labels = tuple(sorted(_re.findall(
            r'([\w.]+)="((?:[^"\\]|\\.)*)"', m.group(2) or "")))
        out[labels] = out.get(labels, 0.0) + float(m.group(3))
    return out


def _series_points(doc) -> dict:
    """{label-key-tuple: {ts_s: value}} from a query_range matrix doc,
    NaN points dropped (grid slots the engine left unfilled)."""
    out: dict = {}
    try:
        result = doc["data"]["result"]
    except (TypeError, KeyError):
        return out
    for series in result:
        key = tuple(sorted(series.get("metric", {}).items()))
        vals = {}
        for ts, v in series.get("values", []):
            try:
                fv = float(v)
            except (TypeError, ValueError):
                continue
            if not math.isnan(fv):
                vals[float(ts)] = fv
        out[key] = vals
    return out


def windowed_p99s_ms(scrape_fn, family: str, labels: dict,
                     run_window_fn, n_windows: int) -> list:
    """Per-window p99s from a CUMULATIVE server histogram: scrape at
    every window boundary, diff bucket counts, interpolate. The
    pair-median protocol (bench #7's noisy-host discipline): callers
    take the MEDIAN of the window p99s so one scheduler hiccup cannot
    fake an SLO breach."""
    out = []
    prev = parse_histogram(scrape_fn(), family, labels)
    for i in range(n_windows):
        run_window_fn(i)
        cur = parse_histogram(scrape_fn(), family, labels)
        out.append(hist_p99_ms(hist_delta(prev, cur)))
        prev = cur
    return out


def median_p99_ms(p99s: list) -> float | None:
    vals = [p for p in p99s if p is not None]
    return round(statistics.median(vals), 3) if vals else None


# ---------------------------------------------------------------------------
# convergence audit: per-(shard, block) rollup digests across replicas


def _http_post_ok(url: str, timeout_s: float = 30.0) -> None:
    req = urllib.request.Request(url, data=b"{}", method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        r.read()


def node_rollup(port: int, namespace: str, shard: int,
                timeout_s: float = 10.0) -> dict:
    """{block_start: (digest, n_series)} from one node's /blocks/rollup
    — the same packed wire format the repair daemons exchange."""
    import base64 as _b64
    from urllib.parse import urlencode

    from m3_tpu.storage.peers import unpack_rollup

    qs = urlencode({"namespace": namespace, "shard": shard})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/blocks/rollup?{qs}",
            timeout=timeout_s) as r:
        doc = json.loads(r.read().decode())
    return unpack_rollup(_b64.b64decode(doc.get("rollup_b64", "")))


def node_repair_cycles(port: int, timeout_s: float = 10.0) -> int:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/repair",
            timeout=timeout_s) as r:
        doc = json.loads(r.read().decode())
    return int(doc.get("totals", {}).get("cycles", 0))


def convergence_audit(cluster, namespaces, budget_cycles: int = 10,
                      interval_s: float = 1.0, poll_s: float = 0.5) -> dict:
    """The anti-entropy acceptance phase: after the chaos schedule heals,
    every replica pair must reach per-(shard, block) rollup-digest
    equality within `budget_cycles` repair cycles — the replica that
    slept through a kill/partition window converges via the daemons, not
    via test code invoking repair.

    Both replicas are flushed first (digests cover persisted volumes;
    the rig's short run otherwise leaves everything in the mutable
    buffer, making equality vacuous), then the audit POLLS — repair runs
    only inside the nodes."""
    from m3_tpu.cluster.placement import ShardState

    for port in cluster.node_ports.values():
        _http_post_ok(f"http://127.0.0.1:{port}/debug/flush")
    owners: dict[int, list[str]] = {}
    for nid, inst in cluster.placement.instances.items():
        for sh in inst.shards.values():
            if sh.state in (ShardState.AVAILABLE, ShardState.LEAVING):
                owners.setdefault(sh.id, []).append(nid)
    pairs = {s: sorted(nids) for s, nids in owners.items() if len(nids) >= 2}
    cycles0 = {nid: node_repair_cycles(port)
               for nid, port in cluster.node_ports.items()}

    def mismatches() -> list[dict]:
        out = []
        for shard, nids in sorted(pairs.items()):
            for namespace in namespaces:
                tables = {
                    nid: node_rollup(cluster.node_ports[nid], namespace,
                                     shard)
                    for nid in nids
                }
                base = tables[nids[0]]
                if any(tables[n] != base for n in nids[1:]):
                    out.append({
                        "namespace": namespace, "shard": shard,
                        "tables": {n: {str(bs): d for bs, (d, _c)
                                       in sorted(t.items())}
                                   for n, t in tables.items()},
                    })
        return out

    # budget in wall time: budget_cycles at the configured interval plus
    # the daemon's jitter headroom and one deadline-length straggler
    deadline = time.monotonic() + budget_cycles * interval_s * 1.5 + 5.0
    remaining = mismatches()
    initially_divergent = len(remaining)
    while remaining and time.monotonic() < deadline:
        time.sleep(poll_s)
        remaining = mismatches()
    cycles_used = max(
        (node_repair_cycles(port) - cycles0[nid]
         for nid, port in cluster.node_ports.items()), default=0)
    return {
        "converged": not remaining,
        "initially_divergent": initially_divergent,
        "replica_pairs": len(pairs),
        "namespaces": list(namespaces),
        "budget_cycles": budget_cycles,
        "cycles_used": cycles_used,
        "mismatches": remaining[:10],
    }


# ---------------------------------------------------------------------------
# soak trajectory: the first-class artifact of the profiling plane
# (ROADMAP #6(a) first leg) — QPS, p99, RSS, top contended locks and
# stall events over time, sampled off the live cluster's /metrics and
# fault-exempt /debug/profile surfaces


class TrajectoryRecorder:
    """Samples the running deployment's saturation plane on a background
    thread into one schema'd artifact. Every fetch is best-effort — a
    killed node yields a gap in that service's row, never a rig crash."""

    SCHEMA = "m3_tpu.trajectory.v1"

    def __init__(self, coord_port: int, profile_ports: dict[str, int],
                 rig: "Rig | None" = None, sample_s: float = 1.0):
        self.coord_port = coord_port
        # service name -> port serving /debug/profile (coordinator and
        # dbnodes on their APIs, aggregator/kvd on the shared debug
        # surface when armed)
        self.profile_ports = dict(profile_ports)
        self.rig = rig
        self.sample_s = sample_s
        self.samples: list[dict] = []
        self.topology_events: list[dict] = []       # annotate() rows
        self._events: dict[tuple, dict] = {}        # dedup key -> event
        self._locks: dict[tuple, dict] = {}         # (svc, site) -> doc
        self._compute_tops: dict[str, list] = {}    # svc -> top programs
        self._prev_hist = None
        self._prev_writes = 0
        self._prev_queries = 0
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # fetchers are methods so tests can stub the transport
    def _fetch_metrics(self) -> str:
        return scrape_metrics(self.coord_port, timeout_s=3.0)

    def _fetch_profile(self, port: int) -> dict:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile", timeout=3.0) as r:
            return json.loads(r.read().decode())

    def _fetch_compute(self, port: int) -> dict:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/compute?top=5",
                timeout=3.0) as r:
            return json.loads(r.read().decode())

    def _rig_totals(self) -> tuple[int, int]:
        if self.rig is None:
            return 0, 0
        with self.rig._lock:
            writes = sum(st["writes_acked"]
                         for st in self.rig.tenant_stats.values())
            queries = sum(st["queries_ok"]
                          for st in self.rig.tenant_stats.values())
        return writes, queries

    def sample_once(self) -> dict:
        now_s = round(time.monotonic() - self._t0, 3)
        row: dict = {"t_s": now_s, "p99_ms": None,
                     "qps_writes": 0.0, "qps_queries": 0.0,
                     "rss_bytes": {}, "stalls": {},
                     "net_bytes": {}, "device_compute": {}}
        writes, queries = self._rig_totals()
        row["qps_writes"] = round((writes - self._prev_writes)
                                  / max(self.sample_s, 1e-6), 1)
        row["qps_queries"] = round((queries - self._prev_queries)
                                   / max(self.sample_s, 1e-6), 1)
        self._prev_writes, self._prev_queries = writes, queries
        try:
            text = self._fetch_metrics()
            cur = parse_histogram(text, "coordinator_request_seconds")
            if self._prev_hist is not None:
                row["p99_ms"] = hist_p99_ms(hist_delta(self._prev_hist, cur))
            self._prev_hist = cur
            # bytes-on-wire ledger (utils/wire, ROADMAP #1): cumulative
            # per-flow totals off the coordinator scrape — a first-class
            # soak column, so a wire-format regression shows up as a
            # bytes/row slope change against the same QPS
            for direction in ("sent", "recv"):
                for labels, val in parse_counters(
                        text, f"net_bytes_{direction}").items():
                    flow = dict(labels).get("flow", "?")
                    row["net_bytes"][f"{flow}_{direction}"] = int(val)
        except Exception:  # noqa: BLE001 - coordinator briefly unreachable
            pass
        for svc, port in self.profile_ports.items():
            try:
                doc = self._fetch_profile(port)
            except Exception:  # noqa: BLE001 - killed/partitioned process
                continue
            row["rss_bytes"][svc] = doc.get("rss_bytes", 0)
            wd = doc.get("watchdog", {}) or {}
            row["stalls"][svc] = sum(lp.get("stalls", 0)
                                     for lp in wd.get("loops", ()))
            for ev in wd.get("recent_events", ()):
                if ev.get("kind") != "stall":
                    continue
                key = (svc, ev.get("loop"), ev.get("t_unix"))
                self._events.setdefault(key, {**ev, "service": svc,
                                               "rig_t_s": now_s})
            for cls in (doc.get("locks", {}) or {}).get("classes", ()):
                self._locks[(svc, cls.get("site"))] = {**cls, "service": svc}
            # device-compute columns (fault-exempt /debug/compute): per-
            # service device time, device-resident cache bytes, padding
            # waste — the soak's view of compute-plane pressure
            try:
                comp = self._fetch_compute(port)
            except Exception:  # noqa: BLE001 - pre-upgrade node or
                continue       # killed process: gap, never a crash
            progs = comp.get("programs", ()) or ()
            caches = comp.get("device_caches", {}) or {}
            self._compute_tops[svc] = [
                {"op": p.get("op"), "sig": p.get("sig"),
                 "execute_seconds_total":
                     round(p.get("execute_seconds_total", 0.0), 6)}
                for p in progs[:5]]
            row["device_compute"][svc] = {
                "execute_seconds_total": round(sum(
                    p.get("execute_seconds_total", 0.0)
                    for p in progs), 6),
                "compile_seconds_total": round(sum(
                    p.get("compile_seconds_total", 0.0)
                    for p in progs), 6),
                "jit_evictions": sum(
                    (comp.get("jit_evictions", {}) or {}).values()),
                "device_cache_bytes": sum(
                    int(c.get("bytes", 0)) for c in caches.values()),
                "device_mem_bytes": sum(
                    int(d.get("bytes_in_use", 0))
                    for d in comp.get("device_memory", ()) or ()),
            }
        self.samples.append(row)
        return row

    def annotate(self, action: str, **doc) -> None:
        """Topology/episode annotations on the trajectory timeline:
        t_s-aligned with the sampled rows, so a p99 excursion can be
        read against the add/drain/restart that caused it."""
        self.topology_events.append(
            {"action": action,
             "t_s": round(time.monotonic() - self._t0, 3), **doc})

    def artifact(self) -> dict:
        events = sorted(self._events.values(),
                        key=lambda e: e.get("t_unix", 0))
        locks = sorted(self._locks.values(),
                       key=lambda d: -d.get("wait_total_ms", 0.0))
        return {
            "schema": self.SCHEMA,
            "sample_interval_s": self.sample_s,
            "services": sorted(self.profile_ports),
            "samples": self.samples,
            "topology_events": list(self.topology_events),
            "stall_events": events,
            "contended_locks": locks[:32],
            "device_compute_top": dict(self._compute_tops),
        }

    def start(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.sample_s):
                try:
                    self.sample_once()
                except Exception:  # noqa: BLE001 - the recorder must
                    pass           # outlive anything it records

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="trajectory-recorder")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


def stall_drill(cluster, recorder: "TrajectoryRecorder | None",
                timeout_s: float = 20.0) -> dict:
    """Deterministic stall-watchdog proof on a LIVE node: restart one
    dbnode under a tick-wedging fault plan, then poll its fault-exempt
    /debug/profile until the node's OWN watchdog reports the stalled
    tick loop (captured stack included); heal and hand the events to the
    trajectory. The detection is entirely in-process on the node — the
    drill only arranges the wedge and reads the verdict."""
    agent_name, service, _kind = cluster.chaos_targets()[0]
    agent = cluster.agents[agent_name]
    port = cluster.node_ports[service]
    base_env = cluster.base_service_env
    t_start = time.time()
    agent.stop(service)
    agent.start(service, env={**base_env, "M3_TPU_FAULTS": STALL_DRILL_SPEC,
                              "M3_TPU_FAULTS_SEED": "0"}, grace_s=0.5)
    from m3_tpu.tools.em import ClusterEnv

    ClusterEnv.wait_until(
        lambda: _http_ok(f"http://127.0.0.1:{port}/health"),
        timeout_s=60, desc="drill node serving")
    events: list[dict] = []

    def stalled():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile",
                    timeout=3.0) as r:
                doc = json.loads(r.read().decode())
        except Exception:  # noqa: BLE001 - mid-restart
            return False
        evs = [e for e in doc.get("watchdog", {}).get("recent_events", ())
               if e.get("kind") == "stall" and e.get("loop") == "dbnode.tick"]
        if evs:
            events.extend(evs)
            return True
        return False

    try:
        ClusterEnv.wait_until(stalled, timeout_s=timeout_s, every_s=0.5,
                              desc="watchdog stall verdict")
    except TimeoutError:
        pass
    finally:
        agent.stop(service)
        agent.start(service, env=base_env, grace_s=0.5)
    if recorder is not None:
        for ev in events:
            key = (service, ev.get("loop"), ev.get("t_unix"))
            recorder._events.setdefault(key, {**ev, "service": service})
    return {"service": service, "window": [t_start, time.time()],
            "fault_spec": STALL_DRILL_SPEC,
            "events": events}


# ---------------------------------------------------------------------------
# full production deployment (real processes) — shared by the CLI and the
# chaos-lane pytest


NODE_CFG = """\
db:
  path: {workdir}/data
  n_shards: {n_shards}
  namespaces:
    - name: default
  # flush the WAL to the OS on every append: a SIGKILLed node must be
  # able to replay every write it acked — the zero-acked-write-loss
  # contract survives SEQUENTIAL outages of both replicas only if no
  # acked byte lives exclusively in a user-space buffer
  commitlog_flush_every_bytes: 1
cluster:
  instance_id: {node_id}
  kv_addr: {kv_addr}
http:
  host: 127.0.0.1
  port: {port}
tick_interval_s: 0.5
# continuous anti-entropy at rig tempo: production defaults are 30s
# cycles, but the convergence audit needs several cycles inside its
# budget, so the rig runs 1s cycles with the same pacing discipline
repair:
  interval_s: 1.0
  jitter_frac: 0.25
  cycle_deadline_s: 10.0
  rate_mbps: 8.0
"""

COORD_CFG = """\
db:
  namespace: {default_ns}
cluster:
  enabled: true
  kv_addr: {kv_addr}
  write_consistency: majority
  read_consistency: one
http:
  host: 127.0.0.1
  port: {port}
tick_interval_s: 0.5
tenants:
  tenants:
{tenant_quota_yaml}
"""

AGG_CFG = """\
instance_id: rig-agg
n_shards: 2
ingest:
  host: 127.0.0.1
  port: {port}
flush_interval_s: 1.0
# the aggregator has no HTTP API; the shared debug surface serves its
# /debug/profile (profiler top-N, contended locks, stall watchdog)
debug_port: {debug_port}
"""


class RigCluster:
    """A real multi-process deployment: N dbnodes (RF=replica_factor)
    + an R-replica quorum kvd metadata plane + coordinator + aggregator,
    every process spawned through em agents with M3_TPU_FAULTS_EXIT=1
    armed (crash-mode fault rules become REAL process deaths)."""

    def __init__(self, workdir: str, tenants: tuple,
                 tenant_quotas: dict[str, dict] | None = None,
                 n_dbnodes: int = 2, kvd_replicas: int = 3,
                 n_shards: int = 4, seed: int = 0):
        import os as _os
        import pathlib

        from m3_tpu.tools.em import AgentClient, ClusterEnv, EmAgent

        free_port = _free_port
        self.workdir = workdir
        self.tenants = tuple(tenants)
        self.seed = seed
        self.n_shards = n_shards
        self._agent_objs = []
        self.agents: dict[str, AgentClient] = {}
        repo_root = str(pathlib.Path(__file__).resolve().parents[2])
        self.base_service_env = {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "PYTHONPATH": repo_root,
            "M3_TPU_FAULTS_EXIT": "1",  # crash rules kill the process
            # the always-on profiling plane, armed fleet-wide: sampling
            # profiler + stall watchdog (M3_TPU_PROFILE) and per-class
            # lock-wait profiling (M3_TPU_LOCK_PROFILE) run for the
            # whole schedule — the trajectory artifact reads them back
            "M3_TPU_PROFILE": "1",
            "M3_TPU_LOCK_PROFILE": "1",
        }
        agent_names = ([f"kv{i}" for i in range(kvd_replicas)]
                       + [f"h{i}" for i in range(n_dbnodes)] + ["hc"])
        for name in agent_names:
            a = EmAgent(_os.path.join(workdir, name), "127.0.0.1:0",
                        agent_id=name)
            self._agent_objs.append(a)
            self.agents[name] = AgentClient(f"http://127.0.0.1:{a.port}")
        self.env = ClusterEnv(self.agents)
        self.node_ports = {f"node{i}": free_port() for i in range(n_dbnodes)}
        self.coord_port = free_port()
        self.agg_port = free_port()
        self.agg_debug_port = free_port()
        self.kvd_ports = {f"kv{i}": free_port() for i in range(kvd_replicas)}
        self.kv_addr = ""
        self.tenant_quotas = tenant_quotas or {}
        self.replica_factor = min(2, n_dbnodes)
        self._next_dbnode = n_dbnodes  # next node index for add_dbnode

    # -- deployment --

    def deploy(self, wait_s: float = 120.0) -> None:
        from m3_tpu.cluster import placement as pl
        from m3_tpu.cluster.kvd import KvdClient
        from m3_tpu.cluster.placement import Instance, initial_placement
        from m3_tpu.query.admin import store_namespace_registry
        from m3_tpu.tools.em import ClusterEnv

        # 1. quorum kvd metadata plane, one replica per kv* agent
        self.kv_addr = self.env.deploy_kvd_quorum(
            self.kvd_ports, env=self.base_service_env)
        kv = KvdClient(self.kv_addr, timeout_s=5.0)

        def plane_up():
            try:
                kv.keys()
                return True
            except Exception:  # noqa: BLE001
                return False

        ClusterEnv.wait_until(plane_up, timeout_s=wait_s, desc="kvd quorum up")

        # 2. placement (RF over the dbnodes) + the tenant namespaces in
        #    the registry (nodes and coordinator both sync from it)
        node_ids = sorted(self.node_ports)
        p = initial_placement(
            [Instance(nid, isolation_group=f"g{i}")
             for i, nid in enumerate(node_ids)],
            n_shards=self.n_shards, replica_factor=self.replica_factor)
        for nid in node_ids:
            p = pl.mark_available(p, nid)
            p.instances[nid].endpoint = \
                f"http://127.0.0.1:{self.node_ports[nid]}"
        pl.store_placement(kv, p)
        self.placement = p
        # nanosecond time unit: the rig writes irregular ns timestamps,
        # and the default SECOND unit would truncate them at every
        # snapshot/flush encode — collapsing datapoints that share a
        # wall second and breaking the exact-match loss audit
        store_namespace_registry(kv, {t: {"time_unit": "ns"}
                                      for t in self.tenants})
        self._kv = kv

        # 3. dbnodes
        for i, nid in enumerate(node_ids):
            agent = self.agents[f"h{i}"]
            agent.put_file("node.yml", NODE_CFG.format(
                workdir=f"{self.workdir}/h{i}",
                n_shards=self.n_shards, node_id=nid,
                kv_addr=self.kv_addr, port=self.node_ports[nid]))
            agent.start(nid, "m3_tpu.services.dbnode", "node.yml",
                        env=self.base_service_env)
        for nid, port in self.node_ports.items():
            ClusterEnv.wait_until(
                lambda p=port: _http_ok(f"http://127.0.0.1:{p}/health"),
                timeout_s=wait_s, desc=f"{nid} health")

        # 4. coordinator (admission quotas in config; runtime-tunable
        #    via the m3_tpu.tenants KV key) + aggregator
        # no quotas -> list each tenant with no limits: the hand-rolled
        # YAML parser has no flow syntax, so a literal `{}` won't parse
        quota_yaml = "".join(
            f"    {t}:\n" + "".join(f"      {k}: {v}\n"
                                    for k, v in (q or {}).items())
            for t, q in self.tenant_quotas.items()) \
            or "".join(f"    {t}:\n" for t in self.tenants)
        self.agents["hc"].put_file("coord.yml", COORD_CFG.format(
            default_ns=self.tenants[0], kv_addr=self.kv_addr,
            port=self.coord_port, tenant_quota_yaml=quota_yaml))
        self.agents["hc"].start("coord", "m3_tpu.services.coordinator",
                                "coord.yml", env=self.base_service_env)
        self.agents["hc"].put_file(
            "agg.yml", AGG_CFG.format(port=self.agg_port,
                                      debug_port=self.agg_debug_port))
        self.agents["hc"].start("agg", "m3_tpu.services.aggregator",
                                "agg.yml", env=self.base_service_env)
        ClusterEnv.wait_until(
            lambda: _http_ok(f"http://127.0.0.1:{self.coord_port}/ready",
                             key="ready"),
            timeout_s=wait_s, desc="coordinator ready")

    def session(self):
        """A fresh client session over the placement (the rig's write
        path — bursty batches through session.write_many)."""
        from m3_tpu.client.breaker import BreakerConfig
        from m3_tpu.client.http_conn import HTTPNodeConnection
        from m3_tpu.client.session import Session
        from m3_tpu.cluster.topology import ConsistencyLevel, TopologyMap

        connections = {
            iid: HTTPNodeConnection(inst.endpoint, timeout_s=5.0)
            for iid, inst in self.placement.instances.items() if inst.endpoint
        }
        return Session(
            TopologyMap(self.placement), connections,
            write_consistency=ConsistencyLevel.MAJORITY,
            read_consistency=ConsistencyLevel.ONE,
            # short cooldown: the rig WANTS to observe recovery inside
            # its budget, not wait out a production-shaped 5s shed window
            breaker_config=BreakerConfig(open_timeout_s=1.0,
                                         retry_jitter_frac=0.25),
        )

    def profile_ports(self) -> dict[str, int]:
        """Every port serving /debug/profile: coordinator + dbnodes on
        their APIs, the aggregator on its debug surface (kvd replicas
        arm the profiler too; their surface is config-opt-in)."""
        return {"coordinator": self.coord_port,
                "aggregator": self.agg_debug_port,
                **dict(self.node_ports)}

    def chaos_targets(self) -> list[tuple]:
        """Every killable process: dbnodes, one kvd replica, the
        aggregator. The coordinator is the measurement plane and stays
        up (its loss is a different drill)."""
        out = []
        for nid in sorted(self.node_ports):
            out.append((self._agent_of(nid), nid, "dbnode"))
        out.append((sorted(self.kvd_ports)[0], "kvd", "kvd"))
        out.append(("hc", "agg", "aggregator"))
        return out

    # -- elasticity verbs (ROADMAP #6(b)) ----------------------------------
    # The rig's only lever is the placement CAS: shard streaming, digest
    # verification, cutover, and the donor grace tick all run inside the
    # nodes (services/handoff.py controllers).

    def _agent_of(self, nid: str) -> str:
        """dbnode id -> its em agent name (node{i} lives on h{i})."""
        return "h" + nid.removeprefix("node")

    def refresh_placement(self) -> None:
        from m3_tpu.cluster import placement as pl

        loaded = pl.load_placement(self._kv)
        if loaded is not None:
            self.placement = loaded[0]

    def add_dbnode(self, wait_s: float = 120.0) -> str:
        """Scale-out verb: spawn a NEW dbnode process on a fresh em
        agent, wait for health, then CAS it into the live placement.
        Its fair share of shards lands INITIALIZING (sourced from the
        donors, which go LEAVING but keep serving); the nodes' handoff
        controllers do the rest."""
        import os as _os

        from m3_tpu.cluster import placement as pl
        from m3_tpu.cluster.placement import Instance
        from m3_tpu.tools.em import AgentClient, ClusterEnv, EmAgent

        i = self._next_dbnode
        self._next_dbnode += 1
        name, nid = f"h{i}", f"node{i}"
        a = EmAgent(_os.path.join(self.workdir, name), "127.0.0.1:0",
                    agent_id=name)
        self._agent_objs.append(a)
        self.agents[name] = AgentClient(f"http://127.0.0.1:{a.port}")
        port = _free_port()
        self.node_ports[nid] = port
        self.agents[name].put_file("node.yml", NODE_CFG.format(
            workdir=f"{self.workdir}/{name}", n_shards=self.n_shards,
            node_id=nid, kv_addr=self.kv_addr, port=port))
        self.agents[name].start(nid, "m3_tpu.services.dbnode", "node.yml",
                                env=self.base_service_env)
        ClusterEnv.wait_until(
            lambda: _http_ok(f"http://127.0.0.1:{port}/health"),
            timeout_s=wait_s, desc=f"{nid} health")
        endpoint = f"http://127.0.0.1:{port}"

        def add(cur):
            return pl.add_instance(
                cur, Instance(nid, isolation_group=f"g{i}",
                              endpoint=endpoint))

        pl.cas_update_placement(self._kv, add)
        self.refresh_placement()
        return nid

    def drain_dbnode(self, nid: str) -> None:
        """Paced-drain verb: CAS remove_instance — every shard the node
        holds goes LEAVING with a new owner INITIALIZING from it; the
        receiving nodes stream at the shared repair rate budget and cut
        over per shard. The process keeps serving until retired."""
        from m3_tpu.cluster import placement as pl

        pl.cas_update_placement(
            self._kv, lambda cur: pl.remove_instance(cur, nid))
        self.refresh_placement()

    def retire_dbnode(self, nid: str) -> None:
        """Stop a fully-drained node's process and forget its port (only
        after wait_placement_settled shows it out of the placement)."""
        agent = self.agents[self._agent_of(nid)]
        try:
            agent.stop(nid)
        except Exception:  # noqa: BLE001 - already dead is drained enough
            pass
        self.node_ports.pop(nid, None)

    def restart_dbnode(self, nid: str, wait_s: float = 120.0) -> None:
        """Rolling-restart verb: SIGKILL (crash consistency — WAL
        replay, no graceful flush) then relaunch and wait for health
        before the caller moves to the next node."""
        from m3_tpu.tools.em import ClusterEnv

        agent = self.agents[self._agent_of(nid)]
        agent.kill(nid)
        agent.start(nid, env=self.base_service_env, grace_s=0.5)
        port = self.node_ports[nid]
        ClusterEnv.wait_until(
            lambda: _http_ok(f"http://127.0.0.1:{port}/health"),
            timeout_s=wait_s, desc=f"{nid} back after restart")

    def wait_placement_settled(self, timeout_s: float = 120.0) -> None:
        """Poll KV until every shard everywhere is AVAILABLE — streamed,
        digest-verified, and cut over by the nodes themselves."""
        from m3_tpu.cluster.placement import ShardState
        from m3_tpu.tools.em import ClusterEnv

        def settled() -> bool:
            self.refresh_placement()
            return all(sh.state is ShardState.AVAILABLE
                       for inst in self.placement.instances.values()
                       for sh in inst.shards.values())

        ClusterEnv.wait_until(settled, timeout_s=timeout_s, every_s=0.5,
                              desc="placement settled (all AVAILABLE)")

    def set_tenant_quotas_kv(self, doc: dict) -> None:
        """Runtime quota update THROUGH the metadata plane: the
        coordinator's KV watch applies it live, no restart."""
        self._kv.set("m3_tpu.tenants", json.dumps(doc).encode())

    def wait_all_healthy(self, timeout_s: float = 120.0) -> None:
        from m3_tpu.tools.em import ClusterEnv

        for nid, port in self.node_ports.items():
            ClusterEnv.wait_until(
                lambda p=port: _http_ok(f"http://127.0.0.1:{p}/health"),
                timeout_s=timeout_s, desc=f"{nid} healthy after chaos")
        ClusterEnv.wait_until(
            lambda: _http_ok(f"http://127.0.0.1:{self.coord_port}/ready",
                             key="ready"),
            timeout_s=timeout_s, desc="coordinator healthy after chaos")

    def teardown(self) -> None:
        try:
            if getattr(self, "_kv", None) is not None:
                self._kv.close()
        except Exception:  # noqa: BLE001
            pass
        self.env.teardown()
        for a in self._agent_objs:
            try:
                a.close()
            except Exception:  # noqa: BLE001
                pass


def _http_ok(url: str, key: str = "ok", timeout_s: float = 5.0) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            return bool(json.loads(r.read().decode()).get(key))
    except Exception:  # noqa: BLE001
        return False


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def node_placement(port: int, timeout_s: float = 10.0) -> dict:
    """One node's /debug/placement: placement version, owned/grace
    shards, and the handoff controller's per-shard progress records."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/placement",
            timeout=timeout_s) as r:
        return json.loads(r.read().decode())


# ---------------------------------------------------------------------------
# the full run: chaos phase + verification + noisy-tenant phase


def run_production_rig(workdir: str, seconds: float = 20.0, seed: int = 7,
                       slo_p99_ms: float = 5000.0) -> dict:
    """Deploy the real cluster, run the seeded kill/partition schedule
    under live load, verify zero acked-write loss and the warning
    contract, then run the noisy-tenant isolation phase (runtime quota
    pushed through kvd; pair-median p99 from the server histograms).
    Returns the full report; raises AssertionError on contract breach
    only from the pytest wrapper — here every fact lands in the report."""
    tenants = ("steady", "noisy", "bulk0", "bulk1")
    cluster = RigCluster(
        workdir, tenants,
        # explicit (label-bounded) quotas; noisy starts UNLIMITED —
        # the KV push mid-run is what starts shedding it
        tenant_quotas={"steady": {"queries_per_sec": 500},
                       "noisy": {}},
        seed=seed)
    report: dict = {"seed": seed, "seconds": seconds}
    try:
        cluster.deploy()
        session = cluster.session()
        ledger = WriteLedger()

        # ---- phase 1: steady load + seeded kill/partition schedule ----
        chaos_s = max(6.0, seconds * 0.6)
        cfg = RigConfig(seed=seed, tenants=tenants, duration_s=chaos_s,
                        slo_p99_ms=slo_p99_ms)
        rig = Rig(cfg, session_write_fn(session),
                  http_query_fn(cluster.coord_port), ledger=ledger)
        # the soak-trajectory recorder runs across EVERY phase: QPS,
        # p99, RSS, contended locks and stall events over time — the
        # first-class artifact of the profiling & saturation plane
        recorder = TrajectoryRecorder(cluster.coord_port,
                                      cluster.profile_ports(), rig=rig)
        recorder.start()
        schedule = ChaosSchedule.generate(seed, chaos_s,
                                          cluster.chaos_targets())
        report["schedule"] = [e.to_doc() for e in schedule]
        runner = ChaosRunner(cluster.agents, schedule,
                             base_env={s: cluster.base_service_env
                                       for _a, s, _k in
                                       cluster.chaos_targets()},
                             seed=seed)
        runner.start()
        phase1 = rig.run(chaos_s)
        runner.join(60.0)
        report["phase1"] = phase1
        report["chaos_executed"] = runner.executed
        report["chaos_errors"] = runner.errors

        # ---- recovery + zero acked-write loss ----
        cluster.wait_all_healthy()
        verify_session = cluster.session()  # fresh breakers for the audit
        # /health answers a tick before a restarted node has re-synced
        # its tenant namespaces from the registry: gate the audit on
        # every tenant actually ANSWERING reads, not on liveness
        from m3_tpu.tools.em import ClusterEnv

        def _tenants_readable():
            try:
                for t in tenants:
                    verify_session.fetch(t, b"rig-readiness-probe", 0, 1)
                return True
            except Exception:  # noqa: BLE001 - not ready yet
                return False

        ClusterEnv.wait_until(_tenants_readable, timeout_s=90,
                              desc="tenant namespaces readable after chaos")
        report["verify"] = ledger.verify(session_fetch_fn(verify_session))

        # ---- convergence audit: anti-entropy actually converged ----
        # the replica that slept through its kill/partition window holds
        # less data than its partner; the nodes' OWN repair daemons must
        # reach per-(shard, block) rollup-digest equality within the
        # cycle budget — nothing here invokes repair
        report["convergence"] = convergence_audit(
            cluster, tenants, budget_cycles=10, interval_s=1.0)

        # ---- stall drill: the watchdog proves itself on a live node ----
        # one dbnode restarted under a tick-wedging fault plan; its OWN
        # watchdog must flag the stalled loop (with the wedged thread's
        # stack) on the fault-exempt /debug/profile before the heal
        report["stall_drill"] = stall_drill(cluster, recorder)
        cluster.wait_all_healthy()

        # ---- phase 2: noisy-tenant isolation under a node kill ----
        # runtime quota push through the kvd metadata plane: noisy goes
        # from unlimited to 3 qps LIVE; steady keeps its headroom
        cluster.set_tenant_quotas_kv({
            "tenants": {"steady": {"queries_per_sec": 500},
                        "noisy": {"queries_per_sec": 3.0,
                                  "burst_s": 1.0}}})
        time.sleep(1.5)  # watch delivery
        qfn = http_query_fn(cluster.coord_port)
        shed_counts = {"noisy": 0, "steady_shed": 0}
        kill_agent, kill_service, _ = cluster.chaos_targets()[0]

        def run_window(i: int) -> None:
            # kill a dbnode in the middle window: isolation must hold
            # WHILE nodes are dying
            if i == 1:
                cluster.agents[kill_agent].kill(kill_service)
            end = time.monotonic() + max(1.5, seconds * 0.08)
            k = 0
            while time.monotonic() < end:
                status, _doc, _h = qfn("noisy", "rig_metric_1",
                                       int(time.time()) - 60,
                                       int(time.time()), 10)
                if status == 429:
                    shed_counts["noisy"] += 1
                status, _doc, _h = qfn("steady", f"rig_metric_{k % 8}",
                                       int(time.time()) - 60,
                                       int(time.time()), 10)
                if status == 429:
                    shed_counts["steady_shed"] += 1
                k += 1
            if i == 1:
                cluster.agents[kill_agent].start(kill_service, grace_s=0.5)

        p99s = windowed_p99s_ms(
            lambda: scrape_metrics(cluster.coord_port),
            "coordinator_tenant_request_seconds", {"namespace": "steady"},
            run_window, n_windows=4)
        report["noisy_phase"] = {
            "steady_window_p99s_ms": p99s,
            "steady_pair_median_p99_ms": median_p99_ms(p99s),
            "noisy_sheds": shed_counts["noisy"],
            "steady_sheds": shed_counts["steady_shed"],
            "slo_p99_ms": slo_p99_ms,
        }
        cluster.wait_all_healthy()
        recorder.stop()
        try:
            recorder.sample_once()  # one final post-heal row
        except Exception:  # noqa: BLE001 - best-effort tail sample
            pass
        trajectory = recorder.artifact()
        report["trajectory"] = trajectory
        try:
            import os as _os

            with open(_os.path.join(workdir, "trajectory.json"), "w") as f:
                json.dump(trajectory, f, indent=2, default=str)
        except OSError:
            pass
        report["final_heartbeats"] = {
            name: ("ok" if "services" in hb else hb.get("error", "?"))
            for name, hb in cluster.env.heartbeats().items()
        }
    finally:
        cluster.teardown()
    return report


def run_elasticity_episode(workdir: str, seconds: float = 20.0,
                           seed: int = 7,
                           slo_p99_ms: float = 5000.0) -> dict:
    """ROADMAP #6(b), the elasticity episode: add-node -> paced drain ->
    rolling restart, all under live zipf load, overlapping a seeded
    chaos schedule on the metadata/aggregation planes (a kvd replica and
    the aggregator; the dbnodes' failures are the episode's own verbs).
    The placement CAS verbs are the ONLY lever the rig pulls — shard
    streaming, digest verification, cutover, and the donor grace tick
    all run inside the nodes (services/handoff.py). Proven at the end:
    zero acked-write loss, every shard AVAILABLE on the post-change
    owners, rollup convergence, and a client read p99 that stayed
    bounded while the topology churned (trajectory rows annotated with
    the topology events)."""
    from m3_tpu.client.http_conn import HTTPNodeConnection
    from m3_tpu.client.topology_watch import PlacementWatcher
    from m3_tpu.tools.em import ClusterEnv

    tenants = ("elastic0", "elastic1")
    cluster = RigCluster(workdir, tenants, n_dbnodes=2, n_shards=4,
                         seed=seed)
    report: dict = {"seed": seed, "seconds": seconds}
    watcher = None
    recorder = None
    try:
        cluster.deploy()
        session = cluster.session()
        # the hot-swap plane under test: the load session follows
        # placement changes through the watcher, never a rebuild
        watcher = PlacementWatcher(
            cluster._kv, session,
            connection_factory=lambda ep: HTTPNodeConnection(
                ep, timeout_s=5.0))
        watcher.poll()
        watcher.start(0.5)
        ledger = WriteLedger()
        cfg = RigConfig(seed=seed, tenants=tenants, duration_s=seconds,
                        slo_p99_ms=slo_p99_ms)
        rig = Rig(cfg, session_write_fn(session),
                  http_query_fn(cluster.coord_port), ledger=ledger)
        recorder = TrajectoryRecorder(cluster.coord_port,
                                      cluster.profile_ports(), rig=rig)
        recorder.start()
        targets = [t for t in cluster.chaos_targets() if t[2] != "dbnode"]
        schedule = ChaosSchedule.generate(seed, max(8.0, seconds), targets)
        report["schedule"] = [e.to_doc() for e in schedule]
        runner = ChaosRunner(cluster.agents, schedule,
                             base_env={s: cluster.base_service_env
                                       for _a, s, _k in targets},
                             seed=seed)
        # load loops driven directly (not rig.run): the episode's verbs
        # pace the run, and the loops stop when the last verb lands
        writer = threading.Thread(target=rig._writer_loop, daemon=True)
        querier = threading.Thread(target=rig._query_loop, daemon=True)
        writer.start()
        querier.start()
        runner.start()
        slice_s = max(2.0, seconds / 5.0)
        time.sleep(slice_s)  # baseline load on the 2-node deployment

        # ---- scale out: add-node, handoff streams onto it live ----
        new_nid = cluster.add_dbnode()
        recorder.annotate("add_node", node=new_nid)
        cluster.wait_placement_settled()
        recorder.annotate("handoff_settled", node=new_nid)
        report["handoff_status"] = {
            nid: node_placement(port)
            for nid, port in cluster.node_ports.items()}
        time.sleep(slice_s)

        # ---- paced drain of an original node ----
        drain_nid = sorted(cluster.node_ports)[0]
        recorder.annotate("drain", node=drain_nid)
        cluster.drain_dbnode(drain_nid)
        cluster.wait_placement_settled()
        time.sleep(1.5)  # the donor's grace tick: it still serves reads
        cluster.retire_dbnode(drain_nid)
        recorder.annotate("drained", node=drain_nid)
        report["drained_node"] = drain_nid
        time.sleep(slice_s)

        # ---- rolling restart (SIGKILL + WAL replay) of survivors ----
        for nid in sorted(cluster.node_ports):
            recorder.annotate("restart", node=nid)
            cluster.restart_dbnode(nid)
        time.sleep(slice_s)

        runner.join(60.0)
        rig._stop.set()
        writer.join(10.0)
        querier.join(10.0)
        report["phase"] = rig.report()
        report["chaos_executed"] = runner.executed
        report["chaos_errors"] = runner.errors

        # ---- verification on the post-change topology ----
        cluster.wait_all_healthy()
        verify_session = cluster.session()

        def _tenants_readable():
            try:
                for t in tenants:
                    verify_session.fetch(t, b"rig-readiness-probe", 0, 1)
                return True
            except Exception:  # noqa: BLE001 - not ready yet
                return False

        ClusterEnv.wait_until(_tenants_readable, timeout_s=90,
                              desc="tenants readable after elasticity")
        report["verify"] = ledger.verify(session_fetch_fn(verify_session))
        report["convergence"] = convergence_audit(
            cluster, tenants, budget_cycles=10, interval_s=1.0)
        report["final_placement"] = {
            iid: {str(sh.id): sh.state.value
                  for sh in inst.shards.values()}
            for iid, inst in cluster.placement.instances.items()}
        recorder.stop()
        report["trajectory"] = recorder.artifact()
        try:
            import os as _os

            with open(_os.path.join(workdir, "elasticity.json"), "w") as f:
                json.dump(report["trajectory"], f, indent=2, default=str)
        except OSError:
            pass
    finally:
        if watcher is not None:
            watcher.stop()
        if recorder is not None:
            recorder.stop()
        cluster.teardown()
    return report


def run_standing_rules_episode(workdir: str, seconds: float = 20.0,
                               seed: int = 11,
                               slo_p99_ms: float = 5000.0) -> dict:
    """ISSUE-18's standing-query episode: a standing-rules-only ruleset
    lands in KV mid-load; the coordinator's flush loop evaluates the
    rules against the quorum cluster while a seeded chaos schedule kills
    dbnodes, a kvd replica and the aggregator (the coordinator — the
    evaluation host — stays up, as in the production episode). Proven at
    the end: zero acked-write loss for the raw load, registry-sync of
    the rule-created namespace, rollup convergence over the tenants AND
    that namespace, standing outputs present and EQUAL across their
    aggregated/raw dual-write legs, every rule recovered to an
    error-free caught-up state (via /debug/standing — a flush that
    failed its output quorum holds the watermark and retries), bounded
    rule-eval lag (p99 of aggregator_standing_rule_eval_lag_seconds,
    annotated onto the trajectory per slice), and the misrouting
    honesty gate: standing rules alone never mark a tier complete, so
    cheapest-tier resolution must keep EVERY query of the episode on
    raw."""
    from m3_tpu.metrics import rules_store
    from m3_tpu.query.admin import load_namespace_registry
    from m3_tpu.tools.em import ClusterEnv

    tenants = ("rules0", "rules1")
    out_ns = "aggregated_1s_10m"  # StoragePolicy("1s:10m").namespace_name
    lag_bound_s = 30.0
    lag_family = "aggregator_standing_rule_eval_lag_seconds"
    ruleset_doc = {"standing": [
        # scalar aggregate over a hot metric
        {"name": "std:rig0:sum", "expr": "sum(rig_metric_0)",
         "policy": "1s:10m"},
        # grouped aggregate: the sid grouping label rides the output
        {"name": "std:rig1:by_sid", "expr": "sum by (sid) (rig_metric_1)",
         "policy": "1s:10m"},
        # avg + static rule labels on every output series
        {"name": "std:rig2:avg", "expr": "avg(rig_metric_2)",
         "policy": "1s:10m", "labels": {"plane": "standing"}},
        # absent input: must evaluate cleanly forever, writing nothing
        {"name": "std:absent", "expr": "sum(rig_metric_never)",
         "policy": "1s:10m"},
    ]}
    cluster = RigCluster(workdir, tenants, n_dbnodes=2, n_shards=4,
                         seed=seed)
    report: dict = {"seed": seed, "seconds": seconds, "out_ns": out_ns,
                    "lag_bound_s": lag_bound_s}
    recorder = None
    try:
        cluster.deploy()
        session = cluster.session()
        ledger = WriteLedger()
        chaos_s = max(8.0, seconds)
        cfg = RigConfig(seed=seed, tenants=tenants, duration_s=chaos_s,
                        slo_p99_ms=slo_p99_ms)
        rig = Rig(cfg, session_write_fn(session),
                  http_query_fn(cluster.coord_port), ledger=ledger)
        recorder = TrajectoryRecorder(cluster.coord_port,
                                      cluster.profile_ports(), rig=rig)
        recorder.start()
        # the ruleset lands through the same KV watch a live operator
        # uses; the coordinator builds its downsampler from the update
        version = rules_store.store_ruleset_doc(cluster._kv, ruleset_doc)
        report["ruleset_version"] = version
        recorder.annotate("ruleset_stored", version=version,
                          rules=len(ruleset_doc["standing"]))
        schedule = ChaosSchedule.generate(seed, chaos_s,
                                          cluster.chaos_targets())
        report["schedule"] = [e.to_doc() for e in schedule]
        runner = ChaosRunner(cluster.agents, schedule,
                             base_env={s: cluster.base_service_env
                                       for _a, s, _k in
                                       cluster.chaos_targets()},
                             seed=seed)
        writer = threading.Thread(target=rig._writer_loop, daemon=True)
        querier = threading.Thread(target=rig._query_loop, daemon=True)
        writer.start()
        querier.start()
        runner.start()

        # registry-sync leg: the first evaluation creates out_ns and the
        # coordinator lands it in the KV namespace registry, where the
        # dbnodes' sync_namespaces tick picks it up before quorum writes
        # can land — so chaos or not, the namespace must appear
        ClusterEnv.wait_until(
            lambda: out_ns in load_namespace_registry(cluster._kv),
            timeout_s=60, desc=f"{out_ns} in KV namespace registry")
        recorder.annotate("tier_namespace_registered", namespace=out_ns)
        report["registry_entry"] = \
            load_namespace_registry(cluster._kv).get(out_ns)

        # eval-lag trajectory: per-slice p99 of the coordinator's
        # rule-eval-lag histogram, annotated onto the soak trajectory
        slice_s = max(2.0, chaos_s / 4.0)
        prev = parse_histogram(scrape_metrics(cluster.coord_port),
                               lag_family)
        lag_slices = []
        deadline = time.monotonic() + chaos_s
        while time.monotonic() < deadline:
            time.sleep(min(slice_s, max(0.1, deadline - time.monotonic())))
            try:
                cur = parse_histogram(scrape_metrics(cluster.coord_port),
                                      lag_family)
            except Exception:  # noqa: BLE001 - scrape raced a fault
                continue
            p99_ms = hist_p99_ms(hist_delta(prev, cur))
            prev = cur
            p99_s = None if p99_ms is None else round(p99_ms / 1e3, 3)
            lag_slices.append(p99_s)
            recorder.annotate("rule_eval_lag", p99_s=p99_s)
        runner.join(60.0)
        rig._stop.set()
        writer.join(10.0)
        querier.join(10.0)
        report["phase"] = rig.report()
        report["chaos_executed"] = runner.executed
        report["chaos_errors"] = runner.errors
        report["rule_eval_lag_slices_s"] = lag_slices

        # ---- recovery: heal, then the standing plane must go clean ----
        cluster.wait_all_healthy()
        verify_session = cluster.session()

        def _readable():
            try:
                for t in (*tenants, out_ns):
                    verify_session.fetch(t, b"rig-readiness-probe", 0, 1)
                return True
            except Exception:  # noqa: BLE001 - not ready yet
                return False

        ClusterEnv.wait_until(_readable, timeout_s=90,
                              desc="tenants + tier readable after chaos")
        report["verify"] = ledger.verify(session_fetch_fn(verify_session))

        def _standing_status():
            url = (f"http://127.0.0.1:{cluster.coord_port}"
                   "/debug/standing")
            with urllib.request.urlopen(url, timeout=10) as r:
                return json.loads(r.read().decode())

        def _standing_clean():
            # every rule error-free, evaluated at least once, watermark
            # within the lag bound of now: an output write that failed
            # its quorum during chaos held last_end and retried — the
            # plane must close back up on its own after the heal
            try:
                doc = _standing_status()
            except Exception:  # noqa: BLE001 - surface racing the heal
                return False
            rules = doc.get("rules", {})
            if set(rules) != {r["name"] for r in ruleset_doc["standing"]}:
                return False
            now_ns = time.time_ns()
            return all(
                st["error"] is None and st["evals"] > 0
                and now_ns - st["last_end_ns"] <= lag_bound_s * 1e9
                for st in rules.values())

        ClusterEnv.wait_until(_standing_clean, timeout_s=90,
                              desc="standing rules error-free + caught up")
        report["standing_status"] = _standing_status()

        # convergence over the tenants AND the rule-created namespace:
        # standing outputs are replicated quorum writes like any other —
        # the repair daemons must converge them too
        report["convergence"] = convergence_audit(
            cluster, (*tenants, out_ns), budget_cycles=10, interval_s=1.0)

        # ---- output audit: presence + dual-write leg parity ----
        # each concrete rule's outputs, read back through the full query
        # path from BOTH legs: the aggregated namespace and the raw
        # write_raw leg in the source tenant. Values at common grid
        # points must be bitwise equal — the legs are one entries batch
        qfn = http_query_fn(cluster.coord_port)
        end_s = int(time.time())
        start_s = end_s - int(chaos_s) - 30
        audit = {}
        parity_ok = True
        total_points = 0
        for rule in ruleset_doc["standing"][:3]:
            name = rule["name"]
            agg = _series_points(qfn(out_ns, name, start_s, end_s, 1)[1])
            raw = _series_points(
                qfn(tenants[0], name, start_s, end_s, 1)[1])
            pts = sum(len(v) for v in agg.values())
            total_points += pts
            common = mismatched = 0
            for key, a_vals in agg.items():
                r_vals = raw.get(key, {})
                for ts, av in a_vals.items():
                    rv = r_vals.get(ts)
                    if rv is None:
                        continue
                    common += 1
                    if av != rv:
                        mismatched += 1
            if mismatched or not common or not pts:
                parity_ok = False
            audit[name] = {"agg_series": len(agg), "agg_points": pts,
                           "raw_series": len(raw),
                           "common_points": common,
                           "mismatched": mismatched}
        report["output_audit"] = audit
        report["output_points"] = total_points
        report["leg_parity_ok"] = parity_ok

        # ---- misrouting honesty gate ----
        text = scrape_metrics(cluster.coord_port)
        tier_reads = {dict(k).get("tier", "?"): v for k, v in
                      parse_counters(text, "query_tier_reads").items()}
        report["tier_reads"] = tier_reads
        report["no_misrouted_reads"] = not any(
            t.startswith("aggregated") for t in tier_reads)
        report["standing_counters"] = {
            leaf: sum(parse_counters(
                text, f"aggregator_standing_rules_{leaf}").values())
            for leaf in ("evaluated", "invalidated", "skipped", "errors")}
        p99 = hist_p99_ms(parse_histogram(text, lag_family))
        report["rule_eval_lag_p99_s"] = (None if p99 is None
                                         else round(p99 / 1e3, 3))
        recorder.stop()
        report["trajectory"] = recorder.artifact()
        try:
            import os as _os

            with open(_os.path.join(workdir, "standing_rules.json"),
                      "w") as f:
                json.dump(report["trajectory"], f, indent=2, default=str)
        except OSError:
            pass
    finally:
        if recorder is not None:
            recorder.stop()
        cluster.teardown()
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="production chaos/load rig")
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--slo-p99-ms", type=float, default=5000.0)
    ap.add_argument("--episode",
                    choices=("production", "elasticity", "standing_rules"),
                    default="production",
                    help="production = kill/partition schedule; "
                         "elasticity = add/drain/restart under load; "
                         "standing_rules = recording rules + retention "
                         "tiers under chaos")
    args = ap.parse_args(argv)
    if args.episode == "standing_rules":
        report = run_standing_rules_episode(args.workdir, args.seconds,
                                            args.seed, args.slo_p99_ms)
        print(json.dumps(report, indent=2, default=str))
        lag = report.get("rule_eval_lag_p99_s")
        ok = (not report.get("verify", {}).get("missing")
              and report.get("convergence", {}).get("converged", False)
              and not report.get("chaos_errors")
              and report.get("output_points", 0) > 0
              and report.get("leg_parity_ok", False)
              and report.get("no_misrouted_reads", False)
              and lag is not None
              and lag <= report.get("lag_bound_s", 30.0))
        return 0 if ok else 1
    if args.episode == "elasticity":
        report = run_elasticity_episode(args.workdir, args.seconds,
                                        args.seed, args.slo_p99_ms)
        print(json.dumps(report, indent=2, default=str))
        ok = (not report.get("verify", {}).get("missing")
              and report.get("convergence", {}).get("converged", False)
              and not report.get("chaos_errors"))
        return 0 if ok else 1
    report = run_production_rig(args.workdir, args.seconds, args.seed,
                                args.slo_p99_ms)
    print(json.dumps(report, indent=2, default=str))
    traj = report.get("trajectory", {})
    ok = (not report.get("verify", {}).get("missing")
          and report.get("convergence", {}).get("converged", False)
          and report.get("noisy_phase", {}).get("noisy_sheds", 0) > 0
          and bool(traj.get("stall_events"))
          and bool(traj.get("contended_locks")))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
