"""PromQL correctness comparator — the scripts/comparator role.

The reference diffs identical queries against a real Prometheus over
seeded data (/root/reference/scripts/comparator/README.md). This harness
does the same over HTTP against the coordinator, with three result
sources:

1. ANALYTIC mode (always on): deterministic seeded series whose query
   answers are derivable in closed form (linear counters -> exact rates,
   constant gauges, exact histogram quantiles, binary-op identities).
   True correctness checking with no Prometheus dependency.
2. SNAPSHOT mode: the full query corpus's responses pinned to a fixture
   file; any numeric drift across changes fails. Regenerate with
   --update after INTENTIONAL semantic changes.
3. LIVE mode (--prom-url): seed the same series into a real Prometheus
   (remote write) and diff query_range responses — the reference's exact
   methodology, for environments that have one.

Usage:
    python -m m3_tpu.tools.comparator [--update] [--prom-url URL]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import urllib.error
import urllib.request

START = 1_600_000_000  # unix seconds; aligned, deterministic
NS = 10**9

# ---------------------------------------------------------------------------
# seeded data: every series is a closed-form function of time
# ---------------------------------------------------------------------------


def seed_points():
    """[(metric, tags, [(t_s, value)])] over 20 minutes at 15s."""
    ts = [START + i * 15 for i in range(81)]
    out = []
    # perfect counters: rate == slope
    out.append(("ctr", {"job": "a", "slope": "2"}, [(t, 2.0 * (t - START)) for t in ts]))
    out.append(("ctr", {"job": "b", "slope": "5"}, [(t, 5.0 * (t - START)) for t in ts]))
    # counter with one reset at t=+600s
    def reset_val(t):
        dt = t - START
        return 3.0 * dt if dt < 600 else 3.0 * (dt - 600)

    out.append(("ctr_reset", {"job": "a"}, [(t, reset_val(t)) for t in ts]))
    # constant gauge + linear gauge + sinusoid
    out.append(("gauge_const", {"k": "v"}, [(t, 42.0) for t in ts]))
    out.append(("gauge_lin", {"k": "v"}, [(t, float(t - START)) for t in ts]))
    out.append(("gauge_sin", {"k": "v"},
                [(t, math.sin((t - START) / 120.0)) for t in ts]))
    # histogram with fixed per-interval bucket increments
    for le, per in (("0.1", 10), ("0.5", 30), ("1", 60), ("+Inf", 100)):
        out.append(("req_bucket", {"le": le},
                    [(t, per * (t - START) / 15.0) for t in ts]))
    return out


QUERIES = [
    # (name, promql, needs)
    ("rate_linear", "rate(ctr[2m])"),
    ("increase_linear", "increase(ctr[2m])"),
    ("irate_linear", "irate(ctr[1m])"),
    ("delta_gauge", "delta(gauge_lin[2m])"),
    ("rate_reset", "rate(ctr_reset[2m])"),
    ("sum_rate", "sum(rate(ctr[2m]))"),
    ("sum_by", "sum by (job) (rate(ctr[2m]))"),
    ("avg_over_time", "avg_over_time(gauge_const[5m])"),
    ("min_max", "max_over_time(gauge_lin[5m]) - min_over_time(gauge_lin[5m])"),
    ("count_over_time", "count_over_time(gauge_const[5m])"),
    ("stddev_const", "stddev_over_time(gauge_const[5m])"),
    ("quantile_ot", "quantile_over_time(0.5, gauge_lin[5m])"),
    ("binary_vector", "ctr / ignoring(slope) group_left gauge_const"),
    ("scalar_arith", "gauge_const * 2 + 1"),
    ("comparison_filter", 'rate(ctr[2m]) > 3'),
    ("bool_compare", "gauge_const == bool 42"),
    ("clamp", "clamp(gauge_lin, 100, 500)"),
    ("abs_neg", "abs(0 - gauge_lin)"),
    ("histogram_q50", "histogram_quantile(0.5, rate(req_bucket[2m]))"),
    ("histogram_q90", "histogram_quantile(0.9, rate(req_bucket[2m]))"),
    ("topk", "topk(1, rate(ctr[2m]))"),
    ("subquery_max", "max_over_time(rate(ctr[2m])[10m:1m])"),
    ("at_modifier", f"gauge_lin @ {START + 300}"),
    ("offset", "gauge_lin offset 5m"),
    ("deriv", "deriv(gauge_lin[5m])"),
    ("predict", "predict_linear(gauge_lin[5m], 60)"),
    ("resets", "resets(ctr_reset[15m])"),
    ("changes", "changes(gauge_const[5m])"),
    ("sort", "sort(rate(ctr[2m]))"),
    ("vector_and", "ctr and ctr{job=\"a\"}"),
    ("absent_present", "present_over_time(gauge_const[5m])"),
    ("holt_winters_lin", "holt_winters(gauge_lin[5m], 0.5, 0.5)"),
    ("absent_ot_present", "absent_over_time(gauge_const[5m])"),
    ("absent_ot_missing", "absent_over_time(no_such_metric[5m])"),
]

# analytic expectations: name -> fn(t_s) -> {series_key: value} where
# series_key is the sorted-label string; None value = skip that step
EPS = 1e-6


def _analytic_expectations():
    q_start, q_end, q_step = START + 600, START + 1140, 60

    def const(v):
        return lambda t: v

    return {
        # linear counters: extrapolated rate == slope exactly (regular
        # samples, interior windows)
        "rate_linear": {"job=a,slope=2": const(2.0),
                        "job=b,slope=5": const(5.0)},
        "increase_linear": {"job=a,slope=2": const(240.0),
                            "job=b,slope=5": const(600.0)},
        "irate_linear": {"job=a,slope=2": const(2.0),
                         "job=b,slope=5": const(5.0)},
        "delta_gauge": {"k=v": const(120.0)},
        "sum_rate": {"": const(7.0)},
        "sum_by": {"job=a": const(2.0), "job=b": const(5.0)},
        "avg_over_time": {"k=v": const(42.0)},
        "count_over_time": {"k=v": const(20.0)},
        "stddev_const": {"k=v": const(0.0)},
        "scalar_arith": {"k=v": const(85.0)},
        "bool_compare": {"k=v": const(1.0)},
        "subquery_max": {"job=a,slope=2": const(2.0),
                         "job=b,slope=5": const(5.0)},
        "at_modifier": {"k=v": const(300.0)},
        "offset": {"k=v": lambda t: float(t - START - 300)},
        "deriv": {"k=v": const(1.0)},
        "predict": {"k=v": lambda t: float(t - START + 60)},
        "changes": {"k=v": const(0.0)},
        # histogram: within-bucket linear interpolation of exact rates
        # rates/s: 0.1->2/3, 0.5->2, 1->4, inf->20/3; q50: target 10/3
        # falls in (2,4] bucket (0.5,1]: 0.5 + (10/3-2)/2 * 0.5 = 0.8333..
        "histogram_q50": {"": const(0.5 + (20 / 3 * 0.5 - 2.0) / 2.0 * 0.5)},
        "absent_present": {"k=v": const(1.0)},
        # linear data: Holt's double smoothing tracks exactly, so the
        # smoothed value equals the window's LAST sample (at t, samples
        # land on 15s marks -> last = t rounded down to 15)
        "holt_winters_lin": {"k=v": lambda t: float(((t - START) // 15) * 15)},
        # gauge_const always has samples -> absent_over_time returns no
        # rows; a never-written metric -> constant 1 with empty labels
        "absent_ot_present": {},
        "absent_ot_missing": {"": const(1.0)},
    }, (q_start, q_end, q_step)


def _series_key(metric: dict) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(metric.items())
                    if k != "__name__")


def run_queries(base_url: str, q_start: int, q_end: int, q_step: int):
    """name -> {series_key: [(t, value)]} from a /api/v1/query_range API."""
    out = {}
    for name, query in QUERIES:
        u = (f"{base_url}/api/v1/query_range?query="
             f"{urllib.request.quote(query, safe='')}"
             f"&start={q_start}&end={q_end}&step={q_step}")
        try:
            doc = json.loads(urllib.request.urlopen(u, timeout=30).read())
        except urllib.error.HTTPError as e:  # coordinator returns errors as 4xx
            try:
                err = json.loads(e.read()).get("error", str(e))
            except Exception:
                err = str(e)
            out[name] = {"__error__": [(0, err)]}
            continue
        if doc.get("status") != "success":
            out[name] = {"__error__": [(0, doc.get("error", "?"))]}
            continue
        res = {}
        for series in doc["data"]["result"]:
            key = _series_key(series.get("metric", {}))
            res[key] = [(int(t), float(v)) for t, v in series.get("values", [])]
        out[name] = res
    return out


def seed_via_http(base_url: str) -> int:
    """One batched Prometheus remote-write request (not 810 point POSTs)."""
    from m3_tpu.utils import protowire, snappy

    series = []
    n = 0
    for metric, tags, pts in seed_points():
        labels = sorted(
            [(b"__name__", metric.encode())]
            + [(k.encode(), v.encode()) for k, v in tags.items()]
        )
        series.append(protowire.PromTimeSeries(
            labels=labels, samples=[(t * 1000, v) for t, v in pts]))
        n += len(pts)
    payload = snappy.compress(protowire.encode_write_request(series))
    urllib.request.urlopen(urllib.request.Request(
        f"{base_url}/api/v1/prom/remote/write", data=payload, method="POST",
        headers={"Content-Type": "application/x-protobuf"},
    ), timeout=60)
    return n


def check_analytic(results) -> list[str]:
    """Differences between results and the closed-form expectations."""
    expect, _rng = _analytic_expectations()
    diffs = []
    for name, series_expect in expect.items():
        got = results.get(name)
        if got is None or "__error__" in got:
            diffs.append(f"{name}: query failed: {got}")
            continue
        for key, fn in series_expect.items():
            rows = got.get(key)
            if rows is None:
                diffs.append(f"{name}/{key}: series missing (have {sorted(got)})")
                continue
            for t, v in rows:
                want = fn(t)
                if want is None:
                    continue
                if not math.isclose(v, want, rel_tol=1e-9, abs_tol=EPS):
                    diffs.append(
                        f"{name}/{key} @ {t}: got {v!r}, want {want!r}")
                    break
    return diffs


def diff_results(a, b, label_a="ours", label_b="theirs") -> list[str]:
    diffs = []
    for name in sorted(set(a) | set(b)):
        ra, rb = a.get(name, {}), b.get(name, {})
        keys = set(ra) | set(rb)
        for key in sorted(keys):
            va, vb = ra.get(key), rb.get(key)
            if va is None or vb is None:
                diffs.append(f"{name}/{key}: only in "
                             f"{label_a if vb is None else label_b}")
                continue
            if len(va) != len(vb):
                diffs.append(f"{name}/{key}: {len(va)} vs {len(vb)} points")
                continue
            for (ta, xa), (tb, xb) in zip(va, vb):
                same_nan = math.isnan(xa) and math.isnan(xb)
                if ta != tb or (not same_nan
                                and not math.isclose(xa, xb, rel_tol=1e-9,
                                                     abs_tol=1e-9)):
                    diffs.append(f"{name}/{key} @ {ta}: {xa!r} vs {xb!r}")
                    break
    return diffs


SNAPSHOT_PATH = os.path.join(os.path.dirname(__file__), "..", "..", "tests",
                             "fixtures", "comparator_snapshot.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", action="store_true",
                    help="regenerate the pinned snapshot")
    ap.add_argument("--prom-url", default=None,
                    help="live Prometheus base URL to diff against")
    ap.add_argument("--base-url", default=None,
                    help="coordinator base URL (default: in-process)")
    args = ap.parse_args(argv)

    expect, (q_start, q_end, q_step) = _analytic_expectations()
    owns_api = args.base_url is None
    if owns_api:
        import tempfile

        from m3_tpu.query.api import CoordinatorAPI
        from m3_tpu.storage.database import Database
        from m3_tpu.storage.options import DatabaseOptions

        tmp = tempfile.mkdtemp(prefix="comparator-")
        db = Database(tmp, DatabaseOptions(n_shards=2))
        db.create_namespace("default")
        db.open(START * NS)
        api = CoordinatorAPI(db)
        port = api.serve(port=0)
        base_url = f"http://127.0.0.1:{port}"
    else:
        base_url = args.base_url

    try:
        seed_via_http(base_url)
        results = run_queries(base_url, q_start, q_end, q_step)

        rc = 0
        diffs = check_analytic(results)
        if diffs:
            print(f"ANALYTIC: {len(diffs)} mismatches")
            for d in diffs[:40]:
                print("  " + d)
            rc = 1
        else:
            print(f"ANALYTIC: ok ({len(_analytic_expectations()[0])} checked)")

        snap_path = os.path.abspath(SNAPSHOT_PATH)
        if args.update:
            with open(snap_path, "w") as f:
                json.dump(results, f, indent=1, sort_keys=True)
            print(f"SNAPSHOT: updated {snap_path}")
        elif os.path.exists(snap_path):
            with open(snap_path) as f:
                pinned = {
                    name: {k: [(int(t), float(v)) for t, v in rows]
                           for k, rows in res.items()}
                    for name, res in json.load(f).items()
                }
            sdiffs = diff_results(results, pinned, "current", "snapshot")
            if sdiffs:
                print(f"SNAPSHOT: {len(sdiffs)} drifts vs pinned")
                for d in sdiffs[:40]:
                    print("  " + d)
                rc = 1
            else:
                print(f"SNAPSHOT: ok ({len(pinned)} queries)")

        if args.prom_url:
            seed_via_prometheus(args.prom_url)
            theirs = run_queries(args.prom_url, q_start, q_end, q_step)
            pdiffs = diff_results(results, theirs, "m3_tpu", "prometheus")
            if pdiffs:
                print(f"PROMETHEUS: {len(pdiffs)} mismatches")
                for d in pdiffs[:40]:
                    print("  " + d)
                rc = 1
            else:
                print("PROMETHEUS: ok")
        return rc
    finally:
        if owns_api:
            api.shutdown()
            db.close()


def seed_via_prometheus(prom_url: str) -> None:
    """Push the seed series to a live Prometheus via remote write."""
    from m3_tpu.utils import protowire, snappy

    series = []
    for metric, tags, pts in seed_points():
        labels = sorted(
            [(b"__name__", metric.encode())]
            + [(k.encode(), v.encode()) for k, v in tags.items()]
        )
        series.append(protowire.PromTimeSeries(
            labels=labels, samples=[(t * 1000, v) for t, v in pts]))
    payload = snappy.compress(protowire.encode_write_request(series))
    urllib.request.urlopen(urllib.request.Request(
        f"{prom_url}/api/v1/write", data=payload, method="POST",
        headers={"Content-Type": "application/x-protobuf",
                 "Content-Encoding": "snappy"},
    ), timeout=30)


if __name__ == "__main__":
    raise SystemExit(main())
