"""Operator tools (the reference's src/cmd/tools inspectors)."""
