"""ThreadSanitizer harness for the native hot-path libraries.

Role parity with the reference's systematic race detection (SURVEY §5:
`go test -race` on every unit/prop CI run). Python-side concurrency is
covered by tests/test_race_stress.py; this tool closes the gap for the
THREADED NATIVE layer (the v2 batch codec's parallel_over fan-out and the
hostops rate kernel), where the GIL protects nothing:

    python -m m3_tpu.tools.race_check

1. builds TSan-instrumented variants of native/m3tsz.cpp and
   native/hostops.cpp (-fsanitize=thread -O1 -g),
2. re-execs itself under LD_PRELOAD=libtsan.so with M3TSZ_SO/M3HOSTOPS_SO
   pointing the ctypes loaders at the instrumented builds,
3. drives the threaded entry points concurrently from multiple Python
   threads (encode/decode batches at nthreads>1, simultaneous rate_csr
   and agg_groups calls over shared input buffers),
4. stresses the fault-injection registry's lock discipline
   (utils/faults.py): many threads hitting shared fault points while the
   plan is concurrently reconfigured — counters, per-point RNGs, and the
   fire schedule must stay consistent and deadlock-free (the registry sits
   on every durability hot path, so a lock bug there corrupts chaos runs),
5. exits 0 when TSan stays silent and the workloads hold their
   invariants, 66 (TSAN_OPTIONS exitcode) on any reported race.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")
_TSAN_DIR = os.path.join(_NATIVE, "tsan")
_CHILD_ENV = "M3_RACE_CHECK_CHILD"


def _build_tsan() -> dict:
    os.makedirs(_TSAN_DIR, exist_ok=True)
    outs = {}
    for src, so, std in (("m3tsz.cpp", "libm3tsz_tsan.so", None),
                         ("hostops.cpp", "libm3hostops_tsan.so", "c++17")):
        out = os.path.join(_TSAN_DIR, so)
        src_path = os.path.join(_NATIVE, src)
        if not os.path.exists(out) or \
                os.path.getmtime(out) < os.path.getmtime(src_path):
            cmd = ["g++", "-O1", "-g", "-fsanitize=thread", "-shared",
                   "-fPIC", "-pthread"]
            if std:
                cmd.append(f"-std={std}")
            cmd += ["-o", out, src_path]
            subprocess.run(cmd, check=True, timeout=180)
        outs[src] = out
    return outs


def _libtsan_path() -> str:
    out = subprocess.run(["g++", "-print-file-name=libtsan.so"],
                         capture_output=True, text=True, check=True)
    return out.stdout.strip()


def main() -> int:
    if os.environ.get(_CHILD_ENV) != "1":
        outs = _build_tsan()
        env = dict(os.environ)
        env.update({
            _CHILD_ENV: "1",
            "LD_PRELOAD": _libtsan_path(),
            "M3TSZ_SO": outs["m3tsz.cpp"],
            "M3HOSTOPS_SO": outs["hostops.cpp"],
            # jax/axon must not initialize under TSan (and must not dial
            # the tunnel): the workloads below never import jax
            "PALLAS_AXON_POOL_IPS": "",
            "JAX_PLATFORMS": "cpu",
            "TSAN_OPTIONS": os.environ.get(
                "TSAN_OPTIONS", "exitcode=66 halt_on_error=0"),
        })
        r = subprocess.run([sys.executable, "-m", "m3_tpu.tools.race_check"],
                           env=env, cwd=_REPO, timeout=600)
        if r.returncode == 0:
            print("race_check: no data races reported by ThreadSanitizer")
        else:
            print(f"race_check: FAILED (rc={r.returncode}) — see TSan "
                  "report above", file=sys.stderr)
        return r.returncode

    # ---- child: the instrumented workloads -------------------------------
    import numpy as np

    from m3_tpu.encoding.m3tsz import native
    from m3_tpu.ops import native_hostops
    from m3_tpu.utils.xtime import TimeUnit

    assert native.available(), "tsan m3tsz build failed to load"
    assert native_hostops.available(), "tsan hostops build failed to load"

    rng = np.random.default_rng(0)
    B, T = 64, 60
    start = 1_600_000_000 * 10**9
    times = start + np.cumsum(rng.integers(1, 50, (B, T)),
                              axis=1).astype(np.int64) * 10**9
    values = rng.normal(100, 10, (B, T))

    # 1) the codec's own thread fan-out (parallel_over chunks)
    streams = native.encode_batch(times, values, times[:, 0] - 10**9,
                                  TimeUnit.SECOND, threads=4)
    native.decode_batch(streams, TimeUnit.SECOND, max_points=T, threads=4)

    # 2) concurrent python callers sharing input buffers
    n = 20_000
    e = rng.integers(0, 500, n)
    w = rng.integers(0, 8, n)
    v = rng.normal(0, 1, n)
    t = rng.integers(0, 10**9, n)
    off = np.arange(0, n + 1, 100, dtype=np.int64)
    ts_sorted = np.sort(rng.integers(0, 10**12, n)).astype(np.int64)
    eval_ts = np.arange(10**10, 10**12, 10**10, dtype=np.int64)

    errs = []

    def worker(k):
        try:
            for _ in range(3):
                native_hostops.agg_groups(e, w, v, t)
                native_hostops.rate_csr(ts_sorted, v, off, eval_ts,
                                        5 * 10**10, True, True, threads=2)
                native.bench_roundtrip_batch(times, values,
                                             int(times[0, 0]) - 10**9,
                                             TimeUnit.SECOND, threads=2)
        except Exception as ex:  # noqa: BLE001
            errs.append((k, ex))

    workers = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for wk in workers:
        wk.start()
    for wk in workers:
        wk.join()
    if errs:
        print(f"workload errors: {errs}", file=sys.stderr)
        return 1

    # 3) fault-registry lock discipline: concurrent check() on shared
    # points while another thread reconfigures the active plan
    from m3_tpu.utils import faults

    fault_errs: list = []

    def fault_worker(k):
        try:
            with open(os.devnull, "wb") as devnull:
                for i in range(2_000):
                    try:
                        faults.check("race.shared", worker=k, i=i)
                        if i % 499 == 0:
                            faults.torn_write(devnull, b"x" * 64, "race.torn")
                    except (faults.InjectedError, faults.InjectedTimeout,
                            faults.SimulatedCrash):
                        pass  # injected on purpose; anything else is a bug
        except Exception as ex:  # noqa: BLE001
            fault_errs.append((k, ex))

    def toggler():
        try:
            for i in range(200):
                faults.configure("race.shared=error:p0.01;race.torn=torn:p0.5",
                                 seed=i)
            faults.disable()
        except Exception as ex:  # noqa: BLE001
            fault_errs.append(("toggler", ex))

    threads = [threading.Thread(target=fault_worker, args=(k,))
               for k in range(6)] + [threading.Thread(target=toggler)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    faults.disable()
    if fault_errs:
        print(f"fault-registry errors: {fault_errs}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
