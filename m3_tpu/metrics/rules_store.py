"""Versioned rule-set storage in the cluster KV + JSON doc codec.

Role parity with the reference's rules store + R2 service data model
(/root/reference/src/metrics/rules/store — versioned rule sets in KV;
src/ctl/service/r2 — CRUD over them) and the matcher's KV-watched dynamic
reload (src/metrics/matcher). The doc format is the same shape as the
config file's `rules:` section, so a ruleset can move freely between
static config and the KV-managed store.
"""

from __future__ import annotations

import json

from m3_tpu.cluster.kv import KeyNotFound, VersionMismatch
from m3_tpu.metrics.aggregation import AggregationType
from m3_tpu.metrics.filters import TagFilter
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.rules import (
    MappingRule,
    PipelineStage,
    RollupRule,
    RollupTarget,
    RuleSet,
    StandingRule,
)
from m3_tpu.metrics.transformation import TransformationType

RULES_KEY = "m3_tpu.rules"


# -- doc codec --------------------------------------------------------------


def filter_to_str(f: TagFilter) -> str:
    return " ".join(
        f"{c.name.decode()}:{'!' if c.negate else ''}{c.pattern}"
        for c in f.clauses
    )


def _mapping_to_doc(r: MappingRule) -> dict:
    doc = {
        "name": r.name,
        "filter": filter_to_str(r.filter),
        "policies": [str(p) for p in r.policies],
    }
    if r.aggregations:
        doc["aggregations"] = [a.name for a in r.aggregations]
    if r.drop:
        doc["drop"] = True
    return doc


def _mapping_from_doc(doc: dict) -> MappingRule:
    return MappingRule(
        name=doc.get("name", ""),
        filter=TagFilter.parse(doc["filter"]),
        policies=tuple(StoragePolicy.parse(p) for p in doc.get("policies", [])),
        aggregations=tuple(
            AggregationType[a.upper()] for a in doc.get("aggregations", [])
        ),
        drop=bool(doc.get("drop", False)),
    )


def _target_to_doc(t: RollupTarget) -> dict:
    doc = {
        "name": t.new_name.decode(),
        "group_by": [g.decode() for g in t.group_by],
        "aggregations": [a.name for a in t.aggregations],
        "policies": [str(p) for p in t.policies],
    }
    if t.transform is not None:
        doc["transform"] = t.transform.name
    if t.forward_aggregations:
        doc["forward_aggregations"] = [a.name for a in t.forward_aggregations]
    if t.forward_resolution_ns:
        doc["forward_resolution_ns"] = t.forward_resolution_ns
    if t.forward_stages:
        doc["forward_stages"] = [
            {"aggregations": [a.name for a in s.aggregations],
             "resolution_ns": s.resolution_ns,
             **({"buffer_past_ns": s.buffer_past_ns}
                if s.buffer_past_ns else {})}
            for s in t.forward_stages
        ]
    return doc


def _target_from_doc(doc: dict) -> RollupTarget:
    transform = doc.get("transform")
    return RollupTarget(
        new_name=doc["name"].encode(),
        group_by=tuple(g.encode() for g in doc.get("group_by", [])),
        aggregations=tuple(
            AggregationType[a.upper()] for a in doc.get("aggregations", ["SUM"])
        ),
        policies=tuple(StoragePolicy.parse(p) for p in doc.get("policies", [])),
        transform=(TransformationType[transform.upper()]
                   if transform else None),
        forward_aggregations=tuple(
            AggregationType[a.upper()]
            for a in doc.get("forward_aggregations", [])
        ),
        forward_resolution_ns=int(doc.get("forward_resolution_ns", 0)),
        forward_stages=tuple(
            PipelineStage(
                aggregations=tuple(AggregationType[a.upper()]
                                   for a in s.get("aggregations", ["SUM"])),
                resolution_ns=int(s["resolution_ns"]),
                buffer_past_ns=int(s.get("buffer_past_ns", 0)),
            )
            for s in doc.get("forward_stages", [])
        ),
    )


def _rollup_to_doc(r: RollupRule) -> dict:
    return {
        "name": r.name,
        "filter": filter_to_str(r.filter),
        "targets": [_target_to_doc(t) for t in r.targets],
    }


def _rollup_from_doc(doc: dict) -> RollupRule:
    return RollupRule(
        name=doc.get("name", ""),
        filter=TagFilter.parse(doc["filter"]),
        targets=tuple(_target_from_doc(t) for t in doc.get("targets", [])),
    )


def _standing_to_doc(r: StandingRule) -> dict:
    doc = {"name": r.name, "expr": r.expr, "policy": str(r.policy)}
    if r.labels:
        doc["labels"] = {k.decode(): v.decode() for k, v in r.labels}
    if not r.write_raw:
        doc["write_raw"] = False
    return doc


def _standing_from_doc(doc: dict) -> StandingRule:
    return StandingRule(
        name=doc.get("name", ""),
        expr=doc["expr"],
        policy=StoragePolicy.parse(doc["policy"]),
        labels=tuple(sorted((k.encode(), v.encode())
                            for k, v in (doc.get("labels") or {}).items())),
        write_raw=bool(doc.get("write_raw", True)),
    )


def ruleset_to_doc(rs: RuleSet) -> dict:
    doc = {
        "mapping": [_mapping_to_doc(r) for r in rs.mapping_rules],
        "rollup": [_rollup_to_doc(r) for r in rs.rollup_rules],
    }
    if rs.standing_rules:
        doc["standing"] = [_standing_to_doc(r) for r in rs.standing_rules]
    return doc


def ruleset_from_doc(doc: dict | None) -> RuleSet:
    rs = RuleSet()
    if not doc:
        return rs
    rs.mapping_rules = [_mapping_from_doc(d) for d in doc.get("mapping", []) or []]
    rs.rollup_rules = [_rollup_from_doc(d) for d in doc.get("rollup", []) or []]
    rs.standing_rules = [_standing_from_doc(d)
                         for d in doc.get("standing", []) or []]
    return rs


def validate_doc(doc: dict) -> None:
    """Raises ValueError on a malformed doc (parse round-trip + rule-name
    uniqueness, the reference store's validation role)."""
    unknown = set(doc) - {"mapping", "rollup", "standing"}
    if unknown:
        # a typo'd key ("mappingRules") would otherwise silently store an
        # EMPTY ruleset and wipe live aggregation
        raise ValueError(f"unknown ruleset doc keys: {sorted(unknown)}")
    rs = ruleset_from_doc(doc)  # raises on bad filters/policies/enums
    for kind, rules in (("mapping", rs.mapping_rules),
                        ("rollup", rs.rollup_rules),
                        ("standing", rs.standing_rules)):
        names = [r.name for r in rules]
        if len(names) != len(set(names)):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate {kind} rule names: {dupes}")
        if any(not n for n in names):
            raise ValueError(f"every {kind} rule needs a name")
    for r in rs.standing_rules:
        # a standing rule is a QUERY — an unparseable expr must be
        # rejected at store time, not discovered at the first flush
        from m3_tpu.query import promql

        try:
            promql.parse(r.expr)
        except Exception as e:  # noqa: BLE001 - parser error surface
            raise ValueError(
                f"standing rule {r.name!r}: bad expr: {e}") from e


# -- KV store ---------------------------------------------------------------


def load_ruleset(kv, key: str = RULES_KEY) -> tuple[RuleSet, int]:
    """(ruleset, kv_version); (empty, 0) when unset. The ruleset's
    .version is the KV version so matcher caches invalidate on reload."""
    try:
        vv = kv.get(key)
    except KeyNotFound:
        return RuleSet(), 0
    rs = ruleset_from_doc(json.loads(vv.data))
    rs.version = vv.version
    return rs, vv.version


def store_ruleset_doc(kv, doc: dict, expect_version: int | None = None,
                      key: str = RULES_KEY) -> int:
    """Validate + write; CAS when expect_version is given."""
    validate_doc(doc)
    raw = json.dumps(doc, sort_keys=True).encode()
    if expect_version is None:
        return kv.set(key, raw)
    if expect_version == 0:
        return kv.set_if_not_exists(key, raw)
    return kv.check_and_set(key, expect_version, raw)


def update_ruleset_doc(kv, mutate, key: str = RULES_KEY, max_retries: int = 16
                       ) -> tuple[dict, int]:
    """CAS read-modify-write: doc = mutate(doc) under optimistic
    concurrency. Returns (new_doc, new_version)."""
    for _ in range(max_retries):
        try:
            vv = kv.get(key)
            doc, version = json.loads(vv.data), vv.version
        except KeyNotFound:
            doc, version = {"mapping": [], "rollup": []}, 0
        new_doc = mutate(doc)
        try:
            return new_doc, store_ruleset_doc(kv, new_doc, version, key)
        except VersionMismatch:
            continue
    raise VersionMismatch(f"rules update contention on {key}")


def watch_ruleset(kv, on_ruleset, key: str = RULES_KEY):
    """on_ruleset(RuleSet) for the current value and every update
    (malformed payloads are skipped). Returns an unwatch callable."""

    def on_change(_key, vv):
        if vv is None:
            rs = RuleSet()
            rs.version = -1  # distinct from any stored version
        else:
            try:
                rs = ruleset_from_doc(json.loads(vv.data))
            except (ValueError, KeyError, TypeError):
                return
            rs.version = vv.version
        on_ruleset(rs)

    return kv.watch(key, on_change)
