"""Aggregation types.

Parity with the reference aggregation enum
(/root/reference/src/metrics/aggregation/type.go:34-55) and its compressed
bitmask sets (types_compressed.go).
"""

from __future__ import annotations

import enum


class AggregationType(enum.IntEnum):
    LAST = 1
    MIN = 2
    MAX = 3
    MEAN = 4
    MEDIAN = 5
    COUNT = 6
    SUM = 7
    SUMSQ = 8
    STDEV = 9
    P10 = 10
    P20 = 11
    P30 = 12
    P40 = 13
    P50 = 14
    P75 = 15
    P90 = 16
    P95 = 17
    P99 = 18
    P999 = 19
    P9999 = 20

    @property
    def quantile(self) -> float | None:
        return _QUANTILES.get(self)

    @property
    def suffix(self) -> bytes:
        return _SUFFIXES[self]


_QUANTILES = {
    AggregationType.MEDIAN: 0.5,
    AggregationType.P10: 0.10,
    AggregationType.P20: 0.20,
    AggregationType.P30: 0.30,
    AggregationType.P40: 0.40,
    AggregationType.P50: 0.50,
    AggregationType.P75: 0.75,
    AggregationType.P90: 0.90,
    AggregationType.P95: 0.95,
    AggregationType.P99: 0.99,
    AggregationType.P999: 0.999,
    AggregationType.P9999: 0.9999,
}

_SUFFIXES = {
    AggregationType.LAST: b".last",
    AggregationType.MIN: b".lower",
    AggregationType.MAX: b".upper",
    AggregationType.MEAN: b".mean",
    AggregationType.MEDIAN: b".median",
    AggregationType.COUNT: b".count",
    AggregationType.SUM: b".sum",
    AggregationType.SUMSQ: b".sum_sq",
    AggregationType.STDEV: b".stdev",
    AggregationType.P10: b".p10",
    AggregationType.P20: b".p20",
    AggregationType.P30: b".p30",
    AggregationType.P40: b".p40",
    AggregationType.P50: b".p50",
    AggregationType.P75: b".p75",
    AggregationType.P90: b".p90",
    AggregationType.P95: b".p95",
    AggregationType.P99: b".p99",
    AggregationType.P999: b".p999",
    AggregationType.P9999: b".p9999",
}


class MetricType(enum.IntEnum):
    COUNTER = 1
    TIMER = 2
    GAUGE = 3


DEFAULT_AGGREGATIONS = {
    MetricType.COUNTER: (AggregationType.SUM,),
    MetricType.TIMER: (
        AggregationType.SUM,
        AggregationType.SUMSQ,
        AggregationType.MEAN,
        AggregationType.MIN,
        AggregationType.MAX,
        AggregationType.COUNT,
        AggregationType.STDEV,
        AggregationType.MEDIAN,
        AggregationType.P50,
        AggregationType.P95,
        AggregationType.P99,
    ),
    MetricType.GAUGE: (AggregationType.LAST,),
}


def compress(types) -> int:
    """Aggregation set -> bitmask (compressed form)."""
    mask = 0
    for t in types:
        mask |= 1 << int(t)
    return mask


def decompress(mask: int) -> tuple[AggregationType, ...]:
    return tuple(t for t in AggregationType if mask & (1 << int(t)))
