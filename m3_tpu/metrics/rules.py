"""Mapping and rollup rules + the active ruleset matcher.

Parity with the reference rules model
(/root/reference/src/metrics/rules — mapping rules route matched metrics to
aggregation types + storage policies; rollup rules emit NEW series keyed by
a tag subset; active_ruleset.go matches incoming IDs). Versioning/tombstones
are collapsed to "the current ruleset" here; the KV-watched dynamic reload
belongs to the cluster layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from m3_tpu.metrics.aggregation import AggregationType
from m3_tpu.metrics.transformation import TransformationType
from m3_tpu.metrics.filters import TagFilter
from m3_tpu.metrics.policy import StoragePolicy


@dataclass(frozen=True)
class PipelineStage:
    """One forwarded aggregation stage (metrics/pipeline applied stage):
    window aggregates of the PREVIOUS stage re-aggregate at this
    resolution. buffer_past_ns is per-stage lateness allowance ON TOP of
    the engine-wide buffer (a coarser stage can wait longer for slow
    upstream forwards)."""

    aggregations: tuple[AggregationType, ...]
    resolution_ns: int
    buffer_past_ns: int = 0


@dataclass
class MappingRule:
    name: str
    filter: TagFilter
    policies: tuple[StoragePolicy, ...]
    aggregations: tuple[AggregationType, ...] = ()  # () = type defaults
    drop: bool = False  # drop policy: matched metrics skip unaggregated store


@dataclass
class RollupTarget:
    new_name: bytes
    group_by: tuple[bytes, ...]  # tags kept on the rolled-up series
    aggregations: tuple[AggregationType, ...]
    policies: tuple[StoragePolicy, ...]
    # optional pipeline transform applied between aggregation and emit
    # (metrics/pipeline + transformation roles: e.g. PerSecond for rates)
    transform: "TransformationType | None" = None
    # multi-stage pipeline (the numForwardedTimes role, reference
    # aggregator/forwarded_writer.go + metrics/pipeline): each stage's
    # window aggregates are FORWARDED into the next stage instead of
    # emitted; only the last stage emits. Arbitrary depth via
    # forward_stages; forward_aggregations/forward_resolution_ns remain as
    # sugar for a single forwarded stage.
    forward_aggregations: tuple[AggregationType, ...] = ()
    forward_resolution_ns: int = 0
    forward_stages: "tuple[PipelineStage, ...]" = ()

    def stages(self) -> "tuple[PipelineStage, ...]":
        """The normalized forward-stage chain."""
        if self.forward_stages:
            return self.forward_stages
        if self.forward_aggregations and self.forward_resolution_ns:
            return (PipelineStage(tuple(self.forward_aggregations),
                                  self.forward_resolution_ns),)
        return ()


@dataclass
class RollupRule:
    name: str
    filter: TagFilter
    targets: tuple[RollupTarget, ...]


@dataclass(frozen=True)
class StandingRule:
    """A standing (recording) query: a PromQL expression evaluated
    continuously on the policy's resolution grid, its output written as
    new series named `name` (the Prometheus recording-rule role, fused
    with the reference's rollup storage policies: the policy both paces
    the evaluation grid and names the aggregated output namespace).

    Unlike mapping/rollup rules — which match individual incoming
    datapoints — a standing rule is a whole QUERY: it compiles through
    query/compiler.py exactly like an ad-hoc request and re-evaluates
    incrementally when its input shards' data versions bump
    (query/standing.py)."""

    name: str            # output metric name (recording-rule convention)
    expr: str            # PromQL over the source namespace
    policy: StoragePolicy  # eval grid resolution + output retention
    labels: tuple[tuple[bytes, bytes], ...] = ()  # stamped on outputs
    # also write outputs into the unaggregated namespace so fine-step
    # dashboard reads within raw retention see them (the aggregated
    # copy serves long-range reads past raw retention via the resolver
    # fanout); False = aggregated-tier only
    write_raw: bool = True


@dataclass
class MatchResult:
    mappings: list[MappingRule] = field(default_factory=list)
    rollups: list[tuple[RollupRule, RollupTarget, bytes, list[tuple[bytes, bytes]]]] = (
        field(default_factory=list)
    )  # (rule, target, rolled-up id, rolled-up tags)

    @property
    def drop_unaggregated(self) -> bool:
        return any(m.drop for m in self.mappings)


class RuleSet:
    """The active ruleset: matches tag dicts to mapping/rollup outcomes."""

    def __init__(self, mapping_rules=(), rollup_rules=(), standing_rules=()):
        self.mapping_rules: list[MappingRule] = list(mapping_rules)
        self.rollup_rules: list[RollupRule] = list(rollup_rules)
        self.standing_rules: list[StandingRule] = list(standing_rules)
        self.version = 1

    def match(self, tags: dict[bytes, bytes]) -> MatchResult:
        from m3_tpu.utils.ident import tags_to_id

        out = MatchResult()
        for rule in self.mapping_rules:
            if rule.filter.matches(tags):
                out.mappings.append(rule)
        for rule in self.rollup_rules:
            if not rule.filter.matches(tags):
                continue
            for target in rule.targets:
                kept = [(k, tags[k]) for k in target.group_by if k in tags]
                rolled_id = tags_to_id(target.new_name, kept)
                out.rollups.append((rule, target, rolled_id, kept))
        return out


class Matcher:
    """Caching matcher front-end (the src/metrics/matcher role): rule match
    results are memoized per canonical id until the ruleset version bumps."""

    def __init__(self, ruleset: RuleSet, cache_size: int = 100_000):
        self.ruleset = ruleset
        self._cache: dict[bytes, MatchResult] = {}
        self._cache_version = ruleset.version
        self._cache_size = cache_size

    def match(self, series_id: bytes, tags: dict[bytes, bytes]) -> MatchResult:
        if self._cache_version != self.ruleset.version:
            self._cache.clear()
            self._cache_version = self.ruleset.version
        hit = self._cache.get(series_id)
        if hit is not None:
            return hit
        result = self.ruleset.match(tags)
        if len(self._cache) < self._cache_size:
            self._cache[series_id] = result
        return result
