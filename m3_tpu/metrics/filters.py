"""Tag filters: glob-style match expressions for rule targeting.

Parity with the reference filter language
(/root/reference/src/metrics/filters/filter.go): a filter like
`app:web* env:{prod,staging} region:!us-*` matches metrics whose tags
satisfy every clause. Supported per-value syntax: `*` wildcards, `{a,b}`
alternation, leading `!` negation.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

_META = re.compile(rb"[\\^$.|?*+()\[\]{}]")


def literal_prefix(src: bytes) -> bytes:
    """Longest literal prefix every match of ``src`` must start with.
    Conservative: a top-level alternation anywhere kills the prefix, and
    a quantifier after the last literal makes that literal optional, so
    it is dropped. The index uses this to binary-search the sorted term
    dictionary to a candidate range before any Python ``re`` runs."""
    if b"|" in src:
        return b""
    m = _META.search(src)
    if m is None:
        return src
    prefix = src[: m.start()]
    if m.group() in (b"*", b"?", b"{") and prefix:
        prefix = prefix[:-1]
    return prefix


def literal_suffix(src: bytes) -> bytes:
    """Longest literal suffix every match of ``src`` must end with (the
    mirror of literal_prefix; a shorter-than-true suffix is still sound
    as a narrowing filter). An escape as the last metacharacter also
    swallows the byte it escapes: ``\\d`` must not contribute ``d``. An
    extension group anywhere (``(?i)``, ``(?i:...)``, lookarounds) kills
    the suffix: inline flags can make the trailing literal match
    case-insensitively, which byte-wise endswith narrowing would miss."""
    if b"|" in src or b"(?" in src:
        return b""
    last = None
    for m in _META.finditer(src):
        last = m
    if last is None:
        return src
    if last.group() == b"\\":
        return src[last.end() + 1:]
    return src[last.end():]


def prefix_upper_bound(prefix: bytes) -> bytes:
    """Smallest byte string greater than every extension of ``prefix``
    (for the half-open vocab range [prefix, upper)); empty when no such
    bound exists (prefix is all 0xFF)."""
    upper = prefix
    while upper and upper[-1] == 0xFF:
        upper = upper[:-1]
    if upper:
        upper = upper[:-1] + bytes([upper[-1] + 1])
    return upper


def _glob_to_regex(glob: str) -> str:
    out = []
    for part in re.split(r"(\*|\{[^}]*\})", glob):
        if part == "*":
            out.append(".*")
        elif part.startswith("{") and part.endswith("}"):
            alts = "|".join(re.escape(a) for a in part[1:-1].split(","))
            out.append(f"(?:{alts})")
        else:
            out.append(re.escape(part))
    return "".join(out)


@dataclass(frozen=True)
class TagClause:
    name: bytes
    pattern: str  # original glob
    negate: bool

    def compiled(self) -> re.Pattern:
        return re.compile(_glob_to_regex(self.pattern).encode())


class TagFilter:
    """Conjunction of per-tag glob clauses; `__name__` targets the metric
    name."""

    def __init__(self, clauses: list[TagClause]):
        self.clauses = clauses
        self._compiled = [(c.name, c.compiled(), c.negate) for c in clauses]

    @classmethod
    def parse(cls, expr: str) -> "TagFilter":
        clauses = []
        for raw in expr.split():
            if ":" not in raw:
                raise ValueError(f"invalid filter clause {raw!r} (want tag:pattern)")
            name, pattern = raw.split(":", 1)
            negate = pattern.startswith("!")
            if negate:
                pattern = pattern[1:]
            clauses.append(TagClause(name.encode(), pattern, negate))
        if not clauses:
            raise ValueError("empty filter")
        return cls(clauses)

    def matches_all(self) -> bool:
        """True for the downsample-all shape (`__name__:*`): every clause
        is an unnegated `*` glob on the metric name, so every NAMED
        metric matches. An aggregated namespace fed only by such rules is
        COMPLETE — the marker cheapest-tier read resolution requires."""
        return all(c.name == b"__name__" and c.pattern == "*"
                   and not c.negate for c in self.clauses)

    def matches(self, tags: dict[bytes, bytes]) -> bool:
        for name, rx, negate in self._compiled:
            value = tags.get(name)
            ok = value is not None and rx.fullmatch(value) is not None
            if negate:
                ok = value is None or rx.fullmatch(value) is None
            if not ok:
                return False
        return True
