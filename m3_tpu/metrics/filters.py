"""Tag filters: glob-style match expressions for rule targeting.

Parity with the reference filter language
(/root/reference/src/metrics/filters/filter.go): a filter like
`app:web* env:{prod,staging} region:!us-*` matches metrics whose tags
satisfy every clause. Supported per-value syntax: `*` wildcards, `{a,b}`
alternation, leading `!` negation.
"""

from __future__ import annotations

import re
from dataclasses import dataclass


def _glob_to_regex(glob: str) -> str:
    out = []
    for part in re.split(r"(\*|\{[^}]*\})", glob):
        if part == "*":
            out.append(".*")
        elif part.startswith("{") and part.endswith("}"):
            alts = "|".join(re.escape(a) for a in part[1:-1].split(","))
            out.append(f"(?:{alts})")
        else:
            out.append(re.escape(part))
    return "".join(out)


@dataclass(frozen=True)
class TagClause:
    name: bytes
    pattern: str  # original glob
    negate: bool

    def compiled(self) -> re.Pattern:
        return re.compile(_glob_to_regex(self.pattern).encode())


class TagFilter:
    """Conjunction of per-tag glob clauses; `__name__` targets the metric
    name."""

    def __init__(self, clauses: list[TagClause]):
        self.clauses = clauses
        self._compiled = [(c.name, c.compiled(), c.negate) for c in clauses]

    @classmethod
    def parse(cls, expr: str) -> "TagFilter":
        clauses = []
        for raw in expr.split():
            if ":" not in raw:
                raise ValueError(f"invalid filter clause {raw!r} (want tag:pattern)")
            name, pattern = raw.split(":", 1)
            negate = pattern.startswith("!")
            if negate:
                pattern = pattern[1:]
            clauses.append(TagClause(name.encode(), pattern, negate))
        if not clauses:
            raise ValueError("empty filter")
        return cls(clauses)

    def matches(self, tags: dict[bytes, bytes]) -> bool:
        for name, rx, negate in self._compiled:
            value = tags.get(name)
            ok = value is not None and rx.fullmatch(value) is not None
            if negate:
                ok = value is None or rx.fullmatch(value) is None
            if not ok:
                return False
        return True
