"""Datapoint transformations used inside rollup pipelines.

Parity with /root/reference/src/metrics/transformation/type.go:39-43
(Absolute/PerSecond/Increase/Add/Reset): unary ops are stateless per value;
binary ops consume (previous, current) window aggregates per element.
"""

from __future__ import annotations

import enum

import numpy as np


class TransformationType(enum.IntEnum):
    ABSOLUTE = 1
    PERSECOND = 2
    INCREASE = 3
    ADD = 4
    RESET = 5

    @property
    def is_binary(self) -> bool:
        return self in (TransformationType.PERSECOND, TransformationType.INCREASE,
                        TransformationType.ADD)


def apply(
    t: TransformationType,
    prev_values: np.ndarray,
    cur_values: np.ndarray,
    prev_times_ns: np.ndarray,
    cur_times_ns: np.ndarray,
) -> np.ndarray:
    """Vectorized transform over aligned (prev, cur) window aggregates.
    prev entries are NaN when there is no prior window for the element."""
    if t == TransformationType.ABSOLUTE:
        return np.abs(cur_values)
    if t == TransformationType.RESET:
        return np.zeros_like(cur_values)
    if t == TransformationType.ADD:
        return np.where(np.isnan(prev_values), cur_values, prev_values + cur_values)
    if t == TransformationType.INCREASE:
        diff = cur_values - prev_values
        # counter semantics: negative deltas mean a reset -> emit current
        diff = np.where(diff < 0, cur_values, diff)
        return np.where(np.isnan(prev_values), np.nan, diff)
    if t == TransformationType.PERSECOND:
        dt = (cur_times_ns - prev_times_ns) / 1e9
        diff = cur_values - prev_values
        diff = np.where(diff < 0, cur_values, diff)
        with np.errstate(divide="ignore", invalid="ignore"):
            rate = np.where(dt > 0, diff / dt, np.nan)
        return np.where(np.isnan(prev_values), np.nan, rate)
    raise ValueError(f"unknown transformation {t}")
