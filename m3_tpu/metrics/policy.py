"""Storage policies: resolution + retention pairs.

Parity with /root/reference/src/metrics/policy/storage_policy.go
("10s:2d"-style policies that route aggregated output to retention tiers).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

_UNIT_NS = {
    "ns": 1,
    "us": 1_000,
    "ms": 1_000_000,
    "s": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
    "d": 24 * 3600 * 1_000_000_000,
    "w": 7 * 24 * 3600 * 1_000_000_000,
    "y": 365 * 24 * 3600 * 1_000_000_000,
}

_DUR_RE = re.compile(r"(\d+)(ns|us|ms|s|m|h|d|w|y)")


def parse_go_duration(s: str) -> int:
    total = 0
    pos = 0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {s!r}")
        total += int(m.group(1)) * _UNIT_NS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ValueError(f"invalid duration {s!r}")
    return total


@dataclass(frozen=True, order=True)
class StoragePolicy:
    resolution_ns: int
    retention_ns: int

    @classmethod
    def parse(cls, s: str) -> "StoragePolicy":
        """'10s:2d' -> StoragePolicy."""
        parts = s.split(":")
        if len(parts) != 2:
            raise ValueError(f"invalid storage policy {s!r}")
        return cls(parse_go_duration(parts[0]), parse_go_duration(parts[1]))

    def __str__(self) -> str:
        return f"{_fmt_dur(self.resolution_ns)}:{_fmt_dur(self.retention_ns)}"

    @property
    def namespace_name(self) -> str:
        """Conventional aggregated-namespace name for this policy."""
        return f"aggregated_{_fmt_dur(self.resolution_ns)}_{_fmt_dur(self.retention_ns)}"


def _fmt_dur(ns: int) -> str:
    for unit, size in (("y", _UNIT_NS["y"]), ("w", _UNIT_NS["w"]), ("d", _UNIT_NS["d"]),
                       ("h", _UNIT_NS["h"]), ("m", _UNIT_NS["m"]), ("s", _UNIT_NS["s"]),
                       ("ms", _UNIT_NS["ms"]), ("us", _UNIT_NS["us"])):
        if ns % size == 0 and ns >= size:
            return f"{ns // size}{unit}"
    return f"{ns}ns"


DEFAULT_POLICIES = (StoragePolicy.parse("10s:2d"),)
