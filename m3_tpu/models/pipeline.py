"""Flagship compute pipeline: fused ingest -> encode -> aggregate step.

This is the device-side "forward step" of the platform: a batch of raw
datapoints (shard x series x timestep grid) is M3TSZ-encoded for storage and
simultaneously rolled up into windowed aggregates (count/sum/min/max/last),
the same work the reference splits between the dbnode write path
(/root/reference/src/dbnode/storage/series/buffer.go:290) and the aggregator
elem consume path
(/root/reference/src/aggregator/aggregator/elem_base.go:130-161) — here both
happen in one fused XLA program over device-resident tensors.

Multi-chip: series are sharded over the mesh 'shard' axis (the analog of M3's
murmur3-mod virtual shards, SURVEY.md §2.10); cross-shard rollups reduce with
psum over ICI instead of forwarding partial aggregates over TCP.
"""

from __future__ import annotations

import functools
import jax
import jax.numpy as jnp

from m3_tpu.encoding.m3tsz import tpu as m3tsz_tpu
from m3_tpu.ops.bits import bits_to_f64
from m3_tpu.utils.xtime import TimeUnit


def window_aggregate(times, values, n_points, start, window_ns: int, n_windows: int):
    """Roll datapoints into fixed windows per series.

    Window w of series b covers [start[b] + w*window_ns, +window_ns); each
    datapoint scatter-reduces into its (series, window) cell, so the whole
    rollup is a handful of vectorized segment reductions — the device-grid
    equivalent of the reference's per-elem lockstep accumulators
    (/root/reference/src/aggregator/aggregation/counter.go:31-139).

    Returns dict of [B, n_windows] arrays: count/sum/min/max/last. Empty
    windows have count 0 and NaN min/max/last. Datapoints past the window
    grid are dropped (count them upstream via the block rotation policy).
    """
    B, T = times.shape
    idx = jnp.arange(T)
    valid = idx[None, :] < n_points[:, None]
    w = ((times - start[:, None].astype(times.dtype)) // window_ns).astype(jnp.int32)
    w = jnp.where(valid & (w >= 0) & (w < n_windows), w, n_windows)  # drop slot
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))

    shape = (B, n_windows + 1)
    ones = jnp.where(valid, 1, 0).astype(jnp.int32)
    v = values
    count = jnp.zeros(shape, jnp.int32).at[b_idx, w].add(ones)
    total = jnp.zeros(shape, v.dtype).at[b_idx, w].add(jnp.where(valid, v, 0.0))
    vmin = jnp.full(shape, jnp.inf, v.dtype).at[b_idx, w].min(jnp.where(valid, v, jnp.inf))
    vmax = jnp.full(shape, -jnp.inf, v.dtype).at[b_idx, w].max(jnp.where(valid, v, -jnp.inf))
    # last = value at the latest timestamp per window; timestamps ascend per
    # series, so the max in-window column index identifies it.
    idx32 = idx.astype(jnp.int32)
    last_col = jnp.full(shape, -1, jnp.int32).at[b_idx, w].max(jnp.where(valid, idx32[None, :], -1))
    last = jnp.take_along_axis(v, jnp.maximum(last_col[:, :n_windows], 0), axis=1)

    count = count[:, :n_windows]
    empty = count == 0
    nan = jnp.nan
    return {
        "count": count,
        "sum": total[:, :n_windows],
        "min": jnp.where(empty, nan, vmin[:, :n_windows]),
        "max": jnp.where(empty, nan, vmax[:, :n_windows]),
        "last": jnp.where(empty, nan, last),
    }


@functools.partial(
    jax.jit, static_argnames=("unit", "capacity_words", "window_ns", "n_windows")
)
def ingest_step(
    times: jnp.ndarray,  # [B, T] int64
    value_bits: jnp.ndarray,  # [B, T] uint64 IEEE-754 bits
    start: jnp.ndarray,  # [B] int64
    n_points: jnp.ndarray,  # [B] int32
    unit: TimeUnit = TimeUnit.SECOND,
    capacity_words: int | None = None,
    window_ns: int = 60_000_000_000,
    n_windows: int = 16,
):
    """One fused ingest step: encode blocks + windowed rollup."""
    blocks = m3tsz_tpu.encode_bits(times, value_bits, start, n_points, unit, capacity_words)
    values = bits_to_f64(value_bits)
    agg = window_aggregate(times, values, n_points, start, window_ns, n_windows)
    return blocks, agg
