"""Proto message stream codec: per-field compression behind M3TSZ
timestamps.

Wire layout per stream:
  64-bit start (the m3tsz prefix) then per datapoint:
    m3tsz timestamp field (delta-of-delta),
    changed-fields bitmask (one bit per schema field, schema order),
    each CHANGED field's payload by type (see below).
  End-of-stream: the m3tsz marker (timestamp opcode 0x100 + EOS).

Field payloads (reference scheme roles, encoder.go/custom_marshal.go):
  DOUBLE  m3tsz XOR float vs the field's previous value
  INT64   zigzag varint of (value - previous)
  BOOL    1 bit
  BYTES   1 bit dict-hit + (index in ceil(log2(cap)) bits | varint len+raw)
"""

from __future__ import annotations

from dataclasses import dataclass

from m3_tpu.encoding.m3tsz import constants as c
from m3_tpu.encoding.m3tsz.decoder import _TimestampIterator, read_varint
from m3_tpu.encoding.m3tsz.encoder import (
    FloatXOREncoder,
    TimestampEncoder,
    finalize_stream,
    write_varint,
)
from m3_tpu.encoding.proto.schema import FieldType, Schema
from m3_tpu.utils.bitstream import IStream, OStream
from m3_tpu.utils.xtime import TimeUnit

_DICT_CAP = 16  # LRU entries per bytes field (reference byte-field dicts)
_DICT_BITS = 4


@dataclass
class ProtoDatapoint:
    timestamp_ns: int
    message: dict  # field name -> value


class _BytesDict:
    def __init__(self) -> None:
        self.entries: list[bytes] = []

    def find(self, v: bytes) -> int:
        try:
            return self.entries.index(v)
        except ValueError:
            return -1

    def push(self, v: bytes) -> None:
        if v in self.entries:
            self.entries.remove(v)
        self.entries.append(v)
        if len(self.entries) > _DICT_CAP:
            self.entries.pop(0)


class ProtoEncoder:
    """Single-series proto stream encoder."""

    def __init__(self, start_ns: int, schema: Schema,
                 default_time_unit: TimeUnit = TimeUnit.SECOND) -> None:
        self._os = OStream()
        self._ts = TimestampEncoder(start_ns, default_time_unit)
        self.schema = schema
        self._prev: dict[int, object] = {}
        self._floats: dict[int, FloatXOREncoder] = {
            f.number: FloatXOREncoder() for f in schema.fields
            if f.type == FieldType.DOUBLE
        }
        self._dicts: dict[int, _BytesDict] = {
            f.number: _BytesDict() for f in schema.fields
            if f.type == FieldType.BYTES
        }
        self.num_encoded = 0

    def encode(self, t_ns: int, message: dict,
               unit: TimeUnit = TimeUnit.SECOND) -> None:
        self._ts.write_time(self._os, t_ns, b"", unit)
        first = self.num_encoded == 0
        changed = []
        for f in self.schema.fields:
            v = _normalize(f, message.get(f.name))
            prev = self._prev.get(f.number)
            if first:
                diff = True
            elif f.type == FieldType.DOUBLE:
                # bit-pattern compare: 0.0 == -0.0 and NaN != NaN under
                # float equality, both wrong for change detection
                diff = c.float_to_bits(v) != c.float_to_bits(prev)
            else:
                diff = v != prev
            changed.append(diff)
        for flag in changed:
            self._os.write_bit(1 if flag else 0)
        for f, flag in zip(self.schema.fields, changed):
            if not flag:
                continue
            v = _normalize(f, message.get(f.name))
            self._write_field(f, v, first)
            self._prev[f.number] = v
        self.num_encoded += 1

    def _write_field(self, f, v, first: bool) -> None:
        os = self._os
        if f.type == FieldType.DOUBLE:
            enc = self._floats[f.number]
            if first:
                enc.write_full_float(os, c.float_to_bits(v))
            else:
                enc.write_next_float(os, c.float_to_bits(v))
        elif f.type == FieldType.INT64:
            prev = self._prev.get(f.number, 0)
            write_varint(os, v - (prev if not first else 0))
        elif f.type == FieldType.BOOL:
            os.write_bit(1 if v else 0)
        elif f.type == FieldType.BYTES:
            d = self._dicts[f.number]
            idx = d.find(v)
            if idx >= 0:
                os.write_bit(1)
                os.write_bits(idx, _DICT_BITS)
            else:
                os.write_bit(0)
                write_varint(os, len(v))
                for b in v:
                    os.write_bits(b, 8)
            d.push(v)

    def stream(self) -> bytes:
        return finalize_stream(self._os)


class ProtoDecoder:
    """Iterates ProtoDatapoints from a proto stream."""

    def __init__(self, data: bytes, schema: Schema,
                 default_time_unit: TimeUnit = TimeUnit.SECOND) -> None:
        self._stream = IStream(data)
        self._ts = _TimestampIterator(default_time_unit)
        self.schema = schema
        self._prev: dict[int, object] = {}
        self._prev_bits: dict[int, int] = {}
        self._prev_xor: dict[int, int] = {}
        self._dicts: dict[int, _BytesDict] = {}

    def __iter__(self):
        while True:
            try:
                self._ts.read_timestamp(self._stream)
            except EOFError:
                return
            if self._ts.done:  # EOS marker
                return
            msg = {}
            changed = [self._stream.read_bits(1) == 1
                       for _ in self.schema.fields]
            for f, flag in zip(self.schema.fields, changed):
                if flag:
                    v = self._read_field(f)
                    self._prev[f.number] = v
                msg[f.name] = self._prev.get(f.number, _zero(f))
            yield ProtoDatapoint(self._ts.prev_time, msg)

    def _read_field(self, f):
        s = self._stream
        if f.type == FieldType.DOUBLE:
            if f.number not in self._prev_bits:
                bits = s.read_bits(64)
                self._prev_bits[f.number] = bits
                self._prev_xor[f.number] = bits
                return c.bits_to_float(bits)
            bits = self._read_next_float(f.number)
            return c.bits_to_float(bits)
        if f.type == FieldType.INT64:
            delta = read_varint(s)
            base = self._prev.get(f.number, 0)
            return base + delta
        if f.type == FieldType.BOOL:
            return s.read_bits(1) == 1
        if f.type == FieldType.BYTES:
            d = self._dict(f.number)
            if s.read_bits(1) == 1:
                v = d.entries[s.read_bits(_DICT_BITS)]
            else:
                n = read_varint(s)
                v = bytes(s.read_bits(8) for _ in range(n))
            d.push(v)
            return v
        raise ValueError(f.type)

    def _dict(self, number: int) -> _BytesDict:
        d = self._dicts.get(number)
        if d is None:
            d = self._dicts[number] = _BytesDict()
        return d

    def _read_next_float(self, number: int) -> int:
        """m3tsz XOR read against this field's own state."""
        s = self._stream
        prev_bits = self._prev_bits[number]
        prev_xor = self._prev_xor[number]
        if s.read_bits(1) == c.OPCODE_ZERO_VALUE_XOR:
            xor = 0
        elif s.read_bits(1) == 0:  # contained '10'
            from m3_tpu.utils.bitstream import leading_zeros64, trailing_zeros64

            pl, pt = leading_zeros64(prev_xor), trailing_zeros64(prev_xor)
            m = 64 - pl - pt
            xor = s.read_bits(m) << pt
        else:  # uncontained '11'
            lead = s.read_bits(6)
            m = s.read_bits(6) + 1
            mant = s.read_bits(m)
            xor = mant << (64 - lead - m)
        bits = prev_bits ^ xor
        self._prev_bits[number] = bits
        # the encoder records the xor unconditionally (including 0)
        self._prev_xor[number] = xor
        return bits


def _normalize(f, v):
    if v is None:
        return _zero(f)
    if f.type == FieldType.DOUBLE:
        return float(v)
    if f.type == FieldType.INT64:
        return int(v)
    if f.type == FieldType.BOOL:
        return bool(v)
    if f.type == FieldType.BYTES:
        return bytes(v)
    raise ValueError(f.type)


def _zero(f):
    return {
        FieldType.DOUBLE: 0.0,
        FieldType.INT64: 0,
        FieldType.BOOL: False,
        FieldType.BYTES: b"",
    }[f.type]


def encode_messages(start_ns: int, schema: Schema,
                    points: list[tuple[int, dict]],
                    unit: TimeUnit = TimeUnit.SECOND) -> bytes:
    enc = ProtoEncoder(start_ns, schema, unit)
    for t, msg in points:
        enc.encode(t, msg, unit)
    return enc.stream()


def decode(data: bytes, schema: Schema,
           unit: TimeUnit = TimeUnit.SECOND) -> list[ProtoDatapoint]:
    if not data:
        return []
    return list(ProtoDecoder(data, schema, unit))
