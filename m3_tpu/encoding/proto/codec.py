"""Proto message stream codec: per-field compression behind M3TSZ
timestamps.

Wire layout per stream:
  64-bit start (the m3tsz prefix) then per datapoint:
    m3tsz timestamp field (delta-of-delta),
    changed-fields bitmask (one bit per schema field, schema order),
    each CHANGED field's payload by type (see below).
  End-of-stream: the m3tsz marker (timestamp opcode 0x100 + EOS).

Field payloads (reference scheme roles, encoder.go/custom_marshal.go):
  DOUBLE   m3tsz XOR float vs the field's previous value
  INT64    zigzag varint of (value - previous)
  BOOL     1 bit
  BYTES    1 bit dict-hit + (index in ceil(log2(cap)) bits | varint len+raw)
  MESSAGE  recursive: a nested changed-bitmask over the sub-schema, then
           each changed sub-field by these same rules with per-PATH state
           (deeper than the reference, which marshals nested messages as
           opaque non-custom bytes — recursing keeps XOR/delta compression
           working inside nested messages)
  repeated varint count then each element encoded FULL (no cross-element
           state): doubles as raw 64 bits, ints as zigzag varints, bools
           as bits, bytes through the field's LRU dict, nested messages
           as canonical custom-marshal bytes through the dict (the
           reference's non-custom marshal + byte-dict scheme —
           custom_marshal.py provides the deterministic bytes)
"""

from __future__ import annotations

from dataclasses import dataclass

from m3_tpu.encoding.m3tsz import constants as c
from m3_tpu.encoding.m3tsz.decoder import _TimestampIterator, read_varint
from m3_tpu.encoding.m3tsz.encoder import (
    FloatXOREncoder,
    TimestampEncoder,
    finalize_stream,
    write_varint,
)
from m3_tpu.encoding.proto import custom_marshal
from m3_tpu.encoding.proto.schema import FieldType, Schema
from m3_tpu.utils.bitstream import IStream, OStream
from m3_tpu.utils.xtime import TimeUnit

_DICT_CAP = 16  # LRU entries per bytes field (reference byte-field dicts)
_DICT_BITS = 4


@dataclass
class ProtoDatapoint:
    timestamp_ns: int
    message: dict  # field name -> value


class _BytesDict:
    def __init__(self) -> None:
        self.entries: list[bytes] = []

    def find(self, v: bytes) -> int:
        try:
            return self.entries.index(v)
        except ValueError:
            return -1

    def push(self, v: bytes) -> None:
        if v in self.entries:
            self.entries.remove(v)
        self.entries.append(v)
        if len(self.entries) > _DICT_CAP:
            self.entries.pop(0)


class ProtoEncoder:
    """Single-series proto stream encoder."""

    def __init__(self, start_ns: int, schema: Schema,
                 default_time_unit: TimeUnit = TimeUnit.SECOND) -> None:
        self._os = OStream()
        self._ts = TimestampEncoder(start_ns, default_time_unit)
        self.schema = schema
        # all compression state is keyed by FIELD PATH (tuples of field
        # numbers) so nested messages compress recursively
        self._prev: dict[tuple, object] = {}
        self._floats: dict[tuple, FloatXOREncoder] = {}
        self._dicts: dict[tuple, _BytesDict] = {}
        self.num_encoded = 0

    def encode(self, t_ns: int, message: dict,
               unit: TimeUnit = TimeUnit.SECOND) -> None:
        self._ts.write_time(self._os, t_ns, b"", unit)
        self._write_message(self.schema, message or {}, ())
        self.num_encoded += 1

    # -- recursive message writing --

    def _write_message(self, schema: Schema, message: dict, path: tuple) -> None:
        changed = []
        values = []
        for f in schema.fields:
            v = _normalize(f, message.get(f.name))
            prev = self._prev.get(path + (f.number,))
            if path + (f.number,) not in self._prev:
                diff = True
            else:
                diff = not _equal(f, v, prev)
            changed.append(diff)
            values.append(v)
        for flag in changed:
            self._os.write_bit(1 if flag else 0)
        for f, flag, v in zip(schema.fields, changed, values):
            if not flag:
                continue
            self._write_field(f, v, path + (f.number,))
            self._prev[path + (f.number,)] = v

    def _write_field(self, f, v, path: tuple) -> None:
        os = self._os
        if f.repeated:
            write_varint(os, len(v))
            for e in v:
                self._write_element(f, e, path)
            return
        if f.type == FieldType.MESSAGE:
            self._write_message(f.message, v, path)
        elif f.type == FieldType.DOUBLE:
            enc = self._floats.get(path)
            if enc is None:
                enc = self._floats[path] = FloatXOREncoder()
                enc.write_full_float(os, c.float_to_bits(v))
            else:
                enc.write_next_float(os, c.float_to_bits(v))
        elif f.type == FieldType.INT64:
            prev = self._prev.get(path, 0)
            write_varint(os, v - (prev if isinstance(prev, int) else 0))
        elif f.type == FieldType.BOOL:
            os.write_bit(1 if v else 0)
        elif f.type == FieldType.BYTES:
            self._write_dict_bytes(path, v)
        else:
            raise ValueError(f.type)

    def _write_element(self, f, e, path: tuple) -> None:
        """One repeated element, encoded with no cross-element state."""
        os = self._os
        if f.type == FieldType.DOUBLE:
            os.write_bits(c.float_to_bits(e), 64)
        elif f.type == FieldType.INT64:
            write_varint(os, e)
        elif f.type == FieldType.BOOL:
            os.write_bit(1 if e else 0)
        elif f.type == FieldType.BYTES:
            self._write_dict_bytes(path, e)
        elif f.type == FieldType.MESSAGE:
            self._write_dict_bytes(path, custom_marshal.marshal(f.message, e))
        else:
            raise ValueError(f.type)

    def _write_dict_bytes(self, path: tuple, v: bytes) -> None:
        os = self._os
        d = self._dicts.setdefault(path, _BytesDict())
        idx = d.find(v)
        if idx >= 0:
            os.write_bit(1)
            os.write_bits(idx, _DICT_BITS)
        else:
            os.write_bit(0)
            write_varint(os, len(v))
            for b in v:
                os.write_bits(b, 8)
        d.push(v)

    def stream(self) -> bytes:
        return finalize_stream(self._os)


class ProtoDecoder:
    """Iterates ProtoDatapoints from a proto stream."""

    def __init__(self, data: bytes, schema: Schema,
                 default_time_unit: TimeUnit = TimeUnit.SECOND) -> None:
        self._stream = IStream(data)
        self._ts = _TimestampIterator(default_time_unit)
        self.schema = schema
        self._prev: dict[tuple, object] = {}
        self._prev_bits: dict[tuple, int] = {}
        self._prev_xor: dict[tuple, int] = {}
        self._dicts: dict[tuple, _BytesDict] = {}

    def __iter__(self):
        while True:
            try:
                self._ts.read_timestamp(self._stream)
            except EOFError:
                return
            if self._ts.done:  # EOS marker
                return
            msg = self._read_message(self.schema, ())
            yield ProtoDatapoint(self._ts.prev_time, msg)

    def _read_message(self, schema: Schema, path: tuple) -> dict:
        changed = [self._stream.read_bits(1) == 1 for _ in schema.fields]
        msg = {}
        for f, flag in zip(schema.fields, changed):
            fpath = path + (f.number,)
            if flag:
                v = self._read_field(f, fpath)
                self._prev[fpath] = v
            msg[f.name] = self._prev.get(fpath, _zero(f))
        return msg

    def _read_field(self, f, path: tuple):
        s = self._stream
        if f.repeated:
            n = read_varint(s)
            return [self._read_element(f, path) for _ in range(n)]
        if f.type == FieldType.MESSAGE:
            return self._read_message(f.message, path)
        if f.type == FieldType.DOUBLE:
            if path not in self._prev_bits:
                bits = s.read_bits(64)
                self._prev_bits[path] = bits
                self._prev_xor[path] = bits
                return c.bits_to_float(bits)
            bits = self._read_next_float(path)
            return c.bits_to_float(bits)
        if f.type == FieldType.INT64:
            delta = read_varint(s)
            base = self._prev.get(path, 0)
            return (base if isinstance(base, int) else 0) + delta
        if f.type == FieldType.BOOL:
            return s.read_bits(1) == 1
        if f.type == FieldType.BYTES:
            return self._read_dict_bytes(path)
        raise ValueError(f.type)

    def _read_element(self, f, path: tuple):
        s = self._stream
        if f.type == FieldType.DOUBLE:
            return c.bits_to_float(s.read_bits(64))
        if f.type == FieldType.INT64:
            return read_varint(s)
        if f.type == FieldType.BOOL:
            return s.read_bits(1) == 1
        if f.type == FieldType.BYTES:
            return self._read_dict_bytes(path)
        if f.type == FieldType.MESSAGE:
            return custom_marshal.unmarshal(f.message,
                                            self._read_dict_bytes(path))
        raise ValueError(f.type)

    def _read_dict_bytes(self, path: tuple) -> bytes:
        s = self._stream
        d = self._dicts.setdefault(path, _BytesDict())
        if s.read_bits(1) == 1:
            v = d.entries[s.read_bits(_DICT_BITS)]
        else:
            n = read_varint(s)
            v = bytes(s.read_bits(8) for _ in range(n))
        d.push(v)
        return v

    def _read_next_float(self, path: tuple) -> int:
        """m3tsz XOR read against this field's own state."""
        s = self._stream
        prev_bits = self._prev_bits[path]
        prev_xor = self._prev_xor[path]
        if s.read_bits(1) == c.OPCODE_ZERO_VALUE_XOR:
            xor = 0
        elif s.read_bits(1) == 0:  # contained '10'
            from m3_tpu.utils.bitstream import leading_zeros64, trailing_zeros64

            pl, pt = leading_zeros64(prev_xor), trailing_zeros64(prev_xor)
            m = 64 - pl - pt
            xor = s.read_bits(m) << pt
        else:  # uncontained '11'
            lead = s.read_bits(6)
            m = s.read_bits(6) + 1
            mant = s.read_bits(m)
            xor = mant << (64 - lead - m)
        bits = prev_bits ^ xor
        self._prev_bits[path] = bits
        # the encoder records the xor unconditionally (including 0)
        self._prev_xor[path] = xor
        return bits


def _equal(f, a, b) -> bool:
    """Structural equality with doubles compared by BIT PATTERN
    (0.0 == -0.0 and NaN != NaN under float equality, both wrong for
    change detection), recursively through repeated/nested values."""
    if f.repeated:
        return (len(a) == len(b)
                and all(_equal_scalar(f, x, y) for x, y in zip(a, b)))
    return _equal_scalar(f, a, b)


def _equal_scalar(f, a, b) -> bool:
    if f.type == FieldType.DOUBLE:
        return c.float_to_bits(a) == c.float_to_bits(b)
    if f.type == FieldType.MESSAGE:
        return all(_equal(sub, a[sub.name], b[sub.name])
                   for sub in f.message.fields)
    return a == b


def _normalize(f, v):
    if f.repeated:
        return [_normalize_scalar(f, e) for e in (v or ())]
    return _normalize_scalar(f, v)


def _normalize_scalar(f, v):
    if v is None:
        return _zero_scalar(f)
    if f.type == FieldType.DOUBLE:
        return float(v)
    if f.type == FieldType.INT64:
        return int(v)
    if f.type == FieldType.BOOL:
        return bool(v)
    if f.type == FieldType.BYTES:
        return bytes(v)
    if f.type == FieldType.MESSAGE:
        return {sub.name: _normalize(sub, (v or {}).get(sub.name))
                for sub in f.message.fields}
    raise ValueError(f.type)


def _zero(f):
    if f.repeated:
        return []
    return _zero_scalar(f)


def _zero_scalar(f):
    if f.type == FieldType.MESSAGE:
        return {sub.name: _zero(sub) for sub in f.message.fields}
    return {
        FieldType.DOUBLE: 0.0,
        FieldType.INT64: 0,
        FieldType.BOOL: False,
        FieldType.BYTES: b"",
    }[f.type]


def encode_messages(start_ns: int, schema: Schema,
                    points: list[tuple[int, dict]],
                    unit: TimeUnit = TimeUnit.SECOND) -> bytes:
    enc = ProtoEncoder(start_ns, schema, unit)
    for t, msg in points:
        enc.encode(t, msg, unit)
    return enc.stream()


def decode(data: bytes, schema: Schema,
           unit: TimeUnit = TimeUnit.SECOND) -> list[ProtoDatapoint]:
    if not data:
        return []
    return list(ProtoDecoder(data, schema, unit))
