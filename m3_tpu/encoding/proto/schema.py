"""Message schemas + per-namespace registry (dbnode/namespace schema
registry role, reference namespace/types.go:254 SchemaRegistry)."""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass


class FieldType(enum.Enum):
    DOUBLE = "double"
    INT64 = "int64"
    BOOL = "bool"
    BYTES = "bytes"


@dataclass(frozen=True)
class Field:
    number: int  # stable field id (proto field-number role)
    name: str
    type: FieldType


@dataclass(frozen=True)
class Schema:
    name: str
    fields: tuple[Field, ...]

    def __post_init__(self):
        nums = [f.number for f in self.fields]
        if len(set(nums)) != len(nums):
            raise ValueError("duplicate field numbers")

    def to_json(self) -> bytes:
        return json.dumps({
            "name": self.name,
            "fields": [
                {"number": f.number, "name": f.name, "type": f.type.value}
                for f in self.fields
            ],
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Schema":
        doc = json.loads(raw)
        return cls(
            name=doc["name"],
            fields=tuple(
                Field(f["number"], f["name"], FieldType(f["type"]))
                for f in doc["fields"]
            ),
        )


class SchemaRegistry:
    """namespace -> deployed Schema, optionally persisted in KV under
    schemas/<namespace> (the dynamic schema registry role)."""

    _KV_PREFIX = "schemas/"

    def __init__(self, kv=None):
        self.kv = kv
        self._local: dict[str, Schema] = {}

    def set(self, namespace: str, schema: Schema) -> None:
        self._local[namespace] = schema
        if self.kv is not None:
            self.kv.set(self._KV_PREFIX + namespace, schema.to_json())

    def get(self, namespace: str) -> Schema | None:
        if self.kv is not None:
            from m3_tpu.cluster.kv import KeyNotFound

            try:
                return Schema.from_json(self.kv.get(self._KV_PREFIX + namespace).data)
            except KeyNotFound:
                pass
        return self._local.get(namespace)
