"""Message schemas + per-namespace registry (dbnode/namespace schema
registry role, reference namespace/types.go:254 SchemaRegistry).

Schemas describe the proto message shape the codec compresses:
scalar fields (double/int64/bool/bytes), NESTED message fields (a
sub-schema, compressed recursively with per-path state), and REPEATED
fields of any type — the same surface the reference's schema-aware proto
encoder handles (/root/reference/src/dbnode/encoding/proto/encoder.go
custom fields vs non-custom marshaled fields)."""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass


class FieldType(enum.Enum):
    DOUBLE = "double"
    INT64 = "int64"
    BOOL = "bool"
    BYTES = "bytes"
    MESSAGE = "message"


@dataclass(frozen=True)
class Field:
    number: int  # stable field id (proto field-number role)
    name: str
    type: FieldType
    repeated: bool = False
    # sub-schema for MESSAGE fields (required when type == MESSAGE)
    message: "Schema | None" = None

    def __post_init__(self):
        if (self.type == FieldType.MESSAGE) != (self.message is not None):
            raise ValueError(
                f"field {self.name}: message schema iff type MESSAGE")


@dataclass(frozen=True)
class Schema:
    name: str
    fields: tuple[Field, ...]

    def __post_init__(self):
        nums = [f.number for f in self.fields]
        if len(set(nums)) != len(nums):
            raise ValueError("duplicate field numbers")

    def _field_doc(self, f: Field) -> dict:
        doc = {"number": f.number, "name": f.name, "type": f.type.value}
        if f.repeated:
            doc["repeated"] = True
        if f.message is not None:
            doc["message"] = json.loads(f.message.to_json())
        return doc

    def to_json(self) -> bytes:
        return json.dumps({
            "name": self.name,
            "fields": [self._field_doc(f) for f in self.fields],
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Schema":
        doc = json.loads(raw)

        def parse(d: dict) -> "Schema":
            return cls(
                name=d["name"],
                fields=tuple(
                    Field(f["number"], f["name"], FieldType(f["type"]),
                          repeated=f.get("repeated", False),
                          message=parse(f["message"]) if "message" in f else None)
                    for f in d["fields"]
                ),
            )

        return parse(doc)


class SchemaRegistry:
    """namespace -> deployed Schema, optionally persisted in KV under
    schemas/<namespace> (the dynamic schema registry role)."""

    _KV_PREFIX = "schemas/"

    def __init__(self, kv=None):
        self.kv = kv
        self._local: dict[str, Schema] = {}

    def set(self, namespace: str, schema: Schema) -> None:
        self._local[namespace] = schema
        if self.kv is not None:
            self.kv.set(self._KV_PREFIX + namespace, schema.to_json())

    def get(self, namespace: str) -> Schema | None:
        if self.kv is not None:
            from m3_tpu.cluster.kv import KeyNotFound

            try:
                return Schema.from_json(self.kv.get(self._KV_PREFIX + namespace).data)
            except KeyNotFound:
                pass
        return self._local.get(namespace)
