"""Canonical protobuf wire marshal/unmarshal for schema'd messages.

Role parity with the reference's custom marshaller
(/root/reference/src/dbnode/encoding/proto/custom_marshal.go): a
DETERMINISTIC proto3 wire encoding — fields in ascending field-number
order, zero values omitted, packed repeated scalars — so equal messages
always marshal to equal bytes (the property change-detection and byte-dict
compression rely on; stock proto marshallers don't guarantee ordering).

The output is valid protobuf wire format for the schema, so externally
produced proto bytes for the same schema unmarshal here and vice versa.
"""

from __future__ import annotations

import struct

from m3_tpu.encoding.proto.schema import Field, FieldType, Schema

_WT_VARINT = 0
_WT_FIXED64 = 1
_WT_LEN = 2


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    val = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _tag(number: int, wt: int) -> bytes:
    return _uvarint((number << 3) | wt)


def _int64_wire(v: int) -> bytes:
    # proto3 int64: two's-complement varint (negatives cost 10 bytes)
    return _uvarint(v & 0xFFFFFFFFFFFFFFFF)


def _scalar_bytes(f: Field, v) -> bytes:
    if f.type == FieldType.DOUBLE:
        return struct.pack("<d", float(v))
    if f.type == FieldType.INT64:
        return _int64_wire(int(v))
    if f.type == FieldType.BOOL:
        return b"\x01" if v else b"\x00"
    raise ValueError(f.type)


def _is_zero(f: Field, v) -> bool:
    if f.repeated:
        return not v
    if f.type == FieldType.DOUBLE:
        # byte compare: -0.0 and NaN are NOT the zero value even though
        # `not v` / v == 0.0 would say otherwise
        return struct.pack("<d", float(v)) == struct.pack("<d", 0.0)
    if f.type == FieldType.INT64:
        return not v
    if f.type == FieldType.BOOL:
        return not v
    if f.type == FieldType.BYTES:
        return not v
    if f.type == FieldType.MESSAGE:
        return not v or not marshal(f.message, v)
    raise ValueError(f.type)


def marshal(schema: Schema, message: dict) -> bytes:
    """Canonical wire bytes; ascending field number, zeros omitted."""
    out = bytearray()
    for f in sorted(schema.fields, key=lambda x: x.number):
        v = message.get(f.name)
        if v is None or _is_zero(f, v):
            continue
        if f.repeated:
            if f.type in (FieldType.DOUBLE, FieldType.INT64, FieldType.BOOL):
                # packed scalars (proto3 default)
                payload = b"".join(_scalar_bytes(f, e) for e in v)
                out += _tag(f.number, _WT_LEN) + _uvarint(len(payload)) + payload
            else:
                for e in v:
                    payload = (marshal(f.message, e)
                               if f.type == FieldType.MESSAGE else bytes(e))
                    out += _tag(f.number, _WT_LEN) + _uvarint(len(payload)) + payload
        elif f.type == FieldType.DOUBLE:
            out += _tag(f.number, _WT_FIXED64) + struct.pack("<d", float(v))
        elif f.type == FieldType.INT64:
            out += _tag(f.number, _WT_VARINT) + _int64_wire(int(v))
        elif f.type == FieldType.BOOL:
            out += _tag(f.number, _WT_VARINT) + b"\x01"
        elif f.type == FieldType.BYTES:
            out += _tag(f.number, _WT_LEN) + _uvarint(len(v)) + bytes(v)
        elif f.type == FieldType.MESSAGE:
            payload = marshal(f.message, v)
            out += _tag(f.number, _WT_LEN) + _uvarint(len(payload)) + payload
    return bytes(out)


def _decode_scalar(f: Field, data: bytes):
    if f.type == FieldType.DOUBLE:
        vals = [struct.unpack("<d", data[i:i + 8])[0]
                for i in range(0, len(data), 8)]
        return vals
    if f.type in (FieldType.INT64, FieldType.BOOL):
        out = []
        pos = 0
        while pos < len(data):
            u, pos = _read_uvarint(data, pos)
            if f.type == FieldType.BOOL:
                out.append(bool(u))
            else:
                out.append(u - (1 << 64) if u >= (1 << 63) else u)
        return out
    raise ValueError(f.type)


def unmarshal(schema: Schema, data: bytes) -> dict:
    """Wire bytes -> message dict (zero values materialized); accepts any
    field order and both packed/unpacked repeated scalars."""
    by_num = {f.number: f for f in schema.fields}
    msg: dict = {}
    pos = 0
    while pos < len(data):
        key, pos = _read_uvarint(data, pos)
        number, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            raw, pos = _read_uvarint(data, pos)
            payload = None
        elif wt == _WT_FIXED64:
            if pos + 8 > len(data):
                raise ValueError("truncated fixed64")
            payload = data[pos:pos + 8]
            pos += 8
            raw = None
        elif wt == _WT_LEN:
            ln, pos = _read_uvarint(data, pos)
            if pos + ln > len(data):
                raise ValueError("truncated length-delimited field")
            payload = data[pos:pos + ln]
            pos += ln
            raw = None
        else:
            raise ValueError(f"unsupported wire type {wt}")
        f = by_num.get(number)
        if f is None:
            continue  # unknown field: skip (proto semantics)
        if f.repeated:
            lst = msg.setdefault(f.name, [])
            if f.type == FieldType.MESSAGE:
                lst.append(unmarshal(f.message, payload))
            elif f.type == FieldType.BYTES:
                lst.append(payload)
            elif wt == _WT_LEN:
                lst.extend(_decode_scalar(f, payload))
            elif f.type == FieldType.DOUBLE:
                lst.append(struct.unpack("<d", payload)[0])
            elif f.type == FieldType.BOOL:
                lst.append(bool(raw))
            else:
                lst.append(raw - (1 << 64) if raw >= (1 << 63) else raw)
        elif f.type == FieldType.DOUBLE:
            msg[f.name] = struct.unpack("<d", payload)[0]
        elif f.type == FieldType.INT64:
            msg[f.name] = raw - (1 << 64) if raw >= (1 << 63) else raw
        elif f.type == FieldType.BOOL:
            msg[f.name] = bool(raw)
        elif f.type == FieldType.BYTES:
            msg[f.name] = payload
        elif f.type == FieldType.MESSAGE:
            msg[f.name] = unmarshal(f.message, payload)
    # materialize zero values for absent fields
    for f in schema.fields:
        if f.name not in msg:
            if f.repeated:
                msg[f.name] = []
            elif f.type == FieldType.MESSAGE:
                msg[f.name] = unmarshal(f.message, b"")
            else:
                msg[f.name] = {FieldType.DOUBLE: 0.0, FieldType.INT64: 0,
                               FieldType.BOOL: False,
                               FieldType.BYTES: b""}[f.type]
    return msg
