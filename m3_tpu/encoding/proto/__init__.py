"""Schema-aware message-value compression — the dbnode/encoding/proto role.

Role parity with the reference's proto encoding
(/root/reference/src/dbnode/encoding/proto/encoder.go, custom_marshal.go,
namespace schema registry in dbnode/namespace): a namespace may carry a
SCHEMA describing structured message values; streams then encode one
message per datapoint with per-field-type compression instead of a single
float:

- timestamps: the M3TSZ delta-of-delta scheme (same TimestampEncoder);
- double fields: M3TSZ XOR float compression per field;
- int fields: zigzag-varint DELTAS against the previous value;
- bool fields: one bit;
- bytes/string fields: an LRU dictionary of recent values per field
  (the reference's byte-field dictionaries) — a dict hit writes an index,
  a miss writes the literal;
- a changed-fields bitmask per datapoint so unchanged fields cost 1 bit.

The wire format is this framework's own (like every non-m3tsz format in
the repo); parity is behavioral, validated by round-trip + compression
tests against the reference's design goals.
"""

from m3_tpu.encoding.proto.schema import Field, FieldType, Schema, SchemaRegistry
from m3_tpu.encoding.proto.codec import ProtoDecoder, ProtoEncoder, decode, encode_messages

__all__ = [
    "Field", "FieldType", "Schema", "SchemaRegistry",
    "ProtoEncoder", "ProtoDecoder", "decode", "encode_messages",
]
