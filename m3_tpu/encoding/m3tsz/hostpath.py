"""Per-platform codec dispatch for the storage serving paths.

The storage engine flushes and reads through exactly one of:
  - the batched XLA kernels (tpu.py / tpu_int.py) when an accelerator
    backend is live — the device path;
  - the native v2 batch codec (native/m3tsz.cpp, word-level bit I/O,
    threaded across cores) on CPU-only hosts, float mode, for both the
    flush encode and the read decode;
  - the pure-Python scalar codec as the always-available fallback (and the
    only decoder for int-optimized and marker-bearing streams host-side).

This mirrors the reference's role split where the Go hot loop IS the
serving path (/root/reference/src/dbnode/encoding/m3tsz/encoder.go): here
the hot loop is the native batch codec or the device kernel, chosen by
platform. utils/dispatch counters record which path served so tests and
/metrics can verify the production path (round-1 failure mode: device
kernels only tests invoked).
"""

from __future__ import annotations

import numpy as np

from m3_tpu.utils import dispatch
from m3_tpu.utils.xtime import TimeUnit


def _device_encode() -> bool:
    """Device encode when forced (M3_TPU_DEVICE_OPS=1, kernel-parity tests)
    or when an accelerator backend is live."""
    import os

    force = os.environ.get("M3_TPU_DEVICE_OPS")
    if force == "1":
        return True
    if force == "0":
        return False
    return bool(dispatch._accelerator_present())


# decode rides the same platform switch as encode
_device_decode = _device_encode


def encode_blocks(times, vbits, starts, n_points,
                  unit: TimeUnit, int_optimized: bool) -> list[bytes]:
    """Encode a sealed [B, T] window to per-series streams on the best
    path for this platform. Raises on overflow (caller bug: capacity)."""
    from m3_tpu.encoding.m3tsz import native

    times = np.asarray(times)
    vbits = np.asarray(vbits)
    if (not int_optimized and not _device_encode()
            and native.available()):
        dispatch.counters["m3tsz_encode_native"] += 1
        return native.encode_batch(times, vbits, np.asarray(starts), unit,
                                   n_points=np.asarray(n_points))
    import jax.numpy as jnp

    from m3_tpu.encoding.m3tsz import tpu as m3tsz_tpu

    if int_optimized:
        from m3_tpu.encoding.m3tsz import tpu_int

        encode_fn = tpu_int.encode_bits_int
        jitted = tpu_int._encode_bits_int_jit
    else:
        encode_fn = m3tsz_tpu.encode_bits
        jitted = m3tsz_tpu._encode_bits_jit
    dispatch.counters["m3tsz_encode_device"] += 1
    # plan-cache attribution: did this shape bucket hit the jit cache or
    # pay a trace+compile? (compute.jit_* on /metrics); the sig keys the
    # per-program execute histogram on the batch rectangle
    sig = f"B{times.shape[0]}xT{times.shape[1]}" + \
        ("|int" if int_optimized else "")
    with dispatch.jit_tracker("m3tsz_encode", jitted, sig=sig):
        blocks = encode_fn(
            jnp.asarray(times), jnp.asarray(vbits),
            jnp.asarray(starts), jnp.asarray(n_points), unit,
        )
    if bool(blocks.overflow):
        raise OverflowError("batched encode overflow")
    return m3tsz_tpu.blocks_to_bytes(blocks)


def encode_blocks_ragged(times, vbits, offsets, starts,
                         unit: TimeUnit, int_optimized: bool,
                         waste_site: str = "encode_ragged") -> list[bytes]:
    """Encode a RAGGED (CSR) sealed window to per-series streams without
    one global [B, max_T] rectangle (ROADMAP #3, the ingest-side padding
    tax): rows bucket by geometric length (ops.ragged.length_buckets) and
    each bucket pads only to ITS max before the ordinary batched encode —
    a window where one series wrote 10k points and a million wrote one no
    longer materializes a million 10k-wide padded lanes.  Streams are
    byte-identical to encode_blocks over the fully-padded window (the
    encoder reads exactly n_points lanes per row; the pad rule matches
    seal's monotone-tail rule), pinned by the seeded parity sweep in
    tests/test_paged_memory.py.  Zero-length rows return b"".

    ``waste_site`` names the padding-waste ledger row: the ingest seal
    keeps the default, while the binary wire codec (utils/wire) passes
    its own site so compute_stats tells re-encode rectangles on the
    serving path apart from sealed-window encode rectangles."""
    from m3_tpu.ops import ragged

    offsets = np.asarray(offsets, np.int64)
    starts = np.asarray(starts)
    lens = np.diff(offsets)
    out: list[bytes] = [b""] * len(lens)
    from m3_tpu.utils import compute_stats

    for rows in ragged.length_buckets(lens):
        if lens[rows[0]] == 0:
            continue
        sub_t, sub_v, sub_n = ragged.csr_to_padded(
            np.asarray(times), np.asarray(vbits), offsets, rows)
        # padding-waste ledger: real points vs this bucket's rectangle
        compute_stats.record_waste(waste_site, "samples",
                                   int(lens[rows].sum()), sub_t.size)
        streams = encode_blocks(sub_t, sub_v, starts[rows], sub_n,
                                unit, int_optimized)
        for r, s in zip(rows.tolist(), streams):
            out[r] = s
    return out


def decode_stream(stream: bytes, unit: TimeUnit,
                  int_optimized: bool) -> tuple[np.ndarray, np.ndarray]:
    """Decode one stream to (times int64, value_bits uint64) on the best
    host path: the native v2 codec for plain float-mode streams, the
    scalar decoder for int-optimized streams (the native codec is
    float-mode only, same contract as the device kernels) and for streams
    carrying time-unit/annotation markers, which the native decoder
    rejects rather than misparses (e.g. repair-written scalar-Encoder
    streams whose block start is not unit-aligned)."""
    from m3_tpu.encoding.m3tsz import native

    if not int_optimized and native.available():
        try:
            t, v, ns = native.decode_batch([stream], unit)
        except ValueError:
            pass  # marker-bearing stream: scalar path below handles it
        else:
            dispatch.counters["m3tsz_decode_native"] += 1
            n = int(ns[0])
            return t[0, :n].copy(), v[0, :n].copy()
    from m3_tpu.encoding.m3tsz import decode as scalar_decode

    dispatch.counters["m3tsz_decode_scalar"] += 1
    dps = scalar_decode(stream, int_optimized=int_optimized,
                        default_time_unit=unit)
    if not dps:
        return np.empty(0, np.int64), np.empty(0, np.uint64)
    t = np.array([d.timestamp_ns for d in dps], np.int64)
    v = np.array([np.float64(d.value) for d in dps], np.float64).view(np.uint64)
    return t, v


def _forced_batch_path() -> str:
    """Test/diagnostic override for the decode_streams_batch ladder:
    M3_TPU_DECODE_BATCH_PATH in {native, device, scalar} pins one rung
    (parity tests force each rung against the per-series path)."""
    import os

    return os.environ.get("M3_TPU_DECODE_BATCH_PATH", "")


def _decode_streams_device(streams: list[bytes], unit: TimeUnit,
                           int_optimized: bool):
    """One vmapped XLA decode over the whole group. Streams whose rows come
    back flagged (annotation/time-unit markers the kernels don't decode)
    fall back to the scalar decoder individually. Shapes are padded to
    powers of two so repeated groups share compiled kernels."""
    import numpy as _np

    from m3_tpu.encoding.m3tsz import tpu as m3tsz_tpu

    from m3_tpu.utils import compute_stats

    maxlen = max(len(s) for s in streams)
    words = m3tsz_tpu.bytes_to_words(
        streams, dispatch.next_pow2((maxlen + 7) // 8))
    # a datapoint costs >= 2 bits, so the longest stream bounds the points
    max_points = dispatch.next_pow2(maxlen * 4 + 16)
    # padding-waste ledger: real stream words vs the pow2 word rectangle
    compute_stats.record_waste(
        "decode_batch", "words",
        sum((len(s) + 7) // 8 for s in streams), int(words.size))
    sig = f"B{words.shape[0]}xW{words.shape[1]}xP{max_points}" + \
        ("|int" if int_optimized else "")
    if int_optimized:
        from m3_tpu.encoding.m3tsz import tpu_int

        with dispatch.jit_tracker("m3tsz_decode", tpu_int.decode_int,
                                  sig=sig):
            dec = tpu_int.decode_int(words, unit, max_points=max_points)
        vals = _np.asarray(dec.values, _np.float64)
        vbits = vals.view(_np.uint64)
    else:
        with dispatch.jit_tracker("m3tsz_decode", m3tsz_tpu._decode_jit,
                                  sig=sig):
            dec = m3tsz_tpu.decode(words, unit, max_points=max_points)
        vbits = _np.asarray(dec.value_bits, _np.uint64)
    times = _np.asarray(dec.times, _np.int64)
    err = _np.asarray(dec.error)
    counts = _np.asarray(dec.n_points)
    dispatch.counters["m3tsz_decode_device_batch"] += 1
    out = []
    for b, stream in enumerate(streams):
        if err[b]:
            out.append(decode_stream(stream, unit, int_optimized))
            continue
        n = int(counts[b])
        out.append((times[b, :n].copy(), vbits[b, :n].copy()))
    return out


def decode_streams_batch(streams: list[bytes | None], unit: TimeUnit,
                         int_optimized: bool
                         ) -> list[tuple[np.ndarray, np.ndarray]]:
    """Decode MANY streams of one (shard, block, volume) group in a single
    batched dispatch — the read-path dual of encode_blocks. Returns
    [(times int64, value_bits uint64)] aligned to the input; empty/None
    streams decode to empty arrays.

    Ladder (same platform dispatch as the flush encode): the vmapped XLA
    kernels when an accelerator is live/forced (float AND int-optimized —
    the batch surface removes the int-opt scalar cliff), else the native
    v2 batch decoder (float-mode only), else a scalar loop. Streams the
    fast rungs reject (annotation/time-unit markers) degrade per stream,
    never the whole group.
    """
    import time as _time

    from m3_tpu.utils import querystats, trace

    empty = (np.empty(0, np.int64), np.empty(0, np.uint64))
    out: list = [empty] * len(streams)
    todo = [i for i, s in enumerate(streams) if s]
    if not todo:
        return out
    subset = [streams[i] for i in todo]
    # one counter bump per GROUP: tests assert read_many issues at most one
    # batched dispatch per (shard, block, volume) group
    dispatch.counters["m3tsz_decode_batch_groups"] += 1
    forced = _forced_batch_path()
    decoded = None
    rung = "scalar"
    use_device = forced == "device" or (not forced and _device_decode())
    use_native = forced == "native" or (not forced and not use_device)
    with trace.span(trace.DECODE_BATCH, streams=len(subset)) as sp:
        t0 = _time.perf_counter()
        if use_device:
            decoded = _decode_streams_device(subset, unit, int_optimized)
            rung = "device"
        if decoded is None and use_native and not int_optimized:
            from m3_tpu.encoding.m3tsz import native

            if native.available():
                try:
                    t, v, ns = native.decode_batch(subset, unit)
                except ValueError:
                    # a marker-bearing stream poisons the whole native
                    # batch: degrade per stream (decode_stream isolates
                    # the bad ones)
                    decoded = [decode_stream(s, unit, int_optimized)
                               for s in subset]
                else:
                    dispatch.counters["m3tsz_decode_native_batch"] += 1
                    decoded = [(t[b, : int(ns[b])].copy(),
                                v[b, : int(ns[b])].copy())
                               for b in range(len(subset))]
                    rung = "native"
        if decoded is None:
            from m3_tpu.encoding.m3tsz import decode as scalar_decode

            dispatch.counters["m3tsz_decode_scalar_batch"] += 1
            decoded = []
            for s in subset:
                dps = scalar_decode(s, int_optimized=int_optimized,
                                    default_time_unit=unit)
                if not dps:
                    decoded.append(empty)
                    continue
                t = np.array([d.timestamp_ns for d in dps], np.int64)
                v = np.array([np.float64(d.value) for d in dps],
                             np.float64).view(np.uint64)
                decoded.append((t, v))
        dt = _time.perf_counter() - t0
        # device-op profiling: which rung served this group (visible on
        # /metrics per rung), how long it took, how many bytes it chewed —
        # the per-query record gets the same attribution
        n_bytes = sum(len(s) for s in subset)
        sc = _decode_scope(rung)
        sc.observe("seconds", dt)
        sc.counter("streams", len(subset))
        sc.counter("bytes", n_bytes)
        # batch-size DISTRIBUTION per rung (count-shaped bounds): whether
        # batches are big enough to amortize a dispatch is the question
        # the per-rung counters alone can't answer
        from m3_tpu.utils.instrument import COUNT_BUCKETS

        sc.observe("batch_size", float(len(subset)), bounds=COUNT_BUCKETS)
        querystats.record(blocks_read=1, bytes_decoded=n_bytes,
                          decode_rung=rung)
        if sp is not None:
            sp.tags["path"] = rung
            sp.tags["bytes"] = n_bytes
    for i, r in zip(todo, decoded):
        out[i] = r
    return out


_decode_scopes: dict = {}


def _decode_scope(rung: str):
    """Cached per-rung metrics scope (decode.batch{path=rung})."""
    sc = _decode_scopes.get(rung)
    if sc is None:
        from m3_tpu.utils.instrument import default_registry

        sc = default_registry().root_scope("decode").subscope("batch",
                                                              path=rung)
        _decode_scopes[rung] = sc
    return sc
