"""Per-platform codec dispatch for the storage serving paths.

The storage engine flushes and reads through exactly one of:
  - the batched XLA kernels (tpu.py / tpu_int.py) when an accelerator
    backend is live — the device path;
  - the native v2 batch codec (native/m3tsz.cpp, word-level bit I/O,
    threaded across cores) on CPU-only hosts, float mode, for both the
    flush encode and the read decode;
  - the pure-Python scalar codec as the always-available fallback (and the
    only decoder for int-optimized and marker-bearing streams host-side).

This mirrors the reference's role split where the Go hot loop IS the
serving path (/root/reference/src/dbnode/encoding/m3tsz/encoder.go): here
the hot loop is the native batch codec or the device kernel, chosen by
platform. utils/dispatch counters record which path served so tests and
/metrics can verify the production path (round-1 failure mode: device
kernels only tests invoked).
"""

from __future__ import annotations

import numpy as np

from m3_tpu.utils import dispatch
from m3_tpu.utils.xtime import TimeUnit


def _device_encode() -> bool:
    """Device encode when forced (M3_TPU_DEVICE_OPS=1, kernel-parity tests)
    or when an accelerator backend is live."""
    import os

    force = os.environ.get("M3_TPU_DEVICE_OPS")
    if force == "1":
        return True
    if force == "0":
        return False
    return bool(dispatch._accelerator_present())


def encode_blocks(times, vbits, starts, n_points,
                  unit: TimeUnit, int_optimized: bool) -> list[bytes]:
    """Encode a sealed [B, T] window to per-series streams on the best
    path for this platform. Raises on overflow (caller bug: capacity)."""
    from m3_tpu.encoding.m3tsz import native

    times = np.asarray(times)
    vbits = np.asarray(vbits)
    if (not int_optimized and not _device_encode()
            and native.available()):
        dispatch.counters["m3tsz_encode_native"] += 1
        return native.encode_batch(times, vbits, np.asarray(starts), unit,
                                   n_points=np.asarray(n_points))
    import jax.numpy as jnp

    from m3_tpu.encoding.m3tsz import tpu as m3tsz_tpu

    if int_optimized:
        from m3_tpu.encoding.m3tsz import tpu_int

        encode_fn = tpu_int.encode_bits_int
    else:
        encode_fn = m3tsz_tpu.encode_bits
    dispatch.counters["m3tsz_encode_device"] += 1
    blocks = encode_fn(
        jnp.asarray(times), jnp.asarray(vbits),
        jnp.asarray(starts), jnp.asarray(n_points), unit,
    )
    if bool(blocks.overflow):
        raise OverflowError("batched encode overflow")
    return m3tsz_tpu.blocks_to_bytes(blocks)


def decode_stream(stream: bytes, unit: TimeUnit,
                  int_optimized: bool) -> tuple[np.ndarray, np.ndarray]:
    """Decode one stream to (times int64, value_bits uint64) on the best
    host path: the native v2 codec for plain float-mode streams, the
    scalar decoder for int-optimized streams (the native codec is
    float-mode only, same contract as the device kernels) and for streams
    carrying time-unit/annotation markers, which the native decoder
    rejects rather than misparses (e.g. repair-written scalar-Encoder
    streams whose block start is not unit-aligned)."""
    from m3_tpu.encoding.m3tsz import native

    if not int_optimized and native.available():
        try:
            t, v, ns = native.decode_batch([stream], unit)
        except ValueError:
            pass  # marker-bearing stream: scalar path below handles it
        else:
            dispatch.counters["m3tsz_decode_native"] += 1
            n = int(ns[0])
            return t[0, :n].copy(), v[0, :n].copy()
    from m3_tpu.encoding.m3tsz import decode as scalar_decode

    dispatch.counters["m3tsz_decode_scalar"] += 1
    dps = scalar_decode(stream, int_optimized=int_optimized,
                        default_time_unit=unit)
    if not dps:
        return np.empty(0, np.int64), np.empty(0, np.uint64)
    t = np.array([d.timestamp_ns for d in dps], np.int64)
    v = np.array([np.float64(d.value) for d in dps], np.float64).view(np.uint64)
    return t, v
