"""Batched M3TSZ encode/decode with the INT OPTIMIZATION as JAX kernels.

The int-optimized value scheme (reference m3tsz.go:78-119 convertToIntFloat,
int_sig_bits_tracker.go, encoder.go int paths) is the reference's
compression win (1.45 B/dp on production workloads). Unlike the float-XOR
scheme, its value stream carries SEQUENTIAL state (running int value,
monotone multiplier, sig-bit hysteresis tracker, float/int mode switches),
so the value fields are computed by a ``lax.scan`` over timesteps carrying
vectorized [B] state — throughput still comes from the batch axis — and the
resulting per-point (hi, lo, len) fields feed the same prefix-sum +
scatter-add packer as the float kernel (tpu._pack_stream).

Streams are bit-identical to the scalar encoder with int_optimized=True
(property-tested in tests/test_tpu_int_codec.py) with the same carve-out as
the scalar path: |value| >= 2^63 integral floats take float mode.

TPU note: the float-mode fallback inside an int stream needs the IEEE bits
of COMPUTED values; the X64 rewriter lacks the f64->u64 bitcast, so bits
are reconstructed arithmetically — exact for the integral-valued floats
this path produces (input values use their host-provided bit patterns).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from m3_tpu.encoding.m3tsz import constants as c
from m3_tpu.encoding.m3tsz.tpu import (
    _EOS_LEN,
    DecodedValues,
    EncodedBlocks,
    _decode_ts_fields,
    _dod_fields,
    _pack_stream,
    _trunc_div,
)
from m3_tpu.ops.bits import (
    I64,
    U64,
    bits_to_f64,
    clz64,
    ctz64,
    mask_low,
    read_window,
    shl,
    shr,
    sign_extend64,
)
from m3_tpu.utils.xtime import TimeUnit, unit_value_ns

# timestamp default bucket (4+64) + worst int value field:
# 3 opcodes + sig update (1+1+6) + mult update (1+3) + sign + 64 diff bits
MAX_BITS_PER_DP_INT = 68 + 80


def _u64(x: int) -> jnp.ndarray:
    return jnp.uint64(x)


def mask_low_dyn(n):
    """mask of the low n bits for dynamic n in [0, 64]."""
    return jnp.where(
        jnp.asarray(n, U64) >= 64,
        ~_u64(0),
        (shl(_u64(1), jnp.asarray(n, U64))) - _u64(1),
    )


def _append(hi, lo, ln, val, nbits):
    """Append an MSB-first bit field (<= 64 bits, value in val) to a
    (hi, lo, len<=128) register."""
    nb = jnp.asarray(nbits, U64)
    hi2 = shl(hi, nb) | shr(lo, _u64(64) - nb)
    lo2 = shl(lo, nb) | (val & mask_low_dyn(nb))
    return hi2, lo2, ln + nb


def _append128(hi, lo, ln, fhi, flo, flen):
    """Append a field of up to 128 bits held in (fhi, flo) MSB-first.
    Correct for flen in [0, 128] as long as the result fits 128 bits."""
    fl = jnp.asarray(flen, U64)
    big = fl >= 64
    hi2 = jnp.where(
        big,
        shl(lo, fl - _u64(64)),
        shl(hi, fl) | shr(lo, _u64(64) - fl),
    )
    lo2 = jnp.where(big, _u64(0), shl(lo, fl))
    return hi2 | fhi, lo2 | flo, ln + fl


def _num_sig(bits_u64):
    """Significant-bit count (bit_length); 0 for 0."""
    return (_u64(64) - clz64(bits_u64)).astype(jnp.int32)


def _f64_bits_of_integral(x):
    """IEEE-754 bits of an integral-valued float64 with |x| < 2^63,
    reconstructed without an f64->u64 bitcast (unavailable on TPU)."""
    neg = jnp.signbit(x)
    u = jnp.abs(x).astype(U64)  # exact arithmetic convert for integral x
    nz = u != 0
    lz = clz64(u)
    msb = _u64(63) - lz
    mant = shr(shl(u, lz + _u64(1)), _u64(12))
    exp = _u64(1023) + msb
    bits = jnp.where(nz, (exp << _u64(52)) | mant, _u64(0))
    return bits | jnp.where(neg, _u64(1) << _u64(63), _u64(0))


def _conv_tables(v):
    """Elementwise convert_to_int_float candidates for every multiplier.

    Returns (fast_ok [..], conv_ok [.., 7], conv_val [.., 7]) mirroring
    m3tsz.go convertToIntFloat / the scalar constants.convert_to_int_float:
    fast path is only valid while the stream's max multiplier is 0."""
    sign = jnp.where(v < 0, -1.0, 1.0)
    mults = jnp.asarray(c.MULTIPLIERS)  # [7]
    scaled = v[..., None] * mults * sign[..., None]
    frac = scaled - jnp.trunc(scaled)  # math.modf fractional part (>= 0 here)
    integ = jnp.trunc(scaled)
    ok0 = frac == 0.0
    okl = (frac < 0.1) & (jnp.nextafter(scaled, 0.0) <= integ)
    nxt = integ + 1.0
    okh = (frac > 0.9) & (jnp.nextafter(scaled, nxt) >= nxt)
    conv_ok = (ok0 | okl | okh) & (scaled < c.MAX_OPT_INT)
    cand = jnp.where(ok0 | okl, integ, nxt)
    conv_val = sign[..., None] * cand
    # fast path: cur_max_mult == 0 and v < MAX_INT and modf(v).frac == 0
    fast_ok = (v < c.MAX_INT) & (v - jnp.trunc(v) == 0.0)
    return fast_ok, conv_ok, conv_val


def _sig_field(num_sig, sig):
    """write_int_sig: (value, nbits) given tracker num_sig and new sig."""
    differs = num_sig != sig
    sig_u = sig.astype(U64)
    # UPDATE_SIG(1) + [ZERO_SIG | NON_ZERO_SIG + 6 bits (sig-1)]
    upd_zero_val = _u64(0b10)  # UPDATE_SIG=1, ZERO_SIG=0
    upd_zero_len = _u64(2)
    upd_nz_val = (_u64(0b11) << _u64(6)) | ((sig_u - _u64(1)) & mask_low(6))
    upd_nz_len = _u64(8)
    val = jnp.where(differs, jnp.where(sig == 0, upd_zero_val, upd_nz_val),
                    _u64(0))  # NO_UPDATE_SIG = single 0 bit
    ln = jnp.where(differs, jnp.where(sig == 0, upd_zero_len, upd_nz_len),
                   _u64(1))
    return val, ln


def _mult_field(num_sig_after, sig, max_mult, mult, float_changed):
    """_write_int_sig_mult's multiplier part: (value, nbits, new_max_mult)."""
    mult_u = mult.astype(U64)
    max_u = max_mult.astype(U64)
    grow = mult > max_mult
    rewrite = (~grow) & (num_sig_after == sig) & (max_mult == mult) & float_changed
    val = jnp.where(grow, _u64(0b1000) | mult_u,
                    jnp.where(rewrite, _u64(0b1000) | max_u, _u64(0)))
    ln = jnp.where(grow | rewrite, _u64(4), _u64(1))
    new_max = jnp.where(grow, mult, max_mult)
    return val, ln, new_max


def _diff_field(diff_bits, neg, num_sig):
    """write_int_val_diff: sign bit + num_sig value bits, as a 128-bit
    (fhi, flo, flen) field — sig can be 64, making the field 65 bits."""
    ns = num_sig.astype(U64)
    negbit = jnp.where(neg, _u64(1), _u64(0))
    fhi = jnp.where(ns >= 64, negbit, _u64(0))
    flo = shl(negbit, ns) | (diff_bits & mask_low_dyn(ns))
    return fhi, flo, ns + _u64(1)


def _xor_field_scalar(xor, prev_xor):
    """Per-element XOR field (hi, lo, len) — next_float inside int streams
    (same scheme as tpu._xor_fields, on [B] vectors)."""
    pl, pt = clz64(prev_xor), ctz64(prev_xor)
    cl, ct = clz64(xor), ctz64(xor)
    zero = xor == 0
    contained = (cl >= pl) & (ct >= pt) & ~zero
    m_prev = _u64(64) - pl - pt
    c_lo = shl(_u64(0b10), m_prev) | shr(xor, pt)
    c_hi = shr(_u64(0b10), _u64(64) - m_prev)
    c_len = _u64(2) + m_prev
    m = _u64(64) - cl - ct
    top = (_u64(0b11) << _u64(12)) | (cl << _u64(6)) | (m - _u64(1))
    u_lo = shl(top, m) | shr(xor, ct)
    u_hi = shr(top, _u64(64) - m)
    u_len = _u64(14) + m
    length = jnp.where(zero, _u64(1), jnp.where(contained, c_len, u_len))
    lo = jnp.where(zero, _u64(0), jnp.where(contained, c_lo, u_lo))
    hi = jnp.where(zero, _u64(0), jnp.where(contained, c_hi, u_hi))
    return hi, lo, length


def _int_value_fields(vb, v, n_points):
    """Value fields for the int-optimized scheme: scan over timesteps with
    [B] state. Returns (hi, lo, len) arrays of shape [B, T]."""
    B, T = v.shape  # noqa: N806
    fast_ok, conv_ok, conv_val = _conv_tables(v)

    def step(carry, inp):
        (max_mult, is_float, int_val, prev_bits, prev_xor,
         num_sig, num_lower, cur_high) = carry
        t, v_t, vb_t, fast_t, cok_t, cval_t, valid_t = inp
        first = t == 0

        # --- convert_to_int_float ---
        use_fast = fast_t & (max_mult == 0)
        m_idx = jnp.arange(7, dtype=jnp.int32)
        m_ok = cok_t & (m_idx[None, :] >= max_mult[:, None])
        any_m = m_ok.any(axis=1)
        first_m = jnp.argmax(m_ok, axis=1).astype(jnp.int32)
        conv_v = jnp.take_along_axis(cval_t, first_m[:, None], axis=1)[:, 0]
        val = jnp.where(use_fast, v_t, jnp.where(any_m, conv_v, v_t))
        mult = jnp.where(use_fast, 0, jnp.where(any_m, first_m, 0))
        pt_float = ~use_fast & ~any_m
        # encoder guard: ints needing > 63 bits take float mode
        too_big = ~pt_float & (jnp.abs(val) >= c.MAX_INT)
        val = jnp.where(too_big, v_t, val)
        mult = jnp.where(too_big, jnp.where(use_fast | any_m, mult, 0), mult)
        pt_float = pt_float | too_big

        # bits of the value when written as a full/xor float: the raw input
        # bits when conversion failed, reconstructed bits when the encoder
        # writes the CONVERTED value (diff-overflow path)
        fbits = jnp.where(pt_float, vb_t, _f64_bits_of_integral(val))

        # ---------- FIRST VALUE ----------
        # float mode: '1' + 64 raw bits
        f1_hi, f1_lo, f1_len = _append(
            *_append(_u64(0), _u64(0), _u64(0), _u64(1), _u64(1)),
            vb_t, _u64(64))
        # int mode: '0' + sig + mult + sign + diff
        aval = jnp.abs(val)
        neg_first = ~(val < 0)  # neg_diff: True unless val < 0 (encoder.py)
        dbits_first = aval.astype(U64)
        sig_first = _num_sig(dbits_first)
        sv, sl = _sig_field(jnp.zeros_like(num_sig), sig_first)
        mv, ml, max_after_first = _mult_field(
            sig_first, sig_first, jnp.zeros_like(max_mult), mult,
            jnp.zeros_like(is_float))
        dfh, dfl, dfn = _diff_field(dbits_first, neg_first, sig_first)
        i1 = _append(_u64(0), _u64(0), _u64(0), _u64(0), _u64(1))
        i1 = _append(*i1, sv, sl)
        i1 = _append(*i1, mv, ml)
        i1_hi, i1_lo, i1_len = _append128(*i1, dfh, dfl, dfn)

        first_hi = jnp.where(pt_float, f1_hi, i1_hi)
        first_lo = jnp.where(pt_float, f1_lo, i1_lo)
        first_len = jnp.where(pt_float, f1_len, i1_len)
        first_max = jnp.where(pt_float, mult, max_after_first)
        first_is_float = pt_float
        first_int_val = jnp.where(pt_float, 0.0, val)
        first_sig = jnp.where(pt_float, 0, sig_first)
        first_bits = vb_t  # write_full_float seeds prev bits/xor; the int
        first_xor = vb_t   # branch never reads them before the next reset

        # ---------- NEXT VALUE ----------
        val_diff = int_val - val
        to_float = pt_float | (val_diff >= c.MAX_INT) | (val_diff <= c.MIN_INT)

        # float-val path (_write_float_val)
        #   not is_float: '0''0''1' + 64 bits
        ff = _append(_u64(0), _u64(0), _u64(0), _u64(0b001), _u64(3))
        ff_hi, ff_lo, ff_len = _append(*ff, fbits, _u64(64))
        #   is_float & repeat: '0''1'
        #   is_float & no-repeat: '1' + xor field (can exceed 64 bits)
        xor = fbits ^ prev_bits
        xh, xl, xlen = _xor_field_scalar(xor, prev_xor)
        nfa = _append(_u64(0), _u64(0), _u64(0), _u64(1), _u64(1))
        nfa_hi, nfa_lo, nfa_len = _append128(*nfa, xh, xl, xlen)
        float_repeat = fbits == prev_bits
        fv_hi = jnp.where(is_float,
                          jnp.where(float_repeat, _u64(0), nfa_hi), ff_hi)
        fv_lo = jnp.where(is_float,
                          jnp.where(float_repeat, _u64(0b01), nfa_lo), ff_lo)
        fv_len = jnp.where(is_float,
                           jnp.where(float_repeat, _u64(2), nfa_len), ff_len)
        fv_is_float = jnp.ones_like(is_float)
        fv_max = jnp.where(is_float, max_mult, mult)  # full-float sets max
        fv_prev_bits = fbits
        fv_prev_xor = jnp.where(is_float & ~float_repeat, xor, prev_xor)
        # full-float (the not-is_float sub-case writes 64 raw bits) resets
        # the xor chain exactly like write_full_float: prev_xor := bits
        fv_prev_xor = jnp.where(~is_float, fbits, fv_prev_xor)

        # int-val path (_write_int_val)
        int_repeat = (val_diff == 0) & ~is_float & (mult == max_mult)
        neg = val_diff < 0
        adiff = jnp.abs(val_diff)
        dbits = adiff.astype(U64)
        sig = _num_sig(dbits)
        # track_new_sig: note the tracker PRESERVES its lower-sig streak
        # state when sig grows (only the in-between branch resets it)
        higher = sig > num_sig
        much_lower = ~higher & ((num_sig - sig) >= c.SIG_DIFF_THRESHOLD)
        new_cur_high = jnp.where(
            much_lower,
            jnp.where(num_lower == 0, sig, jnp.maximum(cur_high, sig)),
            cur_high)
        new_num_lower = jnp.where(
            higher, num_lower, jnp.where(much_lower, num_lower + 1, 0))
        hit_threshold = much_lower & (new_num_lower >= c.SIG_REPEAT_THRESHOLD)
        new_sig = jnp.where(higher, sig,
                            jnp.where(hit_threshold, new_cur_high, num_sig))
        new_num_lower = jnp.where(hit_threshold, 0, new_num_lower)

        is_float_changed = is_float  # (False != is_float)
        rewrite_path = (mult > max_mult) | (num_sig != new_sig) | is_float_changed
        # rewrite: '0''0''0' + sig(new_sig vs num_sig) + mult + sign + diff
        sv2, sl2 = _sig_field(num_sig, new_sig)
        mv2, ml2, max_after = _mult_field(new_sig, new_sig, max_mult, mult,
                                          is_float_changed)
        dv2h, dv2l, dl2 = _diff_field(dbits, neg, new_sig)
        iw = _append(_u64(0), _u64(0), _u64(0), _u64(0b000), _u64(3))
        iw = _append(*iw, sv2, sl2)
        iw = _append(*iw, mv2, ml2)
        iw_hi, iw_lo, iw_len = _append128(*iw, dv2h, dv2l, dl2)
        # no-update: '1' + sign + diff (current num_sig == new_sig)
        nu = _append(_u64(0), _u64(0), _u64(0), _u64(1), _u64(1))
        nu_hi, nu_lo, nu_len = _append128(*nu, dv2h, dv2l, dl2)

        iv_hi = jnp.where(int_repeat, _u64(0),
                          jnp.where(rewrite_path, iw_hi, nu_hi))
        iv_lo = jnp.where(int_repeat, _u64(0b01),
                          jnp.where(rewrite_path, iw_lo, nu_lo))
        iv_len = jnp.where(int_repeat, _u64(2),
                           jnp.where(rewrite_path, iw_len, nu_len))
        iv_sig = jnp.where(int_repeat, num_sig, new_sig)
        iv_num_lower = jnp.where(int_repeat, num_lower, new_num_lower)
        iv_cur_high = jnp.where(int_repeat, cur_high, new_cur_high)
        iv_max = jnp.where(int_repeat, max_mult,
                           jnp.where(rewrite_path, max_after, max_mult))
        iv_int_val = jnp.where(int_repeat, int_val, val)

        next_hi = jnp.where(to_float, fv_hi, iv_hi)
        next_lo = jnp.where(to_float, fv_lo, iv_lo)
        next_len = jnp.where(to_float, fv_len, iv_len)
        next_is_float = jnp.where(to_float, fv_is_float, jnp.zeros_like(is_float))
        next_max = jnp.where(to_float, fv_max, iv_max)
        next_int_val = jnp.where(to_float, int_val, iv_int_val)
        next_sig = jnp.where(to_float, num_sig, iv_sig)
        next_num_lower = jnp.where(to_float, num_lower, iv_num_lower)
        next_cur_high = jnp.where(to_float, cur_high, iv_cur_high)
        next_prev_bits = jnp.where(to_float, fv_prev_bits, prev_bits)
        next_prev_xor = jnp.where(to_float, fv_prev_xor, prev_xor)

        # ---------- select first vs next, gate on validity ----------
        out_hi = jnp.where(first, first_hi, next_hi)
        out_lo = jnp.where(first, first_lo, next_lo)
        out_len = jnp.where(first, first_len, next_len)

        upd = valid_t
        carry = (
            jnp.where(upd, jnp.where(first, first_max, next_max), max_mult),
            jnp.where(upd, jnp.where(first, first_is_float, next_is_float), is_float),
            jnp.where(upd, jnp.where(first, first_int_val, next_int_val), int_val),
            jnp.where(upd, jnp.where(first, first_bits, next_prev_bits), prev_bits),
            jnp.where(upd, jnp.where(first, first_xor, next_prev_xor), prev_xor),
            jnp.where(upd, jnp.where(first, first_sig, next_sig), num_sig),
            jnp.where(upd, jnp.where(first, jnp.zeros_like(num_lower), next_num_lower), num_lower),
            jnp.where(upd, jnp.where(first, jnp.zeros_like(cur_high), next_cur_high), cur_high),
        )
        return carry, (out_hi, out_lo, out_len)

    init = (
        jnp.zeros(B, jnp.int32),            # max_mult
        jnp.zeros(B, bool),                 # is_float
        jnp.zeros(B, jnp.float64),          # int_val
        jnp.zeros(B, U64),                  # prev_float_bits
        jnp.zeros(B, U64),                  # prev_xor
        jnp.zeros(B, jnp.int32),            # num_sig
        jnp.zeros(B, jnp.int32),            # num_lower_sig
        jnp.zeros(B, jnp.int32),            # cur_highest_lower_sig
    )
    idxs = jnp.arange(T)
    valid = idxs[None, :] < n_points[:, None]
    # conv tables are [B, T, 7]; scan wants leading T
    xs = (idxs, v.T, vb.T, fast_ok.T,
          jnp.moveaxis(conv_ok, 1, 0), jnp.moveaxis(conv_val, 1, 0), valid.T)
    _, (hi, lo, ln) = lax.scan(step, init, xs)
    return hi.T, lo.T, ln.T


def encode_bits_int(
    times: jnp.ndarray,  # [B, T] int64 unix nanos
    value_bits: jnp.ndarray,  # [B, T] uint64 IEEE-754 bit patterns
    start: jnp.ndarray,  # [B] int64
    n_points: jnp.ndarray,  # [B] int32
    unit: TimeUnit = TimeUnit.SECOND,
    capacity_words: int | None = None,
    impl: str | None = None,
) -> EncodedBlocks:
    """Batched int-optimized M3TSZ encode (bit-identical to the scalar
    encoder with int_optimized=True). `impl` selects the packer backend
    as in tpu.encode_bits."""
    from m3_tpu.encoding.m3tsz.tpu import _resolve_impl

    return _encode_bits_int_jit(times, value_bits, start, n_points, unit,
                                capacity_words, _resolve_impl(impl))


@functools.partial(jax.jit, static_argnames=("unit", "capacity_words", "impl"))
def _encode_bits_int_jit(
    times: jnp.ndarray,
    value_bits: jnp.ndarray,
    start: jnp.ndarray,
    n_points: jnp.ndarray,
    unit: TimeUnit = TimeUnit.SECOND,
    capacity_words: int | None = None,
    impl: str = "tree",
) -> EncodedBlocks:
    B, T = times.shape  # noqa: N806
    unit_ns = unit_value_ns(unit)
    default_bits = 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64
    if capacity_words is None:
        capacity_words = (64 + MAX_BITS_PER_DP_INT * T + 11 + 63) // 64

    times = times.astype(I64)
    idx = jnp.arange(T)
    valid = idx[None, :] < n_points[:, None]

    # timestamp fields (same as the float kernel)
    prev_t = jnp.concatenate([start[:, None].astype(I64), times[:, :-1]], axis=1)
    dt = times - prev_t
    prev_dt = jnp.concatenate([jnp.zeros((B, 1), I64), dt[:, :-1]], axis=1)
    dod_units = _trunc_div(dt - prev_dt, unit_ns)
    ts_hi, ts_lo, ts_len = _dod_fields(dod_units, default_bits)

    # value fields via the int-scheme scan
    vb = value_bits.astype(U64)
    v = bits_to_f64(vb)
    v_hi, v_lo, v_len = _int_value_fields(vb, v, n_points)

    dp_len = jnp.where(valid, ts_len + v_len, _u64(0))
    end_off = _u64(64) + jnp.sum(dp_len, axis=1)
    total_bits = end_off + _EOS_LEN
    misaligned = jnp.any(start.astype(I64) % unit_ns != 0)
    overflow = jnp.any(total_bits > _u64(capacity_words * 64)) | misaligned
    if default_bits == 32:
        in32 = (dod_units >= -(1 << 31)) & (dod_units <= (1 << 31) - 1)
        overflow = overflow | jnp.any(valid & ~in32)

    words = _pack_stream(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len,
                         valid, start, capacity_words, impl)
    return EncodedBlocks(words=words, bit_lengths=total_bits, overflow=overflow)


@functools.partial(jax.jit, static_argnames=("unit", "max_points"))
def decode_int(
    words: jnp.ndarray,  # [B, W] uint64
    unit: TimeUnit = TimeUnit.SECOND,
    max_points: int = 1024,
) -> DecodedBlocks:
    """Batched decode of int-optimized streams (scan over points, vmapped
    over series). Mirrors the scalar ReaderIterator int paths."""
    unit_ns = unit_value_ns(unit)
    default_bits = 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64

    def decode_one(series_words: jnp.ndarray):
        start = sign_extend64(series_words[0], _u64(64))

        def step(carry, i):
            (off, prev_time, prev_dt, prev_bits, prev_xor, int_val, mult,
             sig, is_float, done, err) = carry
            win = read_window(series_words, off)

            is_marker = shr(win, _u64(55)) == _u64(0x100)
            marker_val = shr(win, _u64(53)) & _u64(3)
            is_eos = is_marker & (marker_val == 0)
            err = err | (is_marker & (marker_val != 0) & ~done)
            is_eos = is_eos | (is_marker & (marker_val != 0))

            dod_u, ts_len = _decode_ts_fields(series_words, off, win, default_bits)
            new_dt = prev_dt + dod_u * unit_ns
            new_time = prev_time + new_dt

            voff = off + ts_len
            first = i == 0

            # ---- first value ----
            fwin = read_window(series_words, voff)
            f_mode_float = shr(fwin, _u64(63)) == _u64(1)
            # float: 1 mode bit + 64 raw bits read at their own window
            f_bits = read_window(series_words, voff + _u64(1))
            # int: parse sig/mult/sign/diff starting at voff+1
            (i_val, i_mult, i_sig, i_len) = _read_sig_mult_diff(
                series_words, voff + _u64(1),
                jnp.int32(0), jnp.int32(0), jnp.float64(0.0))
            first_len = jnp.where(f_mode_float, _u64(65), _u64(1) + i_len)
            first_is_float = f_mode_float
            first_bits = f_bits
            first_int_val = jnp.where(f_mode_float, 0.0, i_val)
            first_mult = jnp.where(f_mode_float, 0, i_mult)
            first_sig = jnp.where(f_mode_float, 0, i_sig)

            # ---- next value ----
            nwin = read_window(series_words, voff)
            b_update = shr(nwin, _u64(63)) == _u64(0)  # OPCODE_UPDATE = 0
            b2 = shr(nwin, _u64(62)) & _u64(1)
            repeat = b_update & (b2 == _u64(1))
            b3 = shr(nwin, _u64(61)) & _u64(1)
            upd_float = b_update & (b2 == _u64(0)) & (b3 == _u64(1))
            upd_int = b_update & (b2 == _u64(0)) & (b3 == _u64(0))

            # update+float: 3 opcode bits + full 64
            uf_bits = read_window(series_words, voff + _u64(3))
            uf_len = _u64(67)
            # update+int: 3 opcode bits + sig/mult/diff
            (ui_val, ui_mult, ui_sig, ui_len) = _read_sig_mult_diff(
                series_words, voff + _u64(3), sig, mult, int_val)
            # no-update: 1 bit + (float: xor field | int: sign+diff)
            #   float xor (read_next_float)
            pl, pt = clz64(prev_xor), ctz64(prev_xor)
            m_prev = _u64(64) - pl - pt
            xwin = read_window(series_words, voff + _u64(1))
            xb1 = shr(xwin, _u64(63))
            xb2 = shr(xwin, _u64(62)) & _u64(1)
            xzero = xb1 == 0
            xcont = (xb1 == 1) & (xb2 == 0)
            c_mant = shr(read_window(series_words, voff + _u64(3)),
                         _u64(64) - m_prev)
            c_xor = shl(c_mant, pt)
            c_len = _u64(2) + m_prev
            lead = shr(xwin, _u64(56)) & _u64(0x3F)
            mm = (shr(xwin, _u64(50)) & _u64(0x3F)) + _u64(1)
            u_mant = shr(read_window(series_words, voff + _u64(15)),
                         _u64(64) - mm)
            trail = _u64(64) - lead - mm
            u_xor = shl(u_mant, trail)
            u_len = _u64(14) + mm
            xor = jnp.where(xzero, _u64(0), jnp.where(xcont, c_xor, u_xor))
            x_len = jnp.where(xzero, _u64(1), jnp.where(xcont, c_len, u_len))
            nf_bits = prev_bits ^ xor
            nf_len = _u64(1) + x_len
            #   int diff with current sig
            nd_val, nd_len = _read_diff(series_words, voff + _u64(1), sig,
                                        int_val)
            nu_len = jnp.where(is_float, nf_len, _u64(1) + nd_len)

            next_len = jnp.where(repeat, _u64(2),
                        jnp.where(upd_float, uf_len,
                         jnp.where(upd_int, _u64(3) + ui_len, nu_len)))
            next_is_float = jnp.where(repeat, is_float,
                             jnp.where(upd_float, True,
                              jnp.where(upd_int, False, is_float)))
            next_bits = jnp.where(upd_float, uf_bits,
                          jnp.where(~b_update & is_float, nf_bits, prev_bits))
            next_xor = jnp.where(upd_float, uf_bits,
                         jnp.where(~b_update & is_float, xor, prev_xor))
            next_int_val = jnp.where(repeat, int_val,
                            jnp.where(upd_int, ui_val,
                             jnp.where(~b_update & ~is_float, nd_val, int_val)))
            next_mult = jnp.where(upd_int, ui_mult, mult)
            next_sig = jnp.where(upd_int, ui_sig, sig)

            # ---- merge first/next ----
            v_len = jnp.where(first, first_len, next_len)
            new_is_float = jnp.where(first, first_is_float, next_is_float)
            new_bits = jnp.where(first, first_bits,
                                 jnp.where(new_is_float, next_bits, prev_bits))
            new_xor = jnp.where(first, first_bits, next_xor)
            new_int_val = jnp.where(first, first_int_val, next_int_val)
            new_mult = jnp.where(first, first_mult, next_mult)
            new_sig = jnp.where(first, first_sig, next_sig)

            out_val_f = jnp.where(
                new_is_float, bits_to_f64(new_bits),
                new_int_val / jnp.asarray(c.MULTIPLIERS)[jnp.clip(new_mult, 0, 6)])
            ok = ~done & ~is_eos
            out_t = jnp.where(ok, new_time, 0)
            out_v = jnp.where(ok, out_val_f, 0.0)
            carry = (
                jnp.where(ok, voff + v_len, off),
                jnp.where(ok, new_time, prev_time),
                jnp.where(ok, new_dt, prev_dt),
                jnp.where(ok, new_bits, prev_bits),
                jnp.where(ok, new_xor, prev_xor),
                jnp.where(ok, new_int_val, int_val),
                jnp.where(ok, new_mult, mult),
                jnp.where(ok, new_sig, sig),
                jnp.where(ok, new_is_float, is_float),
                done | is_eos,
                err,
            )
            return carry, (out_t, out_v, ok)

        init = (
            _u64(64), start, jnp.int64(0), _u64(0), _u64(0),
            jnp.float64(0.0), jnp.int32(0), jnp.int32(0),
            jnp.bool_(False), jnp.bool_(False), jnp.bool_(False),
        )
        carry, (ts, vs, ok) = lax.scan(step, init, jnp.arange(max_points))
        return ts, vs, ok, carry[-1]

    ts, vs, ok, err = jax.vmap(decode_one)(words)
    return DecodedValues(
        times=ts,
        values=vs,
        valid=ok,
        n_points=ok.sum(axis=1).astype(jnp.int32),
        error=err,
    )


def _read_sig_mult_diff(series_words, off, cur_sig, cur_mult, cur_int_val):
    """_read_int_sig_mult + _read_int_val_diff at a dynamic offset.
    Returns (new_int_val, new_mult, new_sig, bits_consumed)."""
    win = read_window(series_words, off)
    upd_sig = shr(win, _u64(63)) == _u64(1)
    zero_sig = shr(win, _u64(62)) & _u64(1)
    sig_bits = (shr(win, _u64(56)) & _u64(0x3F)).astype(jnp.int32) + 1
    new_sig = jnp.where(
        upd_sig, jnp.where(zero_sig == _u64(0), 0, sig_bits), cur_sig)
    sig_len = jnp.where(upd_sig, jnp.where(zero_sig == _u64(0), _u64(2), _u64(8)),
                        _u64(1))
    moff = off + sig_len
    mwin = read_window(series_words, moff)
    upd_mult = shr(mwin, _u64(63)) == _u64(1)
    mult_bits = (shr(mwin, _u64(60)) & _u64(0x7)).astype(jnp.int32)
    new_mult = jnp.where(upd_mult, mult_bits, cur_mult)
    mult_len = jnp.where(upd_mult, _u64(4), _u64(1))
    doff = moff + mult_len
    new_val, diff_len = _read_diff(series_words, doff, new_sig, cur_int_val)
    return new_val, new_mult, new_sig, sig_len + mult_len + diff_len


def _read_diff(series_words, off, sig, cur_int_val):
    """write_int_val_diff inverse: sign bit + sig bits, applied as
    int_val -= signed diff (scalar decoder _read_int_val_diff)."""
    win = read_window(series_words, off)
    sig_u = jnp.asarray(sig, U64)
    neg_opcode = shr(win, _u64(63)) == _u64(c.OPCODE_NEGATIVE)
    bits = shr(shl(win, _u64(1)), _u64(64) - sig_u)  # next sig bits
    bits = jnp.where(sig_u == 0, _u64(0), bits)
    # sig == 64: the 64 value bits span past this window; read them whole
    bits = jnp.where(sig_u >= 64,
                     read_window(series_words, off + _u64(1)), bits)
    # decoder: sign = +1 when NEGATIVE opcode else -1; int_val += sign*bits
    sign = jnp.where(neg_opcode, 1.0, -1.0)
    new_val = cur_int_val + sign * bits.astype(jnp.float64)
    return new_val, sig_u + _u64(1)
