"""Batched M3TSZ encode/decode as JAX/XLA kernels.

The scalar codec (encoder.py/decoder.py) processes one datapoint at a time;
these kernels process a whole (series x timestep) block per dispatch:

- **encode**: two-pass vectorized bit-packing — compute every datapoint's
  field bit-lengths elementwise, prefix-sum them into bit offsets, assemble
  each datapoint's payload in a 192-bit register, then scatter-add the
  (disjoint) bit pieces into the output word tensor. Because every bit is
  produced by exactly one datapoint, integer add == bitwise or.
- **decode**: lax.scan over timesteps (the format is inherently sequential
  per stream) vmapped over series — throughput comes from the batch axis.

Streams are bit-identical to the scalar encoder configured with
int_optimized=False and a fixed time unit (the storage engine's block-write
configuration for device-resident blocks). Annotations, time-unit changes,
and the int optimization stay on the scalar/host path; this mirrors the
reference's split where the hot loop handles the common shape
(/root/reference/src/dbnode/encoding/m3tsz/float_encoder_iterator.go) and
markers are rare control-plane events.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from m3_tpu.ops.bits import (
    I64,
    U64,
    clz64,
    ctz64,
    mask_low,
    read_window,
    reg3_insert,
    reg3_shift_right_to4,
    shl,
    shr,
    sign_extend64,
)
from m3_tpu.utils.xtime import TimeUnit, unit_value_ns

_EOS_FIELD = np.uint64(0x100 << 2)  # 9-bit marker opcode + 2-bit EOS value
_EOS_LEN = np.uint64(11)

# Max bits one datapoint can occupy: timestamp default bucket (4+64) +
# uncontained XOR (2+6+6+64).
MAX_BITS_PER_DP = 146


class EncodedBlocks(NamedTuple):
    """Batch of encoded streams as device tensors."""

    words: jnp.ndarray  # [B, W] uint64, MSB-first bit stream
    bit_lengths: jnp.ndarray  # [B] uint64, total bits incl. EOS marker
    # True if any series exceeded capacity_words OR its start was not
    # aligned to the encode unit (either way the streams are unusable —
    # re-encode with more capacity / an aligned block start).
    overflow: jnp.ndarray  # [] bool


def _dod_fields(dod_units: jnp.ndarray, default_value_bits: int):
    """Per-element timestamp field (hi, lo, len) for a delta-of-delta.

    Bucket scheme per /root/reference/src/dbnode/encoding/scheme.go:44-52:
    0 -> '0'; 7/9/12-bit buckets with opcodes 10/110/1110; default 1111 +
    32 or 64 bits.
    """
    d = dod_units
    zero = d == 0
    fits = lambda n: (d >= -(1 << (n - 1))) & (d <= (1 << (n - 1)) - 1)  # noqa: E731
    in7, in9, in12 = fits(7), fits(9), fits(12)

    db = default_value_bits
    ud = d.astype(U64)
    # Select (len, value) by bucket; value = opcode followed by dod bits.
    length = jnp.where(
        zero,
        jnp.uint64(1),
        jnp.where(in7, jnp.uint64(9), jnp.where(in9, jnp.uint64(12), jnp.where(in12, jnp.uint64(16), jnp.uint64(4 + db)))),
    )
    val7 = (jnp.uint64(0b10) << 7) | (ud & mask_low(7))
    val9 = (jnp.uint64(0b110) << 9) | (ud & mask_low(9))
    val12 = (jnp.uint64(0b1110) << 12) | (ud & mask_low(12))
    if db == 32:
        val_def_hi = jnp.zeros_like(ud)
        val_def_lo = (jnp.uint64(0b1111) << 32) | (ud & mask_low(32))
    else:
        val_def_hi = jnp.full_like(ud, jnp.uint64(0b1111))
        val_def_lo = ud
    lo = jnp.where(
        zero, jnp.uint64(0), jnp.where(in7, val7, jnp.where(in9, val9, jnp.where(in12, val12, val_def_lo)))
    )
    hi = jnp.where(zero | in7 | in9 | in12, jnp.uint64(0), val_def_hi)
    return hi, lo, length


def _xor_fields(xor: jnp.ndarray, prev_xor: jnp.ndarray):
    """Per-element XOR value field (hi, lo, len).

    Zero / contained / uncontained opcodes per the reference float codec
    (/root/reference/src/dbnode/encoding/m3tsz/float_encoder_iterator.go:82-103).
    """
    pl, pt = clz64(prev_xor), ctz64(prev_xor)
    cl, ct = clz64(xor), ctz64(xor)
    zero = xor == 0
    contained = (cl >= pl) & (ct >= pt) & ~zero

    # contained: '10' + xor >> prev_trailing in (64 - pl - pt) bits
    m_prev = jnp.uint64(64) - pl - pt
    c_lo_val = shr(xor, pt)
    c_len = jnp.uint64(2) + m_prev
    # field value = (0b10 << m_prev) | mantissa; may reach 66 bits
    c_hi = shr(jnp.uint64(0b10), jnp.uint64(64) - m_prev)
    c_lo = shl(jnp.uint64(0b10), m_prev) | c_lo_val

    # uncontained: '11' + 6-bit leading + 6-bit (m-1) + m bits
    m = jnp.uint64(64) - cl - ct
    top = (jnp.uint64(0b11) << 12) | (cl << 6) | (m - jnp.uint64(1))  # 14 bits
    mant = shr(xor, ct)
    u_len = jnp.uint64(14) + m
    u_lo = shl(top, m) | mant
    u_hi = shr(top, jnp.uint64(64) - m)

    length = jnp.where(zero, jnp.uint64(1), jnp.where(contained, c_len, u_len))
    lo = jnp.where(zero, jnp.uint64(0), jnp.where(contained, c_lo, u_lo))
    hi = jnp.where(zero, jnp.uint64(0), jnp.where(contained, c_hi, u_hi))
    return hi, lo, length


def _trunc_div(a: jnp.ndarray, b: int) -> jnp.ndarray:
    """Go-style truncating integer division (toward zero)."""
    q = jnp.abs(a) // b
    return jnp.where(a < 0, -q, q).astype(I64)


def encode(
    times: jnp.ndarray,
    values: jnp.ndarray,  # [B, T] float64
    start: jnp.ndarray,
    n_points: jnp.ndarray,
    unit: TimeUnit = TimeUnit.SECOND,
    capacity_words: int | None = None,
    impl: str | None = None,
) -> EncodedBlocks:
    """Encode from float64 values.

    Host/CPU convenience wrapper: the TPU X64 rewriter implements the
    u64->f64 bitcast but NOT the f64->u64 direction (per the rewriter's
    lowering rules; NOT verified on TPU hardware from this environment —
    the tunnel has been down every round), so the
    jitted kernel (encode_bits) takes pre-bitcast uint64 value bits — a free
    numpy view on the host ingest path, and the device-resident
    representation the storage engine keeps anyway. decode's u64->f64
    direction runs fine on-device.
    """
    unit_ns = unit_value_ns(unit)
    if (np.asarray(start) % unit_ns != 0).any():
        raise ValueError(
            f"block start must be aligned to the encode unit ({unit.name}); "
            "the batched kernel never writes time-unit-change markers"
        )
    # Always bitcast on the host: the f64->u64 direction is unimplemented by
    # the TPU X64 rewriter, so device-resident callers should hold bits and
    # call encode_bits directly instead of round-tripping through floats.
    vb = jnp.asarray(np.asarray(values, dtype=np.float64).view(np.uint64))
    return encode_bits(times, vb, start, n_points, unit, capacity_words, impl)


def encode_bits(
    times: jnp.ndarray,  # [B, T] int64 unix nanos
    value_bits: jnp.ndarray,  # [B, T] uint64 IEEE-754 bit patterns
    start: jnp.ndarray,  # [B] int64 block start unix nanos
    n_points: jnp.ndarray,  # [B] int32 valid points per series
    unit: TimeUnit = TimeUnit.SECOND,
    capacity_words: int | None = None,
    impl: str | None = None,
) -> EncodedBlocks:
    """Batched M3TSZ float-mode encode of B series with up to T points
    each. `impl` selects the packer backend (resolved per platform by
    default); it keys the jit cache so env/impl changes retrace."""
    return _encode_bits_jit(times, value_bits, start, n_points, unit,
                            capacity_words, _resolve_impl(impl))


@functools.partial(jax.jit, static_argnames=("unit", "capacity_words", "impl"))
def _encode_bits_jit(
    times: jnp.ndarray,
    value_bits: jnp.ndarray,
    start: jnp.ndarray,
    n_points: jnp.ndarray,
    unit: TimeUnit = TimeUnit.SECOND,
    capacity_words: int | None = None,
    impl: str = "tree",
) -> EncodedBlocks:
    B, T = times.shape  # noqa: N806
    unit_ns = unit_value_ns(unit)
    default_bits = 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64
    if capacity_words is None:
        capacity_words = (64 + MAX_BITS_PER_DP * T + 11 + 63) // 64

    times = times.astype(I64)
    idx = jnp.arange(T)
    valid = idx[None, :] < n_points[:, None]

    # --- timestamp fields ---
    prev_t = jnp.concatenate([start[:, None].astype(I64), times[:, :-1]], axis=1)
    dt = times - prev_t
    prev_dt = jnp.concatenate([jnp.zeros((B, 1), I64), dt[:, :-1]], axis=1)
    dod_ns = dt - prev_dt
    dod_units = _trunc_div(dod_ns, unit_ns)
    ts_hi, ts_lo, ts_len = _dod_fields(dod_units, default_bits)

    # --- value fields ---
    vb = value_bits.astype(U64)
    prev_vb = jnp.concatenate([jnp.zeros((B, 1), U64), vb[:, :-1]], axis=1)
    xor = vb ^ prev_vb
    # prev_xor chain: prev_xor[i] = xor[i-1]; xor[0] == vb[0] which is
    # exactly the prevXOR state after the first (full) float write.
    prev_xor = jnp.concatenate([jnp.zeros((B, 1), U64), xor[:, :-1]], axis=1)
    x_hi, x_lo, x_len = _xor_fields(xor, prev_xor)
    # first datapoint: raw 64-bit float
    is_first = idx[None, :] == 0
    v_hi = jnp.where(is_first, jnp.uint64(0), x_hi)
    v_lo = jnp.where(is_first, vb, x_lo)
    v_len = jnp.where(is_first, jnp.uint64(64), x_len)

    # --- layout ---
    dp_len = jnp.where(valid, ts_len + v_len, jnp.uint64(0))
    end_off = jnp.uint64(64) + jnp.sum(dp_len, axis=1)
    total_bits = end_off + _EOS_LEN
    # A start that isn't a multiple of the unit would make the scalar
    # encoder emit a time-unit-change marker (initial_time_unit -> NONE);
    # this kernel never writes markers, so flag the batch as unusable.
    misaligned = jnp.any(start.astype(I64) % unit_ns != 0)
    overflow = jnp.any(total_bits > jnp.uint64(capacity_words * 64)) | misaligned
    if default_bits == 32:
        # The scalar encoder raises when a dod exceeds the 32-bit default
        # bucket for s/ms units (timestamp_encoder semantics); the batch
        # kernel can't raise mid-trace, so flag the batch unusable instead.
        in32 = (dod_units >= -(1 << 31)) & (dod_units <= (1 << 31) - 1)
        overflow = overflow | jnp.any(valid & ~in32)

    words = _pack_stream(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len,
                         valid, start, capacity_words, impl)
    return EncodedBlocks(words=words, bit_lengths=total_bits, overflow=overflow)


_DP_LIMBS = 7  # one datapoint's (ts + value) fields: <=196 bits -> 7 u32 limbs


def _resolve_impl(impl: str | None = None) -> str:
    """Implementation choice, resolved OUTSIDE jit so it can key the jit
    cache: the log-tree/shifting-buffer u32 kernels ('tree') avoid the
    scatter/gather + u64-emulation costs that dominate on TPU; CPU XLA
    lowers the original scatter/gather design ('scatter') several times
    faster. Overridable via M3_CODEC_IMPL=tree|scatter."""
    import os

    impl = impl or os.environ.get("M3_CODEC_IMPL")
    if impl is not None and impl not in ("tree", "scatter"):
        raise ValueError(f"unknown codec impl {impl!r}: want 'tree' or 'scatter'")
    if impl is not None:
        return impl
    return "scatter" if jax.default_backend() == "cpu" else "tree"


def _pack_stream(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len, valid,
                 start, capacity_words: int, impl: str) -> jnp.ndarray:
    """Stream packer, dispatched on the statically-resolved impl."""
    if impl == "tree":
        return _pack_stream_tree(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len,
                                 valid, start, capacity_words)
    return _pack_stream_scatter(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len,
                                valid, start, capacity_words)


def _pack_stream_scatter(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len, valid,
                         start, capacity_words: int) -> jnp.ndarray:
    """Assemble per-dp (timestamp, value) fields into word tensors via the
    192-bit register + disjoint scatter-add scheme, and cap with EOS.
    CPU path: XLA:CPU lowers these scatters well; on TPU they cost ~12ns
    per scattered element."""
    B, T = ts_len.shape  # noqa: N806
    dp_len = jnp.where(valid, ts_len + v_len, jnp.uint64(0))
    csum = jnp.cumsum(dp_len, axis=1)
    offsets = jnp.uint64(64) + csum - dp_len
    end_off = (jnp.uint64(64) + csum[:, -1]) if T > 0 else jnp.full((B,), 64, U64)
    zero_reg = (jnp.zeros((B, T), U64),) * 3
    reg = reg3_insert(zero_reg, jnp.uint64(0), ts_hi, ts_lo, ts_len)
    reg = reg3_insert(reg, ts_len, v_hi, v_lo, v_len)
    r = offsets & jnp.uint64(63)
    pieces = reg3_shift_right_to4(reg, r)
    w0 = (offsets >> jnp.uint64(6)).astype(jnp.int32)

    words = jnp.zeros((B, capacity_words), U64)
    # 64-bit start prefix occupies word 0 of every series.
    words = words.at[:, 0].set(start.astype(I64).astype(U64))
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    for k, piece in enumerate(pieces):
        words = words.at[b_idx, w0 + k].add(jnp.where(valid, piece, jnp.uint64(0)), mode="drop")

    # --- EOS marker ---
    eos_reg = reg3_insert(
        (jnp.zeros((B,), U64),) * 3, jnp.uint64(0), jnp.zeros((B,), U64),
        jnp.uint64(_EOS_FIELD), jnp.uint64(_EOS_LEN)
    )
    eos_pieces = reg3_shift_right_to4(eos_reg, end_off & jnp.uint64(63))
    ew0 = (end_off >> jnp.uint64(6)).astype(jnp.int32)
    bb = jnp.arange(B)
    for k, piece in enumerate(eos_pieces):
        words = words.at[bb, ew0 + k].add(piece, mode="drop")

    return words


def _pack_stream_tree(ts_hi, ts_lo, ts_len, v_hi, v_lo, v_len, valid,
                      start, capacity_words: int) -> jnp.ndarray:
    """Assemble per-dp (timestamp, value) u64 bit fields into the output
    word tensor by log-tree bit concatenation — no scatter.

    Scatter on TPU costs on the order of ~10ns per scattered element
    (ESTIMATE from scatter's serialized lowering; no TPU run has validated
    it from this environment), which would make the original 4-piece
    scatter-add packer the encode bottleneck.
    Instead: each datapoint becomes a top-aligned u32 limb register; the
    [start prefix] + T dp registers + [EOS] slot sequence is then combined
    pairwise — result = A | (B >> lenA), with the variable shift decomposed
    into log2 static rolls (ops/bits32.py) — doubling register width each
    of the log2(T) levels until one register holds the whole stream. Pure
    elementwise u32 work that XLA fuses and tiles.
    """
    from m3_tpu.ops import bits32 as b32

    B, T = ts_len.shape  # noqa: N806
    w32_cap = capacity_words * 2

    ts_limbs = b32.field128_to_limbs(ts_hi, ts_lo, ts_len)  # [B, T, 4]
    v_limbs = b32.field128_to_limbs(v_hi, v_lo, v_len)
    ts_len32 = ts_len.astype(b32.U32)
    dp = b32.pad_limbs(ts_limbs, _DP_LIMBS) | b32.shift_right_bits(
        b32.pad_limbs(v_limbs, _DP_LIMBS), ts_len32, 128
    )
    dp_len = ts_len32 + v_len.astype(b32.U32)
    dp = jnp.where(valid[..., None], dp, jnp.uint32(0))
    dp_len = jnp.where(valid, dp_len, jnp.uint32(0))

    # slot sequence: [start(64b)] + T dps + [EOS(11b)], padded to a power
    # of two with zero-length slots (no-ops under concatenation).
    # All slots derive from traced data (zeros as 0*traced) — materialized
    # trace-time constants trip a jit fastpath bug ("supplied N buffers but
    # compiled program expected M") on repeat calls.
    s_hi, s_lo = b32.u64_to_pair(start.astype(I64).astype(U64))
    zcol = jnp.zeros_like(s_hi)  # [B] (shape-independent of T: T=0 works)
    start_slot = jnp.stack(
        [s_hi, s_lo] + [zcol] * (_DP_LIMBS - 2), axis=-1
    )[:, None, :]
    eos_slot = jnp.stack(
        [zcol + jnp.uint32(int(_EOS_FIELD) << 21)] + [zcol] * (_DP_LIMBS - 1),
        axis=-1,
    )[:, None, :]
    n_slots = T + 2
    n_pad = 1
    while n_pad < n_slots:
        n_pad *= 2
    pad_slots = [
        jnp.broadcast_to(zcol[:, None, None], (B, n_pad - n_slots, _DP_LIMBS))
    ] if n_pad > n_slots else []
    slots = jnp.concatenate([start_slot, dp, eos_slot] + pad_slots, axis=1)
    zlen = zcol[:, None]  # [B, 1]
    pad_lens = [
        jnp.broadcast_to(zlen, (B, n_pad - n_slots))
    ] if n_pad > n_slots else []
    lens = jnp.concatenate(
        [zlen + jnp.uint32(64), dp_len, zlen + jnp.uint32(int(_EOS_LEN))] + pad_lens,
        axis=1,
    )

    width = _DP_LIMBS
    while slots.shape[1] > 1:
        width = min(width * 2, max(w32_cap, _DP_LIMBS))
        a, bb = slots[:, 0::2], slots[:, 1::2]
        len_a, len_b = lens[:, 0::2], lens[:, 1::2]
        # clamp so pathological (overflowing) lengths still shift to zero
        shift = jnp.minimum(len_a, jnp.uint32(32 * width))
        slots = b32.pad_limbs(a, width) | b32.shift_right_bits(
            b32.pad_limbs(bb, width), shift, 32 * width
        )
        lens = len_a + len_b
    limbs = b32.pad_limbs(slots[:, 0], w32_cap)
    return b32.pair_to_u64(limbs[:, 0::2], limbs[:, 1::2])


def _decode_ts_fields(series_words, off, win, default_bits: int):
    """(dod_units int64, ts_len) decoded at the cursor (shared by the
    float-mode and int-optimized decode scans)."""
    b1 = shr(win, jnp.uint64(63))
    p2 = shr(win, jnp.uint64(62))
    p3 = shr(win, jnp.uint64(61))
    p4 = shr(win, jnp.uint64(60))
    zero = b1 == 0
    in7 = p2 == jnp.uint64(0b10)
    in9 = p3 == jnp.uint64(0b110)
    in12 = p4 == jnp.uint64(0b1110)
    d7 = sign_extend64(shr(win, jnp.uint64(55)), jnp.uint64(7))
    d9 = sign_extend64(shr(win, jnp.uint64(52)), jnp.uint64(9))
    d12 = sign_extend64(shr(win, jnp.uint64(48)), jnp.uint64(12))
    if default_bits == 32:
        ddef = sign_extend64(shr(win, jnp.uint64(28)), jnp.uint64(32))
    else:
        win2 = read_window(series_words, off + jnp.uint64(4))
        ddef = sign_extend64(win2, jnp.uint64(64))
    dod_u = jnp.where(
        zero, 0, jnp.where(in7, d7, jnp.where(in9, d9, jnp.where(in12, d12, ddef)))
    ).astype(I64)
    ts_len = jnp.where(
        zero,
        jnp.uint64(1),
        jnp.where(
            in7,
            jnp.uint64(9),
            jnp.where(in9, jnp.uint64(12), jnp.where(in12, jnp.uint64(16), jnp.uint64(4 + default_bits))),
        ),
    )
    return dod_u, ts_len


class DecodedBlocks(NamedTuple):
    times: jnp.ndarray  # [B, T] int64
    # IEEE-754 bit patterns, NOT floats: the TPU X64 rewriter emulates f64
    # as an f32 pair (f32 exponent range, ~48-bit mantissa), so a device
    # f64 cannot round-trip arbitrary doubles. Bits are exact everywhere;
    # convert with values_f64() on the host, or accept the documented
    # precision loss converting on-device.
    value_bits: jnp.ndarray  # [B, T] uint64
    valid: jnp.ndarray  # [B, T] bool
    n_points: jnp.ndarray  # [B] int32
    # True per series if a non-EOS special marker (annotation / time-unit
    # change) was hit: such streams carry host-path features and must be
    # decoded by the scalar decoder instead.
    error: jnp.ndarray  # [B] bool

    def values_f64(self) -> np.ndarray:
        """Decoded values as float64 (host-side bitcast; always exact)."""
        return np.asarray(jax.device_get(self.value_bits)).view(np.float64)


class DecodedValues(NamedTuple):
    """Decode result carrying materialized float values (int-optimized
    kernel, whose values are computed, not bit-copied)."""

    times: jnp.ndarray  # [B, T] int64
    values: jnp.ndarray  # [B, T] float64
    valid: jnp.ndarray  # [B, T] bool
    n_points: jnp.ndarray  # [B] int32
    error: jnp.ndarray  # [B] bool


def _sx(v: jnp.ndarray, n: int) -> jnp.ndarray:
    """Sign-extend the low n bits of a u32 to int64 (n <= 32, static)."""
    s = np.uint32(1 << (n - 1))
    m = np.uint32((1 << n) - 1) if n < 32 else np.uint32(0xFFFFFFFF)
    x = (v.astype(jnp.uint32) & m) ^ s
    return x.astype(I64) - jnp.int64(int(s))


def decode(
    words: jnp.ndarray,  # [B, W] uint64
    unit: TimeUnit = TimeUnit.SECOND,
    max_points: int = 1024,
    impl: str | None = None,
) -> DecodedBlocks:
    """Batched M3TSZ float-mode decode (platform dispatch; `impl` as in
    encode_bits)."""
    return _decode_jit(words, unit, max_points, _resolve_impl(impl))


@functools.partial(jax.jit, static_argnames=("unit", "max_points", "impl"))
def _decode_jit(
    words: jnp.ndarray,
    unit: TimeUnit,
    max_points: int,
    impl: str,
) -> DecodedBlocks:
    if impl == "tree":
        return _decode_shift(words, unit, max_points)
    return _decode_gather(words, unit, max_points)


def _decode_gather(
    words: jnp.ndarray,  # [B, W] uint64
    unit: TimeUnit = TimeUnit.SECOND,
    max_points: int = 1024,
) -> DecodedBlocks:
    """CPU decode: scan over points, vmapped over series, with per-step
    read_window gathers (XLA:CPU handles these well; on TPU each gather
    costs ~16ns/element)."""
    unit_ns = unit_value_ns(unit)
    default_bits = 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64

    def decode_one(series_words: jnp.ndarray):
        start = sign_extend64(series_words[0], jnp.uint64(64))

        def step(carry, i):
            off, prev_time, prev_dt, prev_bits, prev_xor, done, err = carry
            win = read_window(series_words, off)

            # special marker: 9-bit opcode 0x100 at the cursor; value 0 is
            # end-of-stream, anything else (annotation/time-unit change) is
            # a host-path feature this kernel doesn't decode -> error.
            is_marker = shr(win, jnp.uint64(55)) == jnp.uint64(0x100)
            marker_val = shr(win, jnp.uint64(53)) & jnp.uint64(3)
            err = err | (is_marker & (marker_val != 0) & ~done)
            is_eos = is_marker

            # --- delta-of-delta ---
            dod_u, ts_len = _decode_ts_fields(series_words, off, win, default_bits)
            new_dt = prev_dt + dod_u * unit_ns
            new_time = prev_time + new_dt

            # --- value ---
            voff = off + ts_len
            vwin = read_window(series_words, voff)
            first = i == 0
            vb1 = shr(vwin, jnp.uint64(63))
            vb2 = shr(vwin, jnp.uint64(62)) & jnp.uint64(1)
            xz = vb1 == 0
            contained = (vb1 == 1) & (vb2 == 0)
            # Mantissas can extend past a 64-bit window anchored at the
            # opcode (fields reach 78 bits), so each is read from a window
            # anchored at its own start.
            pl, pt = clz64(prev_xor), ctz64(prev_xor)
            m_prev = jnp.uint64(64) - pl - pt
            c_mant = shr(read_window(series_words, voff + jnp.uint64(2)), jnp.uint64(64) - m_prev)
            c_xor = shl(c_mant, pt)
            c_len = jnp.uint64(2) + m_prev
            lead = shr(vwin, jnp.uint64(56)) & jnp.uint64(0x3F)
            mm = (shr(vwin, jnp.uint64(50)) & jnp.uint64(0x3F)) + jnp.uint64(1)
            u_mant = shr(read_window(series_words, voff + jnp.uint64(14)), jnp.uint64(64) - mm)
            trail = jnp.uint64(64) - lead - mm
            u_xor = shl(u_mant, trail)
            u_len = jnp.uint64(14) + mm
            xor = jnp.where(xz, jnp.uint64(0), jnp.where(contained, c_xor, u_xor))
            x_len = jnp.where(xz, jnp.uint64(1), jnp.where(contained, c_len, u_len))

            new_bits = jnp.where(first, vwin, prev_bits ^ xor)
            new_xor = jnp.where(first, vwin, xor)
            v_len = jnp.where(first, jnp.uint64(64), x_len)

            ok = ~done & ~is_eos
            out_t = jnp.where(ok, new_time, 0)
            out_v = jnp.where(ok, new_bits, jnp.uint64(0))
            carry = (
                jnp.where(ok, off + ts_len + v_len, off),
                jnp.where(ok, new_time, prev_time),
                jnp.where(ok, new_dt, prev_dt),
                jnp.where(ok, new_bits, prev_bits),
                jnp.where(ok, new_xor, prev_xor),
                done | is_eos,
                err,
            )
            return carry, (out_t, out_v, ok)

        init = (
            jnp.uint64(64),
            start,
            jnp.int64(0),
            jnp.uint64(0),
            jnp.uint64(0),
            jnp.bool_(False),
            jnp.bool_(False),
        )
        carry, (ts, vs, ok) = lax.scan(step, init, jnp.arange(max_points))
        return ts, vs, ok, carry[-1]

    ts, vs, ok, err = jax.vmap(decode_one)(words)
    return DecodedBlocks(
        times=ts,
        value_bits=vs,
        valid=ok,
        n_points=ok.sum(axis=1).astype(jnp.int32),
        error=err,
    )


def _decode_shift(
    words: jnp.ndarray,  # [B, W] uint64
    unit: TimeUnit = TimeUnit.SECOND,
    max_points: int = 1024,
) -> DecodedBlocks:
    """Batched M3TSZ float-mode decode via a shifting stream buffer.

    The format is sequential per stream, but per-step RANDOM ACCESS is not
    required: the scan carries the remaining stream as a [B, W] u32 limb
    register and consumes each datapoint from its top — static slices for
    the parse, then a log-decomposed left shift by the datapoint's length.
    This replaces the per-step `read_window` gathers of the original design
    (an estimated ~10 gathers x O(10ns)/element/step would dominate decode;
    estimate, not measured on TPU from this environment) with pure
    elementwise work that XLA tiles; throughput comes from the batch axis
    and HBM bandwidth.
    """
    from m3_tpu.ops import bits32 as b32

    unit_ns = unit_value_ns(unit)
    default_bits = 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64
    B, W = words.shape  # noqa: N806

    start = sign_extend64(words[:, 0], jnp.uint64(64))  # [B] int64
    hi, lo = b32.u64_to_pair(words)
    limbs = jnp.stack([hi, lo], axis=-1).reshape(B, 2 * W)
    buf0 = limbs[:, 2:]  # the 64-bit start prefix is consumed up front
    if buf0.shape[1] < 8:  # parse window needs 8 limbs; tiny streams pad
        buf0 = b32.pad_limbs(buf0, 8)

    u32 = jnp.uint32

    def step(carry, i):
        buf, r, prev_time, prev_dt, pb_h, pb_l, px_h, px_l, done, err = carry

        # Align the next 224 bits at the cursor: funnel the first 8 limbs
        # by r (< 32). A datapoint spans <= 146 bits; with ts_len <= 68 the
        # value window needs bits [ts_len, ts_len + 96) <= 164 < 224.
        w = [buf[:, j] for j in range(8)]
        a = []
        for j in range(7):
            cur, nxt = w[j], w[j + 1]
            a.append(jnp.where(r == 0, cur, b32.shl32(cur, r) | b32.shr32(nxt, 32 - r)))
        a0, a1, a2 = a[0], a[1], a[2]

        # special marker: 9-bit opcode 0x100; value 0 = EOS, else a
        # host-path feature (annotation / time-unit change) -> error.
        is_marker = (a0 >> u32(23)) == u32(0x100)
        marker_val = (a0 >> u32(21)) & u32(3)
        err = err | (is_marker & (marker_val != 0) & ~done)
        is_eos = is_marker

        # --- delta-of-delta (static bit positions within a0..a2) ---
        zero_dod = (a0 >> u32(31)) == 0
        in7 = (a0 >> u32(30)) == u32(0b10)
        in9 = (a0 >> u32(29)) == u32(0b110)
        in12 = (a0 >> u32(28)) == u32(0b1110)
        d7 = _sx(a0 >> u32(23), 7)
        d9 = _sx(a0 >> u32(20), 9)
        d12 = _sx(a0 >> u32(16), 12)
        if default_bits == 32:
            ddef = _sx((a0 << u32(4)) | (a1 >> u32(28)), 32)
        else:
            ddef = sign_extend64(
                b32.pair_to_u64(
                    (a0 << u32(4)) | (a1 >> u32(28)),
                    (a1 << u32(4)) | (a2 >> u32(28)),
                ),
                jnp.uint64(64),
            )
        dod = jnp.where(
            zero_dod, jnp.int64(0),
            jnp.where(in7, d7, jnp.where(in9, d9, jnp.where(in12, d12, ddef))),
        )
        ts_len = jnp.where(
            zero_dod, u32(1),
            jnp.where(in7, u32(9),
                      jnp.where(in9, u32(12),
                                jnp.where(in12, u32(16), u32(4 + default_bits)))),
        )
        new_dt = prev_dt + dod * unit_ns
        new_time = prev_time + new_dt

        # --- value field at bit offset ts_len: word-select + funnel ---
        ws = ts_len >> u32(5)  # 0..2
        tb = ts_len & u32(31)
        v = []
        for j in range(3):
            c0 = jnp.where(ws == 0, a[j], jnp.where(ws == 1, a[j + 1], a[j + 2]))
            c1 = jnp.where(ws == 0, a[j + 1], jnp.where(ws == 1, a[j + 2], a[j + 3]))
            v.append(jnp.where(tb == 0, c0, b32.shl32(c0, tb) | b32.shr32(c1, 32 - tb)))
        v0, v1, v2 = v

        first = i == 0
        vb1 = v0 >> u32(31)
        vb2 = (v0 >> u32(30)) & u32(1)
        xz = vb1 == 0
        contained = (vb1 == 1) & (vb2 == 0)
        pl = b32.pair_clz(px_h, px_l)
        pt = b32.pair_ctz(px_h, px_l)
        m_prev = u32(64) - pl - pt
        # contained: mantissa window at field offset 2
        cw_h = (v0 << u32(2)) | (v1 >> u32(30))
        cw_l = (v1 << u32(2)) | (v2 >> u32(30))
        cm_h, cm_l = b32.pair_shr(cw_h, cw_l, u32(64) - m_prev)
        cx_h, cx_l = b32.pair_shl(cm_h, cm_l, pt)
        c_len = u32(2) + m_prev
        # uncontained: '11' + 6b lead + 6b (m-1) + m mantissa bits at offset 14
        lead = (v0 >> u32(24)) & u32(0x3F)
        mm = ((v0 >> u32(18)) & u32(0x3F)) + u32(1)
        uw_h = (v0 << u32(14)) | (v1 >> u32(18))
        uw_l = (v1 << u32(14)) | (v2 >> u32(18))
        um_h, um_l = b32.pair_shr(uw_h, uw_l, u32(64) - mm)
        trail = u32(64) - lead - mm
        ux_h, ux_l = b32.pair_shl(um_h, um_l, trail)
        u_len = u32(14) + mm

        xor_h = jnp.where(xz, u32(0), jnp.where(contained, cx_h, ux_h))
        xor_l = jnp.where(xz, u32(0), jnp.where(contained, cx_l, ux_l))
        x_len = jnp.where(xz, u32(1), jnp.where(contained, c_len, u_len))

        nb_h = jnp.where(first, v0, pb_h ^ xor_h)
        nb_l = jnp.where(first, v1, pb_l ^ xor_l)
        nx_h = jnp.where(first, v0, xor_h)
        nx_l = jnp.where(first, v1, xor_l)
        v_len = jnp.where(first, u32(64), x_len)

        ok = ~done & ~is_eos
        dp_len = ts_len + v_len
        r2 = r + jnp.where(ok, dp_len, u32(0))
        buf2 = b32.roll_left_words(buf, r2 >> u32(5), 6)
        r3 = r2 & u32(31)

        out_t = jnp.where(ok, new_time, jnp.int64(0))
        carry = (
            buf2,
            r3,
            jnp.where(ok, new_time, prev_time),
            jnp.where(ok, new_dt, prev_dt),
            jnp.where(ok, nb_h, pb_h),
            jnp.where(ok, nb_l, pb_l),
            jnp.where(ok, nx_h, px_h),
            jnp.where(ok, nx_l, px_l),
            done | is_eos,
            err,
        )
        return carry, (out_t, jnp.where(ok, nb_h, u32(0)),
                       jnp.where(ok, nb_l, u32(0)), ok)

    zb = jnp.zeros((B,), u32)
    init = (
        buf0,
        zb,
        start,
        jnp.zeros((B,), I64),
        zb, zb, zb, zb,
        jnp.zeros((B,), bool),
        jnp.zeros((B,), bool),
    )
    carry, (ts, vh, vl, ok) = lax.scan(step, init, jnp.arange(max_points))
    err = carry[-1]
    return DecodedBlocks(
        times=ts.T,
        value_bits=b32.pair_to_u64(vh.T, vl.T),
        valid=ok.T,
        n_points=ok.T.sum(axis=1).astype(jnp.int32),
        error=err,
    )


def blocks_to_bytes(blocks: EncodedBlocks) -> list[bytes]:
    """Materialize encoded device blocks as per-series byte strings
    (host-side, for persistence/interop with the scalar codec)."""
    words = jax.device_get(blocks.words)
    bits = jax.device_get(blocks.bit_lengths)
    out = []
    for row, nbits in zip(words, bits):
        nbytes = (int(nbits) + 7) // 8
        raw = b"".join(int(w).to_bytes(8, "big") for w in row[: (nbytes + 7) // 8])
        out.append(raw[:nbytes])
    return out


def bytes_to_words(streams: list[bytes], capacity_words: int | None = None) -> jnp.ndarray:
    """Pack byte streams into a [B, W] uint64 word tensor for decode."""
    if capacity_words is None:
        capacity_words = max((len(s) + 7) // 8 for s in streams) if streams else 1
    arr = np.zeros((len(streams), capacity_words), dtype=np.uint64)
    for i, s in enumerate(streams):
        padded = s + b"\x00" * (-len(s) % 8)
        arr[i, : len(padded) // 8] = np.frombuffer(padded, dtype=">u8").astype(np.uint64)
    return jnp.asarray(arr)
