"""M3TSZ decoder — host-side scalar reference implementation.

Decodes streams produced by the reference encoder or by this package's
encoders (scalar and TPU); semantics mirror the reference reader iterator
(/root/reference/src/dbnode/encoding/m3tsz/{iterator,timestamp_iterator}.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from m3_tpu.encoding.m3tsz import constants as c
from m3_tpu.utils.bitstream import IStream, sign_extend
from m3_tpu.utils.xtime import (
    TimeUnit,
    from_normalized,
    initial_time_unit,
    unit_is_valid,
    unit_value_ns,
)

_NUM_MARKER_BITS = c.NUM_MARKER_OPCODE_BITS + c.NUM_MARKER_VALUE_BITS


def read_varint(stream: IStream) -> int:
    """Zigzag LEB128 varint (Go encoding/binary.Varint)."""
    uv = 0
    shift = 0
    while True:
        b = stream.read_byte()
        uv |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (uv >> 1) if not uv & 1 else -((uv + 1) >> 1)


@dataclass
class Datapoint:
    timestamp_ns: int
    value: float
    unit: TimeUnit = TimeUnit.NONE
    annotation: bytes = b""


@dataclass
class _TimestampIterator:
    default_time_unit: TimeUnit = TimeUnit.SECOND
    prev_time: int = 0
    prev_time_delta: int = 0
    prev_annotation: bytes = b""
    time_unit: TimeUnit = TimeUnit.NONE
    time_unit_changed: bool = False
    done: bool = False
    scheme: object = None
    cur_annotation: bytes = field(default=b"", repr=False)
    has_read_first: bool = False

    def read_timestamp(self, stream: IStream) -> bool:
        """Advance one timestamp; returns True if this was the first read.

        Uses an explicit first-read flag rather than the reference's
        ``PrevTime != 0`` check (timestamp_iterator.go:62), which
        misclassifies a datapoint landing exactly on the unix epoch; behavior
        is identical for every other stream.
        """
        self.cur_annotation = b""
        if self.has_read_first:
            dod = self._read_marker_or_dod(stream)
            if not self.done:
                self.prev_time_delta += dod
                self.prev_time += self.prev_time_delta
            first = False
        else:
            self._read_first_timestamp(stream)
            self.has_read_first = True
            first = True
        if self.time_unit_changed:
            self.prev_time_delta = 0
            self.time_unit_changed = False
        return first

    def _read_first_timestamp(self, stream: IStream) -> None:
        # First time is a signed 64-bit unix-nano (may be pre-1970).
        start = sign_extend(stream.read_bits(64), 64)
        if self.time_unit == TimeUnit.NONE:
            self.time_unit = initial_time_unit(start, self.default_time_unit)
        self.scheme = c.TIME_ENCODING_SCHEMES.get(self.time_unit)
        dod = self._read_marker_or_dod(stream)
        if not self.done:
            self.prev_time_delta += dod
        self.prev_time = start + self.prev_time_delta

    def _read_marker_or_dod(self, stream: IStream) -> int:
        try:
            opcode_and_value = stream.peek_bits(_NUM_MARKER_BITS)
        except EOFError:
            return self._read_dod(stream)
        opcode = opcode_and_value >> c.NUM_MARKER_VALUE_BITS
        if opcode != c.MARKER_OPCODE:
            return self._read_dod(stream)
        marker = opcode_and_value & ((1 << c.NUM_MARKER_VALUE_BITS) - 1)
        if marker == c.MARKER_END_OF_STREAM:
            stream.read_bits(_NUM_MARKER_BITS)
            self.done = True
            return 0
        elif marker == c.MARKER_ANNOTATION:
            stream.read_bits(_NUM_MARKER_BITS)
            self._read_annotation(stream)
            return self._read_marker_or_dod(stream)
        elif marker == c.MARKER_TIME_UNIT:
            stream.read_bits(_NUM_MARKER_BITS)
            self._read_time_unit(stream)
            return self._read_marker_or_dod(stream)
        return self._read_dod(stream)

    def _read_annotation(self, stream: IStream) -> None:
        n = read_varint(stream) + 1
        if n <= 0:
            raise ValueError(f"expected annotation length to be > 0, got {n}")
        ant = stream.read_bytes(n)
        self.prev_annotation = ant
        self.cur_annotation = ant

    def _read_time_unit(self, stream: IStream) -> None:
        tu = stream.read_byte()
        if unit_is_valid(tu) and TimeUnit(tu) != self.time_unit:
            self.time_unit_changed = True
            self.scheme = c.TIME_ENCODING_SCHEMES.get(TimeUnit(tu))
        self.time_unit = TimeUnit(tu)

    def _read_dod(self, stream: IStream) -> int:
        if self.time_unit_changed:
            # Full 64-bit delta-of-delta in nanos after a unit change.
            self.scheme = c.TIME_ENCODING_SCHEMES.get(self.time_unit)
            return sign_extend(stream.read_bits(64), 64)
        scheme = self.scheme
        if scheme is None:
            raise ValueError(f"no time encoding scheme for unit {self.time_unit}")
        cb = stream.read_bits(1)
        if cb == scheme.zero_bucket.opcode:
            return 0
        for bucket in scheme.buckets:
            cb = (cb << 1) | stream.read_bits(1)
            if cb == bucket.opcode:
                dod = sign_extend(stream.read_bits(bucket.num_value_bits), bucket.num_value_bits)
                return from_normalized(dod, unit_value_ns(self.time_unit))
        nvb = scheme.default_bucket.num_value_bits
        dod = sign_extend(stream.read_bits(nvb), nvb)
        return from_normalized(dod, unit_value_ns(self.time_unit))


class ReaderIterator:
    """Iterates datapoints out of a single M3TSZ stream."""

    def __init__(
        self,
        data: bytes,
        int_optimized: bool = True,
        default_time_unit: TimeUnit = TimeUnit.SECOND,
    ) -> None:
        self._stream = IStream(data)
        self._ts = _TimestampIterator(default_time_unit=default_time_unit)
        self._int_optimized = int_optimized
        self._is_float = False
        self._int_val = 0.0
        self._mult = 0
        self._sig = 0
        self._prev_float_bits = 0
        self._prev_xor = 0
        self._float_not_first = False

    def __iter__(self):
        if self._stream.remaining_bits == 0:
            return
        while True:
            first = self._ts.read_timestamp(self._stream)
            if self._ts.done:
                return
            if first:
                self._read_first_value()
            else:
                self._read_next_value()
            if not self._int_optimized or self._is_float:
                value = c.bits_to_float(self._prev_float_bits)
            else:
                value = c.convert_from_int_float(self._int_val, self._mult)
            yield Datapoint(
                timestamp_ns=self._ts.prev_time,
                value=value,
                unit=self._ts.time_unit,
                annotation=self._ts.cur_annotation,
            )

    # -- float XOR stream --

    def _read_full_float(self) -> None:
        bits = self._stream.read_bits(64)
        self._prev_float_bits = bits
        self._prev_xor = bits

    def _read_next_float(self) -> None:
        if not self._stream.read_bits(1):
            self._prev_xor = 0
            return
        if self._stream.read_bits(1) == 0:  # contained
            prev_leading = 64 - self._prev_xor.bit_length() if self._prev_xor else 64
            prev_trailing = (
                ((self._prev_xor & -self._prev_xor).bit_length() - 1) if self._prev_xor else 0
            )
            num_meaningful = 64 - prev_leading - prev_trailing
            bits = self._stream.read_bits(num_meaningful)
            self._prev_xor = bits << prev_trailing
        else:  # uncontained
            lead_and_len = self._stream.read_bits(12)
            num_leading = (lead_and_len >> 6) & 0x3F
            num_meaningful = (lead_and_len & 0x3F) + 1
            bits = self._stream.read_bits(num_meaningful)
            num_trailing = 64 - num_leading - num_meaningful
            self._prev_xor = bits << num_trailing
        self._prev_float_bits ^= self._prev_xor

    # -- value decode --

    def _read_first_value(self) -> None:
        if not self._int_optimized:
            self._read_full_float()
            return
        if self._stream.read_bits(1) == c.OPCODE_FLOAT_MODE:
            self._read_full_float()
            self._is_float = True
            return
        self._read_int_sig_mult()
        self._read_int_val_diff()

    def _read_next_value(self) -> None:
        if not self._int_optimized:
            self._read_next_float()
            return
        if self._stream.read_bits(1) == c.OPCODE_UPDATE:
            if self._stream.read_bits(1) == c.OPCODE_REPEAT:
                return
            if self._stream.read_bits(1) == c.OPCODE_FLOAT_MODE:
                self._read_full_float()
                self._is_float = True
                return
            self._read_int_sig_mult()
            self._read_int_val_diff()
            self._is_float = False
            return
        if self._is_float:
            self._read_next_float()
            return
        self._read_int_val_diff()

    def _read_int_sig_mult(self) -> None:
        if self._stream.read_bits(1) == c.OPCODE_UPDATE_SIG:
            if self._stream.read_bits(1) == c.OPCODE_ZERO_SIG:
                self._sig = 0
            else:
                self._sig = self._stream.read_bits(c.NUM_SIG_BITS) + 1
        if self._stream.read_bits(1) == c.OPCODE_UPDATE_MULT:
            self._mult = self._stream.read_bits(c.NUM_MULT_BITS)
            if self._mult > c.MAX_MULT:
                raise ValueError("invalid multiplier")

    def _read_int_val_diff(self) -> None:
        if self._sig == 64:
            sign = 1.0 if self._stream.read_bits(1) == c.OPCODE_NEGATIVE else -1.0
            self._int_val += sign * float(self._stream.read_bits(self._sig))
            return
        bits = self._stream.read_bits(self._sig + 1)
        sign = -1.0
        if (bits >> self._sig) == c.OPCODE_NEGATIVE:
            sign = 1.0
            bits ^= 1 << self._sig
        self._int_val += sign * float(bits)


def decode(
    data: bytes,
    int_optimized: bool = True,
    default_time_unit: TimeUnit = TimeUnit.SECOND,
) -> list[Datapoint]:
    return list(ReaderIterator(data, int_optimized, default_time_unit))
