"""M3TSZ wire-format constants.

Bit-compatible with the reference scheme
(/root/reference/src/dbnode/encoding/m3tsz/m3tsz.go:28-62 and
/root/reference/src/dbnode/encoding/scheme.go:28-58).
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass

from m3_tpu.utils.xtime import TimeUnit

# Value XOR opcodes.
OPCODE_ZERO_VALUE_XOR = 0x0
OPCODE_CONTAINED_VALUE_XOR = 0x2
OPCODE_UNCONTAINED_VALUE_XOR = 0x3

# Int-optimization opcodes.
OPCODE_ZERO_SIG = 0x0
OPCODE_NON_ZERO_SIG = 0x1
NUM_SIG_BITS = 6
OPCODE_NO_UPDATE_SIG = 0x0
OPCODE_UPDATE_SIG = 0x1
OPCODE_UPDATE = 0x0
OPCODE_NO_UPDATE = 0x1
OPCODE_UPDATE_MULT = 0x1
OPCODE_NO_UPDATE_MULT = 0x0
OPCODE_POSITIVE = 0x0
OPCODE_NEGATIVE = 0x1
OPCODE_REPEAT = 0x1
OPCODE_NO_REPEAT = 0x0
OPCODE_FLOAT_MODE = 0x1
OPCODE_INT_MODE = 0x0

SIG_DIFF_THRESHOLD = 3
SIG_REPEAT_THRESHOLD = 5

MAX_MULT = 6
NUM_MULT_BITS = 3
MAX_OPT_INT = 10.0**13
MULTIPLIERS = [10.0**i for i in range(MAX_MULT + 1)]

MAX_INT = float(2**63 - 1)
MIN_INT = float(-(2**63))

# Marker scheme: 9-bit opcode 0x100 + 2-bit marker value.
MARKER_OPCODE = 0x100
NUM_MARKER_OPCODE_BITS = 9
NUM_MARKER_VALUE_BITS = 2
MARKER_END_OF_STREAM = 0
MARKER_ANNOTATION = 1
MARKER_TIME_UNIT = 2


@dataclass(frozen=True)
class TimeBucket:
    opcode: int
    num_opcode_bits: int
    num_value_bits: int

    @property
    def min(self) -> int:
        return -(1 << (self.num_value_bits - 1))

    @property
    def max(self) -> int:
        return (1 << (self.num_value_bits - 1)) - 1


@dataclass(frozen=True)
class TimeEncodingScheme:
    zero_bucket: TimeBucket
    buckets: tuple[TimeBucket, ...]
    default_bucket: TimeBucket


def _make_scheme(value_bits: tuple[int, ...], default_value_bits: int) -> TimeEncodingScheme:
    buckets = []
    num_opcode_bits = 1
    opcode = 0
    for i, nvb in enumerate(value_bits):
        opcode = (1 << (i + 1)) | opcode
        buckets.append(TimeBucket(opcode, num_opcode_bits + 1, nvb))
        num_opcode_bits += 1
    default = TimeBucket(opcode | 0x1, num_opcode_bits, default_value_bits)
    return TimeEncodingScheme(TimeBucket(0x0, 1, 0), tuple(buckets), default)


_BUCKET_VALUE_BITS = (7, 9, 12)

TIME_ENCODING_SCHEMES: dict[TimeUnit, TimeEncodingScheme] = {
    TimeUnit.SECOND: _make_scheme(_BUCKET_VALUE_BITS, 32),
    TimeUnit.MILLISECOND: _make_scheme(_BUCKET_VALUE_BITS, 32),
    TimeUnit.MICROSECOND: _make_scheme(_BUCKET_VALUE_BITS, 64),
    TimeUnit.NANOSECOND: _make_scheme(_BUCKET_VALUE_BITS, 64),
}


def float_to_bits(v: float) -> int:
    return struct.unpack(">Q", struct.pack(">d", v))[0]


def bits_to_float(b: int) -> float:
    return struct.unpack(">d", struct.pack(">Q", b & ((1 << 64) - 1)))[0]


def convert_to_int_float(v: float, cur_max_mult: int) -> tuple[float, int, bool]:
    """Try to express v as int * 10^-mult.

    Returns (value, multiplier, is_float). Mirrors convertToIntFloat
    (reference m3tsz/m3tsz.go:78-119) including its rounding tolerance.
    """
    if cur_max_mult == 0 and v < MAX_INT:
        f, r = math.modf(v)
        if f == 0:
            return v, 0, False
    if cur_max_mult > MAX_MULT:
        raise ValueError("supplied multiplier is invalid")

    sign = -1.0 if v < 0 else 1.0
    for mult in range(cur_max_mult, MAX_MULT + 1):
        val = v * MULTIPLIERS[mult] * sign
        if val >= MAX_OPT_INT:
            break
        r, i = math.modf(val)
        if r == 0:
            return sign * i, mult, False
        elif r < 0.1:
            if math.nextafter(val, 0) <= i:
                return sign * i, mult, False
        elif r > 0.9:
            nxt = i + 1
            if math.nextafter(val, nxt) >= nxt:
                return sign * nxt, mult, False
    return v, 0, True


def convert_from_int_float(val: float, mult: int) -> float:
    if mult == 0:
        return val
    return val / MULTIPLIERS[mult]
