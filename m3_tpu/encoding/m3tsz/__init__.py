"""M3TSZ codec: scalar reference implementation + batched TPU kernels."""

from m3_tpu.encoding.m3tsz.decoder import Datapoint, ReaderIterator, decode
from m3_tpu.encoding.m3tsz.encoder import Encoder

__all__ = ["Datapoint", "Encoder", "ReaderIterator", "decode"]
