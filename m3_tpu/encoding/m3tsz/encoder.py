"""M3TSZ encoder — host-side scalar reference implementation.

Produces streams bit-identical to the reference encoder
(/root/reference/src/dbnode/encoding/m3tsz/{encoder,timestamp_encoder,
float_encoder_iterator,int_sig_bits_tracker}.go): delta-of-delta timestamps
with per-unit bucket schemes and special markers, XOR float compression, and
the float->scaled-int optimization.

One deliberate carve-out from bit-identity: integer-valued floats with
|value| >= 2^63 are encoded in float mode here, whereas the reference wraps
them through uint64(int64(v)) into int mode. Such streams differ from the
reference bit-for-bit but decode to the same values either way (our decoder
accepts both forms); the wraparound would otherwise corrupt the sig-bits
budget. See _write_first_value/_write_next_value.

This scalar path is the semantic ground truth that the batched TPU kernels
(m3_tpu.encoding.m3tsz.tpu) are property-tested against; it also serves the
control plane for small/one-off encodes where device dispatch would dominate.
"""

from __future__ import annotations

from m3_tpu.encoding.m3tsz import constants as c
from m3_tpu.utils.bitstream import OStream, leading_zeros64, num_sig, trailing_zeros64
from m3_tpu.utils.xtime import (
    TimeUnit,
    initial_time_unit,
    to_normalized,
    unit_is_valid,
    unit_value_ns,
)


def write_varint(os: OStream, v: int) -> None:
    """Zigzag LEB128 varint (Go encoding/binary.PutVarint)."""
    uv = 2 * v if v >= 0 else -2 * v - 1
    while uv >= 0x80:
        os.write_byte((uv & 0x7F) | 0x80)
        uv >>= 7
    os.write_byte(uv)


class TimestampEncoder:
    """Delta-of-delta timestamp stream state."""

    def __init__(self, start_ns: int, time_unit: TimeUnit) -> None:
        self.prev_time = start_ns
        self.prev_time_delta = 0
        self.prev_annotation = b""
        self.time_unit = initial_time_unit(start_ns, time_unit)
        self.time_unit_encoded_manually = False
        self.has_written_first = False

    def write_time(self, os: OStream, t_ns: int, annotation: bytes, unit: TimeUnit) -> None:
        if not self.has_written_first:
            # First time is always raw nanos: start may not be unit-aligned.
            os.write_bits(self.prev_time & ((1 << 64) - 1), 64)
            self.has_written_first = True
        self._write_next_time(os, t_ns, annotation, unit)

    def _write_next_time(self, os: OStream, t_ns: int, annotation: bytes, unit: TimeUnit) -> None:
        self._write_annotation(os, annotation)
        tu_changed = self._maybe_write_time_unit_change(os, unit)

        time_delta = t_ns - self.prev_time
        self.prev_time = t_ns
        if tu_changed or self.time_unit_encoded_manually:
            # Unit changed: full 64-bit delta-of-delta in nanos, then reset the
            # delta since it may not be a multiple of the new unit.
            dod = time_delta - self.prev_time_delta
            os.write_bits(dod & ((1 << 64) - 1), 64)
            self.prev_time_delta = 0
            self.time_unit_encoded_manually = False
            return
        self._write_dod(os, self.prev_time_delta, time_delta, unit)
        self.prev_time_delta = time_delta

    def write_time_unit(self, os: OStream, unit: TimeUnit) -> None:
        os.write_byte(int(unit))
        self.time_unit = unit
        self.time_unit_encoded_manually = True

    def _maybe_write_time_unit_change(self, os: OStream, unit: TimeUnit) -> bool:
        if not unit_is_valid(unit) or unit == self.time_unit:
            return False
        write_special_marker(os, c.MARKER_TIME_UNIT)
        self.write_time_unit(os, unit)
        return True

    def _write_annotation(self, os: OStream, annotation: bytes) -> None:
        if not annotation or annotation == self.prev_annotation:
            return
        write_special_marker(os, c.MARKER_ANNOTATION)
        write_varint(os, len(annotation) - 1)
        os.write_bytes(annotation)
        self.prev_annotation = bytes(annotation)

    def _write_dod(self, os: OStream, prev_delta: int, cur_delta: int, unit: TimeUnit) -> None:
        u = unit_value_ns(unit)
        dod = to_normalized(cur_delta - prev_delta, u)
        if unit in (TimeUnit.MILLISECOND, TimeUnit.SECOND):
            if not -(1 << 31) <= dod < (1 << 31):
                raise OverflowError(f"deltaOfDelta {dod} overflows 32 bits for unit {unit}")
        scheme = c.TIME_ENCODING_SCHEMES.get(TimeUnit(unit))
        if scheme is None:
            raise ValueError(f"no time encoding scheme for unit {unit}")
        if dod == 0:
            zb = scheme.zero_bucket
            os.write_bits(zb.opcode, zb.num_opcode_bits)
            return
        for b in scheme.buckets:
            if b.min <= dod <= b.max:
                os.write_bits(b.opcode, b.num_opcode_bits)
                os.write_bits(dod & ((1 << b.num_value_bits) - 1), b.num_value_bits)
                return
        db = scheme.default_bucket
        os.write_bits(db.opcode, db.num_opcode_bits)
        os.write_bits(dod & ((1 << db.num_value_bits) - 1), db.num_value_bits)


def write_special_marker(os: OStream, marker: int) -> None:
    os.write_bits(c.MARKER_OPCODE, c.NUM_MARKER_OPCODE_BITS)
    os.write_bits(marker, c.NUM_MARKER_VALUE_BITS)


def finalize_stream(os: OStream) -> bytes:
    """Cap a bit stream with the end-of-stream marker (shared by the
    m3tsz and proto encoders)."""
    if os.bit_length == 0:
        return b""
    raw, pos = os.raw()
    tail = OStream()
    if pos not in (0, 8):
        tail.write_bits(raw[-1] >> (8 - pos), pos)
        head = raw[:-1]
    else:
        head = raw
    write_special_marker(tail, c.MARKER_END_OF_STREAM)
    return head + tail.bytes_padded()


class FloatXOREncoder:
    """Gorilla-style XOR float stream state."""

    def __init__(self) -> None:
        self.prev_xor = 0
        self.prev_float_bits = 0
        self.not_first = False

    def write_full_float(self, os: OStream, bits: int) -> None:
        self.prev_float_bits = bits
        self.prev_xor = bits
        os.write_bits(bits, 64)
        self.not_first = True

    def write_next_float(self, os: OStream, bits: int) -> None:
        xor = self.prev_float_bits ^ bits
        self._write_xor(os, xor)
        self.prev_xor = xor
        self.prev_float_bits = bits

    def _write_xor(self, os: OStream, cur_xor: int) -> None:
        if cur_xor == 0:
            os.write_bits(c.OPCODE_ZERO_VALUE_XOR, 1)
            return
        prev_leading, prev_trailing = leading_zeros64(self.prev_xor), trailing_zeros64(self.prev_xor)
        cur_leading, cur_trailing = leading_zeros64(cur_xor), trailing_zeros64(cur_xor)
        if cur_leading >= prev_leading and cur_trailing >= prev_trailing:
            os.write_bits(c.OPCODE_CONTAINED_VALUE_XOR, 2)
            os.write_bits(cur_xor >> prev_trailing, 64 - prev_leading - prev_trailing)
            return
        os.write_bits(c.OPCODE_UNCONTAINED_VALUE_XOR, 2)
        os.write_bits(cur_leading, 6)
        num_meaningful = 64 - cur_leading - cur_trailing
        os.write_bits(num_meaningful - 1, 6)
        os.write_bits(cur_xor >> cur_trailing, num_meaningful)


class IntSigBitsTracker:
    """Significant-bit width tracker for int diffs
    (reference m3tsz/int_sig_bits_tracker.go)."""

    def __init__(self) -> None:
        self.num_sig = 0
        self.cur_highest_lower_sig = 0
        self.num_lower_sig = 0

    def write_int_val_diff(self, os: OStream, val_bits: int, neg: bool) -> None:
        os.write_bit(c.OPCODE_NEGATIVE if neg else c.OPCODE_POSITIVE)
        os.write_bits(val_bits, self.num_sig)

    def write_int_sig(self, os: OStream, sig: int) -> None:
        if self.num_sig != sig:
            os.write_bit(c.OPCODE_UPDATE_SIG)
            if sig == 0:
                os.write_bit(c.OPCODE_ZERO_SIG)
            else:
                os.write_bit(c.OPCODE_NON_ZERO_SIG)
                os.write_bits(sig - 1, c.NUM_SIG_BITS)
        else:
            os.write_bit(c.OPCODE_NO_UPDATE_SIG)
        self.num_sig = sig

    def track_new_sig(self, sig: int) -> int:
        new_sig = self.num_sig
        if sig > self.num_sig:
            new_sig = sig
        elif self.num_sig - sig >= c.SIG_DIFF_THRESHOLD:
            if self.num_lower_sig == 0:
                self.cur_highest_lower_sig = sig
            elif sig > self.cur_highest_lower_sig:
                self.cur_highest_lower_sig = sig
            self.num_lower_sig += 1
            if self.num_lower_sig >= c.SIG_REPEAT_THRESHOLD:
                new_sig = self.cur_highest_lower_sig
                self.num_lower_sig = 0
        else:
            self.num_lower_sig = 0
        return new_sig


class Encoder:
    """Single-series M3TSZ stream encoder."""

    def __init__(
        self,
        start_ns: int,
        int_optimized: bool = True,
        default_time_unit: TimeUnit = TimeUnit.SECOND,
    ) -> None:
        self._os = OStream()
        self._ts = TimestampEncoder(start_ns, default_time_unit)
        self._float = FloatXOREncoder()
        self._sig = IntSigBitsTracker()
        self._int_optimized = int_optimized
        self._int_val = 0.0
        self._max_mult = 0
        self._is_float = False
        self.num_encoded = 0

    def encode(
        self,
        t_ns: int,
        value: float,
        unit: TimeUnit = TimeUnit.SECOND,
        annotation: bytes = b"",
    ) -> None:
        self._ts.write_time(self._os, t_ns, annotation, unit)
        if self.num_encoded == 0:
            self._write_first_value(value)
        else:
            self._write_next_value(value)
        self.num_encoded += 1

    def _write_first_value(self, v: float) -> None:
        if not self._int_optimized:
            self._float.write_full_float(self._os, c.float_to_bits(v))
            return
        val, mult, is_float = c.convert_to_int_float(v, 0)
        # Values whose integer form needs > 63 bits can't take int mode: the
        # sig-bits field caps at 64 and the stream would be undecodable.
        if not is_float and abs(val) >= c.MAX_INT:
            val, is_float = v, True
        if is_float:
            self._os.write_bit(c.OPCODE_FLOAT_MODE)
            self._float.write_full_float(self._os, c.float_to_bits(v))
            self._is_float = True
            self._max_mult = mult
            return
        self._os.write_bit(c.OPCODE_INT_MODE)
        self._int_val = val
        neg_diff = True
        if val < 0:
            neg_diff = False
            val = -val
        val_bits = int(val)
        sig = num_sig(val_bits)
        self._write_int_sig_mult(sig, mult, False)
        self._sig.write_int_val_diff(self._os, val_bits, neg_diff)

    def _write_next_value(self, v: float) -> None:
        if not self._int_optimized:
            self._float.write_next_float(self._os, c.float_to_bits(v))
            return
        val, mult, is_float = c.convert_to_int_float(v, self._max_mult)
        if not is_float and abs(val) >= c.MAX_INT:
            val, is_float = v, True
        val_diff = 0.0
        if not is_float:
            val_diff = self._int_val - val
        if is_float or val_diff >= c.MAX_INT or val_diff <= c.MIN_INT:
            self._write_float_val(c.float_to_bits(val), mult)
            return
        self._write_int_val(val, mult, is_float, val_diff)

    def _write_float_val(self, bits: int, mult: int) -> None:
        if not self._is_float:
            self._os.write_bit(c.OPCODE_UPDATE)
            self._os.write_bit(c.OPCODE_NO_REPEAT)
            self._os.write_bit(c.OPCODE_FLOAT_MODE)
            self._float.write_full_float(self._os, bits)
            self._is_float = True
            self._max_mult = mult
            return
        if bits == self._float.prev_float_bits:
            self._os.write_bit(c.OPCODE_UPDATE)
            self._os.write_bit(c.OPCODE_REPEAT)
            return
        self._os.write_bit(c.OPCODE_NO_UPDATE)
        self._float.write_next_float(self._os, bits)

    def _write_int_val(self, val: float, mult: int, is_float: bool, val_diff: float) -> None:
        if val_diff == 0 and is_float == self._is_float and mult == self._max_mult:
            self._os.write_bit(c.OPCODE_UPDATE)
            self._os.write_bit(c.OPCODE_REPEAT)
            return
        neg = False
        if val_diff < 0:
            neg = True
            val_diff = -val_diff
        val_diff_bits = int(val_diff)
        sig = num_sig(val_diff_bits)
        new_sig = self._sig.track_new_sig(sig)
        is_float_changed = is_float != self._is_float
        if mult > self._max_mult or self._sig.num_sig != new_sig or is_float_changed:
            self._os.write_bit(c.OPCODE_UPDATE)
            self._os.write_bit(c.OPCODE_NO_REPEAT)
            self._os.write_bit(c.OPCODE_INT_MODE)
            self._write_int_sig_mult(new_sig, mult, is_float_changed)
            self._sig.write_int_val_diff(self._os, val_diff_bits, neg)
            self._is_float = False
        else:
            self._os.write_bit(c.OPCODE_NO_UPDATE)
            self._sig.write_int_val_diff(self._os, val_diff_bits, neg)
        self._int_val = val

    def _write_int_sig_mult(self, sig: int, mult: int, float_changed: bool) -> None:
        self._sig.write_int_sig(self._os, sig)
        if mult > self._max_mult:
            self._os.write_bit(c.OPCODE_UPDATE_MULT)
            self._os.write_bits(mult, c.NUM_MULT_BITS)
            self._max_mult = mult
        elif self._sig.num_sig == sig and self._max_mult == mult and float_changed:
            self._os.write_bit(c.OPCODE_UPDATE_MULT)
            self._os.write_bits(self._max_mult, c.NUM_MULT_BITS)
        else:
            self._os.write_bit(c.OPCODE_NO_UPDATE_MULT)

    def stream(self) -> bytes:
        """Finalized stream: data capped with the end-of-stream marker."""
        return finalize_stream(self._os)

    @property
    def last_value(self) -> float:
        if self._is_float or not self._int_optimized:
            return c.bits_to_float(self._float.prev_float_bits)
        return self._int_val
