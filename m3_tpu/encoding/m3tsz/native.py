"""ctypes bindings for the native C++ M3TSZ codec (native/m3tsz.cpp).

The shared library is built on demand with g++ (no pip deps); callers fall
back to the pure-Python scalar codec when no compiler is available, so the
native path is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from m3_tpu.utils.xtime import TimeUnit, unit_value_ns

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "m3tsz.cpp")
_SO = os.path.join(_REPO_ROOT, "native", "libm3tsz.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    """The loaded library or None (no compiler / build failed)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
        if not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.m3tsz_encode.restype = ctypes.c_int64
        lib.m3tsz_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.m3tsz_decode.restype = ctypes.c_int32
        lib.m3tsz_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.m3tsz_bench_roundtrip.restype = ctypes.c_int64
        lib.m3tsz_bench_roundtrip.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _default_bits(unit: TimeUnit) -> int:
    return 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64


def encode_series(times: np.ndarray, values: np.ndarray, start: int,
                  unit: TimeUnit = TimeUnit.SECOND) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    times = np.ascontiguousarray(times, dtype=np.int64)
    vbits = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    cap = 8 + (len(times) * 146 + 11) // 8 + 16
    out = np.zeros(cap, dtype=np.uint8)
    n = lib.m3tsz_encode(
        times.ctypes.data, vbits.ctypes.data, len(times),
        start, unit_value_ns(unit), _default_bits(unit),
        out.ctypes.data, cap,
    )
    if n == -1:
        raise ValueError("native encode overflow or misaligned start")
    if n == -2:
        raise OverflowError("delta-of-delta overflows 32 bits for this unit")
    return out[:n].tobytes()


def decode_series(stream: bytes, unit: TimeUnit = TimeUnit.SECOND,
                  max_points: int = 1 << 20):
    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    data = np.frombuffer(stream, dtype=np.uint8)
    # a datapoint costs >= 2 bits, so the stream bounds the output size
    max_points = min(max_points, len(data) * 4 + 16)
    times = np.empty(max_points, dtype=np.int64)
    vbits = np.empty(max_points, dtype=np.uint64)
    n = lib.m3tsz_decode(
        data.ctypes.data, len(data), unit_value_ns(unit), _default_bits(unit),
        times.ctypes.data, vbits.ctypes.data, max_points,
    )
    if n < 0:
        raise ValueError("native decode failed (corrupt or host-path stream)")
    return times[:n].copy(), vbits[:n].view(np.float64).copy()


def bench_roundtrip(times: np.ndarray, values: np.ndarray, start: int,
                    unit: TimeUnit = TimeUnit.SECOND) -> float:
    """Datapoints/sec for a [B, T] encode+decode round trip executed
    entirely in native code (one FFI call: the honest CPU baseline)."""
    import time as _time

    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    B, T = times.shape
    times = np.ascontiguousarray(times, dtype=np.int64)
    vbits = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    cap = 8 + (T * 146 + 11) // 8 + 16
    scratch = np.zeros(cap, dtype=np.uint8)
    out_t = np.empty(T, dtype=np.int64)
    out_v = np.empty(T, dtype=np.uint64)
    t0 = _time.perf_counter()
    n = lib.m3tsz_bench_roundtrip(
        times.ctypes.data, vbits.ctypes.data, B, T,
        start, unit_value_ns(unit), _default_bits(unit),
        scratch.ctypes.data, cap, out_t.ctypes.data, out_v.ctypes.data,
    )
    dt = _time.perf_counter() - t0
    if n < 0:
        raise ValueError("native bench roundtrip failed")
    return n / dt
