"""ctypes bindings for the native C++ M3TSZ codec (native/m3tsz.cpp).

The shared library is built on demand with g++ (no pip deps); callers fall
back to the pure-Python scalar codec when no compiler is available, so the
native path is an accelerator, never a requirement.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from m3_tpu.utils.xtime import TimeUnit, unit_value_ns

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "m3tsz.cpp")
# M3TSZ_SO points the loader at an instrumented build (tools/race_check.py
# swaps in the ThreadSanitizer variant); overrides are loaded AS-IS (no
# stale-mtime rebuild, which would overwrite the instrumented artifact
# with a plain -O3 build)
_SO_OVERRIDE = "M3TSZ_SO" in os.environ
_SO = os.environ.get("M3TSZ_SO",
                     os.path.join(_REPO_ROOT, "native", "libm3tsz.so"))

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    """The loaded library or None (no compiler / build failed)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
        if not _SO_OVERRIDE and (
                not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime):
            # intentional build-under-lock: single-flight one-time g++
            # build — concurrent callers must block until the artifact
            # exists (they would only dogpile the compiler otherwise)
            # m3lint: disable=lock-blocking-call
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.m3tsz_encode.restype = ctypes.c_int64
        lib.m3tsz_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.m3tsz_decode.restype = ctypes.c_int32
        lib.m3tsz_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.m3tsz_bench_roundtrip.restype = ctypes.c_int64
        lib.m3tsz_bench_roundtrip.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.m3tsz_encode_batch.restype = ctypes.c_int64
        lib.m3tsz_encode_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.m3tsz_decode_batch.restype = ctypes.c_int64
        lib.m3tsz_decode_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.m3tsz_roundtrip_batch.restype = ctypes.c_int64
        lib.m3tsz_roundtrip_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _default_bits(unit: TimeUnit) -> int:
    return 32 if unit in (TimeUnit.SECOND, TimeUnit.MILLISECOND) else 64


def encode_series(times: np.ndarray, values: np.ndarray, start: int,
                  unit: TimeUnit = TimeUnit.SECOND) -> bytes:
    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    times = np.ascontiguousarray(times, dtype=np.int64)
    vbits = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    cap = 8 + (len(times) * 146 + 11) // 8 + 16
    out = np.zeros(cap, dtype=np.uint8)
    n = lib.m3tsz_encode(
        times.ctypes.data, vbits.ctypes.data, len(times),
        start, unit_value_ns(unit), _default_bits(unit),
        out.ctypes.data, cap,
    )
    if n == -1:
        raise ValueError("native encode overflow or misaligned start")
    if n == -2:
        raise OverflowError("delta-of-delta overflows 32 bits for this unit")
    return out[:n].tobytes()


def decode_series(stream: bytes, unit: TimeUnit = TimeUnit.SECOND,
                  max_points: int = 1 << 20):
    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    data = np.frombuffer(stream, dtype=np.uint8)
    # a datapoint costs >= 2 bits, so the stream bounds the output size
    max_points = min(max_points, len(data) * 4 + 16)
    times = np.empty(max_points, dtype=np.int64)
    vbits = np.empty(max_points, dtype=np.uint64)
    n = lib.m3tsz_decode(
        data.ctypes.data, len(data), unit_value_ns(unit), _default_bits(unit),
        times.ctypes.data, vbits.ctypes.data, max_points,
    )
    if n < 0:
        raise ValueError("native decode failed (corrupt or host-path stream)")
    return times[:n].copy(), vbits[:n].view(np.float64).copy()


def bench_roundtrip(times: np.ndarray, values: np.ndarray, start: int,
                    unit: TimeUnit = TimeUnit.SECOND) -> float:
    """Datapoints/sec for a [B, T] encode+decode round trip executed
    entirely in native code (one FFI call: the honest CPU baseline).

    Measures the FROZEN v1 scalar codec — the stand-in for the reference's
    single-core Go hot loop. The serving path uses the v2 batch codec
    (encode_batch/decode_batch/bench_roundtrip_batch below)."""
    import time as _time

    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    B, T = times.shape
    times = np.ascontiguousarray(times, dtype=np.int64)
    vbits = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    cap = 8 + (T * 146 + 11) // 8 + 16
    scratch = np.zeros(cap, dtype=np.uint8)
    out_t = np.empty(T, dtype=np.int64)
    out_v = np.empty(T, dtype=np.uint64)
    t0 = _time.perf_counter()
    n = lib.m3tsz_bench_roundtrip(
        times.ctypes.data, vbits.ctypes.data, B, T,
        start, unit_value_ns(unit), _default_bits(unit),
        scratch.ctypes.data, cap, out_t.ctypes.data, out_v.ctypes.data,
    )
    dt = _time.perf_counter() - t0
    if n < 0:
        raise ValueError("native bench roundtrip failed")
    return n / dt


def default_threads() -> int:
    """Thread count for the batch codec: the cores this process may use,
    overridable via M3_NATIVE_THREADS."""
    v = os.environ.get("M3_NATIVE_THREADS")
    if v:
        return max(1, int(v))
    try:
        return max(1, len(os.sched_getaffinity(0)))
    except (AttributeError, OSError):
        return max(1, os.cpu_count() or 1)


def encode_batch(times: np.ndarray, values_or_bits: np.ndarray,
                 starts: np.ndarray, unit: TimeUnit = TimeUnit.SECOND,
                 n_points: np.ndarray | None = None,
                 threads: int | None = None) -> list[bytes]:
    """Encode [B, T] series to per-series streams with the v2 word-level
    codec, threaded across series. values_or_bits may be f64 values or u64
    bit patterns; series b encodes its first n_points[b] points (default
    all T). Bit-identical to the scalar/XLA encoders."""
    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    B, T = times.shape
    times = np.ascontiguousarray(times, dtype=np.int64)
    if values_or_bits.dtype == np.uint64:
        vbits = np.ascontiguousarray(values_or_bits)
    else:
        vbits = np.ascontiguousarray(values_or_bits, dtype=np.float64).view(np.uint64)
    starts = np.ascontiguousarray(np.broadcast_to(starts, (B,)), dtype=np.int64)
    np_ptr = 0
    if n_points is not None:
        n_points = np.ascontiguousarray(n_points, dtype=np.int32)
        np_ptr = n_points.ctypes.data
    stride = 8 + (T * 146 + 11) // 8 + 32
    out = np.zeros((B, stride), dtype=np.uint8)
    lens = np.empty(B, dtype=np.int64)
    rc = lib.m3tsz_encode_batch(
        times.ctypes.data, vbits.ctypes.data, B, T, starts.ctypes.data,
        np_ptr, unit_value_ns(unit), _default_bits(unit),
        out.ctypes.data, stride, lens.ctypes.data,
        threads or default_threads(),
    )
    if rc != 0:
        # OverflowError for both codes, matching the device path's single
        # blocks.overflow flag (misaligned start folds into overflow there
        # too) so Shard.snapshot/_flush_locked degrade identically on CPU.
        bad = int(np.argmax(lens < 0))
        code = int(lens[bad])
        if code == -2:
            raise OverflowError("delta-of-delta overflows 32 bits for this unit")
        raise OverflowError(
            f"native batch encode failed for series {bad} (overflow or "
            "misaligned start)")
    return [out[b, :lens[b]].tobytes() for b in range(B)]


def decode_batch(streams: list[bytes], unit: TimeUnit = TimeUnit.SECOND,
                 max_points: int | None = None, threads: int | None = None):
    """Decode per-series streams into padded [B, T] arrays + counts with the
    v2 codec, threaded across series. Returns (times, vbits, n_points)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    B = len(streams)
    if B == 0:
        z = np.zeros((0, 0))
        return z.astype(np.int64), z.astype(np.uint64), np.zeros(0, np.int32)
    maxlen = max(len(s) for s in streams)
    if max_points is None:
        # a datapoint costs >= 2 bits, so the stream bounds the output
        max_points = maxlen * 4 + 16
    stride = maxlen + 16  # >= 9 bytes of slack for unaligned tail loads
    buf = np.zeros((B, stride), dtype=np.uint8)
    lens = np.empty(B, dtype=np.int64)
    for b, s in enumerate(streams):
        buf[b, :len(s)] = np.frombuffer(s, dtype=np.uint8)
        lens[b] = len(s)
    times = np.zeros((B, max_points), dtype=np.int64)
    vbits = np.zeros((B, max_points), dtype=np.uint64)
    out_ns = np.empty(B, dtype=np.int32)
    rc = lib.m3tsz_decode_batch(
        buf.ctypes.data, lens.ctypes.data, stride, B,
        unit_value_ns(unit), _default_bits(unit),
        times.ctypes.data, vbits.ctypes.data, max_points, out_ns.ctypes.data,
        threads or default_threads(),
    )
    if rc != 0:
        bad = int(np.argmax(out_ns < 0))
        raise ValueError(f"native batch decode failed for stream {bad}")
    return times, vbits, out_ns


def bench_roundtrip_batch(times: np.ndarray, values: np.ndarray, start: int,
                          unit: TimeUnit = TimeUnit.SECOND,
                          threads: int | None = None) -> tuple[float, np.ndarray, np.ndarray]:
    """Datapoints/sec for a [B, T] round trip on the v2 serving-path codec
    (word-level bit I/O, threaded). Returns (dp_per_sec, last_times,
    last_vbits) so callers can verify correctness of the final series."""
    import time as _time

    lib = load()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    B, T = times.shape
    times = np.ascontiguousarray(times, dtype=np.int64)
    vbits = np.ascontiguousarray(values, dtype=np.float64).view(np.uint64)
    out_t = np.empty(T, dtype=np.int64)
    out_v = np.empty(T, dtype=np.uint64)
    nth = threads or default_threads()
    t0 = _time.perf_counter()
    n = lib.m3tsz_roundtrip_batch(
        times.ctypes.data, vbits.ctypes.data, B, T,
        start, unit_value_ns(unit), _default_bits(unit),
        out_t.ctypes.data, out_v.ctypes.data, nth,
    )
    dt = _time.perf_counter() - t0
    if n < 0:
        raise ValueError("native batch roundtrip failed")
    return n / dt, out_t, out_v
