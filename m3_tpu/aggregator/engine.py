"""Streaming metrics aggregator.

Role parity with the reference aggregator service
(/root/reference/src/aggregator/aggregator/aggregator.go:157-380: AddUntimed
/AddTimed shard routing, per-elem accumulation, metric lists driving flush)
redesigned for the device grid: adds append to per-shard columnar buffers
keyed by elem index, and a flush computes every (elem x window) aggregate in
one batched pass (m3_tpu.ops.windowed_agg) — the lock-striped map of
streaming accumulators becomes a segment reduction.

Flush emits AggregatedMetric records to a pluggable handler (storage writer,
m3msg producer, ...), with agg-type suffixes appended to multi-aggregation
ids the way the reference names timer outputs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from m3_tpu.metrics.aggregation import (
    DEFAULT_AGGREGATIONS,
    AggregationType,
    MetricType,
)
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.rules import Matcher, PipelineStage, RuleSet
from m3_tpu.metrics.transformation import TransformationType, apply as apply_transform
from m3_tpu.ops import windowed_agg
from m3_tpu.utils import faults
from m3_tpu.utils.hash import murmur3_32

# flush-history depth bound: stage-k windows close against the k-th
# previous flush, so chains deeper than this could never close
MAX_PIPELINE_STAGES = 16


@dataclass(frozen=True)
class ElemKey:
    series_id: bytes
    policy: StoragePolicy
    aggregations: tuple[AggregationType, ...]
    transform: TransformationType | None = None
    # REMAINING pipeline stages this elem's window outputs forward into
    # (arbitrary depth — the reference's numForwardedTimes chains,
    # forwarded_writer.go + metrics/pipeline). Empty = emit directly.
    forward: "tuple[PipelineStage, ...]" = ()
    # forwarded-stage elems carry their SOURCE stage's resolution so two
    # upstream policies forwarding into equal target policies stay
    # distinct instead of conflating their streams
    source_resolution_ns: int = 0


@dataclass
class Elem:
    index: int
    key: ElemKey
    tags: tuple[tuple[bytes, bytes], ...]
    metric_type: MetricType
    # previous emitted window aggregate per aggregation (for binary
    # transforms like PerSecond), keyed by aggregation type
    prev: dict[AggregationType, tuple[int, float]] = field(default_factory=dict)
    # pipeline depth: 0 = fed by raw adds; k>0 = fed by stage k-1's
    # forwarded outputs (windows close against the k-th previous flush
    # watermark — see flush())
    stage: int = 0
    # per-stage extra lateness allowance (PipelineStage.buffer_past_ns)
    stage_buffer_past_ns: int = 0


@dataclass
class AggregatedMetric:
    series_id: bytes  # suffixed id
    tags: tuple[tuple[bytes, bytes], ...]
    timestamp_ns: int  # window end
    value: float
    policy: StoragePolicy


class _ShardBuffer:
    __slots__ = ("elem_idx", "times", "values", "n")

    def __init__(self) -> None:
        cap = 1024
        self.elem_idx = np.empty(cap, np.int64)
        self.times = np.empty(cap, np.int64)
        self.values = np.empty(cap, np.float64)
        self.n = 0

    def append(self, elem: int, t_ns: int, value: float) -> None:
        if self.n == len(self.elem_idx):
            cap = len(self.elem_idx) * 2
            self.elem_idx = np.resize(self.elem_idx, cap)
            self.times = np.resize(self.times, cap)
            self.values = np.resize(self.values, cap)
        self.elem_idx[self.n] = elem
        self.times[self.n] = t_ns
        self.values[self.n] = value
        self.n += 1

    def take(self):
        out = (
            self.elem_idx[: self.n].copy(),
            self.times[: self.n].copy(),
            self.values[: self.n].copy(),
        )
        self.n = 0
        return out


class Aggregator:
    """Single-process aggregator (the coordinator's embedded downsampler
    shape; the dedicated-service wrapper adds election + m3msg IO)."""

    def __init__(
        self,
        ruleset: RuleSet | None = None,
        n_shards: int = 4,
        buffer_past_ns: int = 0,
        max_buffered_per_shard: int = 10_000_000,
    ):
        self.matcher = Matcher(ruleset or RuleSet())
        self.n_shards = n_shards
        self.buffer_past_ns = buffer_past_ns
        self.max_buffered_per_shard = max_buffered_per_shard
        self._elems: dict[ElemKey, Elem] = {}
        self._elem_list: list[Elem] = []
        self._shards: dict[int, _ShardBuffer] = {i: _ShardBuffer() for i in range(n_shards)}
        # carry: samples belonging to windows that were still open at the
        # last flush, kept per shard until their window closes
        self._carry: dict[int, tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        # one coarse lock serializes add vs flush: ingest threads and the
        # flush loop share the columnar buffers (appends are O(1), flush
        # swaps the buffers out under the lock then reduces outside it)
        self._lock = threading.Lock()
        self.num_dropped = 0
        self.num_late_dropped = 0
        # flush watermark: windows ending at/before this have been emitted;
        # samples landing in them are rejected (reference buffer-past rule)
        self._watermark_ns = 0
        self._elem_res: list[int] = []
        self._elem_stage: list[int] = []
        self._elem_stage_bp: list[int] = []
        self._n_quantile_elems = 0
        # completion times of recent flushes, most recent first: a stage-k
        # elem's windows may only close once EVERY upstream window feeding
        # them was forwarded, i.e. after k full flush passes — its
        # threshold is the k-th previous flush's watermark
        self._flush_history: list[int] = []

    # -- add path --

    def _shard_for(self, series_id: bytes) -> int:
        return murmur3_32(series_id) % self.n_shards

    def _elem(self, key: ElemKey, tags, metric_type: MetricType,
              stage: int = 0, stage_buffer_past_ns: int = 0) -> Elem:
        e = self._elems.get(key)
        if e is None:
            e = Elem(len(self._elem_list), key, tuple(tags), metric_type,
                     stage=stage, stage_buffer_past_ns=stage_buffer_past_ns)
            self._elems[key] = e
            self._elem_list.append(e)
            self._elem_res.append(key.policy.resolution_ns)
            self._elem_stage.append(stage)
            self._elem_stage_bp.append(stage_buffer_past_ns)
            if any(a.quantile is not None for a in key.aggregations):
                self._n_quantile_elems += 1
        return e

    def add(
        self,
        metric_type: MetricType,
        series_id: bytes,
        tags: list[tuple[bytes, bytes]],
        t_ns: int,
        value: float,
    ) -> bool:
        """Match rules and buffer the sample into every target elem.
        Returns drop_unaggregated (the caller decides whether to also write
        the raw datapoint)."""
        tag_dict = dict(tags)
        result = self.matcher.match(series_id, tag_dict)
        with self._lock:
            return self._add_locked(metric_type, series_id, tags, t_ns, value, result)

    def _add_locked(self, metric_type, series_id, tags, t_ns, value, result) -> bool:
        for rule in result.mappings:
            aggs = rule.aggregations or DEFAULT_AGGREGATIONS[metric_type]
            for policy in rule.policies:
                elem = self._elem(
                    ElemKey(series_id, policy, tuple(aggs)), tags, metric_type
                )
                self._append(series_id, elem, t_ns, value)
        for _rule, target, rolled_id, rolled_tags in result.rollups:
            forward = target.stages()
            if len(forward) >= MAX_PIPELINE_STAGES:
                # deeper chains would outrun the flush-history window and
                # silently never close — reject loudly instead
                raise ValueError(
                    f"pipeline depth {len(forward) + 1} exceeds the "
                    f"supported {MAX_PIPELINE_STAGES} stages")
            for policy in target.policies:
                elem = self._elem(
                    ElemKey(rolled_id, policy, tuple(target.aggregations),
                            target.transform, forward),
                    [(b"__name__", target.new_name), *rolled_tags],
                    metric_type,
                )
                self._append(rolled_id, elem, t_ns, value)
        return result.drop_unaggregated

    def _append(self, routing_id: bytes, elem: Elem, t_ns: int, value: float) -> None:
        res = elem.key.policy.resolution_ns
        window_end = (t_ns // res + 1) * res
        if window_end + self.buffer_past_ns <= self._watermark_ns:
            # the window was already flushed: a partial re-emit would
            # overwrite the full aggregate downstream
            self.num_late_dropped += 1
            return
        shard = self._shards[self._shard_for(routing_id)]
        if shard.n >= self.max_buffered_per_shard:
            self.num_dropped += 1
            return
        shard.append(elem.index, t_ns, value)

    # -- flush path --

    def flush(self, now_ns: int) -> list[AggregatedMetric]:
        """Close every window whose end + buffer_past has passed and emit
        its aggregates; still-open windows are carried to the next flush."""
        from m3_tpu.utils import trace
        from m3_tpu.utils.instrument import default_registry

        with trace.span(trace.AGG_FLUSH), \
                default_registry().root_scope("aggregator").histogram(
                    "flush_seconds"):
            return self._flush_traced(now_ns)

    def _flush_traced(self, now_ns: int) -> list[AggregatedMetric]:
        # fault point BEFORE any buffer is taken: an injected failure here
        # leaves every pending sample buffered for the next flush tick
        # (chaos tests assert a failed flush never drops closed windows)
        faults.check("aggregator.flush", now_ns=now_ns)
        out: list[AggregatedMetric] = []
        with self._lock:
            self._watermark_ns = max(self._watermark_ns, now_ns)
            res_by_elem = (np.array(self._elem_res, np.int64)
                           if self._elem_res else np.zeros(0, np.int64))
            stage_by_elem = (np.array(self._elem_stage, np.int64)
                             if self._elem_stage else np.zeros(0, np.int64))
            stage_bp_by_elem = (np.array(self._elem_stage_bp, np.int64)
                                if self._elem_stage_bp
                                else np.zeros(0, np.int64))
            taken = {sid: buf.take() for sid, buf in self._shards.items()}
            carries = {sid: self._carry.pop(sid, None) for sid in self._shards}
            # stage-k threshold: the k-th previous flush's completion —
            # after k full passes every upstream window feeding a stage-k
            # window has been forwarded (exact completeness regardless of
            # tick cadence). Unreached depths never close.
            max_stage = int(stage_by_elem.max()) if len(stage_by_elem) else 0
            thresholds = np.full(max_stage + 1, np.iinfo(np.int64).min,
                                 np.int64)
            thresholds[0] = now_ns
            for k in range(1, max_stage + 1):
                if len(self._flush_history) >= k:
                    thresholds[k] = self._flush_history[k - 1]
        for shard_id in taken:
            e_idx, times, values = taken[shard_id]
            carry = carries[shard_id]
            if carry is not None:
                e_idx = np.concatenate([carry[0], e_idx])
                times = np.concatenate([carry[1], times])
                values = np.concatenate([carry[2], values])
            if len(e_idx) == 0:
                continue
            res = res_by_elem[e_idx]
            window_end = (times // res + 1) * res
            thr = thresholds[stage_by_elem[e_idx]]
            closed = (window_end + self.buffer_past_ns
                      + stage_bp_by_elem[e_idx] <= thr)
            if not closed.all():
                keep = ~closed
                with self._lock:
                    self._carry[shard_id] = (e_idx[keep], times[keep], values[keep])
            e_c, t_c, v_c = e_idx[closed], times[closed], values[closed]
            if len(e_c) == 0:
                continue
            w_c = t_c // res[closed]  # window id in units of resolution
            ge, gw, stats, vq, offsets = windowed_agg.aggregate_groups(
                e_c, w_c, v_c, order_seq=np.arange(len(e_c)), times=t_c,
                need_sorted=self._n_quantile_elems > 0,
            )
            out.extend(self._emit(ge, gw, stats, vq, offsets))
        out.sort(key=lambda m: (m.timestamp_ns, m.series_id))
        # clamp to the current head: a non-monotonic flush() must not
        # regress stage watermarks already used to close forwarded-stage
        # windows (stage-k thresholds read history entries as high-water
        # marks)
        head = self._flush_history[0] if self._flush_history else now_ns
        self._flush_history.insert(0, max(now_ns, head))
        del self._flush_history[MAX_PIPELINE_STAGES:]
        return out

    def _emit(self, ge, gw, stats, vq, offsets) -> list[AggregatedMetric]:
        out = []
        # one vectorized extract per aggregation type across ALL groups
        agg_types = set()
        for g in range(len(ge)):
            agg_types.update(self._elem_list[int(ge[g])].key.aggregations)
        extracted = {
            agg: windowed_agg.extract(agg, stats, vq, offsets) for agg in agg_types
        }
        for g in range(len(ge)):
            elem = self._elem_list[int(ge[g])]
            res = elem.key.policy.resolution_ns
            w_end = (int(gw[g]) + 1) * res
            multi = len(elem.key.aggregations) > 1
            for agg in elem.key.aggregations:
                value = float(extracted[agg][g])
                if elem.key.transform is not None:
                    tprev = elem.prev.get(agg)
                    pv = tprev[1] if tprev else np.nan
                    pt = tprev[0] if tprev else 0
                    value_arr = apply_transform(
                        elem.key.transform,
                        np.array([pv]), np.array([value]),
                        np.array([pt]), np.array([w_end]),
                    )
                    elem.prev[agg] = (w_end, value)
                    value = float(value_arr[0])
                    if np.isnan(value):
                        continue
                suffix = agg.suffix if multi else b""
                tags = elem.tags
                if suffix:
                    # suffix the metric name too so downstream storage keys
                    # and ids agree (the reference suffixes the metric ID)
                    tags = tuple(
                        (k, v + suffix if k == b"__name__" else v) for k, v in tags
                    )
                if elem.key.forward:
                    # multi-stage pipeline: this stage's window aggregate
                    # is FORWARDED into the next stage instead of emitted
                    # (forwarded_writer.go role, in-process here;
                    # cross-instance forwarding rides the msg topic)
                    self._forward(elem, suffix, tags, w_end, res, value)
                    continue
                out.append(
                    AggregatedMetric(
                        series_id=elem.key.series_id + suffix,
                        tags=tags,
                        timestamp_ns=w_end,
                        value=value,
                        policy=elem.key.policy,
                    )
                )
        return out

    def _forward(self, elem: Elem, suffix: bytes, tags, w_end: int,
                 res: int, value: float) -> None:
        """AddForwarded: route a window aggregate into the NEXT pipeline
        stage's elem. Timestamped at the source window START so it lands
        in the next stage's window covering that span; stage-k windows
        close against the k-th previous flush watermark (see flush()) so
        late upstream outputs always land first."""
        stage = elem.key.forward[0]
        rest = elem.key.forward[1:]
        policy = StoragePolicy(stage.resolution_ns,
                               elem.key.policy.retention_ns)
        fkey = ElemKey(elem.key.series_id + suffix, policy,
                       tuple(stage.aggregations), forward=rest,
                       source_resolution_ns=res)
        with self._lock:
            felem = self._elem(fkey, tags, elem.metric_type,
                               stage=elem.stage + 1,
                               stage_buffer_past_ns=stage.buffer_past_ns)
            shard = self._shards[self._shard_for(fkey.series_id)]
            if shard.n >= self.max_buffered_per_shard:
                self.num_dropped += 1
                return
            shard.append(felem.index, w_end - res, value)

    @property
    def n_elems(self) -> int:
        return len(self._elem_list)


# ---------------------------------------------------------------------------
# flush handlers
# ---------------------------------------------------------------------------


def storage_flush_handler(db, namespace_for_policy: Callable[[StoragePolicy], str]):
    """Writes aggregated metrics back into per-policy namespaces (the
    coordinator downsampler flush handler role,
    /root/reference/src/cmd/services/m3coordinator/downsample/flush_handler.go)."""

    def handle(metrics: list[AggregatedMetric]) -> int:
        from m3_tpu.utils.instrument import Logger

        # downstream-sink seam: an error/timeout here models the storage
        # write path rejecting a whole flush batch (a crash kills the
        # flush thread like a real SIGKILL would)
        faults.check("aggregator.flush.handler", n_metrics=len(metrics))
        # one storage-side batch per target namespace (db.write_batch's
        # columnar pass) instead of one write_tagged per metric; facades
        # without the batch surface keep the per-metric loop
        by_ns: dict[str, list] = {}
        for m in metrics:
            ns = namespace_for_policy(m.policy)
            if ns is None:
                continue
            tags = [(k, v) for k, v in m.tags if k != b"__name__"]
            name = dict(m.tags).get(b"__name__", b"")
            by_ns.setdefault(ns, []).append(
                (name, tags, m.timestamp_ns, m.value))
        n = 0
        failed = 0
        first_err: Exception | str | None = None
        write_batch = getattr(db, "write_batch", None)
        # cluster facades batch through write_tagged_batch (one
        # /write_batch request per storage host via session.write_many)
        tagged_batch = None if write_batch is not None \
            else getattr(db, "write_tagged_batch", None)
        for ns, entries in by_ns.items():
            # per-entry (or per-namespace) failures count, never abort the
            # whole flush: one bad namespace (e.g. not configured on the
            # storage nodes in cluster mode) must not drop the rest
            if write_batch is not None:
                try:
                    res = write_batch(ns, entries)
                except faults.SimulatedCrash:
                    raise  # no handler survives a kill
                except Exception as e:  # noqa: BLE001 - whole-batch failure
                    failed += len(entries)
                    first_err = first_err if first_err is not None else e
                    continue
                bad = [r for r in res if r is not None]
                failed += len(bad)
                n += len(entries) - len(bad)
                if bad and first_err is None:
                    first_err = bad[0]
                continue
            if tagged_batch is not None:
                try:
                    n += tagged_batch(ns, entries)
                    continue
                except faults.SimulatedCrash:
                    raise
                except Exception:  # noqa: BLE001 - all-or-error surface:
                    # retry per metric below so one sub-consistency entry
                    # (or unconfigured namespace) keeps per-entry counting
                    pass
            for name, tags, t_ns, value in entries:
                try:
                    db.write_tagged(ns, name, tags, t_ns, value)
                    n += 1
                except Exception as e:  # noqa: BLE001 - count and carry on
                    failed += 1
                    if first_err is None:
                        first_err = e
        if failed:
            Logger("downsample").info(
                "aggregated writes failed (is the target namespace "
                "configured on the storage nodes?)",
                failed=failed, written=n, first_error=str(first_err),
            )
        return n

    return handle
