"""Embedded downsampler: the coordinator's in-process aggregator.

Role parity with /root/reference/src/cmd/services/m3coordinator/downsample
(metrics_appender.go rule-matched appends, flush_handler.go writing
aggregated output back to storage) and ingest/write.go's
DownsamplerAndWriter: every incoming write goes to the downsampler (rule
match -> aggregation) and/or the unaggregated namespace.
"""

from __future__ import annotations

import time

from m3_tpu.aggregator.engine import Aggregator, storage_flush_handler
from m3_tpu.metrics.aggregation import MetricType
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.rules import RuleSet
from m3_tpu.storage.options import NamespaceOptions, RetentionOptions


class Downsampler:
    """Aggregator wired to a Database: flush writes into per-policy
    aggregated namespaces (created on demand)."""

    def __init__(self, db, ruleset: RuleSet, local_leader: bool = True,
                 buffer_past_ns: int = 0):
        self.db = db
        self.aggregator = Aggregator(ruleset, buffer_past_ns=buffer_past_ns)
        # local leader mode (leader_local.go role): this process always
        # flushes; the clustered service swaps in an elected flush manager
        self.local_leader = local_leader
        self._handler = storage_flush_handler(db, self._namespace_for)

    def _namespace_for(self, policy: StoragePolicy) -> str:
        name = policy.namespace_name
        if name not in self.db.namespaces:
            self.db.create_namespace(
                name,
                NamespaceOptions(
                    retention=RetentionOptions(
                        retention_ns=policy.retention_ns,
                        block_size_ns=max(policy.resolution_ns * 720,
                                          2 * 3600 * 10**9),
                    ),
                    aggregated_resolution_ns=policy.resolution_ns,
                ),
            )
        return name

    def append(self, metric_type: MetricType, series_id: bytes, tags, t_ns: int,
               value: float) -> bool:
        """Returns True if the raw write should be DROPPED (drop policy)."""
        return self.aggregator.add(metric_type, series_id, list(tags), t_ns, value)

    def flush(self, now_ns: int | None = None) -> int:
        if not self.local_leader:
            return 0
        now_ns = now_ns if now_ns is not None else time.time_ns()
        metrics = self.aggregator.flush(now_ns)
        return self._handler(metrics)


class DownsamplerAndWriter:
    """Fan a write to the downsampler and the unaggregated namespace
    (ingest/write.go:176,264,333)."""

    def __init__(self, db, downsampler: Downsampler | None,
                 unaggregated_namespace: str = "default"):
        self.db = db
        self.downsampler = downsampler
        self.unagg = unaggregated_namespace

    def write(self, metric_type: MetricType, name: bytes, tags, t_ns: int,
              value: float) -> bytes | None:
        drop = False
        if self.downsampler is not None:
            from m3_tpu.utils.ident import tags_to_id

            series_id = tags_to_id(name, tags)
            all_tags = [(b"__name__", name), *tags] if name else list(tags)
            drop = self.downsampler.append(metric_type, series_id, all_tags, t_ns, value)
        if not drop:
            return self.db.write_tagged(self.unagg, name, list(tags), t_ns, value)
        return None
