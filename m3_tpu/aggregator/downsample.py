"""Embedded downsampler: the coordinator's in-process aggregator.

Role parity with /root/reference/src/cmd/services/m3coordinator/downsample
(metrics_appender.go rule-matched appends, flush_handler.go writing
aggregated output back to storage) and ingest/write.go's
DownsamplerAndWriter: every incoming write goes to the downsampler (rule
match -> aggregation) and/or the unaggregated namespace.

The flush loop also hosts the standing-query plane (query/standing.py):
recording rules evaluate incrementally right after the aggregation
flush, under the same leader/local discipline, writing into the same
per-policy aggregated namespaces.
"""

from __future__ import annotations

import time

from m3_tpu.aggregator.engine import Aggregator, storage_flush_handler
from m3_tpu.metrics.aggregation import MetricType
from m3_tpu.metrics.policy import StoragePolicy
from m3_tpu.metrics.rules import RuleSet
from m3_tpu.storage.options import NamespaceOptions, RetentionOptions


class Downsampler:
    """Aggregator wired to a Database: flush writes into per-policy
    aggregated namespaces (created on demand)."""

    def __init__(self, db, ruleset: RuleSet, local_leader: bool = True,
                 buffer_past_ns: int = 0, source_namespace: str = "default",
                 register_namespace=None, now_fn=None):
        self.db = db
        self.aggregator = Aggregator(ruleset, buffer_past_ns=buffer_past_ns)
        # local leader mode (leader_local.go role): this process always
        # flushes; the clustered service swaps in an elected flush manager
        self.local_leader = local_leader
        self.source_namespace = source_namespace
        # registry-sync hook: a namespace created on demand mid-flush
        # must ALSO land in the KV namespace registry, or a dbnode
        # restarting later re-creates it empty and abandons its WAL
        # (the coordinator wires this to the registry CAS when a KV is
        # configured; None = local single-process deployments)
        self.register_namespace = register_namespace
        self._registered: set[str] = set()
        self._handler = storage_flush_handler(db, self._namespace_for)
        self.standing = None
        if ruleset.standing_rules:
            self.standing = self._make_standing(ruleset, now_fn)
        self._now_fn = now_fn

    def _make_standing(self, ruleset: RuleSet, now_fn):
        from m3_tpu.query.standing import StandingEvaluator

        return StandingEvaluator(
            self.db, ruleset.standing_rules,
            source_namespace=self.source_namespace,
            namespace_for=self._namespace_for, now_fn=now_fn,
            write_raw_namespace=self.source_namespace)

    def set_ruleset(self, rs: RuleSet) -> None:
        """Swap the live ruleset (KV reload): the matcher's version bump
        invalidates its match cache; the standing evaluator keeps state
        for surviving rule names."""
        self.aggregator.matcher.ruleset = rs
        if rs.standing_rules:
            if self.standing is None:
                self.standing = self._make_standing(rs, self._now_fn)
            else:
                self.standing.set_rules(rs.standing_rules)
        elif self.standing is not None:
            self.standing.set_rules(())

    def _policy_complete(self, policy: StoragePolicy) -> bool:
        """A tier is COMPLETE (eligible for cheapest-tier read
        resolution) when a downsample-all mapping rule feeds it: every
        named metric lands there at the policy's resolution."""
        rs = self.aggregator.matcher.ruleset
        return any(policy in r.policies and r.filter.matches_all()
                   for r in rs.mapping_rules)

    def _namespace_for(self, policy: StoragePolicy) -> str:
        name = policy.namespace_name
        complete = self._policy_complete(policy)
        if name not in self.db.namespaces:
            # Database.create_namespace runs the full live-bootstrap
            # path (filesets, snapshots, commitlog replay) since PR 7 —
            # a namespace re-created mid-flush picks its WAL back up
            self.db.create_namespace(
                name,
                NamespaceOptions(
                    retention=RetentionOptions(
                        retention_ns=policy.retention_ns,
                        block_size_ns=max(policy.resolution_ns * 720,
                                          2 * 3600 * 10**9),
                    ),
                    aggregated_resolution_ns=policy.resolution_ns,
                    aggregated_complete=complete,
                ),
            )
        if self.register_namespace is not None and name not in self._registered:
            self.register_namespace(name, policy, complete)
            self._registered.add(name)
        return name

    def append(self, metric_type: MetricType, series_id: bytes, tags, t_ns: int,
               value: float) -> bool:
        """Returns True if the raw write should be DROPPED (drop policy)."""
        return self.aggregator.add(metric_type, series_id, list(tags), t_ns, value)

    def flush(self, now_ns: int | None = None) -> int:
        if not self.local_leader:
            return 0
        now_ns = now_ns if now_ns is not None else time.time_ns()
        metrics = self.aggregator.flush(now_ns)
        written = self._handler(metrics)
        if self.standing is not None:
            self.standing.evaluate(now_ns)
        return written


class DownsamplerAndWriter:
    """Fan a write to the downsampler and the unaggregated namespace
    (ingest/write.go:176,264,333)."""

    def __init__(self, db, downsampler: Downsampler | None,
                 unaggregated_namespace: str = "default"):
        self.db = db
        self.downsampler = downsampler
        self.unagg = unaggregated_namespace

    def write(self, metric_type: MetricType, name: bytes, tags, t_ns: int,
              value: float) -> bytes | None:
        drop = False
        if self.downsampler is not None:
            from m3_tpu.utils.ident import tags_to_id

            series_id = tags_to_id(name, tags)
            all_tags = [(b"__name__", name), *tags] if name else list(tags)
            drop = self.downsampler.append(metric_type, series_id, all_tags, t_ns, value)
        if not drop:
            return self.db.write_tagged(self.unagg, name, list(tags), t_ns, value)
        return None
