"""Runtime-dynamic options, adjustable live through the cluster KV.

Role parity with the reference's runtime options manager + kvconfig keys
(/root/reference/src/dbnode/runtime — RuntimeOptions with a listener-based
Manager; /root/reference/src/dbnode/kvconfig — well-known KV keys watched so
operators can retune a live cluster without restarts). The tunables here are
the ones this framework's hot paths consult every pass: whole-query resource
limits (storage/limits.py), the tick's flush/snapshot switches, and the
fileset persist rate limit.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict, dataclass, replace

# the kvconfig key services watch (reference kvconfig/keys.go role)
RUNTIME_KEY = "m3_tpu.runtime"


@dataclass(frozen=True)
class RuntimeOptions:
    # whole-query budgets (0 = unlimited), applied to the node's QueryLimits
    max_series: int = 0
    max_datapoints: int = 0
    max_steps: int = 0
    # tick switches: pausing flush/snapshot is the emergency valve when a
    # node's disk or device is struggling (reference runtime options)
    flush_enabled: bool = True
    snapshot_enabled: bool = True
    # fileset persist pacing in MiB/s (0 = unlimited; the reference's
    # persist rate limit, src/dbnode/ratelimit)
    persist_rate_mbps: float = 0.0

    def to_json(self) -> bytes:
        return json.dumps(asdict(self), sort_keys=True).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "RuntimeOptions":
        """Strictly-typed parse: a dataclass would accept any JSON value,
        and a mistyped payload stored in the KV would then fail inside
        every watcher's listener (where errors are swallowed) — the
        operator would see a 200 and nothing would apply."""
        doc = json.loads(raw)
        known = {}
        for k in doc:
            if k not in cls.__dataclass_fields__:
                continue
            v = doc[k]
            default = cls.__dataclass_fields__[k].default
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"{k} must be a boolean, got {v!r}")
            elif isinstance(default, int):
                if isinstance(v, bool) or not isinstance(v, int):
                    raise ValueError(f"{k} must be an integer, got {v!r}")
            elif isinstance(default, float):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(f"{k} must be a number, got {v!r}")
                v = float(v)
            known[k] = v
        return cls(**known)


class RuntimeOptionsManager:
    """Current options + listeners; optionally fed by a KV watch.

    Listeners run synchronously under the manager lock on every change, in
    registration order; they receive the new RuntimeOptions. A failing
    listener does not block the others (its error is swallowed — a bad
    option application must not wedge the KV watch thread)."""

    def __init__(self, opts: RuntimeOptions | None = None):
        self._opts = opts or RuntimeOptions()
        self._lock = threading.Lock()
        self._listeners: list = []
        self._unwatch = None

    def get(self) -> RuntimeOptions:
        with self._lock:
            return self._opts

    def update(self, **fields) -> RuntimeOptions:
        with self._lock:
            self._opts = replace(self._opts, **fields)
            opts = self._opts
            listeners = list(self._listeners)
        self._notify(listeners, opts)
        return opts

    def set(self, opts: RuntimeOptions) -> None:
        with self._lock:
            self._opts = opts
            listeners = list(self._listeners)
        self._notify(listeners, opts)

    @staticmethod
    def _notify(listeners, opts) -> None:
        for fn in listeners:
            try:
                fn(opts)
            except Exception:  # noqa: BLE001 - see class docstring
                pass

    def register_listener(self, fn) -> callable:
        """fn(RuntimeOptions); called immediately with the current value
        (so wiring a listener is also applying the current state), then on
        every change. Returns an unregister callable."""
        with self._lock:
            self._listeners.append(fn)
            opts = self._opts
        self._notify([fn], opts)

        def unregister():
            with self._lock:
                if fn in self._listeners:
                    self._listeners.remove(fn)

        return unregister

    # -- KV integration --

    def watch_kv(self, kv, key: str = RUNTIME_KEY):
        """Apply the key's current value (if any) and follow updates.
        Returns an unwatch callable."""

        def on_change(_key, vv):
            if vv is None:
                return  # deletion keeps the last applied options
            try:
                self.set(RuntimeOptions.from_json(vv.data))
            except (ValueError, TypeError):
                pass  # malformed payloads must not kill the watch thread

        # kv.watch delivers the current value at registration, so wiring
        # the watch is also applying the key's present state
        self._unwatch = kv.watch(key, on_change)
        return self._unwatch


def apply_to_query_limits(limits, opts: RuntimeOptions) -> None:
    """Mutate a storage QueryLimits in place: accounting reads the fields
    at check time, so updates govern the very next read."""
    limits.max_series = int(opts.max_series)
    limits.max_datapoints = int(opts.max_datapoints)
    limits.max_steps = int(opts.max_steps)


class PersistRateLimiter:
    """Token-bucket pacing for fileset writes (bytes). rate_mbps == 0
    disables. Thread-safe; updated live by a runtime listener."""

    def __init__(self, rate_mbps: float = 0.0):
        self._lock = threading.Lock()
        self._tokens = 0.0
        self._last = time.monotonic()
        self.set_rate(rate_mbps)

    def set_rate(self, rate_mbps: float) -> None:
        with self._lock:
            was_disabled = getattr(self, "_rate", 0) <= 0
            self._rate = float(rate_mbps) * (1 << 20)  # bytes/sec
            self._burst = max(self._rate, 1 << 20)
            if was_disabled:
                # start full: the burst allowance covers the first writes
                # instead of stalling them while the bucket fills
                self._tokens = self._burst
                self._last = time.monotonic()

    def acquire(self, n_bytes: int) -> None:
        """Blocks until n_bytes fit the budget (no-op when unlimited). A
        single request larger than the burst cap is granted when the bucket
        is full, driving the balance negative — otherwise an oversize
        stream could never be satisfied and the flush holding the shard
        maintenance lock would wedge forever."""
        while True:
            with self._lock:
                if self._rate <= 0:
                    return
                now = time.monotonic()
                self._tokens = min(
                    self._burst, self._tokens + (now - self._last) * self._rate
                )
                self._last = now
                if self._tokens >= n_bytes or self._tokens >= self._burst:
                    self._tokens -= n_bytes
                    return
                needed = (min(n_bytes, self._burst) - self._tokens) / self._rate
            time.sleep(min(needed, 0.25))
