"""Raft-lite consensus core for the metadata plane.

The reference hangs every piece of cluster metadata off raft-replicated
etcd (SURVEY §2.7); rounds 3-4 stood kvd up as a single writer plus one
Watch-fed standby, with a documented dual-write hazard: a partitioned
primary and a promoted standby could both accept writes. This module
closes that hole by construction: a small, deterministic raft core —
terms, randomized-timeout leader election, append-entries log
replication, quorum commit, persisted vote/term/log, snapshot install
for lagging followers — that kvd (and anything else needing a replicated
state machine) layers on. No node can serve a write its majority did not
commit, and no node can become leader without a majority vote.

Design: MESSAGE-PASSING, not thread-per-RPC. A `RaftNode` is a pure
state machine over three entry points —

    outs = node.tick()                  # timers: elections, heartbeats
    resp = node.handle(rpc, req)        # inbound RPC from a peer
    outs = node.on_response(peer, rpc, req, resp)   # a peer's answer

— each returning the outbound messages `(peer_id, rpc, payload)` the
transition produced. The caller owns delivery: kvd drives a node with a
real-clock tick thread + per-peer gRPC senders, while tests drive a
whole cluster single-threaded under a VIRTUAL clock (`LocalRaftCluster`)
so every election, partition, and log-divergence heal replays
deterministically from a seed. The clock and the election-timeout RNG
are injectable for exactly that reason.

What of full raft is deliberately left out (metadata-plane scale: three
nodes, tens of writes/sec):
- no membership change protocol (the peer set is static config);
- no pipelined/parallel append streams — one in-flight append per peer,
  follow-ups ride the next ack or tick;
- the persisted journal is one JSON blob rewritten atomically per
  mutation (bounded by `compact_at`), not an incremental WAL;
- read scalability features (follower reads, learner replicas) are
  absent — linearizable reads are leader-lease with a read-index
  fallback (`read_barrier`), nothing more.

Safety features that are NOT skipped: the commit rule only counts
replication of CURRENT-term entries (the figure-8 rule), leaders open
their term with a no-op to commit prior-term tails, vote grants refuse
candidates with stale logs, and followers ignore vote requests while a
live leader is within the minimum election timeout (leader stickiness —
what makes the leader lease safe under bounded clock drift).

Fault seams (utils/faults): `consensus.vote`, `consensus.append`,
`consensus.snapshot` fire inside the inbound handlers (an injected error
is a dropped/failed RPC), `consensus.commit` fires before the leader
advances its commit index, and `consensus.persist` /
`consensus.persist.write` guard the journal exactly like the kvd store
journal's seams.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass

from m3_tpu.utils import faults
from m3_tpu.utils.instrument import default_registry

# replication-seam latency distributions, pre-registered via handles so
# /metrics exposes the consensus seams (zero-count) from process start:
# append-entries handling, and submit->apply commit latency (RaftNode.wait)
_scope = default_registry().root_scope("consensus")
_observe_append = _scope.histogram_handle("append_seconds")
_observe_commit = _scope.histogram_handle("commit_seconds")

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeader(Exception):
    """Raised on submit/read at a non-leader; carries the leader hint."""

    def __init__(self, leader_id: str | None):
        super().__init__(f"not leader (leader hint: {leader_id})")
        self.leader_id = leader_id


class CommandLost(Exception):
    """A submitted command's slot was taken by another leader's entry (or
    leadership was lost before commit) — the command may or may not ever
    commit; the caller must re-check state before retrying."""


@dataclass
class LogEntry:
    term: int
    command: bytes


@dataclass(frozen=True)
class Ticket:
    """A submitted command's claim: (index, term) uniquely name a log slot
    content-wise (the Log Matching property)."""

    index: int
    term: int


class RaftNode:
    """One consensus participant. Thread-safe; every public method may be
    called from any thread. `apply_fn(index, command) -> result` is
    invoked IN COMMIT ORDER under the node lock (keep it fast and never
    call back into the node from it). Empty commands (the leader's
    term-opening no-op) are applied too — state machines must treat
    ``b""`` as a no-op.
    """

    def __init__(
        self,
        node_id: str,
        peer_ids: list[str],
        apply_fn,
        storage_path: str | None = None,
        snapshot_fn=None,
        restore_fn=None,
        clock=time.monotonic,
        rng: random.Random | None = None,
        election_timeout_s: tuple[float, float] = (1.0, 2.0),
        heartbeat_s: float = 0.25,
        compact_at: int = 1024,
    ):
        self.node_id = node_id
        self.peer_ids = [p for p in peer_ids if p != node_id]
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.clock = clock
        self._rng = rng or random.Random(f"raft:{node_id}")
        self.election_timeout_s = election_timeout_s
        self.heartbeat_s = heartbeat_s
        self.compact_at = compact_at
        self._storage_path = storage_path

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)

        # persistent state
        self.term = 0
        self.voted_for: str | None = None
        self._log: list[LogEntry] = []  # entries (snap_idx+1 .. last_index)
        self._snap_idx = 0
        self._snap_term = 0
        self._snap_data: bytes = b""

        # volatile state
        self.role = FOLLOWER
        self.leader_id: str | None = None
        self.commit_index = 0
        self.last_applied = 0
        self._votes: set[str] = set()
        self._next_idx: dict[str, int] = {}
        self._match_idx: dict[str, int] = {}
        # send-time of the latest append this peer ACKED (leader lease)
        self._lease_ack: dict[str, float] = {}
        # leader stickiness for votes. Initialized to NOW, not -inf: a
        # freshly (re)booted node must refuse term-advancing votes for
        # one minimum election timeout — its pre-crash refusal state is
        # volatile, and granting immediately would let a partitioned
        # candidate depose a leader INSIDE that leader's lease window
        # (the lease's safety rests on this guard). Liveness is
        # unaffected: no election deadline fires sooner than the minimum
        # timeout anyway.
        self._last_leader_contact = self.clock()
        self._force_hb = False
        self._hb_due = 0.0
        self._election_deadline = 0.0
        # apply results for proposers, bounded (index -> (term, result))
        self._results: dict[int, tuple[int, object]] = {}

        self._restore()
        if self.restore_fn is not None and self._snap_data:
            self.restore_fn(self._snap_data)
            self.last_applied = self._snap_idx
        self.commit_index = self._snap_idx
        self.last_applied = max(self.last_applied, self._snap_idx)
        self._reset_election_deadline()

    # -- log helpers (1-based indices; <= snap_idx is compacted away) --

    @property
    def last_index(self) -> int:
        return self._snap_idx + len(self._log)

    def term_at(self, idx: int) -> int | None:
        if idx == 0:
            return 0
        if idx == self._snap_idx:
            return self._snap_term
        if self._snap_idx < idx <= self.last_index:
            return self._log[idx - self._snap_idx - 1].term
        return None  # compacted or beyond the log

    def _entry(self, idx: int) -> LogEntry:
        return self._log[idx - self._snap_idx - 1]

    @property
    def majority(self) -> int:
        return (len(self.peer_ids) + 1) // 2 + 1

    # -- persistence (the kvd journal discipline: atomic tmp+fsync+replace) --

    # The lock-blocking-call waivers on the _persist/_step_down/
    # _apply_committed call sites below are deliberate: raft's durability
    # contract requires term/vote/log to hit disk BEFORE the node answers
    # (persist-before-ack), and every answer is computed under the node
    # lock. Moving the fsync off-lock needs an etcd-style ready/advance
    # pipeline — that is ROADMAP #3's async-executor seam, not a comment.
    def _persist(self) -> None:
        if self._storage_path is None:
            return
        faults.check("consensus.persist", node=self.node_id)
        payload = json.dumps({
            "term": self.term,
            "voted_for": self.voted_for,
            "snap_idx": self._snap_idx,
            "snap_term": self._snap_term,
            "snap": self._snap_data.hex(),
            "log": [[e.term, e.command.hex()] for e in self._log],
        }).encode()
        tmp = self._storage_path + ".tmp"
        with open(tmp, "wb") as f:
            faults.torn_write(f, payload, "consensus.persist.write")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._storage_path)

    def _restore(self) -> None:
        if self._storage_path is None or not os.path.exists(self._storage_path):
            return
        try:
            with open(self._storage_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return  # torn tmp never lands under the final name
        self.term = doc["term"]
        self.voted_for = doc["voted_for"]
        self._snap_idx = doc["snap_idx"]
        self._snap_term = doc["snap_term"]
        self._snap_data = bytes.fromhex(doc["snap"])
        self._log = [LogEntry(t, bytes.fromhex(c)) for t, c in doc["log"]]

    # -- timers --

    def _reset_election_deadline(self) -> None:
        lo, hi = self.election_timeout_s
        self._election_deadline = self.clock() + lo + self._rng.random() * (hi - lo)

    def tick(self) -> list[tuple[str, str, dict]]:
        """Advance timers; returns outbound (peer, rpc, payload) messages."""
        with self._lock:
            now = self.clock()
            if self.role != LEADER:
                if now >= self._election_deadline:
                    # m3lint: disable=lock-blocking-call
                    return self._start_election()
                return []
            if self._force_hb or now >= self._hb_due:
                self._force_hb = False
                self._hb_due = now + self.heartbeat_s
                return [self._replicate_msg(p) for p in self.peer_ids]
            return []

    # -- elections --

    def _start_election(self) -> list[tuple[str, str, dict]]:
        self.term += 1
        self.role = CANDIDATE
        self.voted_for = self.node_id
        self.leader_id = None
        self._votes = {self.node_id}
        self._persist()
        self._reset_election_deadline()
        self._cond.notify_all()
        if self._has_majority(self._votes):  # single-node cluster
            return self._become_leader()
        req = {
            "term": self.term,
            "cand": self.node_id,
            "last_idx": self.last_index,
            "last_term": self.term_at(self.last_index),
        }
        return [(p, "vote", dict(req)) for p in self.peer_ids]

    def _has_majority(self, votes: set[str]) -> bool:
        return len(votes) >= self.majority

    def _become_leader(self) -> list[tuple[str, str, dict]]:
        self.role = LEADER
        self.leader_id = self.node_id
        nxt = self.last_index + 1
        self._next_idx = {p: nxt for p in self.peer_ids}
        self._match_idx = {p: 0 for p in self.peer_ids}
        self._lease_ack = {}
        # open the term with a no-op so the previous term's tail commits
        # (a leader may only COUNT replicas of its own-term entries)
        self._log.append(LogEntry(self.term, b""))
        self._persist()
        self._maybe_advance_commit()
        self._hb_due = self.clock() + self.heartbeat_s
        self._cond.notify_all()
        return [self._replicate_msg(p) for p in self.peer_ids]

    def _step_down(self, term: int, leader: str | None = None) -> None:
        changed = term != self.term
        self.term = term
        if changed:
            self.voted_for = None
        self.role = FOLLOWER
        if leader is not None:
            self.leader_id = leader
        self._reset_election_deadline()
        if changed:
            self._persist()
        self._cond.notify_all()

    # -- replication --

    def _replicate_msg(self, peer: str) -> tuple[str, str, dict]:
        """The next append (or snapshot install) for `peer`."""
        nxt = self._next_idx.get(peer, self.last_index + 1)
        if nxt <= self._snap_idx:
            return (peer, "snapshot", {
                "term": self.term,
                "leader": self.node_id,
                "last_idx": self._snap_idx,
                "last_term": self._snap_term,
                "state": self._snap_data.hex(),
                "_sent": self.clock(),
            })
        prev = nxt - 1
        entries = [[e.term, e.command.hex()]
                   for e in self._log[nxt - self._snap_idx - 1:]]
        return (peer, "append", {
            "term": self.term,
            "leader": self.node_id,
            "prev_idx": prev,
            "prev_term": self.term_at(prev),
            "entries": entries,
            "commit": self.commit_index,
            "_sent": self.clock(),
        })

    # -- inbound RPC --

    def handle(self, rpc: str, req: dict) -> dict:
        if rpc == "vote":
            return self._handle_vote(req)
        if rpc == "append":
            return self._handle_append(req)
        if rpc == "snapshot":
            return self._handle_snapshot(req)
        raise ValueError(f"unknown raft rpc {rpc!r}")

    def _handle_vote(self, req: dict) -> dict:
        faults.check("consensus.vote", node=self.node_id)
        with self._lock:
            now = self.clock()
            # leader stickiness: within one minimum election timeout of
            # hearing a leader (or of BOOTING — see __init__), refuse to
            # advance terms for a challenger. This is what makes the
            # leader LEASE safe: a partitioned candidate cannot recruit
            # voters that may still be inside a live leader's window.
            if (req["term"] > self.term
                    and now - self._last_leader_contact
                    < self.election_timeout_s[0]):
                return {"term": self.term, "granted": False}
            if req["term"] > self.term:
                # m3lint: disable=lock-blocking-call
                self._step_down(req["term"])
            granted = False
            if req["term"] == self.term and \
                    self.voted_for in (None, req["cand"]):
                my_last_term = self.term_at(self.last_index) or 0
                up_to_date = (req["last_term"], req["last_idx"]) >= \
                    (my_last_term, self.last_index)
                if up_to_date:
                    granted = True
                    if self.voted_for is None:
                        self.voted_for = req["cand"]
                        # m3lint: disable=lock-blocking-call
                        self._persist()
                    self._reset_election_deadline()
            return {"term": self.term, "granted": granted}

    def _handle_append(self, req: dict) -> dict:
        faults.check("consensus.append", node=self.node_id)
        t0 = time.perf_counter()
        try:
            return self._handle_append_timed(req)
        finally:
            _observe_append(time.perf_counter() - t0)

    def _handle_append_timed(self, req: dict) -> dict:
        with self._lock:
            if req["term"] < self.term:
                return {"term": self.term, "ok": False}
            # m3lint: disable=lock-blocking-call
            self._step_down(req["term"], leader=req["leader"])
            self._last_leader_contact = self.clock()
            prev = req["prev_idx"]
            entries = [LogEntry(t, bytes.fromhex(c)) for t, c in req["entries"]]
            if prev < self._snap_idx:
                # everything at/below the snapshot is committed state here;
                # skip the already-covered prefix of the batch
                drop = self._snap_idx - prev
                entries = entries[drop:]
                prev = self._snap_idx
            if prev > self.last_index:
                return {"term": self.term, "ok": False,
                        "conflict": self.last_index + 1}
            pt = self.term_at(prev)
            if pt != req["prev_term"] and prev > self._snap_idx:
                # fast backup: point the leader at the first index of the
                # conflicting term instead of decrementing one at a time
                conflict = prev
                while conflict > self._snap_idx + 1 and \
                        self.term_at(conflict - 1) == pt:
                    conflict -= 1
                del self._log[prev - self._snap_idx - 1:]
                # m3lint: disable=lock-blocking-call
                self._persist()
                return {"term": self.term, "ok": False, "conflict": conflict}
            changed = False
            for j, e in enumerate(entries):
                idx = prev + 1 + j
                if idx <= self.last_index:
                    if self.term_at(idx) == e.term:
                        continue  # already have it (log matching)
                    del self._log[idx - self._snap_idx - 1:]  # divergence
                self._log.append(e)
                changed = True
            if changed:
                # m3lint: disable=lock-blocking-call
                self._persist()
            match = prev + len(entries)
            # conservative commit bound: only entries VERIFIED to match
            # the leader (<= match) may commit — our tail beyond them
            # could still be a stale term's divergence awaiting truncation
            commit = min(req["commit"], match)
            if commit > self.commit_index:
                self.commit_index = commit
                # m3lint: disable=lock-blocking-call
                self._apply_committed()
            return {"term": self.term, "ok": True, "match": match}

    def _handle_snapshot(self, req: dict) -> dict:
        faults.check("consensus.snapshot", node=self.node_id)
        with self._lock:
            if req["term"] < self.term:
                return {"term": self.term, "ok": False}
            # m3lint: disable=lock-blocking-call
            self._step_down(req["term"], leader=req["leader"])
            self._last_leader_contact = self.clock()
            if req["last_idx"] <= self._snap_idx:
                return {"term": self.term, "ok": True,
                        "match": self._snap_idx}
            state = bytes.fromhex(req["state"])
            if self.term_at(req["last_idx"]) == req["last_term"]:
                # log already holds the snapshot point: just compact to it
                del self._log[: req["last_idx"] - self._snap_idx]
            else:
                self._log = []
            self._snap_idx = req["last_idx"]
            self._snap_term = req["last_term"]
            self._snap_data = state
            if self.restore_fn is not None:
                self.restore_fn(state)
            self.commit_index = max(self.commit_index, self._snap_idx)
            self.last_applied = max(self.last_applied, self._snap_idx)
            # m3lint: disable=lock-blocking-call
            self._persist()
            # m3lint: disable=lock-blocking-call
            self._apply_committed()
            self._cond.notify_all()
            return {"term": self.term, "ok": True, "match": self._snap_idx}

    # -- responses --

    def on_response(self, peer: str, rpc: str, req: dict,
                    resp: dict | None) -> list[tuple[str, str, dict]]:
        if resp is None:
            return []
        with self._lock:
            if resp["term"] > self.term:
                # m3lint: disable=lock-blocking-call
                self._step_down(resp["term"])
                return []
            if rpc == "vote":
                if self.role == CANDIDATE and req["term"] == self.term \
                        and resp.get("granted"):
                    self._votes.add(peer)
                    if self._has_majority(self._votes):
                        # m3lint: disable=lock-blocking-call
                        return self._become_leader()
                return []
            if self.role != LEADER or req["term"] != self.term:
                return []
            if rpc == "snapshot" and resp.get("ok"):
                self._match_idx[peer] = max(
                    self._match_idx.get(peer, 0), resp["match"])
                self._next_idx[peer] = self._match_idx[peer] + 1
                self._lease_ack[peer] = req["_sent"]
                self._cond.notify_all()
                if self._next_idx[peer] <= self.last_index:
                    return [self._replicate_msg(peer)]
                return []
            if rpc != "append":
                return []
            if resp.get("ok"):
                self._match_idx[peer] = max(
                    self._match_idx.get(peer, 0), resp["match"])
                self._next_idx[peer] = self._match_idx[peer] + 1
                self._lease_ack[peer] = req["_sent"]
                # m3lint: disable=lock-blocking-call
                self._maybe_advance_commit()
                self._cond.notify_all()
                if self._next_idx[peer] <= self.last_index:
                    return [self._replicate_msg(peer)]
                return []
            conflict = resp.get("conflict", self._next_idx.get(peer, 2) - 1)
            self._next_idx[peer] = max(1, min(
                conflict, self._next_idx.get(peer, self.last_index + 1) - 1))
            return [self._replicate_msg(peer)]

    def _maybe_advance_commit(self) -> None:
        for n in range(self.last_index, self.commit_index, -1):
            if self.term_at(n) != self.term:
                break  # only own-term entries commit by counting (fig. 8)
            acks = 1 + sum(1 for p in self.peer_ids
                           if self._match_idx.get(p, 0) >= n)
            if acks >= self.majority:
                faults.check("consensus.commit", node=self.node_id, index=n)
                self.commit_index = n
                _scope.counter("commits")
                self._apply_committed()
                break

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            idx = self.last_applied + 1
            e = self._entry(idx)
            result = self.apply_fn(idx, e.command)
            self.last_applied = idx
            self._results[idx] = (e.term, result)
            if len(self._results) > 2048:
                for k in sorted(self._results)[:1024]:
                    del self._results[k]
        self._maybe_compact()
        self._cond.notify_all()

    def _maybe_compact(self) -> None:
        if self.snapshot_fn is None or len(self._log) <= self.compact_at:
            return
        if self.last_applied <= self._snap_idx:
            return
        state = self.snapshot_fn()
        new_term = self.term_at(self.last_applied)
        del self._log[: self.last_applied - self._snap_idx]
        self._snap_idx = self.last_applied
        self._snap_term = new_term
        self._snap_data = state
        self._persist()

    # -- client surface --

    def submit(self, command: bytes) -> Ticket:
        """Append a command at the leader; raises NotLeader elsewhere.
        Returns the (index, term) ticket; commit/apply happens as
        replication proceeds (wait() blocks for it in live mode)."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeader(self.leader_id)
            self._log.append(LogEntry(self.term, command))
            # m3lint: disable=lock-blocking-call
            self._persist()
            idx = self.last_index
            self._force_hb = True  # replicate now, not next heartbeat
            if not self.peer_ids:
                # m3lint: disable=lock-blocking-call
                self._maybe_advance_commit()
            return Ticket(idx, self.term)

    def wait(self, ticket: Ticket, timeout_s: float = 10.0):
        """Block until the ticket's entry applies; returns apply_fn's
        result. Raises CommandLost if the slot committed under a different
        term (leadership was lost and the log rewritten).

        A successful wait records the submit->apply latency into the
        consensus commit histogram — the consensus-plane price of every
        replicated mutation (single-node raft included)."""
        t0 = time.perf_counter()
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                got = self._results.get(ticket.index)
                if got is not None:
                    term, result = got
                    if term != ticket.term:
                        raise CommandLost(
                            f"index {ticket.index} committed at term {term}, "
                            f"submitted at {ticket.term}")
                    _observe_commit(time.perf_counter() - t0)
                    return result
                if self.last_applied >= ticket.index:
                    raise CommandLost(f"result for {ticket.index} evicted")
                # a newer term having overwritten our slot surfaces fast
                if self.term_at(ticket.index) not in (ticket.term, None) \
                        or (self.role != LEADER
                            and self.last_index < ticket.index):
                    raise CommandLost(
                        f"slot {ticket.index} rewritten before commit")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"no quorum commit for index {ticket.index} "
                        f"within {timeout_s}s")
                self._cond.wait(min(remaining, 0.1))

    # -- linearizable reads: leader lease, read-index fallback --

    def has_lease(self) -> bool:
        """True while a quorum acked an append sent within the lease
        window — no other node can have won an election meanwhile (vote
        stickiness holds challengers off for election_timeout_s[0])."""
        with self._lock:
            return self._lease_until() > self.clock()

    def _lease_until(self) -> float:
        if self.role != LEADER:
            return -1e18
        acks = sorted([self.clock()] +
                      [self._lease_ack.get(p, -1e18) for p in self.peer_ids],
                      reverse=True)
        quorum_ack = acks[self.majority - 1]
        return quorum_ack + self.election_timeout_s[0] * 0.9

    def read_barrier(self, timeout_s: float = 5.0) -> bool:
        """Linearizable read point: returns True once this node is
        CONFIRMED leader with every write committed before the call
        applied locally. Fast path is the leader lease; the fallback is
        raft's read-index protocol (heartbeat round confirming the term,
        then wait for the apply watermark). Either path first requires an
        entry of the CURRENT term committed (the term-opening no-op): a
        fresh leader's commit_index may still trail entries a previous
        leader committed, and serving before the no-op lands would read a
        stale state machine."""
        deadline = time.monotonic() + timeout_s
        read_idx = None
        start = None
        with self._cond:
            while True:
                if self.role != LEADER:
                    return False
                if read_idx is None and \
                        self.term_at(self.commit_index) == self.term:
                    read_idx = self.commit_index
                    start = self.clock()
                    if self._lease_until() > start and \
                            self.last_applied >= read_idx:
                        return True
                if read_idx is not None:
                    acked = 1 + sum(
                        1 for p in self.peer_ids
                        if self._lease_ack.get(p, -1e18) >= start)
                    if acked >= self.majority and \
                            self.last_applied >= read_idx:
                        return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._force_hb = True
                self._cond.wait(min(remaining, 0.05))

    # -- introspection --

    def status(self) -> dict:
        with self._lock:
            return {
                "node": self.node_id,
                "role": self.role,
                "term": self.term,
                "leader": self.leader_id,
                "commit": self.commit_index,
                "applied": self.last_applied,
                "last_index": self.last_index,
                "snap_idx": self._snap_idx,
            }


# ---------------------------------------------------------------------------
# deterministic in-process cluster (virtual clock + partitionable links)
# ---------------------------------------------------------------------------


class LocalRaftCluster:
    """N RaftNodes over an in-memory, PARTITIONABLE message bus under one
    virtual clock — the deterministic harness the consensus unit tests
    and the seeded chaos sweep drive. `step()` advances virtual time,
    ticks every live node, and delivers the produced messages in FIFO
    order; a blocked link or dead node silently eats the message (exactly
    what a real partition does to UDP^WgRPC). Faults injected inside
    handlers (consensus.vote/append/snapshot) surface as dropped RPCs."""

    def __init__(self, node_ids: list[str], make_apply, tmp_dir: str | None = None,
                 seed: int = 0, dt: float = 0.05, make_snapshot=None,
                 make_restore=None, **node_kw):
        self.now = 0.0
        self.dt = dt
        self.node_ids = list(node_ids)
        self._make_apply = make_apply
        self._make_snapshot = make_snapshot
        self._make_restore = make_restore
        self._tmp_dir = tmp_dir
        self._node_kw = node_kw
        self.rng = random.Random(seed)
        self.nodes: dict[str, RaftNode] = {}
        self.down: set[str] = set()
        self.blocked: set[tuple[str, str]] = set()  # directed (src, dst)
        self.pending: list[tuple[str, str, str, dict]] = []  # src,dst,rpc,req
        for nid in self.node_ids:
            self._make_node(nid)

    def _make_node(self, nid: str) -> RaftNode:
        path = os.path.join(self._tmp_dir, f"{nid}.raft") \
            if self._tmp_dir else None
        node = RaftNode(
            nid, self.node_ids, self._make_apply(nid),
            storage_path=path,
            snapshot_fn=self._make_snapshot(nid) if self._make_snapshot else None,
            restore_fn=self._make_restore(nid) if self._make_restore else None,
            clock=lambda: self.now,
            rng=random.Random(f"{self.rng.random()}:{nid}"),
            **self._node_kw)
        self.nodes[nid] = node
        return node

    # -- nemesis controls --

    def kill(self, nid: str) -> None:
        self.down.add(nid)
        self.pending = [m for m in self.pending
                        if m[0] != nid and m[1] != nid]

    def restart(self, nid: str) -> RaftNode:
        """Bring a killed node back from its persisted journal (volatile
        state — votes in flight, leadership — dies with the process)."""
        self.down.discard(nid)
        return self._make_node(nid)

    def partition(self, *groups: list[str]) -> None:
        """Only links WITHIN a group stay up; everything across is cut."""
        self.blocked = set()
        group_of = {}
        for gi, g in enumerate(groups):
            for nid in g:
                group_of[nid] = gi
        for a in self.node_ids:
            for b in self.node_ids:
                if a != b and group_of.get(a) != group_of.get(b):
                    self.blocked.add((a, b))

    def heal(self) -> None:
        self.blocked = set()

    def _link_up(self, src: str, dst: str) -> bool:
        return (src not in self.down and dst not in self.down
                and (src, dst) not in self.blocked)

    # -- the pump --

    def step(self) -> None:
        self.now += self.dt
        for nid in self.node_ids:
            if nid in self.down:
                continue
            for out in self.nodes[nid].tick():
                self.pending.append((nid, *out))
        batch, self.pending = self.pending, []
        for src, dst, rpc, req in batch:
            if not self._link_up(src, dst):
                continue
            try:
                resp = self.nodes[dst].handle(rpc, req)
            # the virtual cluster IS the chaos harness: a crash injected
            # at a consensus seam models THAT node dropping the RPC, and
            # the simulation must keep pumping the other nodes
            # m3lint: disable=inv-crash-swallow
            except Exception:  # noqa: BLE001 - injected fault = dropped RPC
                continue
            if src in self.down or not self._link_up(dst, src):
                continue  # the answer dies on the return path
            try:
                for out in self.nodes[src].on_response(dst, rpc, req, resp):
                    self.pending.append((src, *out))
            # m3lint: disable=inv-crash-swallow  (same: simulated drop)
            except Exception:  # noqa: BLE001
                continue

    def run_until(self, cond, max_steps: int = 2000) -> bool:
        for _ in range(max_steps):
            if cond():
                return True
            self.step()
        return cond()

    # -- helpers --

    def live(self) -> list[RaftNode]:
        return [self.nodes[n] for n in self.node_ids if n not in self.down]

    def leader(self) -> RaftNode | None:
        """The live leader of the HIGHEST term, if any."""
        leaders = [n for n in self.live() if n.role == LEADER]
        return max(leaders, key=lambda n: n.term) if leaders else None

    def wait_leader(self, max_steps: int = 2000) -> RaftNode:
        if not self.run_until(lambda: self.leader() is not None, max_steps):
            raise TimeoutError("no leader elected")
        return self.leader()

    def submit_and_commit(self, command: bytes, max_steps: int = 2000):
        """Drive a command through the current leader to APPLIED on the
        leader; returns apply_fn's result."""
        ldr = self.wait_leader(max_steps)
        t = ldr.submit(command)
        if not self.run_until(
                lambda: ldr.last_applied >= t.index or ldr.role != LEADER
                or ldr.term_at(t.index) != t.term, max_steps):
            raise TimeoutError(f"no commit for {t}")
        got = ldr._results.get(t.index)
        if got is None or got[0] != t.term:
            raise CommandLost(str(t))
        return got[1]
