"""m3kvd — the cluster metadata plane: watch-push versioned KV with
leases and linearizable CAS over gRPC.

Role parity: the reference runs every piece of cluster metadata
(placements, elections, rules, runtime options, msg topics) on etcd — a
watchable versioned store with compare-and-set and TTL leases
(/root/reference/src/cluster/kv/types.go:113 for the store contract,
src/cluster/etcd/ for the client wiring, src/cluster/services/leader for
elections). Rounds 1–2 stood this up as a shared JSON file that every
process re-polled once per tick (cluster/kv.py FileKVStore.refresh) —
functional, but pull-based and host-local.

This module is the push-based replacement, redesigned rather than ported:
one kvd process (file-journaled by default) serializes all mutations — a
single writer IS linearizable, the same trick the reference leans on
etcd's raft leader for — and streams change events to every subscribed
client over server-streaming gRPC, so placement changes, rule updates,
and election flips propagate in milliseconds without any polling. Leases
give liveness: a key written with ``ephemeral=True`` attaches to its
writer's session lease and vanishes when the owner stops sending
keep-alives (process death included) — which is what makes
kill-the-leader failover work. Plain writes are persistent (etcd
put-without-lease semantics).

Survivability (round-4 hardening + round-6 consensus):
- revisions are monotonic across restarts (single-node: epoch-based;
  replicated: the raft log index, identical on every node), so surviving
  clients never drop post-restart or post-failover events as replays;
- single-node mode journals the ephemeral-key set (reserved key
  ``_kvd/eph``); a restarted server grace-leases restored ephemeral
  keys — dead owners' election keys are reaped after the grace TTL while
  live owners re-grant their session (keepalive "notfound" → re-grant +
  re-assert) and keep their keys;
- REPLICATED mode (``--node-id`` + ``--peers``, any odd N) runs every
  mutation — set/cas/delete, lease grant/revoke/expiry — through a
  raft-lite log (cluster/consensus.py): the leader acks a write only
  after a MAJORITY committed it, followers answer ``notleader:<addr>``
  and clients re-route on the hint, reads are linearizable via the
  leader lease with a read-index fallback, and lagging or restarted
  nodes catch up by log replay or snapshot install. No node can become
  writable without winning a majority vote, so the old single-standby
  dual-write hazard is structurally impossible — there is no promotion
  path outside consensus.

Wire schema (hand-rolled protowire over raw-bytes gRPC, house style of
query/remote.py — no protobuf codegen):

  Req:    1 key(bytes) 2 data(bytes) 3 expect_version(varint,
          +1-biased so "absent"=0 is distinguishable from "expect 0")
          4 lease_id(varint) 5 prefix(bytes) 6 ttl_ms(varint)
  Resp:   1 version(varint) 2 data(bytes) 3 err(utf8: notfound|conflict)
          4 lease_id(varint) 5 repeated key(bytes)
  Event:  1 key(bytes) 2 version(varint) 3 data(bytes)
          4 deleted(varint bool) 5 bootstrap_done(varint bool)

Client `KvdClient` implements the exact `cluster.kv.KVStore` surface
(get/set/set_if_not_exists/check_and_set/delete/keys/watch/refresh), so
Services/LeaderService/placement/rules/runtime-options run on it
unchanged; `refresh()` is a no-op because watches are pushed.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import threading
import time
from concurrent import futures

from m3_tpu.cluster import consensus
from m3_tpu.cluster.kv import (
    FileKVStore,
    KeyNotFound,
    KVError,
    KVStore,
    VersionedValue,
    VersionMismatch,
)
from m3_tpu.utils import faults
from m3_tpu.utils.protowire import field_bytes, field_varint, iter_fields

_SERVICE = "m3.cluster.Kvd"


def _method(name: str) -> str:
    return f"/{_SERVICE}/{name}"


# ---------------------------------------------------------------------------
# message codecs
# ---------------------------------------------------------------------------


def _enc_req(key: str = "", data: bytes = b"", expect_version: int | None = None,
             lease_id: int = 0, prefix: str = "", ttl_ms: int = 0) -> bytes:
    out = b""
    if key:
        out += field_bytes(1, key.encode())
    if data:
        out += field_bytes(2, data)
    if expect_version is not None:
        out += field_varint(3, expect_version + 1)  # bias: 0 = not a CAS
    if lease_id:
        out += field_varint(4, lease_id)
    if prefix:
        out += field_bytes(5, prefix.encode())
    if ttl_ms:
        out += field_varint(6, ttl_ms)
    return out


def _dec_req(payload: bytes):
    key, data, expect, lease, prefix, ttl = "", b"", None, 0, "", 0
    for fno, _wt, val in iter_fields(payload):
        if fno == 1:
            key = val.decode()
        elif fno == 2:
            data = val
        elif fno == 3:
            expect = val - 1
        elif fno == 4:
            lease = val
        elif fno == 5:
            prefix = val.decode()
        elif fno == 6:
            ttl = val
    return key, data, expect, lease, prefix, ttl


def _enc_resp(version: int = 0, data: bytes = b"", err: str = "",
              lease_id: int = 0, keys: list[str] | None = None) -> bytes:
    out = b""
    if version:
        out += field_varint(1, version)
    if data:
        out += field_bytes(2, data)
    if err:
        out += field_bytes(3, err.encode())
    if lease_id:
        out += field_varint(4, lease_id)
    for k in keys or ():
        out += field_bytes(5, k.encode())
    return out


def _dec_resp(payload: bytes):
    version, data, err, lease, keys = 0, b"", "", 0, []
    for fno, _wt, val in iter_fields(payload):
        if fno == 1:
            version = val
        elif fno == 2:
            data = val
        elif fno == 3:
            err = val.decode()
        elif fno == 4:
            lease = val
        elif fno == 5:
            keys.append(val.decode())
    return version, data, err, lease, keys


def _enc_event(key: str, version: int, data: bytes, deleted: bool,
               bootstrap_done: bool = False, rev: int = 0) -> bytes:
    out = field_bytes(1, key.encode())
    if version:
        out += field_varint(2, version)
    if data:
        out += field_bytes(3, data)
    if deleted:
        out += field_varint(4, 1)
    if bootstrap_done:
        out += field_varint(5, 1)
    if rev:
        out += field_varint(6, rev)
    return out


def _dec_event(payload: bytes):
    key, version, data, deleted, done, rev = "", 0, b"", False, False, 0
    for fno, _wt, val in iter_fields(payload):
        if fno == 1:
            key = val.decode()
        elif fno == 2:
            version = val
        elif fno == 3:
            data = val
        elif fno == 4:
            deleted = bool(val)
        elif fno == 5:
            done = bool(val)
        elif fno == 6:
            rev = val
    return key, version, data, deleted, done, rev


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Lease:
    __slots__ = ("lease_id", "ttl_ms", "expires_at", "keys")

    def __init__(self, lease_id: int, ttl_ms: int):
        self.lease_id = lease_id
        self.ttl_ms = ttl_ms
        self.expires_at = time.monotonic() + ttl_ms / 1e3
        self.keys: set[str] = set()


class KvdServer:
    """Metadata server. SINGLE-NODE mode (no peers): all mutations
    serialize through the backing store's lock — one writer means every
    CAS observes the latest committed version (linearizable without
    consensus). REPLICATED mode (node_id + peers): every mutation is a
    command in a raft-lite log (cluster/consensus.py); the leader acks
    only on majority commit, followers reject writes/reads with a leader
    hint, and the lease table rides the replicated state machine — the
    etcd shape the reference leans on, in-house."""

    # single-node mode only: reserved store key tracking which keys are
    # lease-attached; rides the journal so a restarted server knows which
    # restored keys are ephemeral and must be grace-reaped unless their
    # owner re-attaches (replicated mode carries the whole lease table in
    # raft snapshots instead, the way etcd persists leases in raft state)
    EPH_KEY = "_kvd/eph"

    def __init__(self, listen: str, journal_path: str | None = None,
                 max_workers: int = 16, node_id: str | None = None,
                 peers: dict[str, str] | None = None,
                 orphan_grace_ms: int = 10_000,
                 election_timeout_s: tuple[float, float] = (1.0, 2.0),
                 heartbeat_s: float = 0.25,
                 debug_port: int | None = None):
        import grpc

        self._replicated = bool(peers) and len(peers) > 1
        if self._replicated and node_id not in peers:
            raise ValueError(f"node_id {node_id!r} missing from peers")
        self._node_id = node_id
        self._peers = dict(peers or {})
        self._leases: dict[int, _Lease] = {}
        self._key_lease: dict[str, int] = {}  # current lease owner per key
        self._lock = threading.Lock()
        self._eph_persist_lock = threading.Lock()
        self._subs: list[tuple[str, queue.SimpleQueue]] = []
        self._closed = threading.Event()
        self._orphan_grace_ms = orphan_grace_ms
        # server-global revision, stamped on every change event: versions
        # restart at 1 when a key is deleted and re-created, so clients
        # dedupe replayed events by revision, not version (etcd's
        # store-revision idea). Single-node: EPOCH-BASED so it stays
        # monotonic across a restart — a fresh counter would start below
        # clients' cached revs and every post-restart event would be
        # silently dropped as a replay (round-4 advisor finding).
        # Replicated: the RAFT LOG INDEX (shifted to leave per-command
        # event room), identical on every node — a client failing over to
        # another replica keeps deduping correctly.
        self._rev = (time.time_ns() // 1_000_000) << 16
        self._key_rev: dict[str, int] = {}
        self._raft: consensus.RaftNode | None = None
        # proposals park their gRPC worker in the quorum wait (up to
        # 10s); cap them BELOW the pool size so inbound raft RPCs —
        # the traffic that resolves a quorum loss — can always get a
        # worker while writers are stalled
        self._propose_gate = threading.BoundedSemaphore(
            max(2, max_workers - 4))

        if self._replicated:
            # the raft journal (log + snapshots) IS the durability story;
            # the store itself is in-memory state rebuilt by replay
            self.store: KVStore = KVStore()
            self._lease_seq = 0  # replicated state: deterministic ids
            self._rev = 0
            self._was_leader = False
            self._raft = consensus.RaftNode(
                node_id, list(self._peers), self._apply_command,
                storage_path=journal_path,
                snapshot_fn=self._snapshot_state,
                restore_fn=self._restore_state,
                election_timeout_s=election_timeout_s,
                heartbeat_s=heartbeat_s)
        else:
            self.store = FileKVStore(journal_path) if journal_path else KVStore()
            self._lease_seq = int(time.time() * 1e3) % 1_000_000 * 1_000

        # every store mutation fans out to subscriber queues (the store
        # has per-key watches only, so intercept its notify fanout)
        self._wrap_store_notifications()

        def traced(name, fn):
            # server half of kvd trace propagation: clients send their
            # context as gRPC metadata; the handler's span (and anything
            # the state machine does under it) joins the caller's trace
            def call(req, ctx):
                from m3_tpu.utils import trace as _trace

                tctx = _trace.from_grpc_context(ctx)
                if tctx is None:
                    return fn(req, ctx)
                with _trace.activate(tctx), \
                        _trace.span(_trace.KVD_HANDLE, method=name):
                    return fn(req, ctx)

            return call

        handlers_unary = {
            "Get": traced("Get", self._get),
            "Set": traced("Set", self._set),
            "Cas": traced("Cas", self._cas),
            "Delete": traced("Delete", self._delete),
            "Keys": traced("Keys", self._keys),
            "LeaseGrant": traced("LeaseGrant", self._lease_grant),
            "LeaseKeepAlive": self._lease_keepalive,
            "LeaseRevoke": traced("LeaseRevoke", self._lease_revoke),
            "Health": lambda req, ctx: b"ok",
            "Status": self._status,
            "Raft": self._raft_rpc,
        }

        outer = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                name = details.method.rsplit("/", 1)[-1]
                if name == "Watch":
                    return grpc.unary_stream_rpc_method_handler(outer._watch)
                fn = handlers_unary.get(name)
                if fn is None:
                    return None
                return grpc.unary_unary_rpc_method_handler(fn)

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers))
        self._server.add_generic_rpc_handlers((_Handler(),))
        self.port = self._server.add_insecure_port(listen)
        self._server.start()
        # OTLP-style telemetry export (M3_TPU_EXPORT_* env — kvd has no
        # service config file): ships the kvd span ring + consensus seam
        # histograms to the same collector as the other services
        from m3_tpu.utils.export import exporter_from_config

        self._exporter = exporter_from_config(None, "kvd")
        if self._exporter is not None:
            self._exporter.start()
        # always-on profiling plane; kvd speaks gRPC, so /debug/profile
        # is served by the shared debug HTTP surface (`debug_port`
        # config / M3_TPU_DEBUG_PORT env)
        from m3_tpu.utils import profiler

        profiler.arm_from_env("kvd")
        if debug_port is not None:
            self._debug_server = profiler.DebugServer(port=int(debug_port))
        else:
            self._debug_server = profiler.serve_debug_from_env()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()
        if self._replicated:
            self._driver = _RaftDriver(self._raft, self._peers, self._node_id,
                                       self._closed)
        else:
            # journal restore: grace-lease restored ephemeral keys so a
            # dead owner's election/advert keys are reaped (after the
            # grace TTL) instead of wedging failover forever, while a
            # LIVE owner re-attaches on its next session re-grant
            self._grace_lease_ephemerals()

    def _grace_lease_ephemerals(self) -> None:
        try:
            eph = json.loads(self.store.get(self.EPH_KEY).data.decode())
        except (KeyNotFound, ValueError):
            return
        existing = set(self.store.keys())
        present = [k for k in eph if k in existing]
        if not present:
            return
        with self._lock:
            self._lease_seq += 1
            grace = _Lease(self._lease_seq, self._orphan_grace_ms)
            self._leases[grace.lease_id] = grace
        for k in present:
            # check-and-attach is atomic (one _lock acquisition inside
            # _attach_lease): a live owner re-attaching between a separate
            # check and attach would be silently stolen by the grace lease
            # and reaped despite being healthy
            self._attach_lease(k, grace.lease_id, persist=False,
                               only_if_unowned=True)

    # -- store-change fanout --

    def _wrap_store_notifications(self) -> None:
        """Intercept the store's _notify so every key change (including
        FileKVStore.refresh-discovered ones) reaches subscribers."""
        orig = self.store._notify

        def notify(key: str, vv):
            orig(key, vv)
            self._broadcast(key, vv)

        self.store._notify = notify  # type: ignore[method-assign]

    def _broadcast(self, key: str, vv: VersionedValue | None) -> None:
        with self._lock:
            self._rev += 1
            rev = self._rev
            self._key_rev[key] = rev
            subs = list(self._subs)
        ev = _enc_event(key, vv.version if vv else 0, vv.data if vv else b"",
                        deleted=vv is None, rev=rev)
        for prefix, q in subs:
            if key.startswith(prefix):
                q.put(ev)

    # -- replicated mode: the consensus plumbing --

    def _raft_rpc(self, req: bytes, ctx) -> bytes:
        """Inbound raft RPC from a peer (vote/append/snapshot). Injected
        faults inside the handler surface as a gRPC error — the sender
        drops the message, exactly a lossy link."""
        if self._raft is None:
            raise RuntimeError("not a replicated kvd")
        doc = json.loads(req.decode())
        return json.dumps(self._raft.handle(doc["rpc"], doc["req"])).encode()

    def _status(self, req: bytes, ctx) -> bytes:
        doc = {"node": self._node_id, "replicated": self._replicated}
        if self._raft is not None:
            doc.update(self._raft.status())
        else:
            doc.update({"role": "leader"})
        return json.dumps(doc).encode()

    @property
    def is_leader(self) -> bool:
        return self._raft is None or self._raft.role == consensus.LEADER

    def _leader_hint(self) -> str:
        lid = self._raft.leader_id if self._raft is not None else None
        return self._peers.get(lid, "") if lid else ""

    def _propose(self, cmd: dict, timeout_s: float = 10.0) -> dict:
        """Run a command through the replicated log; returns the apply
        result once a MAJORITY committed it. NotLeader propagates to the
        caller (mapped to a notleader hint for clients). The
        submit -> majority-commit latency lands in the consensus commit
        histogram (recorded by RaftNode.wait)."""
        ticket = self._raft.submit(json.dumps(cmd).encode())
        self._driver.poke()  # replicate now, not at the next tick
        return self._raft.wait(ticket, timeout_s)

    def _mutate(self, cmd: dict) -> bytes:
        if not self._propose_gate.acquire(timeout=2.0):
            # every proposal slot is parked waiting on quorum: shed this
            # write with a hint (the client backs off and retries) rather
            # than consume a worker the raft handlers need to recover
            return _enc_resp(err="notleader:" + self._leader_hint())
        try:
            res = self._propose(cmd)
        except consensus.NotLeader:
            return _enc_resp(err="notleader:" + self._leader_hint())
        except (consensus.CommandLost, TimeoutError):
            # leadership lost mid-commit: the command MAY still commit
            # later — the client re-routes and re-reads/retries
            return _enc_resp(err="notleader:" + self._leader_hint())
        finally:
            self._propose_gate.release()
        return _enc_resp(version=res.get("version", 0),
                         err=res.get("err", ""),
                         lease_id=res.get("lease", 0))

    def _read_ready(self) -> bytes | None:
        """Replicated-mode linearizable read gate: leader lease fast
        path, read-index fallback; non-leaders hand back a hint."""
        if self._raft is None:
            return None
        if self._raft.role != consensus.LEADER or \
                not self._raft.read_barrier(timeout_s=5.0):
            return _enc_resp(err="notleader:" + self._leader_hint())
        return None

    def _apply_command(self, index: int, command: bytes):
        """Replicated state machine: executed in commit order on EVERY
        node. Deterministic by construction — versions come from store
        state, lease ids from a replicated counter, and the lease-liveness
        check reads the replicated lease table (no clock reads), so all
        replicas compute identical results."""
        if not command:
            return None  # the leader's term-opening no-op
        cmd = json.loads(command.decode())
        # event revisions derive from the LOG INDEX — identical on every
        # node, monotonic across restarts/failovers (<<16 leaves room for
        # multi-key commands like a lease revoke reaping many keys)
        with self._lock:
            self._rev = max(self._rev, index << 16)
        op = cmd["op"]
        if op in ("set", "cas"):
            lease = cmd.get("l", 0)
            if lease:
                with self._lock:
                    if lease not in self._leases:
                        # the lease expired (a committed revoke) before
                        # this write committed: ephemeral-or-nothing, and
                        # the check is ATOMIC with the write here — no
                        # rollback dance needed (single-node mode keeps
                        # one; see _rollback_noleased)
                        return {"err": "nolease"}
            data = bytes.fromhex(cmd["d"])
            if op == "cas":
                try:
                    version = self.store.check_and_set(
                        cmd["k"], cmd.get("e") or 0, data)
                except VersionMismatch as e:
                    return {"err": f"conflict:{e}"}
            else:
                version = self.store.set(cmd["k"], data)
            self._attach_lease(cmd["k"], lease, persist=False)
            return {"version": version}
        if op == "del":
            try:
                self.store.delete(cmd["k"])
            except KeyNotFound:
                return {"err": "notfound"}
            self._attach_lease(cmd["k"], 0, persist=False)
            return {"version": 1}
        if op == "grant":
            with self._lock:
                self._lease_seq += 1
                lease_obj = _Lease(self._lease_seq, cmd.get("ttl") or 10_000)
                self._leases[lease_obj.lease_id] = lease_obj
            return {"lease": lease_obj.lease_id, "version": lease_obj.ttl_ms}
        if op == "rev":
            self._expire([cmd["l"]])
            return {"lease": cmd["l"]}
        return {"err": f"unknown op {op}"}

    def _snapshot_state(self) -> bytes:
        """Full state-machine image for lagging followers / compaction."""
        with self.store._lock, self._lock:
            doc = {
                "data": {k: [vv.version, vv.data.hex()]
                         for k, vv in self.store._data.items()},
                "leases": {str(le.lease_id): le.ttl_ms
                           for le in self._leases.values()},
                "key_lease": dict(self._key_lease),
                "seq": self._lease_seq,
                "rev": self._rev,
                "key_rev": dict(self._key_rev),
            }
        return json.dumps(doc).encode()

    def _restore_state(self, state: bytes) -> None:
        doc = json.loads(state.decode())
        now = time.monotonic()
        st = self.store
        with st._lock:
            old = dict(st._data)
            st._data = {k: VersionedValue(v, bytes.fromhex(h))
                        for k, (v, h) in doc["data"].items()}
            changed = [(k, vv) for k, vv in st._data.items()
                       if old.get(k) != vv]
            gone = [k for k in old if k not in st._data]
        with self._lock:
            self._rev = max(self._rev, doc.get("rev", 0))
            for k, r in doc.get("key_rev", {}).items():
                self._key_rev[k] = max(self._key_rev.get(k, 0), r)
            self._lease_seq = doc["seq"]
            grace_s = max(self._orphan_grace_ms / 1e3, 1.0)
            self._leases = {}
            for lid_s, ttl in doc["leases"].items():
                le = _Lease(int(lid_s), ttl)
                # restored leases get the orphan grace: their owners were
                # keepaliving another leader and need a window to re-attach
                le.expires_at = now + max(grace_s, ttl / 1e3)
                self._leases[le.lease_id] = le
            self._key_lease = {k: int(v) for k, v in doc["key_lease"].items()}
            for le in self._leases.values():
                le.keys = {k for k, lid in self._key_lease.items()
                           if lid == le.lease_id}
        # live subscribers on a lagging follower hear about the jump
        for k, vv in changed:
            self.store._notify(k, vv)
        for k in gone:
            self.store._notify(k, None)

    # -- unary handlers --

    def _get(self, req: bytes, ctx) -> bytes:
        not_ready = self._read_ready()
        if not_ready is not None:
            return not_ready
        key, *_ = _dec_req(req)
        try:
            vv = self.store.get(key)
        except KeyNotFound:
            return _enc_resp(err="notfound")
        return _enc_resp(version=vv.version, data=vv.data)

    def _lease_live(self, lease: int) -> bool:
        with self._lock:
            return lease in self._leases

    def _set(self, req: bytes, ctx) -> bytes:
        key, data, _exp, lease, _p, _t = _dec_req(req)
        if self._replicated:
            return self._mutate(
                {"op": "set", "k": key, "d": data.hex(), "l": lease})
        if lease and not self._lease_live(lease):
            # a write meant to be EPHEMERAL must never silently become
            # persistent because its lease expired in flight — an
            # unreapable election key wedges failover forever. Reject so
            # the client re-grants and retries (etcd: lease not found).
            return _enc_resp(err="nolease")
        prior, prior_lease = self._prior_state(key) if lease else (None, 0)
        version = self.store.set(key, data)
        if not self._attach_lease(key, lease):  # 0 detaches a prior owner
            # lease expired BETWEEN the check and the attach (reaper runs
            # every 250ms): roll the write back — ephemeral-or-nothing
            self._rollback_noleased(key, prior, prior_lease)
            return _enc_resp(err="nolease")
        return _enc_resp(version=version)

    def _prior_state(self, key: str) -> tuple[VersionedValue | None, int]:
        """The key's pre-write (VersionedValue, lease owner) for rollback.
        Only captured for LEASED writes — _attach_lease(key, 0) cannot
        fail, so lease-less writes never roll back and must not pay the
        extra store.get per write."""
        try:
            prior = self.store.get(key)
        except KeyNotFound:
            prior = None
        with self._lock:
            prior_lease = self._key_lease.get(key, 0)
        return prior, prior_lease

    def _restore_exact(self, key: str, prior: VersionedValue) -> None:
        """Put back a key's exact prior VersionedValue (the store's own
        mutators would renumber). Follows the store's cross-process
        mutation discipline when it has one (FileKVStore: OS file lock +
        reload before rewriting the journal, so concurrent writers'
        committed keys are never clobbered by a stale in-memory view)."""
        st = self.store
        file_lock = getattr(st, "_file_lock", None)
        if file_lock is not None:
            with st._lock, file_lock():
                st._reload()
                st._data[key] = prior
                st._persist()
                st._notify(key, prior)
        else:
            with st._lock:
                st._data[key] = prior
                st._persist()
                st._notify(key, prior)

    def _rollback_noleased(self, key: str, prior: VersionedValue | None,
                           prior_lease: int) -> None:
        """Undo a write whose requested lease expired in flight. A key
        that existed before gets its prior VersionedValue restored at its
        EXACT version (no spurious delete event, no destroyed version
        history) plus its prior lease attachment; only a key that did not
        previously exist is deleted outright."""
        if prior is None:
            try:
                self.store.delete(key)
            except KeyNotFound:
                pass
            self._attach_lease(key, 0)
            return
        self._restore_exact(key, prior)
        if not self._attach_lease(key, prior_lease):
            # the prior owner's lease ALSO died while we were rolling
            # back: its ephemeral key has a dead owner — reap it as the
            # lease expiry would have
            try:
                self.store.delete(key)
            except KeyNotFound:
                pass

    def _cas(self, req: bytes, ctx) -> bytes:
        key, data, expect, lease, _p, _t = _dec_req(req)
        if self._replicated:
            return self._mutate({"op": "cas", "k": key, "d": data.hex(),
                                 "e": expect or 0, "l": lease})
        if lease and not self._lease_live(lease):
            return _enc_resp(err="nolease")
        prior, prior_lease = self._prior_state(key) if lease else (None, 0)
        try:
            version = self.store.check_and_set(key, expect or 0, data)
        except VersionMismatch as e:
            return _enc_resp(err=f"conflict:{e}")
        if not self._attach_lease(key, lease):
            self._rollback_noleased(key, prior, prior_lease)
            return _enc_resp(err="nolease")
        return _enc_resp(version=version)

    def _delete(self, req: bytes, ctx) -> bytes:
        key, *_ = _dec_req(req)
        if self._replicated:
            return self._mutate({"op": "del", "k": key})
        try:
            self.store.delete(key)
        except KeyNotFound:
            return _enc_resp(err="notfound")
        self._attach_lease(key, 0)  # a deleted key belongs to no lease
        return _enc_resp(version=1)

    def _keys(self, req: bytes, ctx) -> bytes:
        not_ready = self._read_ready()
        if not_ready is not None:
            return not_ready
        _k, _d, _e, _l, prefix, _t = _dec_req(req)
        return _enc_resp(keys=self.store.keys(prefix))

    # -- leases --

    def _attach_lease(self, key: str, lease_id: int,
                      persist: bool = True,
                      only_if_unowned: bool = False) -> bool:
        """Make lease_id (0 = none) the key's ONLY lease owner. Every
        write/delete re-resolves ownership, so a key re-created by a new
        client is never reaped by a previous owner's lease expiry.
        Returns False when a REQUESTED lease no longer exists (expired
        between the caller's liveness check and here) — the caller must
        not let the write stand as silently persistent.
        only_if_unowned makes the ownership check and the attach one
        atomic step (grace-lease restore must never displace a live owner
        that re-attached concurrently)."""
        with self._lock:
            if only_if_unowned and self._key_lease.get(key):
                return False
            had = key in self._key_lease
            old = self._key_lease.pop(key, None)
            if old is not None and old in self._leases:
                self._leases[old].keys.discard(key)
            attached = bool(lease_id and lease_id in self._leases)
            if attached:
                self._leases[lease_id].keys.add(key)
                self._key_lease[key] = lease_id
        if persist and attached != had:
            self._persist_eph()
        return attached or not lease_id

    def _persist_eph(self) -> None:
        """Journal the ephemeral-key set under EPH_KEY (skipping the
        broadcast-triggering set when nothing changed). Serialized by its
        own lock so concurrent attach/expire can't journal a stale
        snapshot last (the snapshot is taken while holding it; _lock alone
        can't be held across store.set — the broadcast re-takes it)."""
        if self._replicated:
            return  # the lease table rides raft snapshots, not the store
        with self._eph_persist_lock:
            with self._lock:
                eph = sorted(self._key_lease)
            data = json.dumps(eph).encode()
            try:
                if self.store.get(self.EPH_KEY).data == data:
                    return
            except KeyNotFound:
                if not eph:
                    return
            self.store.set(self.EPH_KEY, data)

    def _lease_grant(self, req: bytes, ctx) -> bytes:
        _k, _d, _e, _l, _p, ttl_ms = _dec_req(req)
        ttl_ms = ttl_ms or 10_000
        if self._replicated:
            return self._mutate({"op": "grant", "ttl": ttl_ms})
        with self._lock:
            self._lease_seq += 1
            lease = _Lease(self._lease_seq, ttl_ms)
            self._leases[lease.lease_id] = lease
        return _enc_resp(lease_id=lease.lease_id, version=ttl_ms)

    def _lease_keepalive(self, req: bytes, ctx) -> bytes:
        # keepalives refresh LEADER-LOCAL soft state (expires_at), never
        # the log: expiry itself only happens via a committed revoke, so
        # the timer freshness needn't be replicated — a new leader re-arms
        # every lease with the orphan grace instead (see _reap_loop)
        if self._replicated and self._raft.role != consensus.LEADER:
            return _enc_resp(err="notleader:" + self._leader_hint())
        _k, _d, _e, lease_id, _p, _t = _dec_req(req)
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                return _enc_resp(err="notfound")
            lease.expires_at = time.monotonic() + lease.ttl_ms / 1e3
        return _enc_resp(lease_id=lease_id, version=lease.ttl_ms)

    def _lease_revoke(self, req: bytes, ctx) -> bytes:
        _k, _d, _e, lease_id, _p, _t = _dec_req(req)
        if self._replicated:
            # surface _mutate's response as-is: a follower must answer
            # with its notleader hint so the client re-routes the revoke
            # (swallowing it would turn graceful resign into a TTL wait)
            return self._mutate({"op": "rev", "l": lease_id})
        self._expire([lease_id])
        return _enc_resp(lease_id=lease_id or 1)

    def _reap_loop(self) -> None:
        while not self._closed.wait(0.25):
            now = time.monotonic()
            if self._replicated:
                self._reap_replicated(now)
                continue
            with self._lock:
                dead = [lid for lid, le in self._leases.items()
                        if le.expires_at <= now]
            if dead:
                self._expire(dead)

    def _reap_replicated(self, now: float) -> None:
        """Leader-driven lease expiry: an expired lease is REVOKED VIA THE
        LOG, so keys are only reaped once a majority commits it — a
        minority-partitioned ex-leader can never reap an election key
        (its propose has no quorum), which is precisely the dual-write
        hole the old standby promotion had."""
        is_leader = self._raft.role == consensus.LEADER
        with self._lock:
            if is_leader and not self._was_leader:
                # leadership gained: re-arm every lease with the orphan
                # grace — owners were keepaliving the previous leader and
                # need a window to re-attach before expiry commits
                grace_s = max(self._orphan_grace_ms / 1e3, 1.0)
                for le in self._leases.values():
                    le.expires_at = max(le.expires_at,
                                        now + max(grace_s, le.ttl_ms / 1e3))
            self._was_leader = is_leader
            dead = [lid for lid, le in self._leases.items()
                    if le.expires_at <= now] if is_leader else []
        for lid in dead:
            try:
                self._propose({"op": "rev", "l": lid}, timeout_s=2.0)
            except Exception:  # noqa: BLE001 - lost leadership / no quorum:
                break          # the next leader's reaper takes over

    def _expire(self, lease_ids: list[int]) -> None:
        any_owned = False
        for lid in lease_ids:
            with self._lock:
                lease = self._leases.pop(lid, None)
                if lease is None:
                    continue
                # only reap keys this lease still owns — a re-created or
                # re-owned key belongs to someone else now
                owned = [k for k in lease.keys
                         if self._key_lease.get(k) == lid]
                for k in owned:
                    self._key_lease.pop(k, None)
            any_owned = any_owned or bool(owned)
            for key in owned:
                try:
                    self.store.delete(key)  # pushes a deleted event
                except KeyNotFound:
                    pass
        if any_owned:
            self._persist_eph()

    # -- watch streaming --

    def _watch(self, req: bytes, ctx):
        _k, _d, _e, _l, prefix, _t = _dec_req(req)
        q: queue.SimpleQueue = queue.SimpleQueue()
        # bootstrap snapshot BEFORE subscribing would lose updates in the
        # gap; subscribe first, then snapshot — duplicate versions are
        # fine (clients dedupe by version)
        with self._lock:
            self._subs.append((prefix, q))
        try:
            for key in self.store.keys(prefix):
                try:
                    vv = self.store.get(key)
                except KeyNotFound:
                    continue
                with self._lock:
                    rev = self._key_rev.get(key, 0)
                yield _enc_event(key, vv.version, vv.data, deleted=False,
                                 rev=rev)
            yield _enc_event("", 0, b"", deleted=False, bootstrap_done=True)
            while ctx.is_active() and not self._closed.is_set():
                try:
                    ev = q.get(timeout=0.5)
                except Exception:  # noqa: BLE001 - Empty
                    continue
                yield ev
        finally:
            with self._lock:
                try:
                    self._subs.remove((prefix, q))
                except ValueError:
                    pass

    def close(self) -> None:
        self._closed.set()
        if self._raft is not None:
            self._driver.poke()  # unblock sender/tick threads promptly
        if self._exporter is not None:
            self._exporter.close()  # final best-effort flush
        if self._debug_server is not None:
            self._debug_server.close()
        self._server.stop(grace=0.5).wait()


class _RaftDriver:
    """Live-mode pump for a replicated kvd's RaftNode: one tick thread
    advances timers, one sender thread per peer delivers outbound
    messages over gRPC (method Kvd/Raft) and feeds responses back. Each
    peer's queue keeps only the LATEST message per rpc type — a newer
    append carries everything a superseded one did, so there is exactly
    one in-flight message per (peer, rpc) and a slow peer can never build
    an unbounded backlog."""

    TICK_S = 0.02

    def __init__(self, node: consensus.RaftNode, peers: dict[str, str],
                 node_id: str, closed: threading.Event):
        self._node = node
        self._closed = closed
        self._addrs = dict(peers)
        self._wake = threading.Event()
        self._cv = threading.Condition()
        self._pending: dict[str, dict[str, dict]] = {
            p: {} for p in peers if p != node_id}
        threading.Thread(target=self._tick_loop, daemon=True).start()
        for p in self._pending:
            threading.Thread(target=self._send_loop, args=(p,),
                             daemon=True).start()

    def poke(self) -> None:
        self._wake.set()
        with self._cv:
            self._cv.notify_all()

    def _queue(self, outs) -> None:
        if not outs:
            return
        with self._cv:
            for peer, rpc, req in outs:
                self._pending.setdefault(peer, {})[rpc] = req
            self._cv.notify_all()

    def _tick_loop(self) -> None:
        from m3_tpu.utils import profiler

        # the raft pump beats at 50 Hz; the heartbeat interval is padded
        # way up so only a genuinely wedged pump (seconds of silence,
        # i.e. elections stop advancing) flags, not GIL scheduling noise
        hb = profiler.register_heartbeat("kvd.raft_tick",
                                         max(0.5, self.TICK_S * 25))
        try:
            while not self._closed.is_set():
                hb.beat()
                try:
                    # the tick-wedge seam (delay faults model a stuck
                    # pump; the stall watchdog must catch it)
                    faults.check("kvd.tick")
                    self._queue(self._node.tick())
                except Exception as e:  # noqa: BLE001 - injected persist
                    # fault etc.; an ARMED SimulatedCrash (chaos rig)
                    # kills the replica process instead of being swallowed
                    faults.escalate(e)
                self._wake.wait(self.TICK_S)
                self._wake.clear()
        finally:
            hb.close()

    def _send_loop(self, peer: str) -> None:
        import grpc

        channel = stub = None
        while not self._closed.is_set():
            with self._cv:
                box = self._pending[peer]
                if not box:
                    self._cv.wait(0.2)
                    continue
                # elections must not starve behind a fat append
                rpc = next(r for r in ("vote", "snapshot", "append")
                           if r in box)
                req = box.pop(rpc)
            try:
                if channel is None:
                    channel = grpc.insecure_channel(self._addrs[peer])
                    stub = channel.unary_unary(_method("Raft"))
                raw = stub(json.dumps({"rpc": rpc, "req": req}).encode(),
                           timeout=2.0)
                resp = json.loads(raw)
            except Exception:  # noqa: BLE001 - peer down/partitioned:
                try:           # drop; the next tick/heartbeat retries
                    if channel is not None:
                        channel.close()
                except Exception:  # noqa: BLE001
                    pass
                channel = stub = None
                self._closed.wait(0.05)
                continue
            try:
                self._queue(self._node.on_response(peer, rpc, req, resp))
            except Exception:  # noqa: BLE001 - injected fault in response
                pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class KvdClient(KVStore):
    """`cluster.kv.KVStore`-compatible client for a kvd server.

    Watches are PUSHED: one background Watch stream (prefix "") feeds the
    same per-key watcher callbacks the in-memory store uses, so
    Services/LeaderService/rules/runtime-options get cross-process change
    propagation with no per-tick polling. `refresh()` is a no-op kept for
    interface compatibility with FileKVStore call sites."""

    def __init__(self, target: str, timeout_s: float = 10.0):
        super().__init__()
        import grpc

        # comma-separated failover list (the quorum replica set). RPCs
        # rotate on transport errors and follow notleader hints, so the
        # current raft leader is found automatically.
        self._targets = [t.strip() for t in target.split(",") if t.strip()]
        self._cur = 0
        self.timeout_s = timeout_s
        self._channel = grpc.insecure_channel(self._targets[0])
        self._stubs: dict[str, object] = {}
        self._stub_lock = threading.Lock()
        self._versions: dict[str, int] = {}  # last pushed version per key
        self._revs: dict[str, int] = {}  # last pushed server revision per key
        self._watch_thread: threading.Thread | None = None
        self._watch_ready = threading.Event()
        self._closed = threading.Event()
        self._lease_id = 0
        self._lease_ttl_ms = 0
        self._lease_thread: threading.Thread | None = None
        # serializes lease grants: a nolease write retry racing the
        # keepalive's re-grant must not mint two live leases (the key
        # would ride the one that never gets renewed and silently vanish)
        self._lease_lock = threading.Lock()
        # ephemeral keys this session owns (key -> last-asserted data),
        # re-asserted under a fresh lease after a server restart/failover
        self._ephemeral: dict[str, bytes] = {}

    @property
    def target(self) -> str:
        return self._targets[self._cur % len(self._targets)]

    def _stub(self, name: str, streaming: bool = False):
        import grpc  # noqa: F401

        with self._stub_lock:
            st = self._stubs.get(name)
            if st is None:
                if streaming:
                    st = self._channel.unary_stream(_method(name))
                else:
                    st = self._channel.unary_unary(_method(name))
                self._stubs[name] = st
        return st

    def _rotate(self) -> None:
        """Advance to the next configured target (failover)."""
        import grpc

        with self._stub_lock:
            if len(self._targets) > 1:
                self._cur = (self._cur + 1) % len(self._targets)
            try:
                self._channel.close()
            except Exception:  # noqa: BLE001
                pass
            self._channel = grpc.insecure_channel(
                self._targets[self._cur % len(self._targets)])
            self._stubs = {}

    def _redirect(self, addr: str) -> None:
        """Jump straight to a hinted leader address; an empty/absent hint
        (an election in progress) degrades to plain rotation."""
        import grpc

        if not addr:
            self._rotate()
            return
        with self._stub_lock:
            if addr in self._targets:
                self._cur = self._targets.index(addr)
            else:
                # hints can name replicas outside the configured list
                # (operator gave a partial list); adopt them — bounded by
                # the cluster size
                self._targets.append(addr)
                self._cur = len(self._targets) - 1
            try:
                self._channel.close()
            except Exception:  # noqa: BLE001
                pass
            self._channel = grpc.insecure_channel(addr)
            self._stubs = {}

    def _call(self, name: str, req: bytes):
        """Unary call with failover: rotate targets on transport errors,
        follow ``notleader:<addr>`` hints from quorum-mode followers (a
        fresh election may leave the hint empty for a round — then rotate
        and retry); single-target clients retry on server restart."""
        from m3_tpu.utils import trace

        attempts = max(8, 2 * len(self._targets) + 4)
        last_exc: Exception | None = None
        for i in range(attempts):
            try:
                # injected transport faults drive the same rotate/retry
                # failover path a dead kvd does
                faults.check("kvd.rpc", method=name, target=self.target)
                with trace.span(trace.KVD_RPC, method=name,
                                target=self.target):
                    resp = _dec_resp(self._stub(name)(
                        req, timeout=self.timeout_s,
                        metadata=trace.grpc_metadata()))
            except faults.SimulatedCrash:
                # a crash injected at kvd.rpc must take the process down
                # (M3_TPU_FAULTS_EXIT semantics), not feed the retry loop
                raise
            except Exception as e:  # noqa: BLE001 - grpc transport error
                last_exc = e
                self._rotate()
                if self._closed.wait(min(0.2 * (i + 1), 1.0)):
                    break
                continue
            err = resp[2]
            if err.startswith("notleader"):
                last_exc = KVError(f"{self.target}: {err}")
                self._redirect(err.partition(":")[2])
                if self._closed.wait(min(0.1 * (i + 1), 0.5)):
                    break
                continue
            return resp
        raise KVError(f"kvd unreachable at {self._targets}: {last_exc}")

    # -- KVStore surface --

    def get(self, key: str) -> VersionedValue:
        version, data, err, _l, _k = self._call("Get", _enc_req(key=key))
        if err == "notfound":
            raise KeyNotFound(key)
        return VersionedValue(version, data)

    def _write_kv(self, key: str, data: bytes, lease: int,
                  expect_version: int | None = None) -> tuple[int, str]:
        """One Set (or Cas, when expect_version is given) RPC carrying an
        explicit lease attachment.  The single decode path for the write
        error vocabulary: conflicts raise VersionMismatch here; "nolease"
        is returned for the caller's retry policy."""
        if expect_version is None:
            version, _d, err, _l, _k = self._call(
                "Set", _enc_req(key=key, data=data, lease_id=lease))
        else:
            version, _d, err, _l, _k = self._call(
                "Cas", _enc_req(key=key, data=data,
                                expect_version=expect_version,
                                lease_id=lease))
        if err.startswith("conflict"):
            raise VersionMismatch(err.partition(":")[2] or key)
        return version, err

    def set(self, key: str, data: bytes, ephemeral: bool = False) -> int:
        """ephemeral=True attaches the key to this client's session lease
        (vanishes if the process dies). Plain sets are PERSISTENT — and
        clear a prior lease attachment, matching etcd put-without-lease
        (round-4 advisor finding: the lease must not ride every write)."""
        for _attempt in range(2):
            lease = self._session_lease() if ephemeral else 0
            version, err = self._write_kv(key, data, lease)
            if err == "nolease":
                # the session lease expired in flight (server restart or a
                # stalled keepalive): replace it exactly once (racing the
                # keepalive's own re-grant is serialized) and retry so the
                # write stays ephemeral
                self._ensure_fresh_lease(lease)
                continue
            self._track_ephemeral(key, data if ephemeral else None)
            return version
        raise KVError(f"session lease unrecoverable writing {key!r}")

    def set_if_not_exists(self, key: str, data: bytes,
                          ephemeral: bool = False) -> int:
        return self.check_and_set(key, 0, data, ephemeral=ephemeral)

    def check_and_set(self, key: str, expect_version: int, data: bytes,
                      ephemeral: bool = False) -> int:
        for _attempt in range(2):
            lease = self._session_lease() if ephemeral else 0
            version, err = self._write_kv(key, data, lease, expect_version)
            if err == "nolease":
                self._ensure_fresh_lease(lease)  # expired in flight: retry
                continue
            self._track_ephemeral(key, data if ephemeral else None)
            return version
        raise KVError(f"session lease unrecoverable writing {key!r}")

    def delete(self, key: str) -> None:
        _v, _d, err, _l, _k = self._call("Delete", _enc_req(key=key))
        self._track_ephemeral(key, None)
        if err == "notfound":
            raise KeyNotFound(key)

    def keys(self, prefix: str = "") -> list[str]:
        _v, _d, _e, _l, keys = self._call("Keys", _enc_req(prefix=prefix))
        return keys

    def _track_ephemeral(self, key: str, data: bytes | None) -> None:
        with self._lock:
            if data is None:
                self._ephemeral.pop(key, None)
            else:
                self._ephemeral[key] = data

    def refresh(self) -> int:
        """Push-based store: nothing to poll."""
        return 0

    # -- push watches --

    def watch(self, key: str, fn):
        unwatch = super().watch(key, fn)
        self._ensure_watch_stream()
        # deliver current remote value on registration (the in-memory
        # bootstrap above only sees keys already pushed)
        try:
            vv = self.get(key)
            with self._lock:
                known = self._versions.get(key, 0)
            if vv.version > known:
                self._apply_event(key, vv.version, vv.data, deleted=False)
        except KeyNotFound:
            pass
        return unwatch

    def _ensure_watch_stream(self) -> None:
        with self._stub_lock:
            if self._watch_thread is not None:
                return
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True)
            self._watch_thread.start()
        self._watch_ready.wait(self.timeout_s)

    def _watch_loop(self) -> None:
        while not self._closed.is_set():
            try:
                stream = self._stub("Watch", streaming=True)(_enc_req(prefix=""))
                bootstrap_keys: set[str] = set()
                in_bootstrap = True
                for raw in stream:
                    key, version, data, deleted, done, rev = _dec_event(raw)
                    if done:
                        # a reconnect bootstrap is also the deletion
                        # reconcile: anything we cached that the snapshot
                        # no longer contains was deleted while the
                        # stream was down
                        self._reconcile_deletions(bootstrap_keys)
                        in_bootstrap = False
                        self._watch_ready.set()
                        continue
                    if in_bootstrap:
                        bootstrap_keys.add(key)
                    self._apply_event(key, version, data, deleted, rev)
                    self._watch_ready.set()
                    if self._closed.is_set():
                        return
            except Exception:  # noqa: BLE001 - reconnect on any stream error
                # rotate so watch-only clients also fail over to another
                # replica (unary RPCs rotate in _call)
                self._rotate()
                if self._closed.wait(0.5):
                    return

    def _apply_event(self, key: str, version: int, data: bytes,
                     deleted: bool, rev: int = 0) -> None:
        with self._lock:
            last_rev = self._revs.get(key, 0)
            if rev and last_rev and rev <= last_rev:
                return  # replayed event (bootstrap overlap / reconnect)
            if rev:
                self._revs[key] = rev
            if deleted:
                self._versions.pop(key, None)
                self._data.pop(key, None)
            else:
                if not rev and self._versions.get(key, 0) >= version:
                    return  # rev-less duplicate: fall back to version dedupe
                self._versions[key] = version
                self._data[key] = VersionedValue(version, data)
        self._notify(key, None if deleted else VersionedValue(version, data))

    def _reconcile_deletions(self, live_keys: set[str]) -> None:
        with self._lock:
            stale = [k for k in self._data if k not in live_keys]
            for k in stale:
                self._versions.pop(k, None)
                self._revs.pop(k, None)
                self._data.pop(k, None)
        for k in stale:
            self._notify(k, None)

    # -- liveness: session lease --

    def _session_lease(self) -> int:
        """The session lease id, granting one on first ephemeral write."""
        if not self._lease_id:
            self.start_session()
        return self._lease_id

    def _ensure_fresh_lease(self, stale_id: int) -> int:
        """Replace stale_id with a fresh lease exactly once: concurrent
        callers observing the same dead lease serialize here, and whoever
        loses the race adopts the winner's lease instead of granting a
        second one."""
        with self._lease_lock:
            # ONLY the exact stale id re-grants: a zero here means
            # end_session() tore the session down between our caller
            # reading the id and this lock — re-granting would resurrect
            # the session being ended (callers that really want a new
            # session go through start_session explicitly)
            if self._lease_id == stale_id and stale_id:
                # intentional RPC-under-lock: single-flight lease grant —
                # the lock's whole job is to make the losers of the race
                # WAIT for the winner's network round-trip
                # m3lint: disable=lock-blocking-call
                self._grant_locked(self._lease_ttl_ms or 5_000)
            return self._lease_id

    def _grant_locked(self, ttl_ms: int) -> int:
        _v, _d, _e, lease_id, _k = self._call(
            "LeaseGrant", _enc_req(ttl_ms=ttl_ms))
        self._lease_id = lease_id
        self._lease_ttl_ms = ttl_ms
        return lease_id

    def start_session(self, ttl_ms: int = 5_000) -> int:
        """Grant a lease and keep it alive from a background thread;
        ephemeral set/check_and_set attach their keys to the session, so
        this process's keys vanish if it dies (etcd session semantics —
        what elections and service advertisements ride).

        Survives server restart/failover: a keepalive answered with
        "notfound" (the lease died with the old server) re-grants a fresh
        lease and RE-ASSERTS every ephemeral key this client owns before
        the server's orphan grace expires — a live leader keeps its
        leadership across a kvd restart."""
        with self._lease_lock:
            # intentional RPC-under-lock: same single-flight grant
            # discipline as _ensure_fresh_lease
            # m3lint: disable=lock-blocking-call
            lease_id = self._grant_locked(ttl_ms)
        interval = max(0.2, ttl_ms / 3e3)
        if self._lease_thread is not None:
            return lease_id  # re-grant from the existing keepalive thread

        def keepalive():
            while not self._closed.wait(interval):
                cur = self._lease_id
                if not cur:
                    continue  # session explicitly ended; don't resurrect
                try:
                    _v2, _d2, err, _l2, _k2 = self._call(
                        "LeaseKeepAlive", _enc_req(lease_id=cur))
                except faults.SimulatedCrash as e:
                    # armed (chaos rig): the process dies HERE — the
                    # broad retry catch below must never eat a crash
                    # _call deliberately re-raised; unarmed, kill the
                    # keepalive thread loudly instead of silently
                    faults.escalate(e)
                    raise
                except Exception:  # noqa: BLE001 - retry next tick
                    continue
                if err == "notfound" and self._lease_id \
                        and not self._closed.is_set():
                    try:
                        self._regrant(cur)
                    except faults.SimulatedCrash as e:
                        faults.escalate(e)
                        raise
                    except Exception:  # noqa: BLE001 - retry next tick
                        pass

        self._lease_thread = threading.Thread(target=keepalive, daemon=True)
        self._lease_thread.start()
        return lease_id

    def _regrant(self, stale_id: int) -> None:
        """Fresh lease + re-assert owned ephemeral keys (server lost ours).

        Every re-assert RPC carries the EXPLICIT lease this round granted
        — routing through set()/_session_lease's ambient auto-grant would
        resurrect a session end_session() tears down concurrently (it
        sees _lease_id == 0 mid-loop and grants a brand-new lease)."""
        fresh = self._ensure_fresh_lease(stale_id)
        if not fresh:
            # end_session() zeroed the id between the keepalive reading it
            # and here: re-granting would resurrect the session being
            # ended (callers that really want a new session go through
            # start_session explicitly)
            return
        with self._lock:
            owned = list(self._ephemeral.items())
        for key, data in owned:
            for _attempt in range(2):
                if not fresh or self._lease_id != fresh:
                    # torn down (or replaced) mid-loop: stop resurrecting
                    return
                try:
                    vv = self.get(key)
                except KeyNotFound:
                    vv = None
                try:
                    if vv is not None and vv.data != data:
                        # someone else took it while our lease was dead
                        self._track_ephemeral(key, None)
                        break
                    _v, err = self._write_kv(
                        key, data, fresh,
                        expect_version=0 if vv is None else None)
                    if err == "nolease":
                        # the fresh lease died in flight: replace exactly
                        # it (a teardown returns 0 and the loop-head
                        # guard bails)
                        fresh = self._ensure_fresh_lease(fresh)
                        continue
                    self._track_ephemeral(key, data)
                except (VersionMismatch, KVError):
                    self._track_ephemeral(key, None)
                break

    def end_session(self) -> None:
        # zero the id under the lease lock FIRST: the keepalive thread and
        # a concurrent _ensure_fresh_lease() key off self._lease_id, and
        # zeroing after the revoke leaves a window where either resurrects
        # the session we are tearing down
        with self._lease_lock:
            lease_id, self._lease_id = self._lease_id, 0
        if lease_id:
            try:
                # through _call so a quorum plane re-routes the revoke to
                # the leader (a follower would silently drop it otherwise)
                self._call("LeaseRevoke", _enc_req(lease_id=lease_id))
            except faults.SimulatedCrash as e:
                faults.escalate(e)
                raise
            except Exception:  # noqa: BLE001 - server may already be gone
                pass
            with self._lock:
                self._ephemeral.clear()

    def close(self) -> None:
        self._closed.set()
        self.end_session()
        self._channel.close()


class LeaseElection:
    """etcd-style election recipe on kvd: the leader key is ephemeral
    (attached to the campaigner's session lease), so leader death —
    including SIGKILL — expires the lease, deletes the key, and pushes a
    delete event to every watching candidate, which then re-campaigns.
    No polling anywhere in the failover path. Reference analog:
    src/cluster/services/leader (campaign/observe/resign over etcd
    concurrency primitives)."""

    def __init__(self, client: KvdClient, election_id: str, instance_id: str,
                 ttl_ms: int = 3_000):
        self.client = client
        self.instance_id = instance_id
        self.key = f"_election/{election_id}"
        if not client._lease_id:
            client.start_session(ttl_ms)
        self._is_leader = threading.Event()
        self._campaigning = True  # auto-recampaign until resign()/close()
        self._unwatch = client.watch(self.key, self._on_change)
        self.campaign()

    def _on_change(self, _key: str, vv: VersionedValue | None) -> None:
        if vv is None:
            self._is_leader.clear()
            if self._campaigning:
                self.campaign()
        else:
            holder = vv.data.decode()
            if holder == self.instance_id:
                self._is_leader.set()
            else:
                self._is_leader.clear()

    def campaign(self) -> bool:
        self._campaigning = True
        try:
            self.client.set_if_not_exists(self.key, self.instance_id.encode(),
                                          ephemeral=True)
            self._is_leader.set()
            return True
        except VersionMismatch:
            try:
                holder = self.client.get(self.key).data.decode()
                if holder == self.instance_id:
                    self._is_leader.set()
                else:
                    self._is_leader.clear()
            except KeyNotFound:
                pass
            return self._is_leader.is_set()

    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def leader(self) -> str | None:
        try:
            return self.client.get(self.key).data.decode()
        except KeyNotFound:
            return None

    def resign(self) -> None:
        self._campaigning = False
        if self.is_leader():
            try:
                self.client.delete(self.key)
            except KeyNotFound:
                pass
        self._is_leader.clear()

    def close(self) -> None:
        self._campaigning = False
        self._unwatch()


# ---------------------------------------------------------------------------
# daemon entry point
# ---------------------------------------------------------------------------


def parse_peers(spec) -> dict[str, str]:
    """``n1=host:port,n2=host:port,...`` (or an already-parsed dict from a
    config file) -> {node_id: address}."""
    if not spec:
        return {}
    if isinstance(spec, dict):
        return {str(k): str(v) for k, v in spec.items()}
    out = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        nid, sep, addr = part.partition("=")
        if not sep:
            raise ValueError(f"bad peer spec (want id=host:port): {part!r}")
        out[nid.strip()] = addr.strip()
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="m3kvd metadata server")
    ap.add_argument("--listen", default="127.0.0.1:0")
    ap.add_argument("--journal", default="kvd.journal",
                    help="journal path (ON by default; --no-journal for "
                         "a volatile store)")
    ap.add_argument("--no-journal", action="store_true")
    ap.add_argument("--node-id", default="",
                    help="this node's id in --peers (quorum mode)")
    ap.add_argument("--peers", default="",
                    help="n1=host:port,n2=host:port,... — the full "
                         "replica set, this node included (quorum mode)")
    ap.add_argument("-f", "--config", default="", help="yaml/json config file")
    args = ap.parse_args(argv)
    listen, journal = args.listen, args.journal
    node_id, peers = args.node_id, args.peers
    if args.config:
        from m3_tpu.utils.config import load_config

        cfg = load_config(args.config)
        kvd_cfg = cfg.get("kvd", {}) if isinstance(cfg, dict) else {}
        listen = kvd_cfg.get("listen", listen)
        journal = kvd_cfg.get("journal", journal)
        node_id = kvd_cfg.get("node_id", node_id)
        peers = kvd_cfg.get("peers", peers)
        debug_port = kvd_cfg.get("debug_port")
    else:
        debug_port = None
    if args.no_journal:
        journal = ""
    peer_map = parse_peers(peers)
    if peer_map and journal == "kvd.journal":
        # replicas launched from one directory must not clobber each
        # other's journal
        journal = f"kvd.{node_id}.journal"
    server = KvdServer(listen, journal_path=journal or None,
                       node_id=node_id or None, peers=peer_map or None,
                       debug_port=int(debug_port) if debug_port else None)
    print(f"m3kvd listening on port {server.port}", flush=True)
    try:  # port discovery file for orchestrators spawning with port 0
        with open("kvd.port", "w") as f:
            f.write(str(server.port))
    except OSError:
        pass
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.close()


if __name__ == "__main__":
    main()
