"""m3kvd — the cluster metadata plane: watch-push versioned KV with
leases and linearizable CAS over gRPC.

Role parity: the reference runs every piece of cluster metadata
(placements, elections, rules, runtime options, msg topics) on etcd — a
watchable versioned store with compare-and-set and TTL leases
(/root/reference/src/cluster/kv/types.go:113 for the store contract,
src/cluster/etcd/ for the client wiring, src/cluster/services/leader for
elections). Rounds 1–2 stood this up as a shared JSON file that every
process re-polled once per tick (cluster/kv.py FileKVStore.refresh) —
functional, but pull-based and host-local.

This module is the push-based replacement, redesigned rather than ported:
one kvd process (optionally file-journaled for durability) serializes all
mutations — a single writer IS linearizable, the same trick the reference
leans on etcd's raft leader for — and streams change events to every
subscribed client over server-streaming gRPC, so placement changes,
rule updates, and election flips propagate in milliseconds without any
polling. Leases give liveness: a key written under a lease vanishes when
its owner stops sending keep-alives (process death included), which is
what makes kill-the-leader failover work.

Wire schema (hand-rolled protowire over raw-bytes gRPC, house style of
query/remote.py — no protobuf codegen):

  Req:    1 key(bytes) 2 data(bytes) 3 expect_version(varint,
          +1-biased so "absent"=0 is distinguishable from "expect 0")
          4 lease_id(varint) 5 prefix(bytes) 6 ttl_ms(varint)
  Resp:   1 version(varint) 2 data(bytes) 3 err(utf8: notfound|conflict)
          4 lease_id(varint) 5 repeated key(bytes)
  Event:  1 key(bytes) 2 version(varint) 3 data(bytes)
          4 deleted(varint bool) 5 bootstrap_done(varint bool)

Client `KvdClient` implements the exact `cluster.kv.KVStore` surface
(get/set/set_if_not_exists/check_and_set/delete/keys/watch/refresh), so
Services/LeaderService/placement/rules/runtime-options run on it
unchanged; `refresh()` is a no-op because watches are pushed.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import threading
import time
from concurrent import futures

from m3_tpu.cluster.kv import (
    FileKVStore,
    KeyNotFound,
    KVStore,
    VersionedValue,
    VersionMismatch,
)
from m3_tpu.utils.protowire import field_bytes, field_varint, iter_fields

_SERVICE = "m3.cluster.Kvd"


def _method(name: str) -> str:
    return f"/{_SERVICE}/{name}"


# ---------------------------------------------------------------------------
# message codecs
# ---------------------------------------------------------------------------


def _enc_req(key: str = "", data: bytes = b"", expect_version: int | None = None,
             lease_id: int = 0, prefix: str = "", ttl_ms: int = 0) -> bytes:
    out = b""
    if key:
        out += field_bytes(1, key.encode())
    if data:
        out += field_bytes(2, data)
    if expect_version is not None:
        out += field_varint(3, expect_version + 1)  # bias: 0 = not a CAS
    if lease_id:
        out += field_varint(4, lease_id)
    if prefix:
        out += field_bytes(5, prefix.encode())
    if ttl_ms:
        out += field_varint(6, ttl_ms)
    return out


def _dec_req(payload: bytes):
    key, data, expect, lease, prefix, ttl = "", b"", None, 0, "", 0
    for fno, _wt, val in iter_fields(payload):
        if fno == 1:
            key = val.decode()
        elif fno == 2:
            data = val
        elif fno == 3:
            expect = val - 1
        elif fno == 4:
            lease = val
        elif fno == 5:
            prefix = val.decode()
        elif fno == 6:
            ttl = val
    return key, data, expect, lease, prefix, ttl


def _enc_resp(version: int = 0, data: bytes = b"", err: str = "",
              lease_id: int = 0, keys: list[str] | None = None) -> bytes:
    out = b""
    if version:
        out += field_varint(1, version)
    if data:
        out += field_bytes(2, data)
    if err:
        out += field_bytes(3, err.encode())
    if lease_id:
        out += field_varint(4, lease_id)
    for k in keys or ():
        out += field_bytes(5, k.encode())
    return out


def _dec_resp(payload: bytes):
    version, data, err, lease, keys = 0, b"", "", 0, []
    for fno, _wt, val in iter_fields(payload):
        if fno == 1:
            version = val
        elif fno == 2:
            data = val
        elif fno == 3:
            err = val.decode()
        elif fno == 4:
            lease = val
        elif fno == 5:
            keys.append(val.decode())
    return version, data, err, lease, keys


def _enc_event(key: str, version: int, data: bytes, deleted: bool,
               bootstrap_done: bool = False, rev: int = 0) -> bytes:
    out = field_bytes(1, key.encode())
    if version:
        out += field_varint(2, version)
    if data:
        out += field_bytes(3, data)
    if deleted:
        out += field_varint(4, 1)
    if bootstrap_done:
        out += field_varint(5, 1)
    if rev:
        out += field_varint(6, rev)
    return out


def _dec_event(payload: bytes):
    key, version, data, deleted, done, rev = "", 0, b"", False, False, 0
    for fno, _wt, val in iter_fields(payload):
        if fno == 1:
            key = val.decode()
        elif fno == 2:
            version = val
        elif fno == 3:
            data = val
        elif fno == 4:
            deleted = bool(val)
        elif fno == 5:
            done = bool(val)
        elif fno == 6:
            rev = val
    return key, version, data, deleted, done, rev


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Lease:
    __slots__ = ("lease_id", "ttl_ms", "expires_at", "keys")

    def __init__(self, lease_id: int, ttl_ms: int):
        self.lease_id = lease_id
        self.ttl_ms = ttl_ms
        self.expires_at = time.monotonic() + ttl_ms / 1e3
        self.keys: set[str] = set()


class KvdServer:
    """Single-writer metadata server. All mutations serialize through the
    backing store's lock — one writer means every CAS observes the latest
    committed version (linearizable without needing raft here; multi-node
    replication of kvd itself is a deployment concern, as running etcd is
    for the reference)."""

    def __init__(self, listen: str, journal_path: str | None = None,
                 max_workers: int = 16):
        import grpc

        self.store: KVStore = FileKVStore(journal_path) if journal_path else KVStore()
        self._leases: dict[int, _Lease] = {}
        self._key_lease: dict[str, int] = {}  # current lease owner per key
        self._lease_seq = int(time.time() * 1e3) % 1_000_000 * 1_000
        self._lock = threading.Lock()
        self._subs: list[tuple[str, queue.SimpleQueue]] = []
        self._closed = threading.Event()
        # server-global revision, stamped on every change event: versions
        # restart at 1 when a key is deleted and re-created, so clients
        # dedupe replayed events by revision, not version (etcd's
        # store-revision idea)
        self._rev = 0
        self._key_rev: dict[str, int] = {}

        # every store mutation fans out to subscriber queues (the store
        # has per-key watches only, so intercept its notify fanout)
        self._wrap_store_notifications()

        handlers_unary = {
            "Get": self._get,
            "Set": self._set,
            "Cas": self._cas,
            "Delete": self._delete,
            "Keys": self._keys,
            "LeaseGrant": self._lease_grant,
            "LeaseKeepAlive": self._lease_keepalive,
            "LeaseRevoke": self._lease_revoke,
            "Health": lambda req, ctx: b"ok",
        }

        outer = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, details):
                name = details.method.rsplit("/", 1)[-1]
                if name == "Watch":
                    return grpc.unary_stream_rpc_method_handler(outer._watch)
                fn = handlers_unary.get(name)
                if fn is None:
                    return None
                return grpc.unary_unary_rpc_method_handler(fn)

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers))
        self._server.add_generic_rpc_handlers((_Handler(),))
        self.port = self._server.add_insecure_port(listen)
        self._server.start()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._reaper.start()

    # -- store-change fanout --

    def _wrap_store_notifications(self) -> None:
        """Intercept the store's _notify so every key change (including
        FileKVStore.refresh-discovered ones) reaches subscribers."""
        orig = self.store._notify

        def notify(key: str, vv):
            orig(key, vv)
            self._broadcast(key, vv)

        self.store._notify = notify  # type: ignore[method-assign]

    def _broadcast(self, key: str, vv: VersionedValue | None) -> None:
        with self._lock:
            self._rev += 1
            rev = self._rev
            self._key_rev[key] = rev
            subs = list(self._subs)
        ev = _enc_event(key, vv.version if vv else 0, vv.data if vv else b"",
                        deleted=vv is None, rev=rev)
        for prefix, q in subs:
            if key.startswith(prefix):
                q.put(ev)

    # -- unary handlers --

    def _get(self, req: bytes, ctx) -> bytes:
        key, *_ = _dec_req(req)
        try:
            vv = self.store.get(key)
        except KeyNotFound:
            return _enc_resp(err="notfound")
        return _enc_resp(version=vv.version, data=vv.data)

    def _set(self, req: bytes, ctx) -> bytes:
        key, data, _exp, lease, _p, _t = _dec_req(req)
        version = self.store.set(key, data)
        self._attach_lease(key, lease)  # lease 0 detaches a prior owner
        return _enc_resp(version=version)

    def _cas(self, req: bytes, ctx) -> bytes:
        key, data, expect, lease, _p, _t = _dec_req(req)
        try:
            version = self.store.check_and_set(key, expect or 0, data)
        except VersionMismatch as e:
            return _enc_resp(err=f"conflict:{e}")
        self._attach_lease(key, lease)
        return _enc_resp(version=version)

    def _delete(self, req: bytes, ctx) -> bytes:
        key, *_ = _dec_req(req)
        try:
            self.store.delete(key)
        except KeyNotFound:
            return _enc_resp(err="notfound")
        self._attach_lease(key, 0)  # a deleted key belongs to no lease
        return _enc_resp(version=1)

    def _keys(self, req: bytes, ctx) -> bytes:
        _k, _d, _e, _l, prefix, _t = _dec_req(req)
        return _enc_resp(keys=self.store.keys(prefix))

    # -- leases --

    def _attach_lease(self, key: str, lease_id: int) -> None:
        """Make lease_id (0 = none) the key's ONLY lease owner. Every
        write/delete re-resolves ownership, so a key re-created by a new
        client is never reaped by a previous owner's lease expiry."""
        with self._lock:
            old = self._key_lease.pop(key, None)
            if old is not None and old in self._leases:
                self._leases[old].keys.discard(key)
            if lease_id and lease_id in self._leases:
                self._leases[lease_id].keys.add(key)
                self._key_lease[key] = lease_id

    def _lease_grant(self, req: bytes, ctx) -> bytes:
        _k, _d, _e, _l, _p, ttl_ms = _dec_req(req)
        ttl_ms = ttl_ms or 10_000
        with self._lock:
            self._lease_seq += 1
            lease = _Lease(self._lease_seq, ttl_ms)
            self._leases[lease.lease_id] = lease
        return _enc_resp(lease_id=lease.lease_id, version=ttl_ms)

    def _lease_keepalive(self, req: bytes, ctx) -> bytes:
        _k, _d, _e, lease_id, _p, _t = _dec_req(req)
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None:
                return _enc_resp(err="notfound")
            lease.expires_at = time.monotonic() + lease.ttl_ms / 1e3
        return _enc_resp(lease_id=lease_id, version=lease.ttl_ms)

    def _lease_revoke(self, req: bytes, ctx) -> bytes:
        _k, _d, _e, lease_id, _p, _t = _dec_req(req)
        self._expire([lease_id])
        return _enc_resp(lease_id=lease_id or 1)

    def _reap_loop(self) -> None:
        while not self._closed.wait(0.25):
            now = time.monotonic()
            with self._lock:
                dead = [lid for lid, le in self._leases.items()
                        if le.expires_at <= now]
            if dead:
                self._expire(dead)

    def _expire(self, lease_ids: list[int]) -> None:
        for lid in lease_ids:
            with self._lock:
                lease = self._leases.pop(lid, None)
                if lease is None:
                    continue
                # only reap keys this lease still owns — a re-created or
                # re-owned key belongs to someone else now
                owned = [k for k in lease.keys
                         if self._key_lease.get(k) == lid]
                for k in owned:
                    self._key_lease.pop(k, None)
            for key in owned:
                try:
                    self.store.delete(key)  # pushes a deleted event
                except KeyNotFound:
                    pass

    # -- watch streaming --

    def _watch(self, req: bytes, ctx):
        _k, _d, _e, _l, prefix, _t = _dec_req(req)
        q: queue.SimpleQueue = queue.SimpleQueue()
        # bootstrap snapshot BEFORE subscribing would lose updates in the
        # gap; subscribe first, then snapshot — duplicate versions are
        # fine (clients dedupe by version)
        with self._lock:
            self._subs.append((prefix, q))
        try:
            for key in self.store.keys(prefix):
                try:
                    vv = self.store.get(key)
                except KeyNotFound:
                    continue
                with self._lock:
                    rev = self._key_rev.get(key, 0)
                yield _enc_event(key, vv.version, vv.data, deleted=False,
                                 rev=rev)
            yield _enc_event("", 0, b"", deleted=False, bootstrap_done=True)
            while ctx.is_active() and not self._closed.is_set():
                try:
                    ev = q.get(timeout=0.5)
                except Exception:  # noqa: BLE001 - Empty
                    continue
                yield ev
        finally:
            with self._lock:
                try:
                    self._subs.remove((prefix, q))
                except ValueError:
                    pass

    def close(self) -> None:
        self._closed.set()
        self._server.stop(grace=0.5).wait()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class KvdClient(KVStore):
    """`cluster.kv.KVStore`-compatible client for a kvd server.

    Watches are PUSHED: one background Watch stream (prefix "") feeds the
    same per-key watcher callbacks the in-memory store uses, so
    Services/LeaderService/rules/runtime-options get cross-process change
    propagation with no per-tick polling. `refresh()` is a no-op kept for
    interface compatibility with FileKVStore call sites."""

    def __init__(self, target: str, timeout_s: float = 10.0):
        super().__init__()
        import grpc

        self.target = target
        self.timeout_s = timeout_s
        self._channel = grpc.insecure_channel(target)
        self._stubs: dict[str, object] = {}
        self._stub_lock = threading.Lock()
        self._versions: dict[str, int] = {}  # last pushed version per key
        self._revs: dict[str, int] = {}  # last pushed server revision per key
        self._watch_thread: threading.Thread | None = None
        self._watch_ready = threading.Event()
        self._closed = threading.Event()
        self._lease_id = 0
        self._lease_thread: threading.Thread | None = None

    def _stub(self, name: str, streaming: bool = False):
        import grpc  # noqa: F401

        with self._stub_lock:
            st = self._stubs.get(name)
            if st is None:
                if streaming:
                    st = self._channel.unary_stream(_method(name))
                else:
                    st = self._channel.unary_unary(_method(name))
                self._stubs[name] = st
        return st

    # -- KVStore surface --

    def get(self, key: str) -> VersionedValue:
        version, data, err, _l, _k = _dec_resp(
            self._stub("Get")(_enc_req(key=key), timeout=self.timeout_s))
        if err == "notfound":
            raise KeyNotFound(key)
        return VersionedValue(version, data)

    def set(self, key: str, data: bytes) -> int:
        version, _d, _e, _l, _k = _dec_resp(
            self._stub("Set")(_enc_req(key=key, data=data,
                                       lease_id=self._lease_id),
                              timeout=self.timeout_s))
        return version

    def set_if_not_exists(self, key: str, data: bytes) -> int:
        return self.check_and_set(key, 0, data)

    def check_and_set(self, key: str, expect_version: int, data: bytes) -> int:
        version, _d, err, _l, _k = _dec_resp(
            self._stub("Cas")(_enc_req(key=key, data=data,
                                       expect_version=expect_version,
                                       lease_id=self._lease_id),
                              timeout=self.timeout_s))
        if err.startswith("conflict"):
            raise VersionMismatch(err.partition(":")[2] or key)
        return version

    def delete(self, key: str) -> None:
        _v, _d, err, _l, _k = _dec_resp(
            self._stub("Delete")(_enc_req(key=key), timeout=self.timeout_s))
        if err == "notfound":
            raise KeyNotFound(key)

    def keys(self, prefix: str = "") -> list[str]:
        _v, _d, _e, _l, keys = _dec_resp(
            self._stub("Keys")(_enc_req(prefix=prefix), timeout=self.timeout_s))
        return keys

    def refresh(self) -> int:
        """Push-based store: nothing to poll."""
        return 0

    # -- push watches --

    def watch(self, key: str, fn):
        unwatch = super().watch(key, fn)
        self._ensure_watch_stream()
        # deliver current remote value on registration (the in-memory
        # bootstrap above only sees keys already pushed)
        try:
            vv = self.get(key)
            with self._lock:
                known = self._versions.get(key, 0)
            if vv.version > known:
                self._apply_event(key, vv.version, vv.data, deleted=False)
        except KeyNotFound:
            pass
        return unwatch

    def _ensure_watch_stream(self) -> None:
        with self._stub_lock:
            if self._watch_thread is not None:
                return
            self._watch_thread = threading.Thread(
                target=self._watch_loop, daemon=True)
            self._watch_thread.start()
        self._watch_ready.wait(self.timeout_s)

    def _watch_loop(self) -> None:
        while not self._closed.is_set():
            try:
                stream = self._stub("Watch", streaming=True)(_enc_req(prefix=""))
                bootstrap_keys: set[str] = set()
                in_bootstrap = True
                for raw in stream:
                    key, version, data, deleted, done, rev = _dec_event(raw)
                    if done:
                        # a reconnect bootstrap is also the deletion
                        # reconcile: anything we cached that the snapshot
                        # no longer contains was deleted while the
                        # stream was down
                        self._reconcile_deletions(bootstrap_keys)
                        in_bootstrap = False
                        self._watch_ready.set()
                        continue
                    if in_bootstrap:
                        bootstrap_keys.add(key)
                    self._apply_event(key, version, data, deleted, rev)
                    self._watch_ready.set()
                    if self._closed.is_set():
                        return
            except Exception:  # noqa: BLE001 - reconnect on any stream error
                if self._closed.wait(0.5):
                    return

    def _apply_event(self, key: str, version: int, data: bytes,
                     deleted: bool, rev: int = 0) -> None:
        with self._lock:
            last_rev = self._revs.get(key, 0)
            if rev and last_rev and rev <= last_rev:
                return  # replayed event (bootstrap overlap / reconnect)
            if rev:
                self._revs[key] = rev
            if deleted:
                self._versions.pop(key, None)
                self._data.pop(key, None)
            else:
                if not rev and self._versions.get(key, 0) >= version:
                    return  # rev-less duplicate: fall back to version dedupe
                self._versions[key] = version
                self._data[key] = VersionedValue(version, data)
        self._notify(key, None if deleted else VersionedValue(version, data))

    def _reconcile_deletions(self, live_keys: set[str]) -> None:
        with self._lock:
            stale = [k for k in self._data if k not in live_keys]
            for k in stale:
                self._versions.pop(k, None)
                self._revs.pop(k, None)
                self._data.pop(k, None)
        for k in stale:
            self._notify(k, None)

    # -- liveness: session lease --

    def start_session(self, ttl_ms: int = 5_000) -> int:
        """Grant a lease and keep it alive from a background thread; any
        subsequent set/check_and_set attaches its key to the session, so
        this process's keys vanish if it dies (etcd session semantics —
        what elections and service advertisements ride)."""
        _v, _d, _e, lease_id, _k = _dec_resp(
            self._stub("LeaseGrant")(_enc_req(ttl_ms=ttl_ms),
                                     timeout=self.timeout_s))
        self._lease_id = lease_id
        interval = max(0.2, ttl_ms / 3e3)

        def keepalive():
            while not self._closed.wait(interval):
                try:
                    self._stub("LeaseKeepAlive")(
                        _enc_req(lease_id=lease_id), timeout=self.timeout_s)
                except Exception:  # noqa: BLE001 - retry next tick
                    pass

        self._lease_thread = threading.Thread(target=keepalive, daemon=True)
        self._lease_thread.start()
        return lease_id

    def end_session(self) -> None:
        if self._lease_id:
            try:
                self._stub("LeaseRevoke")(
                    _enc_req(lease_id=self._lease_id), timeout=self.timeout_s)
            except Exception:  # noqa: BLE001 - server may already be gone
                pass
            self._lease_id = 0

    def close(self) -> None:
        self._closed.set()
        self.end_session()
        self._channel.close()


class LeaseElection:
    """etcd-style election recipe on kvd: the leader key is ephemeral
    (attached to the campaigner's session lease), so leader death —
    including SIGKILL — expires the lease, deletes the key, and pushes a
    delete event to every watching candidate, which then re-campaigns.
    No polling anywhere in the failover path. Reference analog:
    src/cluster/services/leader (campaign/observe/resign over etcd
    concurrency primitives)."""

    def __init__(self, client: KvdClient, election_id: str, instance_id: str,
                 ttl_ms: int = 3_000):
        self.client = client
        self.instance_id = instance_id
        self.key = f"_election/{election_id}"
        if not client._lease_id:
            client.start_session(ttl_ms)
        self._is_leader = threading.Event()
        self._campaigning = True  # auto-recampaign until resign()/close()
        self._unwatch = client.watch(self.key, self._on_change)
        self.campaign()

    def _on_change(self, _key: str, vv: VersionedValue | None) -> None:
        if vv is None:
            self._is_leader.clear()
            if self._campaigning:
                self.campaign()
        else:
            holder = vv.data.decode()
            if holder == self.instance_id:
                self._is_leader.set()
            else:
                self._is_leader.clear()

    def campaign(self) -> bool:
        self._campaigning = True
        try:
            self.client.set_if_not_exists(self.key, self.instance_id.encode())
            self._is_leader.set()
            return True
        except VersionMismatch:
            try:
                holder = self.client.get(self.key).data.decode()
                if holder == self.instance_id:
                    self._is_leader.set()
                else:
                    self._is_leader.clear()
            except KeyNotFound:
                pass
            return self._is_leader.is_set()

    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def leader(self) -> str | None:
        try:
            return self.client.get(self.key).data.decode()
        except KeyNotFound:
            return None

    def resign(self) -> None:
        self._campaigning = False
        if self.is_leader():
            try:
                self.client.delete(self.key)
            except KeyNotFound:
                pass
        self._is_leader.clear()

    def close(self) -> None:
        self._campaigning = False
        self._unwatch()


# ---------------------------------------------------------------------------
# daemon entry point
# ---------------------------------------------------------------------------


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="m3kvd metadata server")
    ap.add_argument("--listen", default="127.0.0.1:0")
    ap.add_argument("--journal", default="", help="optional journal path")
    ap.add_argument("-f", "--config", default="", help="yaml/json config file")
    args = ap.parse_args(argv)
    listen, journal = args.listen, args.journal
    if args.config:
        from m3_tpu.utils.config import load_config

        cfg = load_config(args.config)
        kvd_cfg = cfg.get("kvd", {}) if isinstance(cfg, dict) else {}
        listen = kvd_cfg.get("listen", listen)
        journal = kvd_cfg.get("journal", journal)
    server = KvdServer(listen, journal_path=journal or None)
    print(f"m3kvd listening on port {server.port}", flush=True)
    try:  # port discovery file for orchestrators spawning with port 0
        with open("kvd.port", "w") as f:
            f.write(str(server.port))
    except OSError:
        pass
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.close()


if __name__ == "__main__":
    main()
