"""Versioned KV store with watches and CAS transactions.

Role parity with the reference KV abstraction
(/root/reference/src/cluster/kv/types.go:113,219): versioned values,
check-and-set, watchable keys. Backends: in-memory (tests/single node) and
a file-backed store (durable single-host deployments standing in for etcd;
a real etcd client can implement the same interface later without touching
callers).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Callable

from m3_tpu.utils import faults


class KVError(Exception):
    pass


class VersionMismatch(KVError):
    pass


class KeyNotFound(KVError):
    pass


@dataclass(frozen=True)
class VersionedValue:
    version: int
    data: bytes


class KVStore:
    """In-memory versioned KV with watches."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._data: dict[str, VersionedValue] = {}
        self._watchers: dict[str, list[Callable[[str, VersionedValue | None], None]]] = {}

    # -- core ops --

    def get(self, key: str) -> VersionedValue:
        with self._lock:
            v = self._data.get(key)
            if v is None:
                raise KeyNotFound(key)
            return v

    def set(self, key: str, data: bytes) -> int:
        with self._lock:
            cur = self._data.get(key)
            version = (cur.version + 1) if cur else 1
            vv = VersionedValue(version, data)
            self._data[key] = vv
            self._persist()
            # notify under the (reentrant) lock so watchers observe updates
            # in version order; watchers must therefore be fast/non-blocking
            self._notify(key, vv)
        return version

    def set_if_not_exists(self, key: str, data: bytes) -> int:
        with self._lock:
            if key in self._data:
                raise VersionMismatch(f"{key} already exists")
            vv = VersionedValue(1, data)
            self._data[key] = vv
            self._persist()
            self._notify(key, vv)
        return 1

    def check_and_set(self, key: str, expect_version: int, data: bytes) -> int:
        """CAS; expect_version 0 means 'must not exist'."""
        with self._lock:
            cur = self._data.get(key)
            cur_version = cur.version if cur else 0
            if cur_version != expect_version:
                raise VersionMismatch(
                    f"{key}: have version {cur_version}, expected {expect_version}"
                )
            vv = VersionedValue(cur_version + 1, data)
            self._data[key] = vv
            self._persist()
            self._notify(key, vv)
        return vv.version

    def delete(self, key: str) -> None:
        with self._lock:
            if key not in self._data:
                raise KeyNotFound(key)
            del self._data[key]
            self._persist()
            self._notify(key, None)

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    # -- watches --

    def watch(self, key: str, fn: Callable[[str, VersionedValue | None], None]) -> Callable:
        """Register a watcher; returns an unwatch function. The current
        value (if any) is delivered immediately, mirroring the reference
        watch bootstrap."""
        with self._lock:
            self._watchers.setdefault(key, []).append(fn)
            cur = self._data.get(key)
            if cur is not None:
                fn(key, cur)  # bootstrap delivery ordered with updates

        def unwatch():
            with self._lock:
                try:
                    self._watchers.get(key, []).remove(fn)
                except ValueError:
                    pass

        return unwatch

    def _notify(self, key: str, vv: VersionedValue | None) -> None:
        with self._lock:
            fns = list(self._watchers.get(key, []))
        for fn in fns:
            try:
                fn(key, vv)
            except Exception:
                pass  # watcher errors never poison the store

    def _persist(self) -> None:  # overridden by FileKVStore
        pass


def kv_from_config(cfg: dict, addr_key: str = "kv_addr",
                   path_key: str = "kv_path"):
    """Build the configured KV backend: `kv_addr` selects the networked
    m3kvd metadata plane (push watches, leases — cluster deployments),
    `kv_path` the file-journaled single-host store, neither → None. One
    helper so every service resolves KV config identically."""
    if cfg.get(addr_key):
        from m3_tpu.cluster.kvd import KvdClient  # lazy: needs grpc

        return KvdClient(cfg[addr_key])
    if cfg.get(path_key):
        return FileKVStore(cfg[path_key])
    return None


class FileKVStore(KVStore):
    """KV durably journaled to a JSON file (single-host etcd stand-in).

    Safe for MULTIPLE PROCESSES sharing the file (the test/dev cluster
    topology): reads reload the file when its identity changed on disk,
    and mutations hold an OS file lock across reload-apply-persist so
    cross-process check_and_set keeps its CAS meaning. Watches fire only
    within the writing process by default; a service's periodic
    refresh() call reloads the file and fires local watches for keys
    other processes changed (the cross-process watch mechanism —
    runtime options, rules, topics all ride it)."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._lock_path = path + ".lock"
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._loaded_sig = ()
        self._reload()

    def _file_sig(self):
        try:
            st = os.stat(self._path)
            return (st.st_mtime_ns, st.st_size, st.st_ino)
        except FileNotFoundError:
            return None

    def _reload(self) -> None:
        sig = self._file_sig()
        if sig == self._loaded_sig:
            return
        if sig is None:
            self._data = {}
            self._loaded_sig = None
            return
        for _attempt in range(3):  # os.replace races re-read harmlessly
            try:
                with open(self._path) as f:
                    raw = json.load(f)
                break
            except (json.JSONDecodeError, FileNotFoundError):
                sig = self._file_sig()
        else:
            return
        self._data = {
            k: VersionedValue(v["version"], bytes.fromhex(v["data"]))
            for k, v in raw.items()
        }
        self._loaded_sig = sig

    import contextlib as _contextlib

    @_contextlib.contextmanager
    def _file_lock(self):
        import fcntl

        with open(self._lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    def refresh(self) -> int:
        """Reload from disk and fire watches for every key another
        process changed or deleted since the last load; returns how many
        keys changed. Services call this each tick."""
        with self._lock:
            before = dict(self._data)
            self._reload()
            after = self._data
            changed = []
            for k, vv in after.items():
                old = before.get(k)
                if old is None or old.version != vv.version:
                    changed.append((k, vv))
            for k in before:
                if k not in after:
                    changed.append((k, None))
            for k, vv in changed:
                self._notify(k, vv)
        return len(changed)

    # reads observe external writers
    def get(self, key: str) -> VersionedValue:
        with self._lock:
            self._reload()
            return super().get(key)

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            self._reload()
            return super().keys(prefix)

    # mutations are serialized across processes
    def set(self, key: str, data: bytes) -> int:
        with self._lock, self._file_lock():
            self._reload()
            return super().set(key, data)

    def set_if_not_exists(self, key: str, data: bytes) -> int:
        with self._lock, self._file_lock():
            self._reload()
            return super().set_if_not_exists(key, data)

    def check_and_set(self, key: str, expect_version: int, data: bytes) -> int:
        with self._lock, self._file_lock():
            self._reload()
            return super().check_and_set(key, expect_version, data)

    def delete(self, key: str) -> None:
        with self._lock, self._file_lock():
            self._reload()
            super().delete(key)

    def _persist(self) -> None:
        # journal writes are already crash-atomic (tmp + fsync + replace);
        # kvd.persist injects failures BEFORE any byte lands and
        # kvd.persist.write can tear the tmp file — either way the
        # committed journal under the final name stays intact
        from m3_tpu.utils.instrument import default_registry

        with default_registry().root_scope("kvd").histogram(
                "persist_seconds"):
            self._persist_timed()

    def _persist_timed(self) -> None:
        faults.check("kvd.persist")
        tmp = self._path + ".tmp"
        payload = json.dumps(
            {
                k: {"version": v.version, "data": v.data.hex()}
                for k, v in self._data.items()
            }
        ).encode()
        with open(tmp, "wb") as f:
            faults.torn_write(f, payload, "kvd.persist.write")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)
        self._loaded_sig = self._file_sig()
