"""Managed change-set workflow over a KV key.

Role parity with the reference's cluster/changeset: writers STAGE changes
against a managed value; a committer APPLIES every staged change in one
CAS'd transition of the value. Staging is a CAS-guarded append, so any
number of writers stage concurrently without losing entries; a commit
racing a concurrent value write fails with VersionMismatch and leaves the
staged changes intact for a retry (exactly-once application: a successful
commit removes exactly the changes it applied, preserving any staged
concurrently with it).

Layout: the managed value lives at <key>; staged changes at
<key>/_changeset as {"changes": [...]}.
"""

from __future__ import annotations

import json
from typing import Callable

from m3_tpu.cluster.kv import KeyNotFound, VersionMismatch


class ChangeSetManager:
    def __init__(self, kv, key: str):
        self.kv = kv
        self.key = key
        self.changes_key = key + "/_changeset"

    # -- value --

    def get(self) -> tuple[dict, int]:
        """(value, version); ({}, 0) when unset."""
        try:
            vv = self.kv.get(self.key)
        except KeyNotFound:
            return {}, 0
        return json.loads(vv.data), vv.version

    # -- staging --

    def _read_changes(self) -> tuple[list[dict], int | None]:
        try:
            vv = self.kv.get(self.changes_key)
        except KeyNotFound:
            return [], None
        return list(json.loads(vv.data).get("changes", [])), vv.version

    def _write_changes(self, changes: list[dict], expect_version: int | None) -> None:
        raw = json.dumps({"changes": changes}).encode()
        if expect_version is None:
            self.kv.set_if_not_exists(self.changes_key, raw)
        else:
            self.kv.check_and_set(self.changes_key, expect_version, raw)

    def stage(self, change: dict, max_retries: int = 64) -> int:
        """Append one change to the staged set; returns how many changes
        are now staged. Concurrent stagers retry on CAS conflicts, so no
        append is lost."""
        for _ in range(max_retries):
            changes, version = self._read_changes()
            changes.append(change)
            try:
                self._write_changes(changes, version)
                return len(changes)
            except VersionMismatch:
                continue  # another stager won; re-read and retry
        raise VersionMismatch(f"stage contention on {self.changes_key}")

    def staged(self) -> list[dict]:
        return self._read_changes()[0]

    # -- committing --

    def commit(self, apply_fn: Callable[[dict, list[dict]], dict]) -> int:
        """Apply every currently-staged change in one transition:
        new_value = apply_fn(current_value, staged_changes). Returns the
        new value's version (current version when nothing is staged).

        Raises VersionMismatch if the value moved between read and write —
        the staged changes stay put, so the caller re-commits against the
        new value. On success exactly the applied changes are removed;
        changes staged concurrently with the commit survive for the next
        one."""
        # value/version FIRST: a commit that races another commit then
        # fails its CAS (the version predates the winner's write). Reading
        # changes first would let the stale snapshot pass a fresh version
        # check — double-applying the winner's changes and consuming
        # unapplied ones.
        value, version = self.get()
        changes, _ = self._read_changes()
        if not changes:
            return version
        new_value = apply_fn(value, changes)
        raw = json.dumps(new_value).encode()
        if version == 0:
            new_version = self.kv.set_if_not_exists(self.key, raw)
        else:
            new_version = self.kv.check_and_set(self.key, version, raw)
        self._consume(len(changes))
        return new_version

    def _consume(self, n: int, max_retries: int = 64) -> None:
        """Remove the first n staged changes (the ones a commit applied);
        appends are tail-only so they form a stable prefix."""
        for _ in range(max_retries):
            changes, version = self._read_changes()
            if version is None:
                return
            rest = changes[n:]
            try:
                # an empty doc stays behind rather than a delete: deleting
                # after the CAS would race a concurrent append and drop it
                self._write_changes(rest, version)
                return
            except VersionMismatch:
                continue  # a concurrent stage appended; retry the trim
            except KeyNotFound:
                return
