"""Managed change-set workflow over a KV key.

Role parity with the reference's cluster/changeset: writers STAGE changes
against a managed value; a committer APPLIES every staged change in one
CAS'd transition of the value. Staging is a CAS-guarded append, so any
number of writers stage concurrently without losing entries.

Exactly-once application is carried by the VALUE key itself: every staged
change gets a monotonically-increasing id, and the committed value records
`applied_upto`, the highest change id folded into it. A committer only
applies changes with id > applied_upto, so a racing commit that reads the
winner's value re-applies nothing, and trimming the staged list is mere
garbage collection (benign under any race). A commit whose value CAS loses
raises VersionMismatch with the staged changes intact for a retry.

Layout: <key> holds {"data": <caller value>, "applied_upto": N};
<key>/_changeset holds {"changes": [{"id": n, "change": {...}}, ...]}.
"""

from __future__ import annotations

import json
from typing import Callable

from m3_tpu.cluster.kv import KeyNotFound, VersionMismatch


class ChangeSetManager:
    def __init__(self, kv, key: str):
        self.kv = kv
        self.key = key
        self.changes_key = key + "/_changeset"

    # -- value --

    def get(self) -> tuple[dict, int]:
        """(value, version); ({}, 0) when unset."""
        value, _applied, version = self._get_full()
        return value, version

    def _get_full(self) -> tuple[dict, int, int]:
        try:
            vv = self.kv.get(self.key)
        except KeyNotFound:
            return {}, 0, 0
        doc = json.loads(vv.data)
        return doc.get("data", {}), int(doc.get("applied_upto", 0)), vv.version

    # -- staging --

    def _read_changes(self) -> tuple[list[dict], int | None]:
        try:
            vv = self.kv.get(self.changes_key)
        except KeyNotFound:
            return [], None
        return list(json.loads(vv.data).get("changes", [])), vv.version

    def _write_changes(self, entries: list[dict], expect_version: int | None) -> None:
        raw = json.dumps({"changes": entries}).encode()
        if expect_version is None:
            self.kv.set_if_not_exists(self.changes_key, raw)
        else:
            self.kv.check_and_set(self.changes_key, expect_version, raw)

    def stage(self, change: dict, max_retries: int = 64) -> int:
        """Append one change to the staged set; returns its change id.
        Concurrent stagers retry on CAS conflicts, so no append is lost."""
        for _ in range(max_retries):
            entries, version = self._read_changes()
            _, applied_upto, _ = self._get_full()
            prev_max = max(
                [e["id"] for e in entries] + [applied_upto]
            ) if (entries or applied_upto) else 0
            cid = prev_max + 1
            entries.append({"id": cid, "change": change})
            try:
                self._write_changes(entries, version)
                return cid
            except VersionMismatch:
                continue  # another stager won; re-read and retry
        raise VersionMismatch(f"stage contention on {self.changes_key}")

    def staged(self) -> list[dict]:
        """Changes staged and not yet applied to the committed value."""
        entries, _ = self._read_changes()
        _, applied_upto, _ = self._get_full()
        return [e["change"] for e in entries if e["id"] > applied_upto]

    # -- committing --

    def commit(self, apply_fn: Callable[[dict, list[dict]], dict]) -> int:
        """Apply every pending change in one transition:
        new_value = apply_fn(current_value, pending_changes). Returns the
        new value's version (current version when nothing is pending).

        Raises VersionMismatch if the value moved between read and write —
        the staged changes stay put, so the caller re-commits against the
        new value. Changes already folded into the value (id <=
        applied_upto) are never re-applied, even by a commit racing the
        one that applied them."""
        value, applied_upto, version = self._get_full()
        entries, _ = self._read_changes()
        pending = [e for e in entries if e["id"] > applied_upto]
        if not pending:
            return version
        new_value = apply_fn(value, [e["change"] for e in pending])
        new_upto = max(e["id"] for e in pending)
        raw = json.dumps({"data": new_value, "applied_upto": new_upto}).encode()
        if version == 0:
            new_version = self.kv.set_if_not_exists(self.key, raw)
        else:
            new_version = self.kv.check_and_set(self.key, version, raw)
        self._gc(new_upto)
        return new_version

    def _gc(self, applied_upto: int, max_retries: int = 64) -> None:
        """Drop staged entries already folded into the value. Pure garbage
        collection: correctness never depends on it (applied_upto gates
        re-application), so losing a race here is harmless."""
        for _ in range(max_retries):
            entries, version = self._read_changes()
            if version is None:
                return
            rest = [e for e in entries if e["id"] > applied_upto]
            if len(rest) == len(entries):
                return
            try:
                self._write_changes(rest, version)
                return
            except VersionMismatch:
                continue
            except KeyNotFound:
                return
