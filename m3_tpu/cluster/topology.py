"""Topology: the client-facing view of a placement + consistency levels.

Role parity with /root/reference/src/dbnode/topology/types.go:65,99 and
consistency_level.go: host->shard mapping derived from the placement, and
the write/read consistency ladder (One / Majority / All, with unstrict
variants used during bootstraps).
"""

from __future__ import annotations

import enum

from m3_tpu.cluster.placement import Placement, ShardState


class ConsistencyLevel(enum.Enum):
    ONE = "one"
    MAJORITY = "majority"
    ALL = "all"
    UNSTRICT_MAJORITY = "unstrict_majority"
    UNSTRICT_ALL = "unstrict_all"


def majority(replica_factor: int) -> int:
    return replica_factor // 2 + 1


def required_acks(level: ConsistencyLevel, replica_factor: int) -> int:
    if level == ConsistencyLevel.ONE:
        return 1
    if level in (ConsistencyLevel.MAJORITY, ConsistencyLevel.UNSTRICT_MAJORITY):
        return majority(replica_factor)
    return replica_factor


def is_unstrict(level: ConsistencyLevel) -> bool:
    return level in (ConsistencyLevel.UNSTRICT_MAJORITY, ConsistencyLevel.UNSTRICT_ALL)


class TopologyMap:
    """Immutable view over one placement version."""

    def __init__(self, placement: Placement):
        self.placement = placement

    @property
    def replica_factor(self) -> int:
        return self.placement.replica_factor

    @property
    def n_shards(self) -> int:
        return self.placement.n_shards

    def hosts_for_shard(self, shard_id: int) -> list[str]:
        """Instance ids owning the shard in ANY state: writes go to
        bootstrapping (INITIALIZING) targets so they don't miss data, and
        to LEAVING donors which keep serving until cutover completes."""
        return sorted(
            inst.id
            for inst in self.placement.instances.values()
            if shard_id in inst.shards
        )

    def readable_hosts_for_shard(self, shard_id: int) -> list[str]:
        """AVAILABLE and LEAVING replicas serve reads (a leaving donor has
        the full data until the handoff finishes); INITIALIZING replicas
        are still bootstrapping and would return partial data."""
        out = []
        for inst in self.placement.instances.values():
            sh = inst.shards.get(shard_id)
            if sh is not None and sh.state in (
                ShardState.AVAILABLE, ShardState.LEAVING
            ):
                out.append(inst.id)
        return sorted(out)
