"""Placements: instances, shard assignments, and rebalancing algorithms.

Role parity with the reference placement model
(/root/reference/src/cluster/placement — instances carrying shard sets with
Initializing/Available/Leaving states driving elastic add/remove/replace)
and its algorithms (placement/algo/sharded.go minimal-churn rebalancing;
mirrored.go paired leader/follower placements for the aggregator).

Multi-chip mapping (SURVEY.md §2.10): a placement's shard->instance
assignment is exactly the mesh 'shard' axis layout; the parallel/ package
builds jax.sharding meshes from a Placement.
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field, replace


class ShardState(enum.Enum):
    INITIALIZING = "INITIALIZING"
    AVAILABLE = "AVAILABLE"
    LEAVING = "LEAVING"


@dataclass(frozen=True)
class Shard:
    id: int
    state: ShardState = ShardState.INITIALIZING
    source_id: str | None = None  # instance streamed from while INITIALIZING


@dataclass
class Instance:
    id: str
    isolation_group: str = "default"  # rack/zone anti-affinity domain
    weight: int = 1
    endpoint: str = ""
    shards: dict[int, Shard] = field(default_factory=dict)
    shard_set_id: int = 0  # mirrored placements: paired instances share ids
    # subclustered placements: all RF replicas of a shard live within one
    # subcluster (cluster/placement/algo/subclustered.go role); 0 = none
    sub_cluster_id: int = 0

    def shard_ids(self, *states: ShardState) -> list[int]:
        if not states:
            return sorted(self.shards)
        return sorted(s.id for s in self.shards.values() if s.state in states)


@dataclass
class Placement:
    instances: dict[str, Instance] = field(default_factory=dict)
    n_shards: int = 0
    replica_factor: int = 1
    is_mirrored: bool = False
    version: int = 0

    # -- queries --

    def instances_for_shard(self, shard_id: int) -> list[Instance]:
        return [
            inst for inst in self.instances.values()
            if shard_id in inst.shards
            and inst.shards[shard_id].state != ShardState.LEAVING
        ]

    def validate(self) -> None:
        counts = {s: 0 for s in range(self.n_shards)}
        for inst in self.instances.values():
            for sid, sh in inst.shards.items():
                if sh.state != ShardState.LEAVING:
                    counts[sid] += 1
        bad = {s: c for s, c in counts.items() if c != self.replica_factor}
        if bad:
            raise ValueError(f"shards without RF={self.replica_factor} owners: {bad}")

    # -- serialization (stored in KV) --

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "n_shards": self.n_shards,
                "replica_factor": self.replica_factor,
                "is_mirrored": self.is_mirrored,
                "version": self.version,
                "instances": {
                    iid: {
                        "isolation_group": inst.isolation_group,
                        "weight": inst.weight,
                        "endpoint": inst.endpoint,
                        "shard_set_id": inst.shard_set_id,
                        "sub_cluster_id": inst.sub_cluster_id,
                        "shards": [
                            {"id": s.id, "state": s.state.value, "source": s.source_id}
                            for s in inst.shards.values()
                        ],
                    }
                    for iid, inst in self.instances.items()
                },
            }
        ).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Placement":
        doc = json.loads(raw)
        p = cls(
            n_shards=doc["n_shards"],
            replica_factor=doc["replica_factor"],
            is_mirrored=doc.get("is_mirrored", False),
            version=doc.get("version", 0),
        )
        for iid, d in doc["instances"].items():
            inst = Instance(
                id=iid,
                isolation_group=d.get("isolation_group", "default"),
                weight=d.get("weight", 1),
                endpoint=d.get("endpoint", ""),
                shard_set_id=d.get("shard_set_id", 0),
                sub_cluster_id=d.get("sub_cluster_id", 0),
            )
            for s in d["shards"]:
                inst.shards[s["id"]] = Shard(
                    s["id"], ShardState(s["state"]), s.get("source")
                )
            p.instances[iid] = inst
        return p


# ---------------------------------------------------------------------------
# sharded placement algorithm (minimal-churn add/remove/replace)
# ---------------------------------------------------------------------------


def initial_placement(
    instances: list[Instance], n_shards: int, replica_factor: int
) -> Placement:
    """Spread each replica of every shard across instances, preferring
    distinct isolation groups per shard."""
    p = Placement(n_shards=n_shards, replica_factor=replica_factor)
    for inst in instances:
        p.instances[inst.id] = _bare_copy(inst)
    if len(instances) < replica_factor:
        raise ValueError("need at least RF instances")
    # round-robin by load, respecting isolation groups where possible
    for sid in range(n_shards):
        owners: list[Instance] = []
        for _r in range(replica_factor):
            cand = _least_loaded(p, exclude={o.id for o in owners},
                                 avoid_groups={o.isolation_group for o in owners})
            cand.shards[sid] = Shard(sid, ShardState.INITIALIZING)
            owners.append(cand)
    # initial placement: every shard immediately AVAILABLE (no data to move)
    for inst in p.instances.values():
        inst.shards = {
            sid: Shard(sid, ShardState.AVAILABLE) for sid in inst.shards
        }
    p.version = 1
    return p


def _bare_copy(inst: Instance) -> Instance:
    """Copy of an instance with an empty shard set."""
    return replace(inst, shards={})


def _active_shards(inst: Instance) -> int:
    return sum(1 for s in inst.shards.values() if s.state != ShardState.LEAVING)


def _least_loaded(p: Placement, exclude: set[str], avoid_groups: set[str]) -> Instance:
    def load(inst: Instance) -> float:
        return len(inst.shards) / max(inst.weight, 1)

    cands = [i for i in p.instances.values() if i.id not in exclude]
    if not cands:
        raise ValueError("no candidate instances")
    preferred = [i for i in cands if i.isolation_group not in avoid_groups]
    pool = preferred or cands
    return min(pool, key=lambda i: (load(i), i.id))


def _move_fair_share(donors: list[Instance], new_inst: Instance,
                     target_load: int) -> None:
    """Stream shards from the most-loaded donors onto a joining instance
    until it carries target_load: INITIALIZING on the target (sourced from
    the donor), LEAVING on the donor until bootstrap completes."""
    for donor in sorted(donors, key=_active_shards, reverse=True):
        if donor.id == new_inst.id:
            continue
        while (len(new_inst.shards) < target_load
               and _active_shards(donor) > target_load):
            movable = [
                s for s in donor.shards.values()
                if s.state == ShardState.AVAILABLE and s.id not in new_inst.shards
            ]
            if not movable:
                break
            sh = movable[0]
            new_inst.shards[sh.id] = Shard(sh.id, ShardState.INITIALIZING, donor.id)
            donor.shards[sh.id] = Shard(sh.id, ShardState.LEAVING)


def add_instance(p: Placement, new: Instance) -> Placement:
    """Move a fair share of shards onto the new instance; moved shards are
    INITIALIZING on the target (sourced from the donor) and stay AVAILABLE
    on the donor until the target finishes bootstrapping."""
    out = Placement.from_json(p.to_json())
    new_inst = _bare_copy(new)
    out.instances[new_inst.id] = new_inst
    total = p.n_shards * p.replica_factor
    target_load = total // len(out.instances)
    _move_fair_share(list(out.instances.values()), new_inst, target_load)
    out.version += 1
    return out


def remove_instance(p: Placement, instance_id: str,
                    within_subcluster: bool = False) -> Placement:
    """Reassign the leaving instance's shards, minimizing churn
    (reference algo/sharded.go selection): an instance ALREADY holding the
    shard in LEAVING state reclaims it in place (zero data movement —
    reverses an unfinished move); otherwise the least-loaded peer outside
    the current owners' isolation groups streams it."""
    out = Placement.from_json(p.to_json())
    leaving = out.instances.get(instance_id)
    if leaving is None:
        raise KeyError(instance_id)
    for sid in list(leaving.shards):
        leaving.shards[sid] = Shard(sid, ShardState.LEAVING)
        owners = [
            i for i in out.instances.values()
            if sid in i.shards and i.shards[sid].state != ShardState.LEAVING
        ]
        # churn-minimizing reclaim: a peer mid-handoff of this same shard
        # keeps it instead of a third instance streaming a fresh copy
        reclaim = [
            i for i in out.instances.values()
            if i.id != instance_id and sid in i.shards
            and i.shards[sid].state == ShardState.LEAVING
            and (not within_subcluster
                 or i.sub_cluster_id == leaving.sub_cluster_id)
        ]
        if reclaim:
            reclaim[0].shards[sid] = Shard(sid, ShardState.AVAILABLE)
            # the cancelled handoff leaves nothing to stream: the leaver's
            # copy can drop right away (mark_available would never reap it
            # — no INITIALIZING shard links back via source_id)
            del leaving.shards[sid]
            continue
        if len(owners) >= out.replica_factor:
            # a prior move (add/replace) already has this shard's
            # replacement INITIALIZING elsewhere; assigning another owner
            # would over-replicate. The leaver's LEAVING copy stays until
            # that in-flight move cuts over and reaps it.
            continue
        exclude = {i.id for i in owners} | {instance_id}
        if within_subcluster:
            exclude |= {i.id for i in out.instances.values()
                        if i.sub_cluster_id != leaving.sub_cluster_id}
        try:
            target = _least_loaded(
                out,
                exclude=exclude,
                avoid_groups={i.isolation_group for i in owners},
            )
        except ValueError:
            if within_subcluster:
                # a subcluster sized exactly at RF has no spare member to
                # take the shard; removal would break the invariant —
                # the operator must replace_instance instead
                raise ValueError(
                    f"subcluster {leaving.sub_cluster_id} has no spare "
                    f"instance for shard {sid}; use replace_instance (or "
                    "add an instance to the subcluster first)") from None
            raise
        target.shards[sid] = Shard(sid, ShardState.INITIALIZING, instance_id)
    if not leaving.shards:
        del out.instances[instance_id]  # nothing left to hand off
    out.version += 1
    return out


def replace_instance(p: Placement, old_id: str, new: Instance) -> Placement:
    """Swap an instance: the replacement inherits every shard, INITIALIZING
    from the departed peer's replicas."""
    out = Placement.from_json(p.to_json())
    old = out.instances.get(old_id)
    if old is None:
        raise KeyError(old_id)
    new_inst = _bare_copy(new)
    # inherit only the shards the old instance was SERVING: a shard it
    # was already handing off (LEAVING) has its replacement INITIALIZING
    # elsewhere — inheriting it too would over-replicate, and the
    # in-flight owner keeps its original source_id (mark_available reaps
    # the old instance's LEAVING copy when that move completes)
    new_inst.shards = {
        sid: Shard(sid, ShardState.INITIALIZING, old_id)
        for sid, sh in old.shards.items() if sh.state != ShardState.LEAVING
    }
    for sid in list(old.shards):
        old.shards[sid] = Shard(sid, ShardState.LEAVING)
    out.instances[new_inst.id] = new_inst
    out.version += 1
    return out


def mark_available(p: Placement, instance_id: str, shard_ids: list[int] | None = None
                   ) -> Placement:
    """Complete bootstrap: INITIALIZING -> AVAILABLE on the instance, and
    drop the corresponding LEAVING shard from the donor."""
    out = Placement.from_json(p.to_json())
    inst = out.instances[instance_id]
    ids = shard_ids if shard_ids is not None else list(inst.shards)
    for sid in ids:
        sh = inst.shards.get(sid)
        if sh is None or sh.state != ShardState.INITIALIZING:
            continue
        inst.shards[sid] = Shard(sid, ShardState.AVAILABLE)
        if sh.source_id:
            donor = out.instances.get(sh.source_id)
            if donor and sid in donor.shards and donor.shards[sid].state == ShardState.LEAVING:
                del donor.shards[sid]
    # prune instances that were draining and now own nothing
    drained = [
        iid for iid, inst in out.instances.items()
        if not inst.shards and iid != instance_id
        and p.instances.get(iid) is not None and p.instances[iid].shards
    ]
    for iid in drained:
        del out.instances[iid]
    out.version += 1
    return out


# -- KV-backed placement store (the placement service storage role,
#    reference cluster/placement/service + kvstore) --

PLACEMENT_KEY = "placements/m3db"


def load_placement(kv, key: str = PLACEMENT_KEY) -> tuple["Placement", int] | None:
    """(placement, kv_version) or None when no placement exists."""
    from m3_tpu.cluster.kv import KeyNotFound

    try:
        vv = kv.get(key)
    except KeyNotFound:
        return None
    return Placement.from_json(vv.data), vv.version


def store_placement(kv, p: "Placement", key: str = PLACEMENT_KEY) -> int:
    return kv.set(key, p.to_json())


def cas_update_placement(kv, update_fn, key: str = PLACEMENT_KEY,
                         max_retries: int = 10) -> "Placement":
    """Read-modify-write with compare-and-set; update_fn(Placement) ->
    Placement. Retries on concurrent writers (the changeset/CAS discipline
    of the reference's etcd-backed placement updates)."""
    from m3_tpu.cluster.kv import VersionMismatch

    for _ in range(max_retries):
        loaded = load_placement(kv, key)
        if loaded is None:
            raise KeyError(f"no placement at {key!r}")
        p, version = loaded
        new_p = update_fn(p)
        try:
            kv.check_and_set(key, version, new_p.to_json())
            return new_p
        except VersionMismatch:
            continue
    raise RuntimeError(f"placement CAS contention on {key!r}")


def mirrored_placement(pairs: list[tuple[Instance, Instance]], n_shards: int) -> Placement:
    """Mirrored placement (aggregator leader/follower pairs): both members
    of a pair carry identical shard sets and share a shard_set_id
    (cluster/placement/algo/mirrored.go role)."""
    p = Placement(n_shards=n_shards, replica_factor=2, is_mirrored=True)
    for set_id, (a, b) in enumerate(pairs, start=1):
        for inst in (a, b):
            cp = _bare_copy(inst)
            cp.shard_set_id = set_id
            p.instances[cp.id] = cp
    n_pairs = len(pairs)
    for sid in range(n_shards):
        set_id = (sid % n_pairs) + 1
        for inst in p.instances.values():
            if inst.shard_set_id == set_id:
                inst.shards[sid] = Shard(sid, ShardState.AVAILABLE)
    p.version = 1
    return p


# ---------------------------------------------------------------------------
# subclustered placement algorithm
# ---------------------------------------------------------------------------
#
# Role parity with /root/reference/src/cluster/placement/algo/subclustered.go:
# instances partition into fixed-size subclusters and every replica of a
# shard lives WITHIN one subcluster, so a shard's replica group never spans
# subcluster boundaries (bounds blast radius and keeps replica streams on
# subcluster-local links — on TPU topology, a subcluster maps to one ICI
# domain so replica traffic never crosses DCN).


def subclustered_placement(
    instances: list[Instance], n_shards: int, replica_factor: int,
    instances_per_subcluster: int,
) -> Placement:
    """Initial subclustered placement. Each subcluster must be able to
    hold RF replicas (instances_per_subcluster >= RF); shards spread over
    subclusters round-robin, replicas within their subcluster preferring
    distinct isolation groups."""
    if instances_per_subcluster < replica_factor:
        raise ValueError("subcluster smaller than replica factor")
    if len(instances) < instances_per_subcluster:
        raise ValueError("need at least one full subcluster")
    p = Placement(n_shards=n_shards, replica_factor=replica_factor)
    for i, inst in enumerate(instances):
        cp = _bare_copy(inst)
        cp.sub_cluster_id = i // instances_per_subcluster + 1
        p.instances[cp.id] = cp
    # only FULL subclusters take shards (a partial trailing group waits
    # for members, reference semantics)
    full = [
        sc for sc in sorted({i.sub_cluster_id for i in p.instances.values()})
        if sum(1 for i in p.instances.values() if i.sub_cluster_id == sc)
        >= instances_per_subcluster
    ]
    if not full:
        raise ValueError("no full subcluster")
    for sid in range(n_shards):
        sc = full[sid % len(full)]
        members = {i.id for i in p.instances.values()
                   if i.sub_cluster_id != sc}
        owners: list[Instance] = []
        for _r in range(replica_factor):
            cand = _least_loaded(
                p,
                exclude=members | {o.id for o in owners},
                avoid_groups={o.isolation_group for o in owners},
            )
            cand.shards[sid] = Shard(sid, ShardState.AVAILABLE)
            owners.append(cand)
    p.version = 1
    return p


def validate_subclusters(p: Placement) -> None:
    """Every shard's non-LEAVING replicas share one subcluster."""
    shard_sc: dict[int, set[int]] = {}
    for inst in p.instances.values():
        for sid, sh in inst.shards.items():
            if sh.state != ShardState.LEAVING:
                shard_sc.setdefault(sid, set()).add(inst.sub_cluster_id)
    bad = {sid: scs for sid, scs in shard_sc.items() if len(scs) > 1}
    if bad:
        raise ValueError(f"shards spanning subclusters: {bad}")


def add_instance_subclustered(
    p: Placement, new: Instance, instances_per_subcluster: int,
) -> Placement:
    """Join the first under-full subcluster (or open a new one) and take a
    fair share of THAT subcluster's shards only — the subcluster invariant
    means a joining instance can only relieve its own group."""
    out = Placement.from_json(p.to_json())
    counts: dict[int, int] = {}
    for inst in out.instances.values():
        counts[inst.sub_cluster_id] = counts.get(inst.sub_cluster_id, 0) + 1
    under = [sc for sc, n in sorted(counts.items())
             if n < instances_per_subcluster]
    sc = under[0] if under else max(counts) + 1
    new_inst = _bare_copy(new)
    new_inst.sub_cluster_id = sc
    out.instances[new_inst.id] = new_inst
    members = [i for i in out.instances.values()
               if i.sub_cluster_id == sc and i.id != new_inst.id]
    if members:
        sc_load = sum(_active_shards(i) for i in members)
        target_load = sc_load // (len(members) + 1)
        _move_fair_share(members, new_inst, target_load)
    out.version += 1
    return out


def remove_instance_subclustered(p: Placement, instance_id: str) -> Placement:
    """Remove an instance; its shards stay within its subcluster."""
    return remove_instance(p, instance_id, within_subcluster=True)
