"""Service discovery + leader election over the KV store.

Role parity with /root/reference/src/cluster/services/types.go:36,326,371:
instances advertise themselves with heartbeats; a leader service runs
campaign/resign elections. Elections are lease-based CAS records in KV (the
etcd-election stand-in): the leader must re-assert within the TTL or any
campaigner can seize the key.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass

from m3_tpu.cluster.kv import KeyNotFound, KVStore, VersionMismatch


@dataclass
class Advertisement:
    service: str
    instance_id: str
    endpoint: str
    heartbeat_ns: int


class Services:
    def __init__(self, kv: KVStore, heartbeat_ttl_s: float = 10.0):
        self.kv = kv
        self.ttl = heartbeat_ttl_s

    def _key(self, service: str, instance_id: str) -> str:
        return f"_sd/{service}/{instance_id}"

    def advertise(self, service: str, instance_id: str, endpoint: str = "") -> None:
        ad = Advertisement(service, instance_id, endpoint, time.time_ns())
        self.kv.set(self._key(service, instance_id), json.dumps(ad.__dict__).encode())

    def instances(self, service: str, now_ns: int | None = None) -> list[Advertisement]:
        """Live (heartbeat within TTL) instances of a service."""
        now_ns = now_ns if now_ns is not None else time.time_ns()
        out = []
        for key in self.kv.keys(f"_sd/{service}/"):
            try:
                doc = json.loads(self.kv.get(key).data)
            except KeyNotFound:
                continue  # deregistered between keys() and get()
            ad = Advertisement(**doc)
            if now_ns - ad.heartbeat_ns <= self.ttl * 1e9:
                out.append(ad)
        return sorted(out, key=lambda a: a.instance_id)


class LeaderService:
    """Lease-based election: campaign() seizes or renews a lease record;
    followers observe; resign() releases. TTL expiry lets a new leader
    seize (failure detection)."""

    def __init__(self, kv: KVStore, election_id: str, instance_id: str,
                 lease_ttl_s: float = 10.0):
        self.kv = kv
        self.election_id = election_id
        self.instance_id = instance_id
        self.ttl = lease_ttl_s
        self._key = f"_leader/{election_id}"
        self._lock = threading.Lock()

    def campaign(self, now_ns: int | None = None) -> bool:
        """Try to become (or stay) leader; returns True when leading."""
        now_ns = now_ns if now_ns is not None else time.time_ns()
        record = json.dumps(
            {"leader": self.instance_id, "renewed_ns": now_ns}
        ).encode()
        with self._lock:
            try:
                cur = self.kv.get(self._key)
            except KeyNotFound:
                try:
                    self.kv.set_if_not_exists(self._key, record)
                    return True
                except VersionMismatch:
                    return False
            doc = json.loads(cur.data)
            expired = now_ns - doc["renewed_ns"] > self.ttl * 1e9
            if doc["leader"] != self.instance_id and not expired:
                return False
            try:
                self.kv.check_and_set(self._key, cur.version, record)
                return True
            except VersionMismatch:
                return False

    def leader(self, now_ns: int | None = None) -> str | None:
        now_ns = now_ns if now_ns is not None else time.time_ns()
        try:
            doc = json.loads(self.kv.get(self._key).data)
        except Exception:
            return None
        if now_ns - doc["renewed_ns"] > self.ttl * 1e9:
            return None
        return doc["leader"]

    def is_leader(self, now_ns: int | None = None) -> bool:
        return self.leader(now_ns) == self.instance_id

    def resign(self) -> None:
        with self._lock:
            try:
                cur = self.kv.get(self._key)
                doc = json.loads(cur.data)
                if doc["leader"] == self.instance_id:
                    self.kv.delete(self._key)
            except Exception:
                pass
