"""Vectorized 64-bit bit-manipulation primitives.

Device-side equivalents of the host helpers in m3_tpu.utils.bitstream,
operating elementwise on uint64 tensors. These underpin the batched M3TSZ
kernels (m3_tpu.encoding.m3tsz.tpu); the scalar semantics they must match are
the reference's (/root/reference/src/dbnode/encoding/encoding.go:29-43).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

import m3_tpu.ops  # noqa: F401  (enables x64)

U64 = jnp.uint64
I64 = jnp.int64

# numpy scalars inline as trace literals; module-level jnp scalars become
# hoisted jaxpr constants and trip a jit fastpath buffer-count bug.
import numpy as _np

_ZERO = _np.uint64(0)
_ONE = _np.uint64(1)
_SIXTYFOUR = _np.uint64(64)


def u64(x) -> jnp.ndarray:
    return jnp.asarray(x, dtype=U64)


def clz64(v: jnp.ndarray) -> jnp.ndarray:
    """Count leading zeros; clz(0) = 64. Returns uint64."""
    v = v.astype(U64)
    return jnp.where(v == 0, _SIXTYFOUR, lax.clz(v).astype(U64))


def ctz64(v: jnp.ndarray) -> jnp.ndarray:
    """Count trailing zeros; ctz(0) = 0 (reference convention for XOR
    streams: LeadingAndTrailingZeros(0) = (64, 0))."""
    v = v.astype(U64)
    iso = v & (jnp.uint64(0) - v)  # lowest set bit
    return jnp.where(v == 0, _ZERO, jnp.uint64(63) - lax.clz(iso).astype(U64))


def shl(v: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Left shift, safe for n in [0, 64] (n>=64 -> 0)."""
    v = v.astype(U64)
    n = jnp.asarray(n, dtype=U64)
    return jnp.where(n >= 64, _ZERO, v << jnp.minimum(n, jnp.uint64(63)))


def shr(v: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Logical right shift, safe for n in [0, 64] (n>=64 -> 0)."""
    v = v.astype(U64)
    n = jnp.asarray(n, dtype=U64)
    return jnp.where(n >= 64, _ZERO, v >> jnp.minimum(n, jnp.uint64(63)))


def mask_low(n: jnp.ndarray) -> jnp.ndarray:
    """(1 << n) - 1, safe for n in [0, 64]."""
    n = jnp.asarray(n, dtype=U64)
    return jnp.where(n >= 64, ~_ZERO, shl(_ONE, n) - _ONE)


def sign_extend64(v: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Interpret low n bits of v as an n-bit two's-complement int64."""
    v = v.astype(U64) & mask_low(n)
    sign = shl(_ONE, jnp.asarray(n, U64) - _ONE)
    return (v ^ sign).astype(I64) - sign.astype(I64)


def f64_to_bits(v: jnp.ndarray) -> jnp.ndarray:
    return lax.bitcast_convert_type(jnp.asarray(v, jnp.float64), U64)


def bits_to_f64(v: jnp.ndarray) -> jnp.ndarray:
    return lax.bitcast_convert_type(v.astype(U64), jnp.float64)


# ---------------------------------------------------------------------------
# Multi-limb registers: limb 0 is the MOST significant word; bit 63 of limb 0
# is stream bit 0 (streams are MSB-first).
# ---------------------------------------------------------------------------


def reg3_insert(
    reg: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray],
    used: jnp.ndarray,
    field_hi: jnp.ndarray,
    field_lo: jnp.ndarray,
    field_len: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """OR a <=128-bit field (right-aligned in (hi, lo)) into a 192-bit
    register so its first bit lands at bit position `used`.

    The field occupies bits [used, used+field_len); callers guarantee those
    bits are currently zero and used+field_len <= 192.
    """
    used = jnp.asarray(used, U64)
    field_len = jnp.asarray(field_len, U64)
    # Left-shift the 128-bit value into a 192-bit register:
    # shift amount from right-aligned-192 position.
    s = jnp.uint64(192) - used - field_len
    ls = s >> jnp.uint64(6)  # limb shift 0..2
    bs = s & jnp.uint64(63)  # bit shift 0..63
    # in-limbs of the right-aligned 192-bit value: [0, hi, lo]
    in_limbs = (_ZERO * field_hi, field_hi.astype(U64), field_lo.astype(U64))

    def limb_at(idx):
        # in_limbs[idx] with idx possibly out of range -> 0
        out = _ZERO * field_lo.astype(U64)
        for k in range(3):
            out = jnp.where(idx == k, in_limbs[k], out)
        return out

    out = []
    for j in range(3):
        jj = jnp.asarray(j, U64)
        lo_part = shl(limb_at(jj + ls), bs)
        # carry bits from the next-lower limb
        hi_part = jnp.where(bs == 0, _ZERO, shr(limb_at(jj + ls + _ONE), _SIXTYFOUR - bs))
        out.append(reg[j] | lo_part | hi_part)
    return tuple(out)


def reg3_shift_right_to4(
    reg: tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray], r: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Shift a 192-bit register right by r in [0, 63], producing 4 limbs."""
    r = jnp.asarray(r, U64)
    p0, p1, p2 = (x.astype(U64) for x in reg)
    inv = _SIXTYFOUR - r
    carry = lambda v: jnp.where(r == 0, _ZERO, shl(v, inv))  # noqa: E731
    o0 = shr(p0, r)
    o1 = shr(p1, r) | carry(p0)
    o2 = shr(p2, r) | carry(p1)
    o3 = carry(p2)
    return o0, o1, o2, o3


def read_window(words: jnp.ndarray, bitoff: jnp.ndarray) -> jnp.ndarray:
    """Read 64 bits starting at absolute bit offset from a uint64 word array
    (MSB-first). Out-of-range reads return zero bits."""
    bitoff = jnp.asarray(bitoff, U64)
    w = (bitoff >> jnp.uint64(6)).astype(jnp.int64)
    r = bitoff & jnp.uint64(63)
    first = words.at[w].get(mode="fill", fill_value=0)
    second = words.at[w + 1].get(mode="fill", fill_value=0)
    return jnp.where(r == 0, first, shl(first, r) | shr(second, _SIXTYFOUR - r))
