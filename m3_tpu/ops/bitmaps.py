"""Device bitmap algebra for batched postings evaluation.

The reference evaluates boolean queries by roaring-container loops
(/root/reference/src/m3ninx/search/searcher/conjunction.go:78-111); here a
batch of Q candidate sets over N docs is a dense [Q, W] uint64 tensor and
AND/OR/ANDNOT are single fused vector ops, with lax.population_count for
cardinalities — the shape used by the 50-regex-queries benchmark
(BASELINE.md config #4).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

import m3_tpu.ops  # noqa: F401  (x64)


@jax.jit
def conjunct(masks: jnp.ndarray) -> jnp.ndarray:
    """AND-reduce [Q, W] -> [W]."""
    def f(a, b):
        return a & b

    return lax.reduce(masks, jnp.uint64(~jnp.uint64(0)), f, dimensions=(0,))


@jax.jit
def disjunct(masks: jnp.ndarray) -> jnp.ndarray:
    """OR-reduce [Q, W] -> [W]."""
    def f(a, b):
        return a | b

    return lax.reduce(masks, jnp.uint64(0), f, dimensions=(0,))


@jax.jit
def and_not(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return a & ~b


@jax.jit
def pairwise_and(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[Q, W] & [Q, W] elementwise (Q independent queries at once)."""
    return a & b


@jax.jit
def cardinality(masks: jnp.ndarray) -> jnp.ndarray:
    """Set sizes of a [Q, W] batch -> [Q] int32."""
    return lax.population_count(masks).sum(axis=-1).astype(jnp.int32)


# -- bool <-> word bridges for fused programs --------------------------------
#
# The device-compiled index (index/device.py) builds per-matcher doc
# membership as boolean vectors (scatter-friendly), then runs its dense
# intersect legs on uint64 words (population-count/AND/OR-friendly).
# These helpers are meant to be TRACED INLINE inside a cached program —
# they are not jit entry points themselves.


def words_from_bool(bits):
    """[..., N] bool -> [..., N/64] uint64 words (little-endian bit
    order, matching postings.to_bitmap/from_bitmap). N must be a
    multiple of 64 — the caller pads the doc axis to a word-aligned
    shape bucket."""
    u8 = jnp.packbits(bits, axis=-1, bitorder="little")
    grouped = u8.reshape(u8.shape[:-1] + (u8.shape[-1] // 8, 8))
    return lax.bitcast_convert_type(grouped, jnp.uint64)


def and_reduce_words(words):
    """AND-reduce [Q, W] uint64 -> [W] (the conjunct leg, traceable with
    a leading axis of any static size, including zero -> all-ones)."""
    return lax.reduce(words, jnp.uint64(~jnp.uint64(0)),
                      lambda a, b: a & b, dimensions=(0,))


def or_reduce_words(words):
    """OR-reduce [Q, W] uint64 -> [W] (the disjunct leg)."""
    return lax.reduce(words, jnp.uint64(0), lambda a, b: a | b,
                      dimensions=(0,))
