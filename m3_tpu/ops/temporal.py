"""Device kernels for the PromQL temporal hot loops.

The jax lowering of m3_tpu.query.windows' columnar math (reference hot
loops: /root/reference/src/query/functions/temporal/{rate,aggregation}.go):
window bounds (cheap searchsorted) stay on host; the heavy [S x steps]
matrix math — prefix-sum window reductions, extrapolated-rate algebra,
staleness gathers — runs as one fused XLA program per shape.

All kernels take ragged sample arrays padded to a power of two (values
pad 0.0, so prefix sums are unaffected; lo/hi indices never reach pads)
and a [S, steps] lo/hi bound pair. ``query.windows`` dispatches here via
``utils.dispatch`` and keeps numpy as the flag-off fallback.

The math bodies live in the module-level ``stage_*`` functions: PURE
traced functions of jax arrays with no dispatch, padding or host logic.
The per-op jitted wrappers below (``_kernels``) and the whole-query
compiler (``query/compiler.py``, ROADMAP #2) compose the SAME stage
functions — op-by-op dispatch and whole-plan fusion share one
implementation, so a plan fused end-to-end cannot drift numerically from
the per-op kernels it replaced.
"""

from __future__ import annotations

import functools

import numpy as np

from m3_tpu.utils import dispatch

NS = 1_000_000_000

# elementwise matrix math wins earlier than sort-based ops
DEVICE_THRESHOLD = 16_384


def _pad_samples(values: np.ndarray, times: np.ndarray | None = None):
    n = len(values)
    N = dispatch.next_pow2(n)
    v = np.concatenate([values, np.zeros(N - n)])
    if times is None:
        return v, None
    t = np.concatenate([times, np.full(N - n, np.iinfo(np.int64).max, np.int64)])
    return v, t


# ---------------------------------------------------------------------------
# pure traced stage kernels (composable: see module doc)
# ---------------------------------------------------------------------------


def stage_sum_avg_std(v, lo, hi):
    """(count, s1, s2) per window via prefix sums (pads are 0.0, so the
    cumsum tail never changes a window that ends before the pad)."""
    import jax.numpy as jnp

    csum = jnp.concatenate([jnp.zeros(1), jnp.cumsum(v)])
    csq = jnp.concatenate([jnp.zeros(1), jnp.cumsum(v * v)])
    count = (hi - lo).astype(jnp.float64)
    return count, csum[hi] - csum[lo], csq[hi] - csq[lo]


def stage_instant_values(v, lo, hi):
    """Latest sample per (series, step) window, NaN when empty — the
    PromQL lookback/staleness gather."""
    import jax.numpy as jnp

    has = hi > lo
    idx = jnp.clip(hi - 1, 0, v.shape[0] - 1)
    return jnp.where(has, v[idx], jnp.nan)


def stage_over_time(fn: str, csum, lo, hi):
    """sum/avg/count/present_over_time matrices with the NaN-when-empty
    masking of windows.over_time — ``fn`` is a trace-time constant.

    ``csum`` is the [n+1] sample prefix-sum array, computed on HOST like
    the window bounds (np.cumsum — the exact array windows._window_sums
    gathers from, so the fused path is bit-identical to the interpreter
    here; XLA:CPU's own cumsum is also an order of magnitude slower than
    numpy's, see the whole-query compiler's host-prep note)."""
    import jax.numpy as jnp

    count = (hi - lo).astype(jnp.float64)
    empty = count == 0
    if fn == "count":
        return jnp.where(empty, jnp.nan, count)
    if fn == "present":
        return jnp.where(empty, jnp.nan, 1.0)
    s1 = csum[hi] - csum[lo]
    if fn == "sum":
        return jnp.where(empty, jnp.nan, s1)
    if fn == "avg":
        return jnp.where(empty, jnp.nan, s1 / jnp.where(empty, 1, count))
    raise ValueError(f"unknown composable over_time fn {fn}")


def stage_extrapolated_rate(v, adj, t, lo, hi, eval_ts, range_ns,
                            is_counter: bool, is_rate: bool):
    """Mirrors upstream promql extrapolatedRate (windows.py host path).

    Known deviation: XLA may reassociate (sampled/count)*1.1 when
    computing the extrapolation threshold, so a window whose edge gap
    EXACTLY equals the threshold (possible only with perfectly regular
    sample spacing) can take the other extrapolation branch than the
    numpy path. Both branches are valid upstream-Prometheus behavior;
    off the knife edge the paths agree bit-for-bit on exact inputs."""
    import jax.numpy as jnp

    n = v.shape[0]
    count = (hi - lo).astype(jnp.float64)
    ok = count >= 2
    safe_lo = jnp.clip(lo, 0, n - 1)
    safe_hi = jnp.clip(hi - 1, 0, n - 1)
    first_v = adj[safe_lo]
    last_v = adj[safe_hi]
    raw_first_v = v[safe_lo]
    first_t = t[safe_lo].astype(jnp.float64)
    last_t = t[safe_hi].astype(jnp.float64)
    result = last_v - first_v

    window_start = (eval_ts - range_ns).astype(jnp.float64)[None, :]
    window_end = eval_ts.astype(jnp.float64)[None, :]
    sampled = (last_t - first_t) / NS
    dur_to_start = (first_t - window_start) / NS
    dur_to_end = (window_end - last_t) / NS
    avg_between = sampled / jnp.maximum(count - 1, 1)
    threshold = avg_between * 1.1

    if is_counter:
        dur_to_zero = jnp.where(
            result > 0, sampled * (raw_first_v / result), jnp.inf
        )
        dur_to_start = jnp.where(
            (result > 0) & (raw_first_v >= 0) & (dur_to_zero < dur_to_start),
            dur_to_zero,
            dur_to_start,
        )

    dur_to_start = jnp.where(dur_to_start >= threshold, avg_between / 2,
                             dur_to_start)
    dur_to_end = jnp.where(dur_to_end >= threshold, avg_between / 2,
                           dur_to_end)

    extrap = sampled + dur_to_start + dur_to_end
    factor = jnp.where(sampled > 0, extrap / sampled, jnp.nan)
    out = result * factor
    if is_rate:
        out = out / (range_ns / NS)
    return jnp.where(ok & (sampled > 0), out, jnp.nan)


def stage_instant_delta(v, t, lo, hi, is_counter: bool, is_rate: bool):
    """irate/idelta from the last two samples in each window
    (windows.instant_delta host math)."""
    import jax.numpy as jnp

    n = v.shape[0]
    ok = (hi - lo) >= 2
    i_last = jnp.clip(hi - 1, 0, n - 1)
    i_prev = jnp.clip(hi - 2, 0, n - 1)
    v_last, v_prev = v[i_last], v[i_prev]
    t_last = t[i_last].astype(jnp.float64)
    t_prev = t[i_prev].astype(jnp.float64)
    diff = v_last - v_prev
    if is_counter:
        diff = jnp.where(v_last < v_prev, v_last, diff)
    out = diff
    if is_rate:
        dt = (t_last - t_prev) / NS
        out = jnp.where(dt > 0, diff / dt, jnp.nan)
    return jnp.where(ok, out, jnp.nan)


def stage_window_minmax(v, lo, hi, levels: int, is_min: bool):
    """min/max_over_time via a sparse table (ROADMAP carried follow-up):
    ``levels`` log-levels of shifted pairwise min/max over the sample
    array — m[k][i] = op(v[i : i + 2^k]) — then every (series, step)
    window answers with TWO gathers: op(m[k][lo], m[k][hi - 2^k]) where
    k = floor(log2(hi - lo)). The two anchored ranges tile [lo, hi)
    with overlap, which min/max absorb. O(N log W) build amortized over
    all S x T windows vs the O(N W) rescan; NaN samples propagate
    through the table exactly like np.minimum.reduceat on the host
    path, so the compiled result is bit-identical to the interpreter.

    ``levels`` is a trace-time constant (bucketed from the query's max
    window sample count, so executables stay O(log) per axis); window
    reads never cross a series row (bounds are row-local), so pad and
    neighbor-row contamination in high table levels is unreachable."""
    import jax
    import jax.numpy as jnp

    op = jnp.minimum if is_min else jnp.maximum
    fill = jnp.inf if is_min else -jnp.inf
    n = v.shape[0]
    rows = [v]
    cur = v
    for k in range(1, levels):
        w = 1 << (k - 1)
        shifted = jnp.concatenate([cur[w:], jnp.full((w,), fill)])[:n]
        cur = op(cur, shifted)
        rows.append(cur)
    tbl = jnp.stack(rows)  # [levels, N]
    length = hi - lo
    has = length > 0
    safe_len = jnp.maximum(length, 1).astype(jnp.int64)
    k = (63 - jax.lax.clz(safe_len)).astype(lo.dtype)
    k = jnp.clip(k, 0, levels - 1)
    span = jnp.left_shift(jnp.ones((), lo.dtype), k)
    a = tbl[k, jnp.clip(lo, 0, n - 1)]
    b = tbl[k, jnp.clip(hi - span, 0, n - 1)]
    return jnp.where(has, op(a, b), jnp.nan)


def stage_reset_adjusted(v, is_first, row_start_index):
    """Counter monotonization: v + cumulative in-row reset drops.
    row_start_index[i] = index of sample i's row's first sample."""
    import jax.numpy as jnp

    prev = jnp.concatenate([jnp.zeros(1), v[:-1]])
    drop = jnp.where((v < prev) & ~is_first, prev, 0.0)
    cdrop = jnp.cumsum(drop)
    cdrop0 = jnp.concatenate([jnp.zeros(1), cdrop])
    row_base = cdrop0[row_start_index]
    return v + (cdrop - row_base)


@functools.lru_cache(maxsize=None)
def _kernels():
    import jax
    import jax.numpy as jnp

    import m3_tpu.ops  # noqa: F401  (x64)

    @functools.partial(jax.jit, static_argnames=("max_len",))
    def holt_winters(v, lo, hi, sf, tf, max_len):
        """Double exponential smoothing per window (windows.holt_winters
        host math, upstream Prometheus semantics): fori_loop over window
        OFFSETS with [S, steps] state matrices — the per-sample recurrence
        is sequential, so time is the loop axis and (series x step) the
        vector axis (the layout the TPU VPU wants)."""
        n = v.shape[0]
        shape = lo.shape

        def body(j, st):
            found_first, found_second, prev, curr, trend, idx = st
            pos = lo + j
            val = v[jnp.clip(pos, 0, n - 1)]
            valid = (pos < hi) & ~jnp.isnan(val)
            take_first = valid & ~found_first
            curr = jnp.where(take_first, val, curr)
            idx = idx + take_first
            found_first = found_first | take_first
            sub = valid & found_first & ~take_first
            take_second = sub & ~found_second
            trend = jnp.where(take_second, val - curr, trend)
            found_second = found_second | take_second
            tv = jnp.where(idx == 1, trend,
                           tf * (curr - prev) + (1 - tf) * trend)
            new_curr = sf * val + (1 - sf) * (curr + tv)
            prev = jnp.where(sub, curr, prev)
            trend = jnp.where(sub, tv, trend)
            curr = jnp.where(sub, new_curr, curr)
            idx = idx + sub
            return (found_first, found_second, prev, curr, trend, idx)

        init = (jnp.zeros(shape, bool), jnp.zeros(shape, bool),
                jnp.zeros(shape), jnp.zeros(shape), jnp.zeros(shape),
                jnp.zeros(shape, jnp.int64))
        _ff, fs, _p, curr, _tr, _i = jax.lax.fori_loop(0, max_len, body, init)
        return jnp.where(fs, curr, jnp.nan)

    return {
        "sum_avg_std": jax.jit(stage_sum_avg_std),
        "instant_values": jax.jit(stage_instant_values),
        "extrapolated_rate": jax.jit(
            stage_extrapolated_rate,
            static_argnames=("is_counter", "is_rate")),
        "holt_winters": holt_winters,
        "reset_adjusted": jax.jit(stage_reset_adjusted),
        "window_minmax": jax.jit(
            stage_window_minmax, static_argnames=("levels", "is_min")),
    }


def _pad_bounds(lo: np.ndarray, hi: np.ndarray):
    """Pad BOTH axes to powers of two with empty windows, so varying
    series counts AND step counts (dashboard zooms) reuse O(log^2)
    compiled shapes instead of one XLA program per exact shape."""
    S, T = lo.shape
    Sp, Tp = dispatch.next_pow2(S), dispatch.next_pow2(T)
    if Sp == S and Tp == T:
        return lo, hi, S, T
    lo_p = np.zeros((Sp, Tp), np.int64)
    hi_p = np.zeros((Sp, Tp), np.int64)
    lo_p[:S, :T] = lo
    hi_p[:S, :T] = hi
    return lo_p, hi_p, S, T


def _pad_eval_ts(eval_ts: np.ndarray) -> np.ndarray:
    T = len(eval_ts)
    Tp = dispatch.next_pow2(T)
    if Tp == T:
        return eval_ts
    fill = eval_ts[-1] if T else 0
    return np.concatenate([eval_ts, np.full(Tp - T, fill, np.int64)])


def reset_adjust_inputs(offsets: np.ndarray, n: int, n_padded: int):
    """(is_first, row_start_index) arrays for stage_reset_adjusted over a
    CSR sample array padded from n to n_padded (pads form their own row)."""
    is_first = np.zeros(n_padded, bool)
    is_first[offsets[:-1][offsets[:-1] < n]] = True
    row_id = np.repeat(np.arange(len(offsets) - 1), np.diff(offsets))  # [n]
    row_start = np.full(n_padded, n, np.int64)
    row_start[:n] = offsets[:-1][row_id]
    if n_padded > n:
        is_first[n] = True
    return is_first, row_start


def instant_values(values: np.ndarray, lo: np.ndarray, hi: np.ndarray):
    v, _ = _pad_samples(values)
    lo_p, hi_p, S, T = _pad_bounds(lo, hi)
    out = _kernels()["instant_values"](v, lo_p, hi_p)
    return np.asarray(out)[:S, :T]


def sum_avg_std(values: np.ndarray, lo: np.ndarray, hi: np.ndarray):
    v, _ = _pad_samples(values)
    lo_p, hi_p, S, T = _pad_bounds(lo, hi)
    count, s1, s2 = _kernels()["sum_avg_std"](v, lo_p, hi_p)
    return (np.asarray(count)[:S, :T], np.asarray(s1)[:S, :T],
            np.asarray(s2)[:S, :T])


def extrapolated_rate(
    values: np.ndarray,
    adjusted: np.ndarray,
    times: np.ndarray,
    lo: np.ndarray,
    hi: np.ndarray,
    eval_ts: np.ndarray,
    range_ns: int,
    is_counter: bool,
    is_rate: bool,
):
    v, t = _pad_samples(values, times)
    adj, _ = _pad_samples(adjusted)
    lo_p, hi_p, S, T = _pad_bounds(lo, hi)
    out = _kernels()["extrapolated_rate"](
        v, adj, t, lo_p, hi_p, _pad_eval_ts(eval_ts), np.int64(range_ns),
        bool(is_counter), bool(is_rate),
    )
    return np.asarray(out)[:S, :T]


def holt_winters(values: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                 sf: float, tf: float):
    v, _ = _pad_samples(values)
    lo_p, hi_p, S, T = _pad_bounds(lo, hi)
    max_len = int((hi - lo).max()) if lo.size else 0
    # pad the static loop bound to a power of two: extra offsets fall
    # outside every window (pos >= hi) and no-op, buying shape reuse
    max_len = dispatch.next_pow2(max(max_len, 1))
    out = _kernels()["holt_winters"](v, lo_p, hi_p, float(sf), float(tf),
                                     max_len)
    return np.asarray(out)[:S, :T]


# sparse-table scratch bound: levels x padded-sample f64 elements (128MB);
# past it the min/max base stays on the host reduceat path
MINMAX_SCRATCH_ELEMS = 1 << 24


def minmax_levels(max_len: int) -> int:
    """Static level count for stage_window_minmax, bucketed to powers of
    two so nearby max-window-lengths share one executable."""
    return max(dispatch.next_pow2(max(max_len, 1)).bit_length(), 1)


def window_minmax(values: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                  is_min: bool):
    """Device min/max_over_time over [lo, hi) windows (sparse table)."""
    v, _ = _pad_samples(values)
    lo_p, hi_p, S, T = _pad_bounds(lo, hi)
    levels = minmax_levels(int((hi - lo).max()) if lo.size else 0)
    out = _kernels()["window_minmax"](v, lo_p, hi_p, levels, bool(is_min))
    return np.asarray(out)[:S, :T]


def reset_adjusted(values: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """Device counter monotonization over CSR rows."""
    n = len(values)
    if n == 0:
        return values
    v, _ = _pad_samples(values)
    is_first, row_start = reset_adjust_inputs(offsets, n, len(v))
    out = _kernels()["reset_adjusted"](v, is_first, row_start)
    return np.asarray(out)[:n]
