"""Ragged (offsets, lengths) columnar layout kernels (ROADMAP #3).

Following PAPERS.md "Ragged Paged Attention" (TPU kernels over ragged,
paged KV blocks): variable-length series stay CONCATENATED with an
(offsets) index vector — CSR, the layout `query/windows.RaggedSeries`
and the whole-query compiler's slab prep already consume — instead of
being padded to rectangles or materialized as one Python array pair per
series.  This module is the pure-kernel layer of that layout, shared by
the storage read finalize (`Shard.finish_read_many`), the paged buffer
seal (`ShardBuffer.seal_csr`) and the length-bucketed ragged encode
(`hostpath.encode_blocks_ragged`):

- ``merge_csr`` is the batched twin of ``buffer.merge_dedup``: one
  vectorized sortedness probe over EVERY row at once, one global
  lexsort + keep-last dedup only when some row actually needs it, one
  compress pass for the range filter — replacing the per-series
  ``np.concatenate`` + ``merge_dedup`` calls that profiled at ~15% of
  the sparse read path (PR 14 handoff).
- ``assemble_rows`` builds the CSR from per-row part lists with slice
  assigns into ONE preallocated pair of columns (no per-series
  concatenate objects).
- ``length_buckets`` groups rows of similar length so a batched
  rectangular consumer (the device block encoder) pads each row only to
  its bucket's max, never the global max — the ingest-side padding tax.
- ``bf16_pack``/``bf16_unpack`` are the reduced-precision page mirror
  (EQuARX's quantized-collective argument applied to the device-resident
  hot tier, and the seam ROADMAP #4's quantized wire format reuses):
  round-to-nearest-even truncation of float32 to its high 16 bits.

Parity discipline matches the stage kernels in ops/temporal.py: every
function here is pure, and the seeded property sweep in
tests/test_paged_memory.py pins exact NaN masks / exact uint64 bit
patterns against the per-series reference implementations, including
empty, singleton and page-boundary-straddling rows.
"""

from __future__ import annotations

import numpy as np


def row_ids(offsets: np.ndarray) -> np.ndarray:
    """Per-sample row id vector for a CSR offsets array."""
    return np.repeat(np.arange(len(offsets) - 1, dtype=np.int64),
                     np.diff(offsets))


def rows_strictly_increasing(times: np.ndarray, offsets: np.ndarray) -> bool:
    """True when every row's times are strictly increasing (the
    merge_dedup fast-path predicate, probed for ALL rows in one pass)."""
    n = len(times)
    if n <= 1:
        return True
    ok = times[1:] > times[:-1]
    # adjacent pairs that straddle a row boundary don't constrain order
    starts = offsets[1:-1]
    b = starts[(starts > 0) & (starts < n)]
    if len(b):
        ok = ok.copy()
        ok[np.asarray(b, np.int64) - 1] = True
    return bool(ok.all())


def merge_csr(times: np.ndarray, vbits: np.ndarray, offsets: np.ndarray,
              start_ns: int | None = None, end_ns: int | None = None):
    """``merge_dedup`` applied to every CSR row at once.

    Row semantics are identical to calling ``merge_dedup(row_t, row_v,
    start_ns, end_ns)`` per row: stable sort by time with later appends
    winning timestamp ties, then the half-open range filter.  The fast
    path (every row already strictly increasing — decoded blocks in time
    order with no buffer overlap, the steady-state read) costs one
    vectorized probe + at most one compress; only when some row is out
    of order or duplicated does the global lexsort run.
    """
    n = len(times)
    if n == 0:
        return times, vbits, offsets.astype(np.int64, copy=False)
    if not rows_strictly_increasing(times, offsets):
        rid = row_ids(offsets)
        order = np.lexsort((np.arange(n), times, rid))
        times, vbits, rid = times[order], vbits[order], rid[order]
        keep = np.ones(n, bool)
        same = (rid[1:] == rid[:-1]) & (times[1:] == times[:-1])
        keep[:-1] = ~same
        if start_ns is not None:
            keep &= times >= start_ns
        if end_ns is not None:
            keep &= times < end_ns
        counts = np.bincount(rid[keep], minlength=len(offsets) - 1)
        new_offsets = np.empty(len(offsets), np.int64)
        new_offsets[0] = 0
        np.cumsum(counts, out=new_offsets[1:])
        return times[keep], vbits[keep], new_offsets
    sel = None
    if start_ns is not None:
        sel = times >= start_ns
    if end_ns is not None:
        m = times < end_ns
        sel = m if sel is None else (sel & m)
    if sel is None or bool(sel.all()):
        return times, vbits, offsets.astype(np.int64, copy=False)
    ksum = np.empty(n + 1, np.int64)
    ksum[0] = 0
    np.cumsum(sel, out=ksum[1:])
    return times[sel], vbits[sel], ksum[np.asarray(offsets, np.int64)]


def assemble_rows(parts_rows: list[list[tuple[np.ndarray, np.ndarray]]],
                  start_ns: int | None = None, end_ns: int | None = None):
    """(times, vbits, offsets) CSR from per-row part lists.

    The outer loop only FLATTENS (list appends); the data moves in ONE
    np.concatenate per column — no per-series concatenate objects, no
    per-part slice assigns.  Rows arrive in order, so part data is
    already row-contiguous and the offsets come from a length scatter;
    each row's part order is preserved, which is what keeps
    ``merge_csr``'s keep-last conflict resolution identical to the
    serial path's filesets-then-buffer append order.
    """
    R = len(parts_rows)
    flat_t: list = []
    flat_v: list = []
    rows_of: list = []
    lens_of: list = []
    # hot flatten loop (one iteration per (series, part)): bound methods
    # hoisted — at a million parts the attribute lookups are the loop
    ft, fv, ro, lo = (flat_t.append, flat_v.append, rows_of.append,
                      lens_of.append)
    for i, parts in enumerate(parts_rows):
        for t, v in parts:
            n = t.shape[0]
            if n:
                ft(t)
                fv(v)
                ro(i)
                lo(n)
    offsets = np.zeros(R + 1, np.int64)
    if not flat_t:
        return np.empty(0, np.int64), np.empty(0, np.uint64), offsets
    # rows_of is non-decreasing (outer loop order): a weighted bincount
    # scatters the per-part lengths into per-row counts in one pass
    counts = np.bincount(np.asarray(rows_of, np.int64),
                         weights=np.asarray(lens_of, np.float64),
                         minlength=R).astype(np.int64)
    np.cumsum(counts, out=offsets[1:])
    times = np.concatenate(flat_t)
    vbits = np.concatenate(flat_v)
    return merge_csr(times, vbits, offsets, start_ns, end_ns)


def pairs_to_csr(pairs: list[tuple[np.ndarray, np.ndarray]]):
    """(times, vbits, offsets) from per-row (times, vbits) pairs — the
    compatibility ramp for callers that still produce per-series arrays
    (datapoint-limit chunked reads, cluster facades, the M3_TPU_PAGED=0
    seed path)."""
    R = len(pairs)
    offsets = np.empty(R + 1, np.int64)
    offsets[0] = 0
    np.cumsum(np.fromiter((len(t) for t, _ in pairs), np.int64, R),
              out=offsets[1:])
    if R == 0 or offsets[-1] == 0:
        return np.empty(0, np.int64), np.empty(0, np.uint64), offsets
    times = np.concatenate([t for t, _ in pairs])
    vbits = np.concatenate([v for _, v in pairs])
    return times, vbits.astype(np.uint64, copy=False), offsets


def split_csr(times: np.ndarray, vbits: np.ndarray, offsets: np.ndarray
              ) -> list[tuple[np.ndarray, np.ndarray]]:
    """Per-row (times, vbits) views of a CSR — the inverse ramp of
    ``pairs_to_csr`` for callers that still consume per-series pairs
    (Session.fetch_many, the read_many facades).  Rows are zero-copy
    slices of the CSR columns, so a wire frame landed by
    ``utils/wire.unpack_samples`` fans out to per-series consumers
    without duplicating the sample volume."""
    return [(times[offsets[i]:offsets[i + 1]],
             vbits[offsets[i]:offsets[i + 1]])
            for i in range(len(offsets) - 1)]


def combine_fragments(frags: list, n_rows: int):
    """Combine already-merged CSR fragments into one CSR ordered by
    target row id — the namespace-level combine: each shard's finalize
    produced a merged CSR over ITS series, and every target row lives in
    exactly ONE fragment, so this is a pure O(N) scatter (no sort).
    ``frags``: [(row_ids [R_f] int64, times, vbits, offsets)]."""
    counts = np.zeros(n_rows, np.int64)
    for idxs, _t, _v, offs in frags:
        counts[idxs] = np.diff(offs)
    offsets = np.zeros(n_rows + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    n = int(offsets[-1])
    times = np.empty(n, np.int64)
    vbits = np.empty(n, np.uint64)
    for idxs, t, v, offs in frags:
        if not len(t):
            continue
        lens = np.diff(offs)
        within = np.arange(len(t), dtype=np.int64) \
            - np.repeat(offs[:-1], lens)
        dst = np.repeat(offsets[idxs], lens) + within
        times[dst] = t
        vbits[dst] = v
    return times, vbits, offsets


def length_buckets(lens: np.ndarray, factor: float = 2.0):
    """Row-index groups of geometrically-similar length: within a
    bucket every row is within ``factor`` of the bucket max, so padding
    each bucket to ITS max wastes < factor x the real sample volume —
    vs the one-rectangle pad to the GLOBAL max, which a single long
    row blows up to O(rows x max_len).  Zero-length rows come back as
    their own group (callers usually skip them).  Returns a list of
    int64 row-index arrays, together covering arange(len(lens))."""
    lens = np.asarray(lens, np.int64)
    if len(lens) == 0:
        return []
    buckets = np.zeros(len(lens), np.int64)
    pos = lens > 0
    if pos.any():
        buckets[pos] = 1 + np.floor(
            np.log(lens[pos].astype(np.float64)) / np.log(factor)
        ).astype(np.int64)
    out = []
    for b in np.unique(buckets):
        out.append(np.nonzero(buckets == b)[0].astype(np.int64))
    return out


def csr_to_padded(times: np.ndarray, vbits: np.ndarray,
                  offsets: np.ndarray, rows: np.ndarray):
    """Padded [len(rows), max_len] (times, vbits, n_points) for a set of
    CSR rows — the rectangular view one length bucket hands the batched
    block encoder.  Timestamp padding repeats each row's LAST value (the
    rows are time-sorted, so that is the row max — the same monotone-pad
    rule `ShardBuffer.seal` uses so masked encoder lanes see sane
    deltas); value padding is zero."""
    rows = np.asarray(rows, np.int64)
    lens = (offsets[rows + 1] - offsets[rows]).astype(np.int64)
    B = len(rows)
    T = int(lens.max()) if B else 0
    T = max(T, 1)
    out_t = np.zeros((B, T), np.int64)
    out_v = np.zeros((B, T), np.uint64)
    if B == 0:
        return out_t, out_v, lens.astype(np.int32)
    row_pos = np.repeat(np.arange(B), lens)
    cum = np.empty(B, np.int64)
    cum[0] = 0
    np.cumsum(lens[:-1], out=cum[1:])
    col = np.arange(int(lens.sum())) - np.repeat(cum, lens)
    src = np.repeat(offsets[rows], lens) + col
    out_t[row_pos, col] = times[src]
    out_v[row_pos, col] = vbits[src]
    nonempty = lens > 0
    if nonempty.any():
        last = times[(offsets[rows + 1] - 1)[nonempty]]
        pad_mask = np.arange(T)[None, :] >= lens[nonempty, None]
        sub = out_t[nonempty]
        out_t[nonempty] = np.where(pad_mask, last[:, None], sub)
    return out_t, out_v, lens.astype(np.int32)


# ---------------------------------------------------------------------------
# reduced-precision page mirror (the EQuARX argument: where the
# consumer's output tolerance permits, ship/hold half the bytes)
# ---------------------------------------------------------------------------


def bf16_pack(values: np.ndarray) -> np.ndarray:
    """float64 -> uint16 bfloat16 bit patterns (round-to-nearest-even on
    the float32 intermediate — the hardware bf16 conversion rule). NaN
    payloads collapse to the canonical quiet NaN so masks survive.

    This numpy pair is the REFERENCE semantics of the hot tier's device
    mirror (which converts with ``astype(jnp.bfloat16)`` on device) and
    the host-side codec seam ROADMAP #4's quantized wire format adopts;
    tests/test_paged_memory.py pins the two conversions value-equal so
    they cannot drift."""
    with np.errstate(over="ignore"):  # finite > f32 max rounds to inf
        f32 = np.asarray(values, np.float64).astype(np.float32)
    u32 = f32.view(np.uint32)
    rounded = u32 + 0x7FFF + ((u32 >> 16) & 1)
    out = (rounded >> 16).astype(np.uint16)
    nan = np.isnan(f32)
    if nan.any():
        out = np.where(nan, np.uint16(0x7FC0), out)
    return out


def bf16_unpack(packed: np.ndarray) -> np.ndarray:
    """uint16 bfloat16 bit patterns -> float64."""
    u32 = packed.astype(np.uint32) << 16
    return u32.view(np.float32).astype(np.float64)
