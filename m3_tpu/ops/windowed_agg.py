"""Batched windowed aggregation over (element x window) groups.

The device-grid replacement for the reference's per-elem streaming
accumulators (/root/reference/src/aggregator/aggregation/{counter,gauge,
timer}.go and the CKMS quantile streams in aggregation/quantile/cm): raw
(elem, window, value) triples are segment-reduced in one vectorized pass;
quantiles come from a grouped sort — EXACT, unlike CKMS's eps-approximation
(deviation documented per SURVEY.md §7.5; memory is bounded by samples per
open window rather than sketch size).

numpy implementation (columnar, no per-sample Python); the group layout is
chosen so a jnp.segment_* lowering is mechanical.
"""

from __future__ import annotations

import numpy as np

from m3_tpu.metrics.aggregation import AggregationType


def aggregate_groups(
    elem_ids: np.ndarray,  # [N] int64
    window_ids: np.ndarray,  # [N] int64
    values: np.ndarray,  # [N] float64
    order_seq: np.ndarray | None = None,  # [N] append order (LAST tiebreak)
    times: np.ndarray | None = None,  # [N] timestamps; LAST = max time
):
    """Group by (elem, window) and compute every base statistic.

    Returns (group_elem, group_window, stats dict of [G] arrays, and a
    grouped-sorted values array + group offsets for quantile extraction).
    """
    n = len(values)
    if order_seq is None:
        order_seq = np.arange(n)
    if times is None:
        times = np.zeros(n, np.int64)
    # group identity via lexsort on (elem, window); within a group rows
    # order by (time, append-seq) so LAST = latest timestamp, ties -> the
    # later append (reference gauge lastAt semantics)
    order = np.lexsort((order_seq, times, window_ids, elem_ids))
    e, w, v = elem_ids[order], window_ids[order], values[order]
    if n == 0:
        empty = np.empty(0)
        return (
            np.empty(0, np.int64), np.empty(0, np.int64),
            {k: empty for k in ("count", "sum", "sumsq", "min", "max", "mean",
                                 "last", "stdev")},
            empty, np.zeros(1, np.int64),
        )
    new_group = np.ones(n, bool)
    new_group[1:] = (e[1:] != e[:-1]) | (w[1:] != w[:-1])
    group_start = np.nonzero(new_group)[0]
    offsets = np.concatenate([group_start, [n]])
    counts = np.diff(offsets).astype(np.float64)

    csum = np.concatenate([[0.0], np.cumsum(v)])
    s1 = csum[offsets[1:]] - csum[offsets[:-1]]
    csq = np.concatenate([[0.0], np.cumsum(v * v)])
    s2 = csq[offsets[1:]] - csq[offsets[:-1]]
    gmin = np.minimum.reduceat(v, offsets[:-1])
    gmax = np.maximum.reduceat(v, offsets[:-1])
    mean = s1 / counts
    var = np.maximum(s2 / counts - mean**2, 0.0)
    last = v[offsets[1:] - 1]  # order_seq tiebreak: last append wins

    # grouped sort for quantiles: sort values WITHIN groups
    vq = values[np.lexsort((values, window_ids, elem_ids))]

    stats = {
        "count": counts,
        "sum": s1,
        "sumsq": s2,
        "min": gmin,
        "max": gmax,
        "mean": mean,
        "last": last,
        "stdev": np.sqrt(var),
    }
    return e[group_start], w[group_start], stats, vq, offsets


def group_quantiles(vq: np.ndarray, offsets: np.ndarray, q: float) -> np.ndarray:
    """Interpolated quantile per group from grouped-sorted values.

    Same interpolation as the reference timer aggregation contract
    (linear between closest ranks).
    """
    counts = np.diff(offsets)
    rank = q * (counts - 1)
    lo = np.floor(rank).astype(np.int64)
    frac = rank - lo
    i0 = offsets[:-1] + lo
    i1 = np.minimum(i0 + 1, offsets[1:] - 1)
    return vq[i0] * (1 - frac) + vq[i1] * frac


def extract(
    agg_type: AggregationType,
    stats: dict,
    vq: np.ndarray,
    offsets: np.ndarray,
) -> np.ndarray:
    q = agg_type.quantile
    if q is not None:
        return group_quantiles(vq, offsets, q)
    key = {
        AggregationType.LAST: "last",
        AggregationType.MIN: "min",
        AggregationType.MAX: "max",
        AggregationType.MEAN: "mean",
        AggregationType.COUNT: "count",
        AggregationType.SUM: "sum",
        AggregationType.SUMSQ: "sumsq",
        AggregationType.STDEV: "stdev",
    }[agg_type]
    return stats[key]
