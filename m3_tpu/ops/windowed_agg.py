"""Batched windowed aggregation over (element x window) groups.

The device-grid replacement for the reference's per-elem streaming
accumulators (/root/reference/src/aggregator/aggregation/{counter,gauge,
timer}.go and the CKMS quantile streams in aggregation/quantile/cm): raw
(elem, window, value) triples are segment-reduced in one vectorized pass;
quantiles come from a grouped sort — EXACT, unlike CKMS's eps-approximation
(deviation documented per SURVEY.md §7.5; memory is bounded by samples per
open window rather than sketch size).

Two implementations share one contract: the numpy host path below, and a
jax lowering (sort + ``jax.ops.segment_*`` reductions) that
``utils.dispatch`` selects for large flushes on an accelerator — the device
path the aggregator's production flush actually runs, not a test-only
kernel. Inputs are padded to a power of two so XLA compiles O(log) shapes.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from m3_tpu.metrics.aggregation import AggregationType
from m3_tpu.utils import dispatch

# device sort+segment-reduce pays off later than pure elementwise ops
DEVICE_THRESHOLD = 32_768
# below this the numpy path wins over the FFI round trip
NATIVE_THRESHOLD = 4_096


def _order_is_append(order_seq: np.ndarray) -> bool:
    return (len(order_seq) == 0
            or (order_seq[0] == 0 and order_seq[-1] == len(order_seq) - 1
                and bool((np.diff(order_seq) == 1).all())))


def aggregate_groups(
    elem_ids: np.ndarray,  # [N] int64
    window_ids: np.ndarray,  # [N] int64
    values: np.ndarray,  # [N] float64
    order_seq: np.ndarray | None = None,  # [N] append order (LAST tiebreak)
    times: np.ndarray | None = None,  # [N] timestamps; LAST = max time
    need_sorted: bool = True,  # grouped-sorted vq (quantile input) wanted?
):
    """Group by (elem, window) and compute every base statistic.

    Returns (group_elem, group_window, stats dict of [G] arrays, and a
    grouped-sorted values array + group offsets for quantile extraction).
    With ``need_sorted=False`` the returned vq is empty (callers with no
    quantile aggregations skip the grouped sort entirely).
    """
    n = len(values)
    if order_seq is None:
        order_seq = np.arange(n)
    if times is None:
        times = np.zeros(n, np.int64)
    device = n > 0 and dispatch.use_device(n, DEVICE_THRESHOLD)
    dispatch.record("windowed_agg.aggregate_groups", device)
    if device:
        return _aggregate_groups_device(elem_ids, window_ids, values,
                                        order_seq, times)
    # CPU serving path: the native columnar kernel when available and the
    # flush is big enough to amortize the FFI call. The native "last" uses
    # (time, append-index) — identical to the numpy (time, order_seq)
    # tiebreak only when order_seq IS append order, which every engine
    # caller passes; custom order_seq falls through to numpy. NaN values
    # fall through too (native min/max comparisons would skip NaNs).
    if (n >= NATIVE_THRESHOLD and os.environ.get("M3_TPU_NATIVE_OPS") != "0"
            and _order_is_append(order_seq)):
        from m3_tpu.ops import native_hostops

        if native_hostops.available() and not np.isnan(values).any():
            dispatch.counters["windowed_agg.aggregate_groups[native]"] += 1
            return native_hostops.agg_groups(elem_ids, window_ids, values,
                                             times, want_sorted=need_sorted)
    # group identity via lexsort on (elem, window); within a group rows
    # order by (time, append-seq) so LAST = latest timestamp, ties -> the
    # later append (reference gauge lastAt semantics)
    order = np.lexsort((order_seq, times, window_ids, elem_ids))
    e, w, v = elem_ids[order], window_ids[order], values[order]
    if n == 0:
        empty = np.empty(0)
        return (
            np.empty(0, np.int64), np.empty(0, np.int64),
            {k: empty for k in ("count", "sum", "sumsq", "min", "max", "mean",
                                 "last", "stdev")},
            empty, np.zeros(1, np.int64),
        )
    new_group = np.ones(n, bool)
    new_group[1:] = (e[1:] != e[:-1]) | (w[1:] != w[:-1])
    group_start = np.nonzero(new_group)[0]
    offsets = np.concatenate([group_start, [n]])
    counts = np.diff(offsets).astype(np.float64)

    csum = np.concatenate([[0.0], np.cumsum(v)])
    s1 = csum[offsets[1:]] - csum[offsets[:-1]]
    csq = np.concatenate([[0.0], np.cumsum(v * v)])
    s2 = csq[offsets[1:]] - csq[offsets[:-1]]
    gmin = np.minimum.reduceat(v, offsets[:-1])
    gmax = np.maximum.reduceat(v, offsets[:-1])
    mean = s1 / counts
    var = np.maximum(s2 / counts - mean**2, 0.0)
    last = v[offsets[1:] - 1]  # order_seq tiebreak: last append wins

    # grouped sort for quantiles: sort values WITHIN groups
    vq = (values[np.lexsort((values, window_ids, elem_ids))]
          if need_sorted else np.empty(0))

    stats = {
        "count": counts,
        "sum": s1,
        "sumsq": s2,
        "min": gmin,
        "max": gmax,
        "mean": mean,
        "last": last,
        "stdev": np.sqrt(var),
    }
    return e[group_start], w[group_start], stats, vq, offsets


@functools.lru_cache(maxsize=None)
def _grouped_stats_jit():
    """Build the jitted device kernel lazily (jax import deferred)."""
    import jax
    import jax.numpy as jnp

    import m3_tpu.ops  # noqa: F401  (x64)

    @jax.jit
    def kernel(e, w, v, seq, t):
        # sort rows by (elem, window, time, append-seq): group identity plus
        # the LAST-wins ordering inside each group
        order = jnp.lexsort((seq, t, w, e))
        es, ws, vs = e[order], w[order], v[order]
        n = e.shape[0]
        new_group = jnp.concatenate(
            [jnp.ones(1, bool), (es[1:] != es[:-1]) | (ws[1:] != ws[:-1])]
        )
        seg = jnp.cumsum(new_group) - 1  # [N] group index, 0-based
        ones = jnp.ones(n, jnp.float64)
        count = jax.ops.segment_sum(ones, seg, num_segments=n)
        s1 = jax.ops.segment_sum(vs, seg, num_segments=n)
        s2 = jax.ops.segment_sum(vs * vs, seg, num_segments=n)
        gmin = jax.ops.segment_min(vs, seg, num_segments=n)
        gmax = jax.ops.segment_max(vs, seg, num_segments=n)
        idx_last = jax.ops.segment_max(jnp.arange(n), seg, num_segments=n)
        last = vs[jnp.clip(idx_last, 0, n - 1)]
        # grouped sort for quantiles: values ascending WITHIN (elem, window)
        vq = v[jnp.lexsort((v, w, e))]
        return es, ws, new_group, count, s1, s2, gmin, gmax, last, vq

    return kernel


def _aggregate_groups_device(elem_ids, window_ids, values, order_seq, times):
    """jax lowering of aggregate_groups; pads N to a power of two with a
    sentinel group that is trimmed on the way out.

    When the series-sharded compute mesh is armed (M3_TPU_QUERY_SHARD /
    a live multi-device accelerator — parallel.mesh.active_compute_mesh),
    the padded sample triples are placed across it so the flush rollup
    runs as one SPMD program: the kernel's grouped sort makes XLA gather
    rows across devices, but the segment reductions and their combines
    stay partitioned — the m3_agg_groups path rides the same mesh as the
    fused-query plane (the psum-lowered grouped reductions live there)."""
    n = len(values)
    N = dispatch.next_pow2(n)
    pad = N - n
    BIG = np.iinfo(np.int64).max
    e_p = np.concatenate([elem_ids, np.full(pad, BIG, np.int64)])
    w_p = np.concatenate([window_ids, np.full(pad, BIG, np.int64)])
    v_p = np.concatenate([values, np.zeros(pad)])
    s_p = np.concatenate([order_seq.astype(np.int64),
                          np.arange(pad, dtype=np.int64) + (1 << 60)])
    t_p = np.concatenate([times, np.full(pad, BIG, np.int64)])

    from m3_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.active_compute_mesh()
    if mesh is not None and N % int(mesh.devices.size) == 0:
        import jax

        sh = mesh_mod.vec_sharding(mesh)
        e_p, w_p, v_p, s_p, t_p = (jax.device_put(a, sh)
                                   for a in (e_p, w_p, v_p, s_p, t_p))
        dispatch.counters["windowed_agg.aggregate_groups[mesh]"] += 1

    from m3_tpu.utils import compute_stats

    # padding-waste ledger: real sample rows vs the pow2-padded batch
    compute_stats.record_waste("windowed_agg", "samples", n, N)
    kernel = _grouped_stats_jit()
    with dispatch.jit_tracker(
            "grouped_stats", kernel, sig=f"N{N}",
            lower=lambda: kernel.lower(e_p, w_p, v_p, s_p, t_p)):
        out = kernel(e_p, w_p, v_p, s_p, t_p)
    es, ws, new_group, count, s1, s2, gmin, gmax, last, vq = (
        np.asarray(x) for x in out
    )
    group_start = np.nonzero(new_group)[0]
    n_groups_total = len(group_start)
    # pads share the (BIG, BIG) key: exactly one trailing sentinel group
    G = n_groups_total - (1 if pad else 0)
    sel = slice(0, G)
    counts = count[sel]
    mean = s1[sel] / counts
    var = np.maximum(s2[sel] / counts - mean**2, 0.0)
    stats = {
        "count": counts,
        "sum": s1[sel],
        "sumsq": s2[sel],
        "min": gmin[sel],
        "max": gmax[sel],
        "mean": mean,
        "last": last[sel],
        "stdev": np.sqrt(var),
    }
    offsets = np.concatenate([group_start[:G], [n]]).astype(np.int64)
    return es[group_start[:G]], ws[group_start[:G]], stats, vq[:n], offsets


# ---------------------------------------------------------------------------
# pure traced stage kernels over [S, T] value matrices
# ---------------------------------------------------------------------------
#
# The whole-query compiler (query/compiler.py, ROADMAP #2) composes these
# into its fused per-plan XLA program: PromQL `by`/`without` aggregations
# over a [series, steps] matrix with the exact NaN semantics of
# Engine._eval_aggregate (count counts non-NaN; empty groups are NaN).
# ``seg`` maps each series row to its group id; ``num_groups`` is a
# trace-time constant (the compiler's group-count bucket).


def stage_grouped_reduce(op: str, vals, seg, num_groups: int):
    """sum/avg/min/max/count over groups of rows; [num_groups, T] out."""
    import jax
    import jax.numpy as jnp

    nan = jnp.isnan(vals)
    count = jax.ops.segment_sum((~nan).astype(jnp.float64), seg,
                                num_segments=num_groups)
    any_present = count > 0
    if op == "count":
        out = count
    elif op in ("sum", "avg"):
        s1 = jax.ops.segment_sum(jnp.where(nan, 0.0, vals), seg,
                                 num_segments=num_groups)
        out = s1 if op == "sum" else s1 / jnp.where(any_present, count, 1)
    elif op == "min":
        out = jax.ops.segment_min(jnp.where(nan, jnp.inf, vals), seg,
                                  num_segments=num_groups)
    elif op == "max":
        out = jax.ops.segment_max(jnp.where(nan, -jnp.inf, vals), seg,
                                  num_segments=num_groups)
    else:
        raise ValueError(f"unknown grouped reduce op {op}")
    return jnp.where(any_present, out, jnp.nan)


def stage_grouped_quantile(vals, seg, num_groups: int, phi):
    """Prometheus-interpolated quantile per (group, step), NaN-aware.

    One grouped sort per step column (rows ordered (group, value), NaN
    last within each group — the jnp sort order matches numpy's) and a
    rank-interpolating gather, mirroring Engine._quantile_cols: empty
    (group, step) -> NaN, phi < 0 -> -inf, phi > 1 -> +inf."""
    import jax
    import jax.numpy as jnp

    S = vals.shape[0]
    T = vals.shape[1]
    sizes = jax.ops.segment_sum(jnp.ones(S), seg, num_segments=num_groups)
    starts = jnp.concatenate(
        [jnp.zeros(1), jnp.cumsum(sizes)])[:-1].astype(jnp.int64)  # [G]
    # one 2-D lexsort down the columns: primary key seg, ties by value,
    # NaN last within each group (jnp float sort order matches numpy's)
    order = jnp.lexsort(
        (vals, jnp.broadcast_to(seg[:, None], vals.shape)), axis=0)
    sorted_cols = jnp.take_along_axis(vals, order, axis=0)
    cnt = jax.ops.segment_sum((~jnp.isnan(vals)).astype(jnp.float64), seg,
                              num_segments=num_groups)  # [G, T]
    present = cnt > 0
    rank = jnp.where(present, phi * (cnt - 1), 0.0)
    rank_lo = jnp.floor(rank)
    i_lo = jnp.clip(rank_lo.astype(jnp.int64), 0, S - 1)
    i_hi = jnp.clip(jnp.minimum(i_lo + 1, cnt.astype(jnp.int64) - 1),
                    0, S - 1)
    cols = jnp.arange(T)[None, :]
    base = starts[:, None]
    v0 = sorted_cols[jnp.clip(base + i_lo, 0, S - 1), cols]
    v1 = sorted_cols[jnp.clip(base + i_hi, 0, S - 1), cols]
    out = v0 + (rank - rank_lo) * (v1 - v0)
    out = jnp.where(phi < 0, -jnp.inf, out)
    out = jnp.where(phi > 1, jnp.inf, out)
    return jnp.where(present, out, jnp.nan)


def group_quantiles(vq: np.ndarray, offsets: np.ndarray, q: float) -> np.ndarray:
    """Interpolated quantile per group from grouped-sorted values.

    Same interpolation as the reference timer aggregation contract
    (linear between closest ranks).
    """
    counts = np.diff(offsets)
    rank = q * (counts - 1)
    lo = np.floor(rank).astype(np.int64)
    frac = rank - lo
    i0 = offsets[:-1] + lo
    i1 = np.minimum(i0 + 1, offsets[1:] - 1)
    return vq[i0] * (1 - frac) + vq[i1] * frac


def extract(
    agg_type: AggregationType,
    stats: dict,
    vq: np.ndarray,
    offsets: np.ndarray,
) -> np.ndarray:
    q = agg_type.quantile
    if q is not None:
        return group_quantiles(vq, offsets, q)
    key = {
        AggregationType.LAST: "last",
        AggregationType.MIN: "min",
        AggregationType.MAX: "max",
        AggregationType.MEAN: "mean",
        AggregationType.COUNT: "count",
        AggregationType.SUM: "sum",
        AggregationType.SUMSQ: "sumsq",
        AggregationType.STDEV: "stdev",
    }[agg_type]
    return stats[key]
