"""ctypes bindings for the native CPU host ops (native/hostops.cpp).

Serving-path kernels (grouped aggregation, extrapolated rate) used by
``ops.windowed_agg`` / ``query.windows`` when no accelerator is live, plus
the reference-cost-model scalar baselines ``bench_all`` measures against.
Built on demand with g++ like the native m3tsz codec; every caller falls
back to the numpy host path when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
_SRC = os.path.join(_REPO_ROOT, "native", "hostops.cpp")
# M3HOSTOPS_SO points the loader at an instrumented build
# (tools/race_check.py swaps in the ThreadSanitizer variant); overrides
# load AS-IS — no stale-mtime rebuild over the instrumented artifact
_SO_OVERRIDE = "M3HOSTOPS_SO" in os.environ
_SO = os.environ.get("M3HOSTOPS_SO",
                     os.path.join(_REPO_ROOT, "native", "libm3hostops.so"))

_lock = threading.Lock()
_lib = None
_tried = False

_P = ctypes.c_void_p
_I64 = ctypes.c_int64
_I32 = ctypes.c_int32


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
             "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src_mtime = os.path.getmtime(_SRC) if os.path.exists(_SRC) else 0
        if not _SO_OVERRIDE and (
                not os.path.exists(_SO) or os.path.getmtime(_SO) < src_mtime):
            # intentional build-under-lock: single-flight one-time g++
            # build, as in encoding/m3tsz/native.py
            # m3lint: disable=lock-blocking-call
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.m3_agg_groups.restype = _I64
        lib.m3_agg_groups.argtypes = [_P, _P, _P, _P, _I64, _I32] + [_P] * 12
        lib.m3_agg_baseline_scalar.restype = ctypes.c_double
        lib.m3_agg_baseline_scalar.argtypes = [_P, _P, _P, _P, _I64]
        lib.m3_rate_csr.restype = None
        lib.m3_rate_csr.argtypes = [_P, _P, _P, _I64, _P, _I64, _I64,
                                    _I32, _I32, _I32, _P]
        lib.m3_rate_baseline_scalar.restype = None
        lib.m3_rate_baseline_scalar.argtypes = [_P, _P, _P, _I64, _P, _I64,
                                                _I64, _I32, _I32, _P]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def default_threads() -> int:
    v = os.environ.get("M3_NATIVE_THREADS")
    if v:
        return max(1, int(v))
    try:
        return max(1, len(os.sched_getaffinity(0)))
    except (AttributeError, OSError):
        return max(1, os.cpu_count() or 1)


def agg_groups(elem_ids, window_ids, values, times, want_sorted: bool = True):
    """Native grouped aggregation; same contract as the numpy host path in
    windowed_agg.aggregate_groups. Returns (ge, gw, stats, vq, offsets)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native hostops unavailable")
    n = len(values)
    e = np.ascontiguousarray(elem_ids, np.int64)
    w = np.ascontiguousarray(window_ids, np.int64)
    v = np.ascontiguousarray(values, np.float64)
    t = np.ascontiguousarray(times, np.int64)
    ge = np.empty(n, np.int64)
    gw = np.empty(n, np.int64)
    outs = [np.empty(n, np.float64) for _ in range(8)]
    vq = np.empty(n if want_sorted else 0, np.float64)
    offsets = np.empty(n + 1, np.int64)
    G = lib.m3_agg_groups(
        e.ctypes.data, w.ctypes.data, v.ctypes.data, t.ctypes.data,
        n, 1 if want_sorted else 0,
        ge.ctypes.data, gw.ctypes.data,
        *(o.ctypes.data for o in outs),
        vq.ctypes.data if want_sorted else None, offsets.ctypes.data,
    )
    if G < 0:
        raise ValueError("native agg_groups failed")
    names = ("count", "sum", "sumsq", "min", "max", "mean", "last", "stdev")
    stats = {k: outs[i][:G] for i, k in enumerate(names)}
    return ge[:G], gw[:G], stats, vq, offsets[:G + 1].copy()


def rate_csr(times, values, offsets, eval_ts, range_ns: int,
             is_counter: bool, is_rate: bool, threads: int | None = None):
    """Native columnar extrapolated rate; [S, K] matrix, numpy-path math."""
    lib = load()
    if lib is None:
        raise RuntimeError("native hostops unavailable")
    t = np.ascontiguousarray(times, np.int64)
    v = np.ascontiguousarray(values, np.float64)
    off = np.ascontiguousarray(offsets, np.int64)
    ev = np.ascontiguousarray(eval_ts, np.int64)
    S = len(off) - 1
    K = len(ev)
    out = np.empty((S, K), np.float64)
    lib.m3_rate_csr(
        t.ctypes.data, v.ctypes.data, off.ctypes.data, S,
        ev.ctypes.data, K, range_ns,
        1 if is_counter else 0, 1 if is_rate else 0,
        threads or default_threads(), out.ctypes.data,
    )
    return out


def agg_baseline_scalar(ids: list[bytes], window_ids, values) -> tuple[float, int]:
    """Run the per-sample reference-shape baseline loop once (one FFI call);
    returns (checksum of window sums, n samples). Caller times it."""
    lib = load()
    if lib is None:
        raise RuntimeError("native hostops unavailable")
    blob = b"".join(ids)
    off = np.zeros(len(ids) + 1, np.int64)
    np.cumsum([len(i) for i in ids], out=off[1:])
    buf = np.frombuffer(blob, np.uint8)
    w = np.ascontiguousarray(window_ids, np.int64)
    v = np.ascontiguousarray(values, np.float64)
    total = lib.m3_agg_baseline_scalar(
        buf.ctypes.data, off.ctypes.data, w.ctypes.data, v.ctypes.data,
        len(ids),
    )
    return float(total), len(ids)


def rate_baseline_scalar(times, values, offsets, eval_ts, range_ns: int,
                         is_counter: bool, is_rate: bool):
    """Run the per-(series, step) window-rescan baseline once; returns the
    [S, K] matrix. Caller times it."""
    lib = load()
    if lib is None:
        raise RuntimeError("native hostops unavailable")
    t = np.ascontiguousarray(times, np.int64)
    v = np.ascontiguousarray(values, np.float64)
    off = np.ascontiguousarray(offsets, np.int64)
    ev = np.ascontiguousarray(eval_ts, np.int64)
    S = len(off) - 1
    K = len(ev)
    out = np.empty((S, K), np.float64)
    lib.m3_rate_baseline_scalar(
        t.ctypes.data, v.ctypes.data, off.ctypes.data, S,
        ev.ctypes.data, K, range_ns,
        1 if is_counter else 0, 1 if is_rate else 0, out.ctypes.data,
    )
    return out
