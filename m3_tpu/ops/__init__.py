"""TPU kernel library: batched bit-packing, windowed aggregation, postings
bitmap algebra, and PromQL temporal ops.

IMPORT SIDE EFFECT: this package enables jax_enable_x64 process-wide on
import. The codec and timestamp kernels fundamentally require 64-bit
integers (unix-nano timestamps, IEEE-754 bit patterns), so every m3_tpu
compute module depends on it. If you embed m3_tpu inside another JAX
application, import m3_tpu (or set jax_enable_x64) before creating arrays,
and be aware that Python floats will now default to float64 — annotate
dtypes explicitly in the host application.
"""

import jax

jax.config.update("jax_enable_x64", True)
