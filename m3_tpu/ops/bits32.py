"""u32 multi-limb bit-stream machinery for the batched M3TSZ kernels.

TPUs have no native 64-bit integers: every u64 op in a kernel is emulated by
the XLA X64 rewriter (~2-10x cost), and scatter/gather lower to
per-element loops (estimated ~10ns/element from their serialized lowering —
NOT validated on TPU hardware from this environment — i.e. hundreds of ms
for a 1M-datapoint block). These helpers exist so the codec hot loops can
run as
pure 32-bit elementwise ops on whole `[..., W]` limb tensors:

- **limb registers**: a bit stream is a row of u32 limbs, MSB-first
  (stream bit 0 = bit 31 of limb 0 — "top-aligned").
- **variable shifts without gathers**: shifting a register by a
  data-dependent bit count decomposes into log2(W) static rolls selected
  per element by the shift's bits, plus an elementwise bit funnel. A
  static roll is a slice+pad, so the whole operation stays elementwise —
  no scatter, no gather, no per-lane dynamic indexing.

The scalar semantics these mirror are the reference bit stream's
(/root/reference/src/dbnode/encoding/encoding.go:29-43); the batched
layout they enable replaces the reference's per-stream sequential
OStream/IStream with whole-block tensor ops (SURVEY.md section 7's
"blockwise two-pass design").
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

import m3_tpu.ops  # noqa: F401  (enables x64)

U32 = jnp.uint32
import numpy as _np

_Z32 = _np.uint32(0)  # numpy scalar: inlines as a literal, never a hoisted const


def u64_to_pair(v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Split u64 -> (hi, lo) u32."""
    v = v.astype(jnp.uint64)
    return (v >> jnp.uint64(32)).astype(U32), v.astype(U32)


def pair_to_u64(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    return (hi.astype(jnp.uint64) << jnp.uint64(32)) | lo.astype(jnp.uint64)


def shl32(v: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Left shift, safe for n in [0, 32] (n>=32 -> 0)."""
    n = jnp.asarray(n, U32)
    return jnp.where(n >= 32, _Z32, v.astype(U32) << jnp.minimum(n, jnp.uint32(31)))


def shr32(v: jnp.ndarray, n: jnp.ndarray) -> jnp.ndarray:
    """Logical right shift, safe for n in [0, 32] (n>=32 -> 0)."""
    n = jnp.asarray(n, U32)
    return jnp.where(n >= 32, _Z32, v.astype(U32) >> jnp.minimum(n, jnp.uint32(31)))


def clz32(v: jnp.ndarray) -> jnp.ndarray:
    """Count leading zeros of u32; clz32(0) = 32. Returns uint32."""
    v = v.astype(U32)
    return jnp.where(v == 0, jnp.uint32(32), lax.clz(v).astype(U32))


def pair_clz(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    """clz of the 64-bit (hi, lo) pair; 64 for zero."""
    return jnp.where(hi == 0, jnp.uint32(32) + clz32(lo), clz32(hi))


def pair_ctz(hi: jnp.ndarray, lo: jnp.ndarray) -> jnp.ndarray:
    """ctz of the 64-bit (hi, lo) pair; 0 for zero (reference convention
    LeadingAndTrailingZeros(0) = (64, 0))."""
    ctz_lo = jnp.uint32(31) - clz32(lo & (_Z32 - lo))
    ctz_hi = jnp.uint32(31) - clz32(hi & (_Z32 - hi))
    both_zero = (hi == 0) & (lo == 0)
    out = jnp.where(lo == 0, jnp.uint32(32) + ctz_hi, ctz_lo)
    return jnp.where(both_zero, _Z32, out)


def pair_shl(hi: jnp.ndarray, lo: jnp.ndarray, n: jnp.ndarray):
    """64-bit left shift of a (hi, lo) pair, n in [0, 64]."""
    n = jnp.asarray(n, U32)
    big = n >= 32
    nb = jnp.where(big, n - 32, n)
    h = jnp.where(big, shl32(lo, nb), shl32(hi, nb) | shr32(lo, 32 - nb))
    l = jnp.where(big, _Z32, shl32(lo, nb))  # noqa: E741
    return h, l


def pair_shr(hi: jnp.ndarray, lo: jnp.ndarray, n: jnp.ndarray):
    """64-bit logical right shift of a (hi, lo) pair, n in [0, 64]."""
    n = jnp.asarray(n, U32)
    big = n >= 32
    nb = jnp.where(big, n - 32, n)
    l = jnp.where(big, shr32(hi, nb), shr32(lo, nb) | shl32(hi, 32 - nb))  # noqa: E741
    h = jnp.where(big, _Z32, shr32(hi, nb))
    return h, l


def _bit(n: jnp.ndarray, k: int) -> jnp.ndarray:
    return (jnp.asarray(n, U32) >> jnp.uint32(k)) & jnp.uint32(1)


def roll_right_words(x: jnp.ndarray, n_words: jnp.ndarray, max_words: int) -> jnp.ndarray:
    """Shift limbs toward higher index by a per-row word count (zero fill).

    x: [..., W]; n_words: broadcastable to x[..., 0] (without the limb
    axis); max_words bounds n_words statically so only ceil(log2) levels of
    static rolls are emitted.
    """
    n = jnp.asarray(n_words, U32)[..., None]
    k = 0
    while (1 << k) <= max_words:
        step = 1 << k
        if step < x.shape[-1]:
            rolled = jnp.concatenate(
                [jnp.zeros_like(x[..., :step]), x[..., :-step]], axis=-1
            )
        else:
            rolled = jnp.zeros_like(x)
        x = jnp.where(_bit(n[..., 0], k)[..., None] == 1, rolled, x)
        k += 1
    return x


def roll_left_words(x: jnp.ndarray, n_words: jnp.ndarray, max_words: int) -> jnp.ndarray:
    """Shift limbs toward lower index by a per-row word count (zero fill)."""
    n = jnp.asarray(n_words, U32)[..., None]
    k = 0
    while (1 << k) <= max_words:
        step = 1 << k
        if step < x.shape[-1]:
            rolled = jnp.concatenate(
                [x[..., step:], jnp.zeros_like(x[..., :step])], axis=-1
            )
        else:
            rolled = jnp.zeros_like(x)
        x = jnp.where(_bit(n[..., 0], k)[..., None] == 1, rolled, x)
        k += 1
    return x


def shift_right_bits(x: jnp.ndarray, n_bits: jnp.ndarray, max_bits: int) -> jnp.ndarray:
    """Shift a top-aligned limb register right by per-row n_bits (stream
    moves toward higher offsets; zeros shift in at the top)."""
    n = jnp.asarray(n_bits, U32)
    x = roll_right_words(x, n >> jnp.uint32(5), max_bits // 32)
    r = (n & jnp.uint32(31))[..., None]
    prev = jnp.concatenate([jnp.zeros_like(x[..., :1]), x[..., :-1]], axis=-1)
    return jnp.where(r == 0, x, shr32(x, r) | shl32(prev, 32 - r))


def shift_left_bits(x: jnp.ndarray, n_bits: jnp.ndarray, max_bits: int) -> jnp.ndarray:
    """Shift a top-aligned limb register left by per-row n_bits (consumes
    the stream head; zeros shift in at the bottom)."""
    n = jnp.asarray(n_bits, U32)
    x = roll_left_words(x, n >> jnp.uint32(5), max_bits // 32)
    r = (n & jnp.uint32(31))[..., None]
    nxt = jnp.concatenate([x[..., 1:], jnp.zeros_like(x[..., :1])], axis=-1)
    return jnp.where(r == 0, x, shl32(x, r) | shr32(nxt, 32 - r))


def pad_limbs(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Zero-pad a top-aligned limb register on the right to `width` limbs
    (or truncate — callers only truncate streams already flagged as
    overflowing their capacity)."""
    w = x.shape[-1]
    if width == w:
        return x
    if width < w:
        return x[..., :width]
    pad = [(0, 0)] * (x.ndim - 1) + [(0, width - w)]
    return jnp.pad(x, pad)


def field128_to_limbs(hi: jnp.ndarray, lo: jnp.ndarray, length: jnp.ndarray) -> jnp.ndarray:
    """Convert a right-aligned <=128-bit (hi, lo) u64 field into a
    top-aligned 4-limb u32 register: bit 0 of the field lands at bit 31 of
    limb 0.  length in [0, 128]."""
    h1, h0 = u64_to_pair(hi)
    l1, l0 = u64_to_pair(lo)
    reg = jnp.stack([h1, h0, l1, l0], axis=-1)  # right-aligned 128-bit
    return shift_left_bits(reg, jnp.uint32(128) - jnp.asarray(length, U32), 128)
