"""Message consumer: TCP server delivering messages to a handler with
batched acks.

Role parity with /root/reference/src/msg/consumer/consumer.go:152-211 (ack
batching) and the server accept loop in x/server. At-least-once: a message
is acked only after the handler returns; redelivered duplicates are the
handler's concern (idempotent writes downstream).
"""

from __future__ import annotations

import socket
import threading
from contextlib import nullcontext as _nullcontext
from typing import Callable

from m3_tpu.msg.protocol import recv_frame, send_frame
from m3_tpu.utils import faults, trace
from m3_tpu.utils.instrument import default_registry

_scope = default_registry().root_scope("msg")
# pre-resolved: this seam runs once per ingested frame
_observe_recv = _scope.histogram_handle("recv_seconds")


class Consumer:
    def __init__(
        self,
        handler: Callable[[int, bytes], None],  # (shard, payload)
        host: str = "127.0.0.1",
        port: int = 0,
        ack_batch: int = 16,
    ):
        self.handler = handler
        self.ack_batch = ack_batch
        self._server = socket.create_server((host, port))
        self.port = self._server.getsockname()[1]
        self._closed = False
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        self.num_processed = 0

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,), daemon=True)
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        pending_acks: list[int] = []
        conn.settimeout(0.05)  # idle timeout doubles as the ack-flush tick
        try:
            while not self._closed:
                try:
                    # an injected error tears the connection down (outer
                    # OSError handler) → the producer reconnects + retries
                    faults.check("msg.consumer.recv")
                    frame = recv_frame(conn)
                except TimeoutError:
                    if pending_acks:
                        send_frame(conn, {"type": "ack", "ids": pending_acks})
                        pending_acks = []
                    continue
                if frame is None:
                    return
                header, payload = frame
                if header.get("type") != "msg":
                    continue
                try:
                    # the envelope's trace context (if any) wraps the
                    # handler, so downstream writes join the publisher's
                    # trace; the recv histogram times handler + delivery
                    import time as _time

                    ctx = trace.parse_traceparent(header.get("tp"))
                    t0 = _time.perf_counter()
                    try:
                        with trace.activate(ctx) if ctx is not None else \
                                _nullcontext(), \
                                trace.span(trace.MSG_RECV,
                                           shard=header.get("shard", 0)):
                            self.handler(header.get("shard", 0), payload)
                    finally:
                        _observe_recv(_time.perf_counter() - t0)
                    self.num_processed += 1
                except Exception:
                    continue  # no ack -> producer redelivers
                pending_acks.append(header["id"])
                if len(pending_acks) >= self.ack_batch:
                    send_frame(conn, {"type": "ack", "ids": pending_acks})
                    pending_acks = []
        except OSError:
            pass
        finally:
            if pending_acks:
                try:
                    send_frame(conn, {"type": "ack", "ids": pending_acks})
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
