"""Wire protocol: length-prefixed messages with acks.

Role parity with the reference m3msg protocol
(/root/reference/src/msg/generated/proto/msgpb/msg.proto:7-19 + protocol/
proto): a Message carries (shard, sentinel id, payload); an Ack carries the
ids being acknowledged. Frames are u32-length-prefixed JSON headers with a
raw payload, avoiding a codegen dependency.
"""

from __future__ import annotations

import json
import socket
import struct
from dataclasses import dataclass


@dataclass
class Message:
    shard: int
    msg_id: int
    payload: bytes


def send_frame(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    h = json.dumps(header).encode()
    sock.sendall(struct.pack(">II", len(h), len(payload)) + h + payload)


def recv_frame(sock: socket.socket) -> tuple[dict, bytes] | None:
    # a timeout may only surface at the frame boundary (first byte); once a
    # frame is partially read, keep reading so framing never desyncs
    head = _recv_exact(sock, 8, allow_timeout=True)
    if head is None:
        return None
    hlen, plen = struct.unpack(">II", head)
    h = _recv_exact(sock, hlen)
    if h is None:
        return None
    payload = _recv_exact(sock, plen) if plen else b""
    if plen and payload is None:
        return None
    return json.loads(h), payload or b""


def _recv_exact(sock: socket.socket, n: int, allow_timeout: bool = False
                ) -> bytes | None:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except TimeoutError:
            if allow_timeout and not buf:
                raise
            continue
        if not chunk:
            return None
        buf += chunk
    return buf
