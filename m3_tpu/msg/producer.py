"""Message producer: ordered, acked, at-least-once pub/sub over TCP.

Role parity with the reference producer
(/root/reference/src/msg/producer — writer fan-out per consumer service ->
shard -> message writers with retry-until-ack, ref-counted messages,
backpressure buffer; data-flow doc msg/README.md:5-17). One writer thread
per consumer connection drains a per-shard queue; unacked messages
redeliver after a timeout; the buffer applies backpressure by dropping
oldest when full (configurable).
"""

from __future__ import annotations

import socket
import threading
import time
from contextlib import nullcontext as _nullcontext
from dataclasses import dataclass

from m3_tpu.msg.protocol import recv_frame, send_frame
from m3_tpu.utils import faults, trace
from m3_tpu.utils.instrument import default_registry

_scope = default_registry().root_scope("msg")
# pre-resolved: this seam runs once per sent frame
_observe_send = _scope.histogram_handle("send_seconds")


@dataclass
class _Pending:
    msg_id: int
    shard: int
    payload: bytes
    sent_at: float = 0.0
    attempts: int = 0
    # publisher's trace context (traceparent string): rides the frame
    # envelope so the consumer's handler spans join the publishing trace
    tp: str | None = None


class Producer:
    """Publishes messages to one consumer endpoint with ack tracking."""

    def __init__(
        self,
        endpoint: tuple[str, int],
        retry_after_s: float = 2.0,
        max_buffer: int = 100_000,
        on_drop=None,
    ):
        self.endpoint = endpoint
        self.retry_after_s = retry_after_s
        self.max_buffer = max_buffer
        self.on_drop = on_drop
        self._pending: dict[int, _Pending] = {}
        self._queue: list[int] = []
        # mirror of _queue's membership, maintained under _lock: BOTH
        # requeue paths (the writer's send-failure handler and the stale
        # scan) consult it immediately before inserting, so a message can
        # never be queued twice — double-queued ids double-send on flappy
        # links (each pop transmits)
        self._queued: set[int] = set()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._next_id = 1
        self._closed = False
        self._sock: socket.socket | None = None
        self._acker: threading.Thread | None = None
        self.num_dropped = 0
        # saturation plane: unacked backlog vs max_buffer, drop count
        from m3_tpu.utils.instrument import monitor_queue

        self._unmonitor = monitor_queue(
            "msg_producer", lambda: len(self._pending), max_buffer,
            drops_fn=lambda: self.num_dropped, owner=self,
            endpoint=f"{endpoint[0]}:{endpoint[1]}")
        self._writer = threading.Thread(target=self._run_writer, daemon=True)
        self._writer.start()

    # -- publish --

    def publish(self, shard: int, payload: bytes) -> int:
        with self._cv:
            if len(self._pending) >= self.max_buffer:
                # backpressure: drop the oldest unacked message, whether it
                # is still queued or already in flight (dict preserves
                # insertion order = publish order)
                oldest = next(iter(self._pending), None)
                if oldest is not None:
                    dropped = self._pending.pop(oldest)
                    try:
                        self._queue.remove(oldest)
                    except ValueError:
                        pass
                    self._queued.discard(oldest)
                    self.num_dropped += 1
                    if self.on_drop:
                        self.on_drop(dropped)
            msg_id = self._next_id
            self._next_id += 1
            ctx = trace.current()
            self._pending[msg_id] = _Pending(
                msg_id, shard, payload,
                tp=ctx.to_traceparent() if ctx is not None else None)
            self._queue.append(msg_id)
            self._queued.add(msg_id)
            self._cv.notify()
            return msg_id

    @property
    def unacked(self) -> int:
        with self._lock:
            return len(self._pending)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._unmonitor()
        if self._sock:
            try:
                self._sock.close()
            except OSError:
                pass

    # -- writer/acker loops --

    def _connect(self) -> socket.socket | None:
        try:
            faults.check("msg.producer.connect", endpoint=self.endpoint)
            sock = socket.create_connection(self.endpoint, timeout=5)
            sock.settimeout(None)
            return sock
        except OSError:
            return None

    def _run_writer(self) -> None:
        while not self._closed:
            if self._sock is None:
                self._sock = self._connect()
                if self._sock is None:
                    time.sleep(0.2)
                    continue
                self._acker = threading.Thread(
                    target=self._run_acker, args=(self._sock,), daemon=True
                )
                self._acker.start()
            with self._cv:
                # redeliver stale unacked messages on EVERY iteration, not
                # only when the queue drains — under sustained publish load
                # the empty-queue wait below may never run, and at-least-once
                # depends on this check (reference message_writer retries on
                # a ticker, msg/producer/writer/message_writer.go)
                self._requeue_stale_locked()
                while not self._queue and not self._closed:
                    # also wake to retry unacked messages
                    self._cv.wait(timeout=self.retry_after_s / 2)
                    self._requeue_stale_locked()
                if self._closed:
                    return
                msg_id = self._queue.pop(0)
                self._queued.discard(msg_id)
                p = self._pending.get(msg_id)
            if p is None:
                continue  # acked while queued
            try:
                header = {"type": "msg", "id": p.msg_id, "shard": p.shard}
                if p.tp:
                    header["tp"] = p.tp  # envelope trace propagation
                ctx = trace.parse_traceparent(p.tp)
                t0 = time.perf_counter()
                try:
                    with trace.activate(ctx) if ctx is not None else \
                            _nullcontext(), \
                            trace.span(trace.MSG_SEND, msg_id=p.msg_id,
                                       shard=p.shard):
                        faults.check("msg.producer.send", msg_id=p.msg_id)
                        send_frame(self._sock, header, p.payload)
                finally:
                    _observe_send(time.perf_counter() - t0)
                with self._lock:
                    p.sent_at = time.monotonic()
                    p.attempts += 1
            except OSError:
                self._requeue_after_error(msg_id)
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _requeue_after_error(self, msg_id: int) -> None:
        """Front-requeue a message whose send failed — unless it was acked
        mid-send or is ALREADY queued again (the stale scan may have
        re-appended it between our pop and the failure; queuing it twice
        double-sends)."""
        with self._cv:
            if msg_id in self._pending and msg_id not in self._queued:
                self._queue.insert(0, msg_id)
                self._queued.add(msg_id)

    def _requeue_stale_locked(self) -> None:
        now = time.monotonic()
        # throttle: the O(pending) scan runs at most every retry_after_s/2,
        # so the per-message fast path stays O(1) under sustained load
        if now - getattr(self, "_last_requeue_scan", 0.0) < self.retry_after_s / 2:
            return
        self._last_requeue_scan = now
        for p in self._pending.values():
            if (
                p.msg_id not in self._queued  # live set, not a scan snapshot
                and p.sent_at
                and now - p.sent_at > self.retry_after_s
            ):
                self._queue.append(p.msg_id)
                self._queued.add(p.msg_id)

    def _run_acker(self, sock: socket.socket) -> None:
        while not self._closed:
            try:
                frame = recv_frame(sock)
            except OSError:
                return
            if frame is None:
                return
            header, _ = frame
            if header.get("type") == "ack":
                with self._lock:
                    for msg_id in header.get("ids", []):
                        self._pending.pop(msg_id, None)
