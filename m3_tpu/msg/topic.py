"""Topic metadata in KV + topic-routed publishing.

Role parity with the reference msg/topic (types.go: a topic names a shard
space and the consumer services subscribed to it, each Shared or
Replicated) and the producer's consumer-service writers
(msg/producer/writer/consumer_service_writer.go): the round-1 gap was
shard->consumer routing hardcoded per connection. A TopicProducer resolves
each consumer service's PLACEMENT from KV to find which instance owns each
topic shard and routes publishes accordingly.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from m3_tpu.cluster import placement as pl
from m3_tpu.msg.producer import Producer

SHARED = "shared"          # each message goes to ONE owner of its shard
REPLICATED = "replicated"  # each message goes to EVERY owner of its shard

_TOPIC_PREFIX = "topics/"


@dataclass
class ConsumerService:
    service_id: str  # its placement lives at placements/<service_id>
    consumption_type: str = SHARED


@dataclass
class Topic:
    name: str
    n_shards: int
    consumer_services: list[ConsumerService] = field(default_factory=list)
    version: int = 0

    def to_json(self) -> bytes:
        return json.dumps({
            "name": self.name,
            "n_shards": self.n_shards,
            "consumer_services": [
                {"service_id": c.service_id,
                 "consumption_type": c.consumption_type}
                for c in self.consumer_services
            ],
            "version": self.version,
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Topic":
        doc = json.loads(raw)
        return cls(
            name=doc["name"],
            n_shards=doc["n_shards"],
            consumer_services=[
                ConsumerService(c["service_id"],
                                c.get("consumption_type", SHARED))
                for c in doc.get("consumer_services", [])
            ],
            version=doc.get("version", 0),
        )


def topic_key(name: str) -> str:
    return _TOPIC_PREFIX + name


def get_topic(kv, name: str) -> Topic | None:
    from m3_tpu.cluster.kv import KeyNotFound

    try:
        vv = kv.get(topic_key(name))
    except KeyNotFound:
        return None
    return Topic.from_json(vv.data)


def create_topic(kv, topic: Topic) -> int:
    topic.version += 1
    return kv.set_if_not_exists(topic_key(topic.name), topic.to_json())


def put_topic(kv, topic: Topic) -> int:
    topic.version += 1
    return kv.set(topic_key(topic.name), topic.to_json())


def delete_topic(kv, name: str) -> None:
    kv.delete(topic_key(name))


def list_topics(kv) -> list[str]:
    return [k[len(_TOPIC_PREFIX):] for k in kv.keys(_TOPIC_PREFIX)]


def _cas_update_topic(kv, name: str, fn, max_retries: int = 10) -> Topic:
    """CAS read-modify-write: concurrent consumer edits must not lose each
    other (same discipline as cluster/placement.cas_update_placement)."""
    from m3_tpu.cluster.kv import KeyNotFound, VersionMismatch

    for _ in range(max_retries):
        try:
            vv = kv.get(topic_key(name))
        except KeyNotFound:
            raise KeyError(f"no topic {name!r}") from None
        t = Topic.from_json(vv.data)
        t = fn(t)
        t.version += 1
        try:
            kv.check_and_set(topic_key(name), vv.version, t.to_json())
            return t
        except VersionMismatch:
            continue
    raise RuntimeError(f"topic CAS contention on {name!r}")


def add_consumer(kv, name: str, consumer: ConsumerService) -> Topic:
    def add(t: Topic) -> Topic:
        if not any(c.service_id == consumer.service_id
                   for c in t.consumer_services):
            t.consumer_services.append(consumer)
        return t

    return _cas_update_topic(kv, name, add)


def remove_consumer(kv, name: str, service_id: str) -> Topic:
    def drop(t: Topic) -> Topic:
        t.consumer_services = [
            c for c in t.consumer_services if c.service_id != service_id
        ]
        return t

    return _cas_update_topic(kv, name, drop)


class TopicProducer:
    """Publishes to every consumer service of a topic, routing each shard
    to the instance(s) owning it in the consumer service's placement."""

    def __init__(self, kv, topic_name: str, producer_factory=None):
        self.kv = kv
        self.topic_name = topic_name
        self._factory = producer_factory or (
            lambda endpoint: Producer(endpoint))
        self._producers: dict[str, Producer] = {}  # endpoint str -> producer
        self._routing: list[tuple[str, dict[int, list[str]]]] = []
        self._topic_version = -1
        self._placement_versions: dict[str, int] = {}
        self.refresh()

    def refresh(self) -> None:
        """Re-resolve topic + consumer placements from KV (call on watch
        ticks; skips the rebuild when every version is unchanged)."""
        t = get_topic(self.kv, self.topic_name)
        if t is None:
            raise KeyError(f"no topic {self.topic_name!r}")
        placements = {}
        versions: dict[str, int] = {}
        for c in t.consumer_services:
            loaded = pl.load_placement(self.kv, f"placements/{c.service_id}")
            if loaded is None:
                continue
            placements[c.service_id] = loaded[0]
            versions[c.service_id] = loaded[1]
        if (t.version == self._topic_version
                and versions == self._placement_versions):
            return
        routing: list[tuple[str, dict[int, list[str]]]] = []
        for c in t.consumer_services:
            placement = placements.get(c.service_id)
            if placement is None:
                continue
            shard_map: dict[int, list[str]] = {}
            for inst in placement.instances.values():
                if not inst.endpoint:
                    continue
                for sid in inst.shards:
                    shard_map.setdefault(sid, []).append(inst.endpoint)
            routing.append((c.consumption_type, shard_map))
        self._routing = routing
        self._topic_version = t.version
        self._placement_versions = versions
        self.n_shards = t.n_shards

    def _producer_for(self, endpoint: str) -> Producer:
        p = self._producers.get(endpoint)
        if p is None:
            from m3_tpu.client.http_conn import parse_endpoint

            p = self._factory(parse_endpoint(endpoint))
            self._producers[endpoint] = p
        return p

    def publish(self, shard: int, payload: bytes) -> int:
        """Route to every consumer service; Shared sends to the shard's
        first owner, Replicated to all owners. Returns sends issued."""
        sent = 0
        for ctype, shard_map in self._routing:
            owners = shard_map.get(shard % self.n_shards, [])
            if not owners:
                continue
            targets = owners if ctype == REPLICATED else owners[:1]
            for endpoint in targets:
                self._producer_for(endpoint).publish(shard, payload)
                sent += 1
        return sent

    @property
    def unacked(self) -> int:
        return sum(p.unacked for p in self._producers.values())

    def close(self) -> None:
        for p in self._producers.values():
            p.close()
