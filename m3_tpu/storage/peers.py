"""Peer block access + peers bootstrap + replica repair.

Role parity with the reference's peers bootstrapper
(/root/reference/src/dbnode/storage/bootstrap/bootstrapper/peers — new
nodes stream blocks from replicas) and the background repairer
(storage/repair.go:839-1011 — compare per-series block checksums across
replicas, stream + merge differing blocks). A peer is anything exposing
block metadata and stream reads: an in-process Database (integration
harness) or a NodeAPI HTTP client; the same divergence math runs
device-resident for device-held blocks via parallel.collectives.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.request
import zlib
from dataclasses import dataclass
from typing import Protocol

import numpy as np

from m3_tpu.client.breaker import BreakerConfig, HostPolicy
from m3_tpu.storage.buffer import merge_dedup
from m3_tpu.storage.fileset import FilesetWriter
from m3_tpu.utils import faults


class PeerSource(Protocol):
    def block_metadata(self, namespace: str, shard: int, block_start: int
                       ) -> dict[bytes, dict]: ...

    def stream_block(self, namespace: str, shard: int, block_start: int,
                     series_id: bytes) -> tuple[bytes, bytes]: ...

    def block_starts(self, namespace: str, shard: int) -> list[int]: ...

    def rollup_digests(self, namespace: str, shard: int
                       ) -> dict[int, tuple[int, int]]: ...


# -- rollup digest wire format ---------------------------------------------
#
# The repair plane's steady-state traffic is "are we in sync?" — one row
# per flushed block, exchanged every cycle by every replica pair. That
# must not be per-series float64 JSON (ROADMAP #5(c), EQuARX discipline:
# comparison traffic wants the leanest encoding that answers the
# question), so the whole shard's digest table rides as ONE packed
# little-endian array: (block_start i64, digest u64, n_series u32) per
# block — 20 bytes per block vs ~60 bytes of JSON object keys alone.

ROLLUP_DTYPE = np.dtype([("block_start", "<i8"), ("digest", "<u8"),
                         ("n_series", "<u4")])


def pack_rollup(digests: dict[int, tuple[int, int]]) -> bytes:
    """{block_start: (digest, n_series)} -> packed ROLLUP_DTYPE bytes,
    rows sorted by block_start (deterministic wire bytes)."""
    arr = np.empty(len(digests), ROLLUP_DTYPE)
    for i, bs in enumerate(sorted(digests)):
        digest, n_series = digests[bs]
        arr[i] = (bs, digest, n_series)
    return arr.tobytes()


def unpack_rollup(raw: bytes) -> dict[int, tuple[int, int]]:
    if len(raw) % ROLLUP_DTYPE.itemsize:
        raise ValueError(
            f"rollup payload length {len(raw)} not a multiple of "
            f"{ROLLUP_DTYPE.itemsize}")
    arr = np.frombuffer(raw, ROLLUP_DTYPE)
    return {int(r["block_start"]): (int(r["digest"]), int(r["n_series"]))
            for r in arr}


def local_rollup_digests(db, namespace: str, shard_id: int
                         ) -> dict[int, tuple[int, int]]:
    """{block_start: (rollup digest, n_series)} over this node's flushed
    volumes for one shard. O(1) per block after the first computation —
    digests cache on the immutable FilesetReader, so a repair cycle over
    an in-sync shard costs a dict walk, not a data pass."""
    ns = db.namespaces.get(namespace)
    if ns is None or shard_id not in ns.shards:
        return {}
    out: dict[int, tuple[int, int]] = {}
    for bs, reader in list(ns.shards[shard_id]._filesets.items()):
        try:
            out[bs] = (reader.rollup_digest(), reader.n_series)
        except ValueError:
            # captured reader closed by a concurrent flush swap + retire
            # drain: skip; the next cycle sees the new volume
            continue
    return out


class InProcessPeer:
    """Peer backed by a Database in the same process (integration/test)."""

    def __init__(self, db):
        self.db = db

    def _reader(self, namespace: str, shard: int, block_start: int):
        ns = self.db.namespaces.get(namespace)
        if ns is None or shard not in ns.shards:
            return None
        return ns.shards[shard]._filesets.get(block_start)

    def block_starts(self, namespace: str, shard: int) -> list[int]:
        ns = self.db.namespaces.get(namespace)
        if ns is None or shard not in ns.shards:
            return []
        return ns.shards[shard].flushed_block_starts

    def block_metadata(self, namespace, shard, block_start):
        reader = self._reader(namespace, shard, block_start)
        out = {}
        if reader is None:
            return out
        for i in range(reader.n_series):
            sid, _tags, stream = reader.read_at(i)
            out[sid] = {"checksum": zlib.adler32(stream), "size": len(stream)}
        return out

    def stream_block(self, namespace, shard, block_start, series_id):
        reader = self._reader(namespace, shard, block_start)
        if reader is None:
            return b"", b""
        return reader.read(series_id) or b"", reader.tags_of(series_id) or b""

    def rollup_digests(self, namespace, shard):
        return local_rollup_digests(self.db, namespace, shard)

    def flush_shard(self, shard):
        return self.db.flush_shard(shard)


class PeerClientError(Exception):
    """A peer answered with a deterministic 4xx (e.g. a namespace it
    doesn't have): the REQUEST is wrong, the host is healthy. Never
    retried and never counted against the host's circuit — one bad probe
    must not open a shared breaker and stall bootstrap of everything else
    that peer serves."""


# per-host breaker+retry policies shared by every HTTPPeer talking to the
# same base URL: bootstrap and repair often build several peer objects per
# replica, and they must share one circuit so a dead peer is shed
# process-wide instead of serializing a fresh timeout per object
PEER_POLICY_CONFIG = BreakerConfig(
    failure_threshold=3,
    open_timeout_s=2.0,
    retry_attempts=3,
    retry_backoff_s=0.05,
    retry_jitter_frac=0.25,  # de-synchronize replicas re-probing a peer
)
_host_policies: dict[str, HostPolicy] = {}
_host_policies_lock = threading.Lock()


def peer_policy(base_url: str, config: BreakerConfig | None = None) -> HostPolicy:
    with _host_policies_lock:
        pol = _host_policies.get(base_url)
        if pol is None:
            pol = HostPolicy(base_url, config or PEER_POLICY_CONFIG,
                             no_count=(PeerClientError,))
            _host_policies[base_url] = pol
        return pol


def reset_peer_policies() -> None:
    """Drop all shared peer breaker state (tests)."""
    with _host_policies_lock:
        _host_policies.clear()


class HTTPPeer:
    """Peer over the dbnode NodeAPI (services/dbnode.py).

    Every request runs through the host's shared CircuitBreaker + bounded
    jittered retry (client/breaker.py): transient errors get a couple of
    backed-off retries, and a dead peer opens the circuit so
    bootstrap/repair shed it locally (BreakerOpen, caught by the callers'
    per-peer error handling) instead of serializing 10s urlopen timeouts
    per block."""

    # process-wide default request timeout; dbnode config / the
    # m3_tpu.repair KV key override it per-peer (repair.peer_timeout_s) so
    # one slow replica cannot pin a 10s stall into every probe
    DEFAULT_TIMEOUT_S = 10.0

    def __init__(self, base_url: str, timeout_s: float | None = None,
                 policy: HostPolicy | None = None):
        self.base = base_url.rstrip("/")
        self.timeout = (timeout_s if timeout_s is not None
                        else self.DEFAULT_TIMEOUT_S)
        self.policy = policy if policy is not None else peer_policy(self.base)

    def _get(self, path: str):
        return self.policy.call(self._fetch, path)

    def _get_raw(self, path: str, accept: str):
        """GET returning (content_type, raw_payload) — the binary-frame
        negotiation seam (utils/wire.py): Accept advertises the frame
        codec; the caller dispatches on the Content-Type that came back."""
        return self.policy.call(self._fetch, path, None, accept)

    def _post(self, path: str, doc: dict):
        return self.policy.call(self._fetch, path, json.dumps(doc).encode())

    def _fetch(self, path: str, body: bytes | None = None,
               accept: str | None = None):
        import urllib.error

        from m3_tpu.utils import trace
        from m3_tpu.utils.instrument import default_registry

        with trace.span(trace.PEER_HTTP, peer=self.base), \
                default_registry().root_scope("peer").histogram(
                    "http_seconds"):
            faults.check("peer.http", url=self.base + path)
            headers = trace.inject_headers()
            if accept is not None:
                headers["Accept"] = accept
            req = urllib.request.Request(self.base + path, data=body,
                                         headers=headers)
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    if accept is not None:
                        return (r.getheader("Content-Type") or
                                "application/json"), r.read()
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    # admission-control shed: backpressure (honored
                    # Retry-After + jittered retry in HostPolicy), NOT a
                    # client error and NOT a breaker failure
                    from m3_tpu.client.breaker import Backpressure
                    from m3_tpu.client.http_conn import _retry_after_s

                    raise Backpressure(
                        f"429 from {self.base}{path}",
                        retry_after_s=_retry_after_s(
                            e.headers.get("Retry-After")),
                    ) from e
                if 400 <= e.code < 500:
                    raise PeerClientError(
                        f"{e.code} from {self.base}{path}") from e
                raise

    def block_starts(self, namespace, shard):
        from urllib.parse import quote

        return [int(b) for b in self._get(
            f"/blocks/starts?namespace={quote(namespace, safe='')}"
            f"&shard={shard}"
        )]

    def block_metadata(self, namespace, shard, block_start):
        from urllib.parse import quote

        doc = self._get(
            f"/blocks/metadata?namespace={quote(namespace, safe='')}"
            f"&shard={shard}&block_start={block_start}"
        )
        return {
            base64.b64decode(k): v for k, v in doc.items()
        }

    def stream_block(self, namespace, shard, block_start, series_id):
        from urllib.parse import quote

        from m3_tpu.utils import wire

        # URL-encode the base64: '+' would decode as a space in query strings
        sid = quote(base64.b64encode(series_id).decode(), safe="")
        path = (f"/blocks/stream?namespace={quote(namespace, safe='')}"
                f"&shard={shard}&block_start={block_start}&series_id={sid}")
        if wire.packed_enabled():
            ctype, payload = self._get_raw(path, wire.CONTENT_TYPE)
            wire.account("stream_block", recv=len(payload))
            if wire.is_packed(ctype):
                stream, tags = wire.unpack_blobs(payload, wire.KIND_BLOCK)
                return stream, tags
            # mixed-version fleet: older peer answered JSON — parse it,
            # never fail the repair/bootstrap pull
            wire.count_fallback("server_json")
            doc = json.loads(payload)
        else:
            doc = self._get(path)
        return (base64.b64decode(doc["stream"]), base64.b64decode(doc["tags"]))

    def rollup_digests(self, namespace, shard):
        from urllib.parse import quote

        from m3_tpu.utils import wire

        path = (f"/blocks/rollup?namespace={quote(namespace, safe='')}"
                f"&shard={shard}")
        if wire.packed_enabled():
            ctype, payload = self._get_raw(path, wire.CONTENT_TYPE)
            wire.account("rollup", recv=len(payload))
            if wire.is_packed(ctype):
                (packed,) = wire.unpack_blobs(payload, wire.KIND_ROLLUP)
                return unpack_rollup(packed)
            wire.count_fallback("server_json")
            doc = json.loads(payload)
        else:
            doc = self._get(path)
        return unpack_rollup(base64.b64decode(doc.get("rollup_b64", "")))

    def flush_shard(self, shard):
        """Donor buffer/WAL tail handoff (shard handoff cutover safety):
        make the peer flush every buffered window of this shard so its
        rollup digests cover acked-but-unflushed writes — without this,
        cutover would verify against stale filesets and the donor's
        mutable window would die with the LEAVING shard."""
        doc = self._post("/shards/flush", {"shard": int(shard)})
        return int(doc.get("flushed", 0))


def bootstrap_shard_from_peers(db, namespace: str, shard_id: int,
                               peers: list[PeerSource],
                               known_starts: set[int] | None = None,
                               pacer=None) -> int:
    """Stream every flushed block a replica set has for this shard into
    local fileset volumes (the new-node bootstrap path). Returns blocks
    written. Majority checksum wins when peers disagree. Callers that
    already probed the peers' block starts pass them via known_starts to
    avoid re-fetching.

    `pacer` (optional, `.acquire(n_bytes)`) is the repair plane's token
    bucket: every stream pulled off a peer pays into the shared budget so
    a mass reassignment cannot starve foreground reads (the same storm-
    safety discipline `repair_shard_block` applies)."""
    ns = db.namespaces[namespace]
    shard = ns.shards[shard_id]
    if known_starts is not None:
        all_starts = set(known_starts)
    else:
        all_starts = set()
        for p in peers:
            try:
                all_starts.update(p.block_starts(namespace, shard_id))
            except faults.SimulatedCrash:
                # a crash injected at the peer.http seam is THIS process
                # dying mid-probe, not the peer being down: it must never
                # degrade into "peer adds no blocks" (that would falsify
                # every chaos assertion downstream)
                faults.escalate()
                raise
            except Exception:  # noqa: BLE001 - unreachable peer adds none
                pass
    written = 0
    for bs in sorted(all_starts):
        if bs in shard._filesets:
            continue  # already have a volume
        merged = _merged_block_from_peers(namespace, shard_id, bs, peers,
                                          pacer=pacer)
        if not merged:
            continue
        writer = FilesetWriter(
            shard.fs_root, namespace, shard_id, bs,
            ns.opts.retention.block_size_ns, volume=0,
        )
        for sid, (tags, stream) in sorted(merged.items()):
            writer.write_series(sid, tags, stream)
        writer.close()
        from m3_tpu.storage.fileset import FilesetReader

        shard._filesets[bs] = FilesetReader(
            shard.fs_root, namespace, shard_id, bs, 0
        )
        shard.bump_data_version()
        written += 1
    # the reverse index learns the streamed series (spanning every index
    # block the data block overlaps, like fs bootstrap)
    if ns.index is not None:
        from m3_tpu.utils.ident import decode_tags

        for bs in sorted(all_starts):
            reader = shard._filesets.get(bs)
            if reader is None:
                continue
            for i in range(reader.n_series):
                sid, tags_blob = reader.entry_at(i)
                if tags_blob:
                    ns.index_insert_spanning(sid, decode_tags(tags_blob), bs)
    return written


def _merged_block_from_peers(namespace, shard_id, bs, peers, pacer=None):
    """(series -> (tags, stream)) agreed by majority checksum; divergent
    series fall back to the first non-empty stream."""
    metas = []
    for p in peers:
        try:
            metas.append(p.block_metadata(namespace, shard_id, bs))
        except faults.SimulatedCrash:
            faults.escalate()  # our own injected death, not a peer error
            raise
        except Exception:  # noqa: BLE001 - unreachable peer contributes none
            metas.append({})
    all_sids = set()
    for m in metas:
        all_sids.update(m)
    out = {}
    for sid in all_sids:
        checksums: dict[int, int] = {}
        for m in metas:
            if sid in m:
                c = m[sid]["checksum"]
                checksums[c] = checksums.get(c, 0) + 1
        best = max(checksums.items(), key=lambda kv: kv[1])[0] if checksums else None
        for p, m in zip(peers, metas):
            if sid in m and (best is None or m[sid]["checksum"] == best):
                try:
                    stream, tags = p.stream_block(namespace, shard_id, bs, sid)
                except faults.SimulatedCrash:
                    faults.escalate()
                    raise
                except Exception:  # noqa: BLE001 - try the next replica
                    continue
                if stream:
                    if pacer is not None:
                        pacer.acquire(len(stream))
                    out[sid] = (tags, stream)
                    break
    return out


@dataclass
class RepairResult:
    checked: int = 0
    diverged: int = 0
    repaired: int = 0


def repair_shard_block(db, namespace: str, shard_id: int, block_start: int,
                       peers: list[PeerSource],
                       pacer=None) -> RepairResult:
    """Compare this node's block against peers and merge differences.

    The reference compares sizes/checksums then streams + merges differing
    blocks; here divergent series are decoded from every replica, merged
    last-write-wins, re-encoded, and written as a higher volume.

    Convergence: replica streams for one series merge in a DETERMINISTIC
    order (sorted by stream checksum) so two replicas repairing against
    each other resolve a same-timestamp value conflict to the SAME winner
    — otherwise each side would adopt the other's value and oscillate
    forever, and the rig's digest-equality audit could never settle.

    `pacer` (optional, `.acquire(n_bytes)`) is the RepairDaemon's token
    bucket: every stream pulled off a peer pays into the repair budget so
    a post-outage repair storm cannot starve the serving path.

    Locking: the slow phase (peer RPCs, decode/merge/re-encode) runs
    OUTSIDE the shard maintenance lock so a repair over slow peers never
    stalls the tick's flush/expire. Only the volume write + swap takes the
    lock; if a flush swapped in a new volume meanwhile, the merge is stale
    and is abandoned for the next repair cycle to redo.
    """
    from m3_tpu.encoding.m3tsz import Encoder
    from m3_tpu.encoding.m3tsz import decode as scalar_decode

    ns = db.namespaces[namespace]
    shard = ns.shards[shard_id]
    with shard._maint_lock:
        reader = shard._filesets.get(block_start)
    local_meta = {}
    result = RepairResult()
    try:
        if reader is not None:
            for i in range(reader.n_series):
                sid, _tags, stream = reader.read_at(i)
                local_meta[sid] = zlib.adler32(stream)
    except ValueError:
        # captured reader closed by a concurrent flush + retire-grace
        # expiry; stale pass, redo next cycle
        return result
    peer_metas = []
    for p in peers:
        try:
            peer_metas.append(p.block_metadata(namespace, shard_id, block_start))
        except faults.SimulatedCrash:
            faults.escalate()  # our own injected death, not a peer error
            raise
        except Exception:  # noqa: BLE001 - unreachable peer contributes none
            peer_metas.append({})
    all_sids = set(local_meta)
    for m in peer_metas:
        all_sids.update(m)
    result.checked = len(all_sids)

    divergent: list[bytes] = []
    for sid in all_sids:
        local = local_meta.get(sid)
        for m in peer_metas:
            if sid in m and m[sid]["checksum"] != local:
                divergent.append(sid)
                break
    result.diverged = len(divergent)
    if not divergent:
        return result

    unit = ns.opts.write_time_unit
    merged: dict[bytes, tuple[bytes, bytes]] = {}
    for sid in divergent:
        parts_t, parts_v = [], []
        streams = []
        try:
            tags = reader.tags_of(sid) if reader else None
            own = reader.read(sid) if reader is not None else None
        except ValueError:
            # a merge slower than the retire grace can find the captured
            # reader closed after a concurrent flush; the merge is stale
            # either way (the swap check below would abandon it), so bail
            # now and let the next repair cycle re-compare
            result.repaired = 0
            return result
        have = set()
        if own:
            streams.append(own)
            have.add(local_meta[sid])
        for p, m in zip(peers, peer_metas):
            if m:
                pm = m.get(sid)
                if pm is None:
                    continue  # peer's own metadata says it lacks this series
                if pm["checksum"] in have:
                    # byte-identical to a stream already in hand (ours or a
                    # previously fetched peer's): re-pulling it buys the
                    # merge nothing and charges the repair rate budget —
                    # under RF=3 that's roughly half the storm's wire cost
                    continue
            try:
                stream, ptags = p.stream_block(namespace, shard_id, block_start, sid)
            except faults.SimulatedCrash:
                faults.escalate()
                raise
            except Exception:  # noqa: BLE001 - peer unreachable mid-stream
                continue
            if stream:
                if pacer is not None:
                    pacer.acquire(len(stream))
                streams.append(stream)
                tags = tags or ptags
                have.add(zlib.adler32(stream))
        # deterministic merge order: both sides of a replica pair must
        # concatenate the same streams in the same order so last-write-wins
        # picks the same value for a conflicting timestamp on both nodes
        streams.sort(key=lambda s: (zlib.adler32(s), s))
        for stream in streams:
            dps = scalar_decode(stream, int_optimized=ns.opts.int_optimized,
                                default_time_unit=unit)
            if dps:
                parts_t.append(np.array([d.timestamp_ns for d in dps], np.int64))
                parts_v.append(
                    np.array([d.value for d in dps], np.float64).view(np.uint64)
                )
        if not parts_t:
            continue
        times, vbits = merge_dedup(np.concatenate(parts_t), np.concatenate(parts_v))
        enc = Encoder(block_start, int_optimized=ns.opts.int_optimized,
                      default_time_unit=unit)
        for t, vb in zip(times, vbits):
            enc.encode(int(t), float(np.uint64(vb).view(np.float64)), unit)
        merged[sid] = (tags or b"", enc.stream())
        result.repaired += 1

    if not merged:
        # nothing could actually be streamed (e.g. peers unreachable):
        # writing an empty volume would mask the block forever
        result.repaired = 0
        return result

    with shard._maint_lock:
        if shard._filesets.get(block_start) is not reader:
            # a flush swapped in a new volume while we merged: our result
            # is stale; the next repair cycle re-compares against it
            result.repaired = 0
            return result
        # write a higher volume carrying merged + untouched series
        volume = (reader.volume + 1) if reader else 0
        writer = FilesetWriter(
            shard.fs_root, namespace, shard_id, block_start,
            ns.opts.retention.block_size_ns, volume,
        )
        seen = set()
        for sid, (tags, stream) in sorted(merged.items()):
            writer.write_series(sid, tags, stream)
            seen.add(sid)
        if reader is not None:
            for i in range(reader.n_series):
                sid, tags, stream = reader.read_at(i)
                if sid not in seen:
                    writer.write_series(sid, tags, stream)
        writer.close()
        from m3_tpu.storage.fileset import FilesetReader

        if reader is not None:
            # retire, don't close: a concurrent Shard.read may still hold
            # this reader from its snapshot (see Shard._retire)
            shard._retire(reader)
        shard._filesets[block_start] = FilesetReader(
            shard.fs_root, namespace, shard_id, block_start, volume
        )
        shard.bump_data_version()
        if shard.cache is not None:  # cached decodes predate the repair
            shard.cache.invalidate_block(namespace, shard_id, block_start)
    # peer-only series become queryable
    if ns.index is not None:
        from m3_tpu.utils.ident import decode_tags

        for sid, (tags, _stream) in merged.items():
            if tags:
                ns.index_insert_spanning(sid, decode_tags(tags), block_start)
    return result
