"""Columnar in-memory write buffer.

The reference buffers writes per series object with one encoder per
out-of-order stream (/root/reference/src/dbnode/storage/series/buffer.go:77,
1261), merging on flush. TPU-first redesign: a shard keeps one append-only
struct-of-arrays log per block window (series idx / time / value bits);
writes are O(1) host appends and the whole window seals to compressed
blocks in a single batched device encode — the insert-queue batching
pattern (storage/shard_insert_queue.go) applied to the buffer itself.
Out-of-order and duplicate writes are resolved at seal time by a stable
sort + last-write-wins dedup, equivalent to the reference's merge of
multiple encoders at flush.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from m3_tpu.storage import pagepool

_GROW = 1024


def merge_dedup(times: np.ndarray, vbits: np.ndarray,
                start_ns: int | None = None, end_ns: int | None = None):
    """Stable sort by time + last-write-wins dedup (+ optional range filter).

    The single definition of write-conflict resolution: later appends win on
    timestamp ties, everywhere (buffer reads, seals, shard merges).
    """
    # fast path: already strictly increasing (the common case — a single
    # decoded block, or blocks concatenated in time order with no buffer
    # overlap) makes sort AND dedup no-ops; O(n) check vs O(n log n) sort
    # matters when read_many calls this once per series
    if len(times) > 1 and not np.all(times[1:] > times[:-1]):
        order = np.argsort(times, kind="stable")
        times, vbits = times[order], vbits[order]
        keep = np.ones(len(times), bool)
        keep[:-1] = times[1:] != times[:-1]
        times, vbits = times[keep], vbits[keep]
    if start_ns is not None or end_ns is not None:
        sel = np.ones(len(times), bool)
        if start_ns is not None:
            sel &= times >= start_ns
        if end_ns is not None:
            sel &= times < end_ns
        times, vbits = times[sel], vbits[sel]
    return times, vbits


class _ColumnLog:
    """Growable (series_idx, time, value_bits) append log."""

    __slots__ = ("sidx", "times", "vbits", "n")

    def __init__(self) -> None:
        self.sidx = np.empty(_GROW, dtype=np.int32)
        self.times = np.empty(_GROW, dtype=np.int64)
        self.vbits = np.empty(_GROW, dtype=np.uint64)
        self.n = 0

    def append(self, sidx: int, t_ns: int, vbits: int) -> None:
        if self.n == len(self.sidx):
            cap = len(self.sidx) * 2
            self.sidx = np.resize(self.sidx, cap)
            self.times = np.resize(self.times, cap)
            self.vbits = np.resize(self.vbits, cap)
        self.sidx[self.n] = sidx
        self.times[self.n] = t_ns
        self.vbits[self.n] = vbits
        self.n += 1

    def extend(self, sidx: np.ndarray, t_ns: np.ndarray,
               vbits: np.ndarray) -> None:
        """Bulk append: one capacity check + three slice-assigns for the
        whole batch (write_many's per-window store), vs one append per
        row. Row order is preserved, so seal's last-write-wins dedup
        resolves batched and per-point writes identically."""
        m = len(sidx)
        need = self.n + m
        if need > len(self.sidx):
            cap = len(self.sidx)
            while cap < need:
                cap *= 2
            self.sidx = np.resize(self.sidx, cap)
            self.times = np.resize(self.times, cap)
            self.vbits = np.resize(self.vbits, cap)
        self.sidx[self.n : need] = sidx
        self.times[self.n : need] = t_ns
        self.vbits[self.n : need] = vbits
        self.n = need

    def view(self):
        return self.sidx[: self.n], self.times[: self.n], self.vbits[: self.n]

    def release(self) -> None:
        """No-op twin of PagedColumnLog.release (grow-arrays just die)."""


@dataclass
class RaggedSealedWindow:
    """One block window sealed to the ragged (offsets, lengths) layout:
    sorted by (series, time), deduped last-write-wins, NO rectangular
    padding — the CSR the length-bucketed ragged encode consumes
    (hostpath.encode_blocks_ragged) and the paged-memory twin of
    SealedWindow (ROADMAP #3)."""

    block_start: int
    series_indices: np.ndarray  # [B] int32 buffer-level series indices
    times: np.ndarray           # [N] int64
    value_bits: np.ndarray      # [N] uint64
    offsets: np.ndarray         # [B+1] int64 row boundaries
    raw_count: int = 0

    @property
    def n_series(self) -> int:
        return len(self.series_indices)

    @property
    def n_points(self) -> np.ndarray:
        return np.diff(self.offsets).astype(np.int32)


@dataclass
class SealedWindow:
    """One block window grouped into a padded (series x point) batch."""

    block_start: int
    series_indices: np.ndarray  # [B] int32 buffer-level series indices
    times: np.ndarray  # [B, T] int64 (padded)
    value_bits: np.ndarray  # [B, T] uint64 (padded)
    n_points: np.ndarray  # [B] int32
    starts: np.ndarray = field(default=None)  # [B] int64, all == block_start
    # raw log rows this seal covered: drop_window_prefix(bs, raw_count)
    # removes exactly these, preserving concurrent appends after the seal
    raw_count: int = 0

    @property
    def n_series(self) -> int:
        return len(self.series_indices)


class ShardBuffer:
    """Per-shard buffer: series registry + one column log per block window."""

    def __init__(self, block_size_ns: int) -> None:
        self._block_size_ns = block_size_ns
        self._series: dict[bytes, int] = {}
        self.series_ids: list[bytes] = []
        self.series_tags: list[bytes] = []  # encoded tag blobs
        self._logs: dict[int, _ColumnLog] = {}
        # paged columnar memory (ROADMAP #3): window logs draw fixed-size
        # pages from a shared pool instead of doubling grow-arrays; the
        # M3_TPU_PAGED=0 hatch (read once, at buffer construction) pins
        # the seed _ColumnLog bodies for bisection
        self._paged = pagepool.active()
        self._pool = (pagepool.monitor_pool(pagepool.PagePool())
                      if self._paged else None)
        # one lock per shard buffer (the reference's per-shard lock):
        # HTTP handler threads write while the tick thread seals/expires
        self._lock = threading.RLock()

    def _new_log(self):
        return (pagepool.PagedColumnLog(self._pool) if self._paged
                else _ColumnLog())

    # -- write path --

    def series_index(self, series_id: bytes, encoded_tags: bytes = b"") -> int:
        with self._lock:
            idx = self._series.get(series_id)
            if idx is None:
                idx = len(self.series_ids)
                self._series[series_id] = idx
                self.series_ids.append(series_id)
                self.series_tags.append(encoded_tags)
            return idx

    def write(self, series_id: bytes, t_ns: int, vbits: int, encoded_tags: bytes = b"") -> int:
        """Returns the buffer-level series index (stable for this buffer)."""
        with self._lock:
            idx = self.series_index(series_id, encoded_tags)
            bs = t_ns - (t_ns % self._block_size_ns)
            log = self._logs.get(bs)
            if log is None:
                log = self._logs[bs] = self._new_log()
            log.append(idx, t_ns, vbits)
            return idx

    def write_many(self, series_ids: list[bytes], times: np.ndarray,
                   vbits: np.ndarray, tags_list: list[bytes]) -> None:
        """Bulk write under ONE lock acquisition: resolve (registering)
        every series index, then ONE _ColumnLog.extend per block window
        in the batch — numpy slice-assign, not N appends. Equivalent to
        calling write() per row; rows keep arrival order per window so
        seal-time conflict resolution is unchanged."""
        with self._lock:
            reg = self._series
            idxs = np.empty(len(series_ids), np.int32)
            for i, sid in enumerate(series_ids):
                idx = reg.get(sid)
                if idx is None:
                    idx = len(self.series_ids)
                    reg[sid] = idx
                    self.series_ids.append(sid)
                    self.series_tags.append(tags_list[i])
                idxs[i] = idx
            bs = times - (times % self._block_size_ns)
            for w in np.unique(bs):
                sel = bs == w
                log = self._logs.get(int(w))
                if log is None:
                    log = self._logs[int(w)] = self._new_log()
                log.extend(idxs[sel], times[sel], vbits[sel])

    # -- read path --

    def read(self, series_id: bytes, start_ns: int, end_ns: int):
        """All buffered (t, vbits) for a series in [start, end), merged
        across block windows, deduped last-write-wins."""
        with self._lock:
            idx = self._series.get(series_id)
            if idx is None:
                return np.empty(0, np.int64), np.empty(0, np.uint64)
            ts_parts, vb_parts = [], []
            for bs, log in self._logs.items():
                if bs + self._block_size_ns <= start_ns or bs >= end_ns:
                    continue
                sidx, times, vbits = log.view()
                sel = sidx == idx
                ts_parts.append(times[sel])
                vb_parts.append(vbits[sel])
        if not ts_parts:
            return np.empty(0, np.int64), np.empty(0, np.uint64)
        return merge_dedup(
            np.concatenate(ts_parts), np.concatenate(vb_parts), start_ns, end_ns
        )

    def read_many_csr(self, series_ids: list[bytes], start_ns: int,
                      end_ns: int):
        """Buffered rows for MANY series in ONE pass per window: the
        batched twin of read(), returning a (times, vbits, offsets) CSR
        aligned to the request.  Rows keep the exact concatenation order
        read() produces per series (windows in _logs iteration order,
        append order within a window) and are NOT merged/filtered — the
        caller's ragged finalize (`ops.ragged.merge_csr`) applies the
        one last-write-wins + range pass over filesets AND buffer parts
        together, which resolves identically.  Requires unique ids (the
        caller falls back to per-series read() on duplicates)."""
        R = len(series_ids)
        empty = (np.empty(0, np.int64), np.empty(0, np.uint64),
                 np.zeros(R + 1, np.int64))
        with self._lock:
            pos_of = np.full(len(self.series_ids), -1, np.int64)
            found = False
            for pos, sid in enumerate(series_ids):
                idx = self._series.get(sid)
                if idx is not None:
                    pos_of[idx] = pos
                    found = True
            if not found:
                return empty
            parts_p, parts_t, parts_v = [], [], []
            for bs, log in self._logs.items():
                if bs + self._block_size_ns <= start_ns or bs >= end_ns:
                    continue
                sidx, times, vbits = log.view()
                pos = pos_of[sidx]
                m = pos >= 0
                if m.any():
                    parts_p.append(pos[m])
                    parts_t.append(times[m])
                    parts_v.append(vbits[m])
        if not parts_t:
            return empty
        rid = np.concatenate(parts_p) if len(parts_p) > 1 else parts_p[0]
        t = np.concatenate(parts_t) if len(parts_t) > 1 else parts_t[0]
        v = np.concatenate(parts_v) if len(parts_v) > 1 else parts_v[0]
        order = np.argsort(rid, kind="stable")
        counts = np.bincount(rid, minlength=R)
        offsets = np.empty(R + 1, np.int64)
        offsets[0] = 0
        np.cumsum(counts, out=offsets[1:])
        return t[order], v[order], offsets

    # -- seal/flush path --

    def block_starts(self) -> list[int]:
        with self._lock:
            return sorted(self._logs)

    def points_in(self, block_start: int) -> int:
        log = self._logs.get(block_start)
        return log.n if log else 0

    def _seal_sorted(self, block_start: int, drop: bool):
        """Locked extract + the ONE sort/dedup definition both seal
        layouts share: stable (series, time) sort, same-timestamp dedupe
        keeping the LAST append.  Returns (sidx, times, vbits,
        raw_count, fill_ratio) or None for an absent/empty window."""
        with self._lock:
            log = self._logs.get(block_start)
            if log is None or log.n == 0:
                return None
            raw_count = log.n
            sidx, times, vbits = (a.copy() for a in log.view())
            fill = log.fill_ratio() if hasattr(log, "fill_ratio") else 1.0
            if drop:
                del self._logs[block_start]
                log.release()
        order = np.lexsort((np.arange(len(sidx)), times, sidx))
        sidx, times, vbits = sidx[order], times[order], vbits[order]
        keep = np.ones(len(sidx), bool)
        if len(sidx) > 1:
            same = (sidx[1:] == sidx[:-1]) & (times[1:] == times[:-1])
            keep[:-1] = ~same
        return sidx[keep], times[keep], vbits[keep], raw_count, fill

    def seal(self, block_start: int, drop: bool = True) -> SealedWindow | None:
        """Group one block window into a padded batch for device encode.

        Stable-sorts by (series, time), dedupes last-write-wins, pads to the
        max points of any series in the window.
        """
        ext = self._seal_sorted(block_start, drop)
        if ext is None:
            return None
        sidx, times, vbits, raw_count, _fill = ext

        uniq, counts = np.unique(sidx, return_counts=True)
        B, T = len(uniq), int(counts.max())
        out_t = np.zeros((B, T), np.int64)
        out_v = np.zeros((B, T), np.uint64)
        row = np.repeat(np.arange(B), counts)
        col = np.arange(len(sidx)) - np.repeat(
            np.concatenate([[0], np.cumsum(counts)[:-1]]), counts
        )
        out_t[row, col] = times
        out_v[row, col] = vbits
        # pad timestamps past n_points monotonically so the encoder's
        # masked lanes still see sane deltas
        pad_mask = np.arange(T)[None, :] >= counts[:, None]
        out_t = np.where(pad_mask, out_t.max(axis=1, keepdims=True), out_t)
        return SealedWindow(
            block_start=block_start,
            series_indices=uniq.astype(np.int32),
            times=out_t,
            value_bits=out_v,
            n_points=counts.astype(np.int32),
            starts=np.full(B, block_start, dtype=np.int64),
            raw_count=raw_count,
        )

    def seal_csr(self, block_start: int,
                 drop: bool = True) -> RaggedSealedWindow | None:
        """Seal one block window to the RAGGED layout: same stable sort
        by (series, time) + last-write-wins dedup as seal(), but the
        output stays a CSR — no rectangular scatter, no padding, so a
        window where one series wrote 10k points and a million wrote one
        costs O(samples), not O(series x 10k).  The length-bucketed
        ragged encode (hostpath.encode_blocks_ragged) consumes this
        directly and produces byte-identical streams to the padded
        path."""
        from m3_tpu.utils.instrument import default_registry

        ext = self._seal_sorted(block_start, drop)
        if ext is None:
            return None
        sidx, times, vbits, raw_count, fill = ext
        # page-occupancy telemetry: how much of the window's page
        # allocation held real rows at seal time (padding-waste measure)
        default_registry().root_scope("storage").subscope(
            "page_pool").observe("page_fill", fill)
        uniq, counts = np.unique(sidx, return_counts=True)
        offsets = np.empty(len(uniq) + 1, np.int64)
        offsets[0] = 0
        np.cumsum(counts, out=offsets[1:])
        return RaggedSealedWindow(
            block_start=block_start,
            series_indices=uniq.astype(np.int32),
            times=times,
            value_bits=vbits,
            offsets=offsets,
            raw_count=raw_count,
        )

    def drop_window(self, block_start: int) -> None:
        with self._lock:
            log = self._logs.pop(block_start, None)
            if log is not None:
                log.release()

    def drop_window_prefix(self, block_start: int, n: int) -> None:
        """Drop the first n appended rows of a window — the rows a seal
        covered — KEEPING anything appended concurrently after the seal
        (they flush with the next volume instead of vanishing)."""
        with self._lock:
            log = self._logs.get(block_start)
            if log is None:
                return
            if log.n <= n:
                del self._logs[block_start]
                log.release()
                return
            if hasattr(log, "drop_prefix"):
                # paged log: advance the head, free covered pages — no
                # suffix copy under the shard lock
                log.drop_prefix(n)
                return
            # bulk copy the surviving suffix: this runs under the shard
            # lock, so a per-row python loop would stall every writer
            rest = _ColumnLog()
            m = log.n - n
            cap = max(_GROW, m)
            rest.sidx = np.empty(cap, dtype=np.int32)
            rest.times = np.empty(cap, dtype=np.int64)
            rest.vbits = np.empty(cap, dtype=np.uint64)
            rest.sidx[:m] = log.sidx[n:log.n]
            rest.times[:m] = log.times[n:log.n]
            rest.vbits[:m] = log.vbits[n:log.n]
            rest.n = m
            self._logs[block_start] = rest

    def expire_before(self, cutoff_block_start: int) -> int:
        with self._lock:
            dropped = 0
            for bs in list(self._logs):
                if bs < cutoff_block_start:
                    log = self._logs.pop(bs)
                    dropped += log.n
                    log.release()
            return dropped

    @property
    def n_series(self) -> int:
        return len(self.series_ids)
