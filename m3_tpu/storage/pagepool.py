"""Paged columnar memory for the ingest buffer (ROADMAP #3).

The seed `_ColumnLog` keeps one grow-array triple per block window:
growth doubles (up to 2x overshoot per window), a window drop frees
nothing until the arrays die, and `drop_window_prefix` COPIES the whole
surviving suffix under the shard lock at every flush.  Following
PAPERS.md "Ragged Paged Attention" (fixed pages, ragged index vectors),
this module replaces the grow-arrays with a shared pool of FIXED-SIZE
columnar pages:

- ``PagePool`` hands out pages cut from arena slabs (slabs are never
  resized, so page views stay stable); freed pages go to a free list
  and are reused before the arena grows; a free list deeper than
  ``max_free_pages`` releases whole all-free slabs back to the OS —
  counted as evictions on the saturation plane.
- ``PagedColumnLog`` is the `_ColumnLog` twin backed by a page list +
  a head offset: appends fill the tail page, bulk appends fill pages
  slab-assign by slab-assign, and ``drop_prefix`` just advances the
  head and frees fully-covered pages — O(pages freed), no copy under
  the shard lock.

Saturation-plane discipline (m3lint ``inv-pagepool-gauge``): every
``PagePool(...)`` construction site must call ``monitor_pool`` in the
same scope — pools feed the aggregate ``queue_*{queue=page_pool}``
gauges refreshed by the PR-11 snapshot hook, so occupancy and eviction
are dashboards, not mysteries.

``M3_TPU_PAGED=0`` pins the seed grow-array `_ColumnLog` and the seed
per-series finalize bodies everywhere (bisection hatch, the
``M3_TPU_PIPELINE=0`` discipline).
"""

from __future__ import annotations

import os
import threading
import weakref

import numpy as np

from m3_tpu.utils.instrument import monitor_queue, register_snapshot_hook

PAGE_ROWS = 1024          # rows per page (sidx i32 + times i64 + vbits u64)
_SLAB_PAGES = 64          # pages allocated per arena slab
_BYTES_PER_ROW = 4 + 8 + 8


def active() -> bool:
    """The M3_TPU_PAGED hatch: unset/1 = paged columnar memory + ragged
    finalize, 0 = the seed grow-array/per-series-concatenate bodies."""
    return os.environ.get("M3_TPU_PAGED", "1") != "0"


class _Slab:
    __slots__ = ("sidx", "times", "vbits", "free_count")

    def __init__(self) -> None:
        n = _SLAB_PAGES * PAGE_ROWS
        self.sidx = np.empty(n, np.int32)
        self.times = np.empty(n, np.int64)
        self.vbits = np.empty(n, np.uint64)
        self.free_count = 0  # pages of this slab currently on the free list


class PagePool:
    """Fixed-size columnar page allocator shared by one shard's window
    logs.  Thread safety: allocation/free take the pool's own lock (the
    shard buffer lock already serializes its callers; the pool lock
    keeps the pool safe for any future cross-window sharing and for the
    snapshot hook reading occupancy from scrape threads)."""

    def __init__(self, max_free_pages: int = 4 * _SLAB_PAGES):
        self._lock = threading.Lock()
        self._slabs: dict[int, _Slab] = {}
        self._next_slab = 0
        self._free: list[int] = []
        self.max_free_pages = max_free_pages
        self.pages_in_use = 0
        self.evicted_pages = 0  # pages released back to the OS

    # page id encodes (slab, page-within-slab)

    def alloc(self) -> int:
        with self._lock:
            if self._free:
                pid = self._free.pop()
                self._slabs[pid // _SLAB_PAGES].free_count -= 1
            else:
                sid = self._next_slab
                self._next_slab += 1
                slab = self._slabs[sid] = _Slab()
                base = sid * _SLAB_PAGES
                self._free.extend(range(base + _SLAB_PAGES - 1, base, -1))
                slab.free_count = _SLAB_PAGES - 1
                pid = base
            self.pages_in_use += 1
            return pid

    def free(self, pages: list[int]) -> None:
        if not pages:
            return
        with self._lock:
            for pid in pages:
                self._free.append(pid)
                self._slabs[pid // _SLAB_PAGES].free_count += 1
            self.pages_in_use -= len(pages)
            if len(self._free) > self.max_free_pages:
                self._evict_locked()

    def _evict_locked(self) -> None:
        """Release whole all-free slabs until the free list is back under
        bound (arena shrink — the pool's eviction story; in-use pages are
        never touched)."""
        doomed = [sid for sid, slab in self._slabs.items()
                  if slab.free_count == _SLAB_PAGES]
        for sid in doomed:
            if len(self._free) <= self.max_free_pages:
                break
            base = sid * _SLAB_PAGES
            self._free = [p for p in self._free
                          if not base <= p < base + _SLAB_PAGES]
            del self._slabs[sid]
            self.evicted_pages += _SLAB_PAGES

    def columns(self, pid: int):
        """(sidx, times, vbits) views of one page — stable for the page's
        lifetime (slabs never resize)."""
        slab = self._slabs[pid // _SLAB_PAGES]
        off = (pid % _SLAB_PAGES) * PAGE_ROWS
        end = off + PAGE_ROWS
        return (slab.sidx[off:end], slab.times[off:end],
                slab.vbits[off:end])

    @property
    def total_pages(self) -> int:
        return len(self._slabs) * _SLAB_PAGES

    @property
    def resident_bytes(self) -> int:
        return self.total_pages * PAGE_ROWS * _BYTES_PER_ROW


class PagedColumnLog:
    """`_ColumnLog` twin over pool pages: logical row i lives at
    physical offset head+i of the page list."""

    __slots__ = ("pool", "pages", "head", "n", "_view_cache")

    def __init__(self, pool: PagePool) -> None:
        self.pool = pool
        self.pages: list[int] = []
        self.head = 0  # physical offset of logical row 0 in pages[0]
        self.n = 0
        self._view_cache = None  # (n, head, sidx, times, vbits)

    def _phys_end(self) -> int:
        return self.head + self.n

    def append(self, sidx: int, t_ns: int, vbits: int) -> None:
        end = self._phys_end()
        if end == len(self.pages) * PAGE_ROWS:
            self.pages.append(self.pool.alloc())
        ps, pt, pv = self.pool.columns(self.pages[end // PAGE_ROWS])
        off = end % PAGE_ROWS
        ps[off] = sidx
        pt[off] = t_ns
        pv[off] = vbits
        self.n += 1

    def extend(self, sidx: np.ndarray, t_ns: np.ndarray,
               vbits: np.ndarray) -> None:
        """Bulk append filling pages slab-assign by slab-assign; row
        order is preserved so seal-time last-write-wins conflict
        resolution is unchanged (the `_ColumnLog.extend` contract)."""
        m = len(sidx)
        end = self._phys_end()
        need_pages = -(-(end + m) // PAGE_ROWS)
        while len(self.pages) < need_pages:
            self.pages.append(self.pool.alloc())
        done = 0
        while done < m:
            pos = end + done
            pid = self.pages[pos // PAGE_ROWS]
            off = pos % PAGE_ROWS
            take = min(PAGE_ROWS - off, m - done)
            ps, pt, pv = self.pool.columns(pid)
            ps[off:off + take] = sidx[done:done + take]
            pt[off:off + take] = t_ns[done:done + take]
            pv[off:off + take] = vbits[done:done + take]
            done += take
        self.n += m

    def view(self):
        """Contiguous (sidx, times, vbits) copies of the logical rows.
        Cached by (n, head): steady-state reads between writes pay the
        materialization once; any append or prefix drop invalidates by
        construction (n/head change)."""
        cached = self._view_cache
        if cached is not None and cached[0] == self.n \
                and cached[1] == self.head:
            return cached[2], cached[3], cached[4]
        sidx = np.empty(self.n, np.int32)
        times = np.empty(self.n, np.int64)
        vbits = np.empty(self.n, np.uint64)
        done = 0
        while done < self.n:
            pos = self.head + done
            ps, pt, pv = self.pool.columns(self.pages[pos // PAGE_ROWS])
            off = pos % PAGE_ROWS
            take = min(PAGE_ROWS - off, self.n - done)
            sidx[done:done + take] = ps[off:off + take]
            times[done:done + take] = pt[off:off + take]
            vbits[done:done + take] = pv[off:off + take]
            done += take
        self._view_cache = (self.n, self.head, sidx, times, vbits)
        return sidx, times, vbits

    def drop_prefix(self, k: int) -> None:
        """Drop the first k logical rows by advancing the head and
        freeing fully-covered pages — O(pages freed), vs the seed
        path's full suffix copy under the shard lock."""
        k = min(k, self.n)
        self.head += k
        self.n -= k
        # (n, head) is NOT unique over the log's lifetime once a prefix
        # drop has run (a refill can land on a previously-cached pair
        # and serve pre-flush rows — lost-write class): invalidate
        self._view_cache = None
        full = self.head // PAGE_ROWS
        if full:
            self.pool.free(self.pages[:full])
            del self.pages[:full]
            self.head -= full * PAGE_ROWS
        if self.n == 0 and self.pages:
            self.pool.free(self.pages)
            self.pages = []
            self.head = 0

    def release(self) -> None:
        """Return every page to the pool (window drop/expiry)."""
        if self.pages:
            self.pool.free(self.pages)
            self.pages = []
        self.head = 0
        self.n = 0
        self._view_cache = None

    def fill_ratio(self) -> float:
        cap = len(self.pages) * PAGE_ROWS
        return (self.head + self.n) / cap if cap else 1.0


# ---------------------------------------------------------------------------
# saturation-plane registration (PR-11 snapshot-hook seam)
# ---------------------------------------------------------------------------

_pools_lock = threading.Lock()
_pools: "weakref.WeakSet[PagePool]" = weakref.WeakSet()


def monitor_pool(pool: PagePool) -> PagePool:
    """Register a pool with the aggregate saturation gauges.  Every
    ``PagePool(...)`` construction site must call this in the same
    scope (m3lint ``inv-pagepool-gauge``) — the aggregate keeps the
    gauge label set bounded while per-shard pools come and go."""
    with _pools_lock:
        _pools.add(pool)
    return pool


def _aggregate():
    used = total = evicted = bytes_ = 0
    with _pools_lock:
        pools = list(_pools)
    for p in pools:
        used += p.pages_in_use
        total += p.total_pages
        evicted += p.evicted_pages
        bytes_ += p.resident_bytes
    return used, total, evicted, bytes_


# ONE module-level registration covers every pool (depth = pages in use,
# capacity = pages resident, drops = pages evicted back to the OS); the
# byte figure rides a gauge from the snapshot hook below. The monitor
# refresh evaluates depth_fn FIRST (instrument._refresh_queue_monitors),
# so depth computes the aggregate once per snapshot and the other two
# callables read the memo instead of re-walking every pool.
_agg_memo = [(0, 0, 0, 0)]


def _agg_fresh() -> int:
    _agg_memo[0] = _aggregate()
    return _agg_memo[0][0]


monitor_queue("page_pool", _agg_fresh,
              capacity=lambda: _agg_memo[0][1],
              drops_fn=lambda: _agg_memo[0][2])


def _snapshot_hook(registry) -> None:
    # fresh walk (the monitor memo only refreshes for the default
    # registry's snapshots)
    _used, _total, _evicted, nbytes = _aggregate()
    registry.root_scope("storage").subscope("page_pool").gauge(
        "resident_bytes", float(nbytes))


register_snapshot_hook(_snapshot_hook)
