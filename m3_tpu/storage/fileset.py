"""Immutable fileset files.

File-set parity with the reference's per-(namespace, shard, blockstart,
volume) layout — info/data/index/summaries/bloomfilter/digest/checkpoint
files (suffix inventory /root/reference/src/dbnode/persist/fs/fs.go:26-56),
with the checkpoint written last so partial flushes are detectable
(SURVEY.md §5 checkpoint/resume). Formats are this framework's own compact
binary encodings, not the reference msgpack codec.

Layout on disk:
  <root>/<namespace>/<shard>/fileset-<blockstart>-<volume>-<suffix>.db

  info:       JSON header (block_start, block_size, volume, counts)
  data:       concatenated per-series M3TSZ streams
  index:      sorted entries: u32 id_len + id, u32 tags_len + tags,
              u64 offset, u64 length  (offset/length into data)
  summaries:  every Nth index entry: u32 id_len + id, u64 index_offset
  bloom:      u32 n_hashes, u64 n_bits, bitset bytes (murmur3 k-hash)
  digest:     JSON of adler32 digests of each file
  checkpoint: adler32 of the digest file; existence == fileset complete
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from bisect import bisect_left
from dataclasses import dataclass

from m3_tpu.utils import faults
from m3_tpu.utils.hash import murmur3_32

SUFFIXES = ("info", "data", "index", "summaries", "bloom", "offsets",
            "digest", "checkpoint")
_SUMMARY_EVERY = 32


def fileset_path(root: str, namespace: str, shard: int, block_start: int, volume: int,
                 suffix: str) -> str:
    return os.path.join(
        root, namespace, str(shard), f"fileset-{block_start}-{volume}-{suffix}.db"
    )


class BloomFilter:
    def __init__(self, n_items: int, bits_per_item: int = 10):
        self.n_bits = max(64, n_items * bits_per_item)
        self.n_hashes = 7
        self.bits = bytearray((self.n_bits + 7) // 8)

    def _positions(self, key: bytes):
        h1 = murmur3_32(key, 0)
        h2 = murmur3_32(key, 0x9747B28C)
        for i in range(self.n_hashes):
            yield (h1 + i * h2) % self.n_bits

    def add(self, key: bytes) -> None:
        for p in self._positions(key):
            self.bits[p >> 3] |= 1 << (p & 7)

    def may_contain(self, key: bytes) -> bool:
        return all(self.bits[p >> 3] & (1 << (p & 7)) for p in self._positions(key))

    def to_bytes(self) -> bytes:
        return struct.pack(">IQ", self.n_hashes, self.n_bits) + bytes(self.bits)

    @classmethod
    def from_bytes(cls, data: bytes) -> "BloomFilter":
        n_hashes, n_bits = struct.unpack_from(">IQ", data, 0)
        bf = cls.__new__(cls)
        bf.n_hashes = n_hashes
        bf.n_bits = n_bits
        bf.bits = bytearray(data[12:])
        return bf


@dataclass
class IndexEntry:
    series_id: bytes
    encoded_tags: bytes
    offset: int
    length: int


class FilesetWriter:
    """Writes one complete fileset; checkpoint file lands last.

    Crash safety: every component file is written ATOMICALLY — temp file,
    fsync, `os.replace` — so a kill at any byte offset (see
    utils/faults.py torn writes) leaves either no file or a complete one,
    never a short/garbage file under the final name; the checkpoint (also
    atomic, written after everything else is fsynced) is what marks the
    volume complete, and FilesetReader verifies the digest chain on open.
    Fault points: fileset.persist (per file), fileset.write (torn bytes),
    fileset.checkpoint."""

    def __init__(self, root: str, namespace: str, shard: int, block_start: int,
                 block_size_ns: int, volume: int = 0):
        self.root = root
        self.namespace = namespace
        self.shard = shard
        self.block_start = block_start
        self.block_size_ns = block_size_ns
        self.volume = volume
        self._entries: list[IndexEntry] = []
        self._data = bytearray()

    def write_series(self, series_id: bytes, encoded_tags: bytes, stream: bytes) -> None:
        self._entries.append(
            IndexEntry(series_id, encoded_tags, len(self._data), len(stream))
        )
        self._data += stream

    def _path(self, suffix: str) -> str:
        return fileset_path(
            self.root, self.namespace, self.shard, self.block_start, self.volume, suffix
        )

    def _write_atomic(self, suffix: str, payload: bytes) -> None:
        """temp + fsync + rename: the final name only ever points at a
        complete, durable file (a crash leaves at most a .tmp, which
        list_filesets/bootstrap never look at)."""
        from m3_tpu.utils.instrument import default_registry

        faults.check("fileset.persist", suffix=suffix)
        path = self._path(suffix)
        tmp = path + ".tmp"
        with default_registry().root_scope("fileset").histogram(
                "persist_seconds"):
            with open(tmp, "wb") as f:
                faults.torn_write(f, payload, "fileset.write")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def close(self) -> dict:
        os.makedirs(os.path.dirname(self._path("info")), exist_ok=True)
        self._entries.sort(key=lambda e: e.series_id)

        index = bytearray()
        summaries = bytearray()
        offsets = bytearray()  # per-entry byte offset into the index file
        bloom = BloomFilter(max(1, len(self._entries)))
        for i, e in enumerate(self._entries):
            if i % _SUMMARY_EVERY == 0:
                summaries += struct.pack(">I", len(e.series_id)) + e.series_id
                summaries += struct.pack(">Q", len(index))
            offsets += struct.pack("<Q", len(index))
            index += struct.pack(">I", len(e.series_id)) + e.series_id
            index += struct.pack(">I", len(e.encoded_tags)) + e.encoded_tags
            index += struct.pack(">QQ", e.offset, e.length)
            bloom.add(e.series_id)

        info = json.dumps(
            {
                "block_start": self.block_start,
                "block_size_ns": self.block_size_ns,
                "volume": self.volume,
                "n_series": len(self._entries),
                "data_length": len(self._data),
            }
        ).encode()

        files = {
            "info": info,
            "data": bytes(self._data),
            "index": bytes(index),
            "summaries": bytes(summaries),
            "bloom": bloom.to_bytes(),
            "offsets": bytes(offsets),
        }
        digests = {}
        for suffix, payload in files.items():
            self._write_atomic(suffix, payload)
            digests[suffix] = zlib.adler32(payload)
        digest_payload = json.dumps(digests).encode()
        self._write_atomic("digest", digest_payload)
        # checkpoint last (after everything else is fsynced): its presence
        # marks the fileset complete even across power loss
        faults.check("fileset.checkpoint")
        self._write_atomic("checkpoint",
                           struct.pack(">I", zlib.adler32(digest_payload)))
        # fsync the directory so the new names themselves are durable
        dfd = os.open(os.path.dirname(self._path("info")), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return digests


class FilesetReader:
    """Reads a complete fileset WITHOUT materializing the index.

    The round-1 reader parsed every index entry into Python lists at open —
    wrong for multi-million-series shards. This reader mmaps the index and
    data files and looks series up the way the reference seeker does
    (/root/reference/src/dbnode/persist/fs/seek.go): bloom gate ->
    summaries binary search -> short scan of at most _SUMMARY_EVERY
    entries in the mapped index -> data slice. Ordinal access uses the
    per-entry offsets file (one u64 per series; built by a single scan for
    legacy sets without one)."""

    def __init__(self, root: str, namespace: str, shard: int, block_start: int,
                 volume: int = 0, verify: bool = True):
        import mmap as _mmap

        import numpy as np

        self.root = root
        self.namespace = namespace
        self.shard = shard
        self.block_start = block_start
        self.volume = volume

        if not os.path.exists(self._path("checkpoint")):
            raise FileNotFoundError(
                f"fileset incomplete (no checkpoint): shard={shard} bs={block_start}"
            )
        with open(self._path("info"), "rb") as f:
            self.info = json.loads(f.read())
        self.block_size_ns = self.info["block_size_ns"]
        with open(self._path("digest"), "rb") as f:
            digest_payload = f.read()
        if verify:
            with open(self._path("checkpoint"), "rb") as f:
                (want,) = struct.unpack(">I", f.read(4))
            if zlib.adler32(digest_payload) != want:
                raise ValueError("digest file corrupt (checkpoint mismatch)")
            digests = json.loads(digest_payload)
            for suffix, want_digest in digests.items():
                with open(self._path(suffix), "rb") as f:
                    if zlib.adler32(f.read()) != want_digest:
                        raise ValueError(f"{suffix} file corrupt (digest mismatch)")

        with open(self._path("bloom"), "rb") as f:
            self.bloom = BloomFilter.from_bytes(f.read())

        def _map(suffix: str):
            f = open(self._path(suffix), "rb")
            try:
                if os.fstat(f.fileno()).st_size == 0:
                    return f, b""
                return f, _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
            except Exception:
                f.close()
                raise

        self._index_file, self._index = _map("index")
        self._data_file, self._data = _map("data")
        # summaries: small (1/_SUMMARY_EVERY of entries) — parsed eagerly
        with open(self._path("summaries"), "rb") as f:
            raw = f.read()
        self._summary_ids: list[bytes] = []
        self._summary_offs: list[int] = []
        off = 0
        while off < len(raw):
            (idlen,) = struct.unpack_from(">I", raw, off)
            off += 4
            self._summary_ids.append(raw[off : off + idlen])
            off += idlen
            (ixoff,) = struct.unpack_from(">Q", raw, off)
            off += 8
            self._summary_offs.append(ixoff)
        # per-entry index offsets: mmap'd numpy view when the file exists,
        # else built lazily by one scan (legacy filesets)
        self._offsets = None
        if os.path.exists(self._path("offsets")):
            with open(self._path("offsets"), "rb") as f:
                raw_off = f.read()
            if raw_off:
                self._offsets = np.frombuffer(raw_off, dtype="<u8")

    def _path(self, suffix: str) -> str:
        return fileset_path(
            self.root, self.namespace, self.shard, self.block_start, self.volume, suffix
        )

    @property
    def n_series(self) -> int:
        return int(self.info["n_series"])

    def _parse_entry(self, off: int):
        """(series_id, tags, data_off, data_len, next_off) at index
        offset off."""
        ix = self._index
        (idlen,) = struct.unpack_from(">I", ix, off)
        off += 4
        sid = bytes(ix[off : off + idlen])
        off += idlen
        (tlen,) = struct.unpack_from(">I", ix, off)
        off += 4
        tags = bytes(ix[off : off + tlen])
        off += tlen
        data_off, data_len = struct.unpack_from(">QQ", ix, off)
        return sid, tags, data_off, data_len, off + 16

    def _entry_offsets(self):
        import numpy as np

        if self._offsets is None:  # legacy fileset: one sequential scan
            offs = np.empty(self.n_series, np.uint64)
            off = 0
            for i in range(self.n_series):
                offs[i] = off
                (idlen,) = struct.unpack_from(">I", self._index, off)
                (tlen,) = struct.unpack_from(">I", self._index, off + 4 + idlen)
                off += 4 + idlen + 4 + tlen + 16
            self._offsets = offs
        return self._offsets

    def _find(self, series_id: bytes):
        """Index offset of the entry for series_id, or None — summaries
        bisect then a bounded scan."""
        if not self._summary_ids:
            return None
        si = bisect_left(self._summary_ids, series_id)
        if si == len(self._summary_ids) or self._summary_ids[si] != series_id:
            si -= 1  # scan forward from the preceding summary
        if si < 0:
            return None
        off = self._summary_offs[si]
        end = len(self._index)
        for _ in range(_SUMMARY_EVERY):
            if off >= end:
                return None
            sid, _tags, _do, _dl, nxt = self._parse_entry(off)
            if sid == series_id:
                return off
            if sid > series_id:
                return None
            off = nxt
        return None

    def series_ids(self) -> list[bytes]:
        offs = self._entry_offsets()
        return [self._parse_entry(int(o))[0] for o in offs]

    def read(self, series_id: bytes) -> bytes | None:
        """Stream bytes for a series, or None. Bloom gate, then seek."""
        if not self.bloom.may_contain(series_id):
            return None
        off = self._find(series_id)
        if off is None:
            return None
        _sid, _tags, data_off, data_len, _nxt = self._parse_entry(off)
        return bytes(self._data[data_off : data_off + data_len])

    def read_many(self, series_ids: list[bytes]) -> list[bytes | None]:
        """Streams for MANY series in one pass — the batched-fetch half of
        the fused read path. Large requests merge-join the sorted request
        against the sorted index in ONE sequential walk (each entry parsed
        at most once, no per-series bloom probe or bisect); small requests
        keep the per-series seek (a full walk would touch every entry for
        a handful of ids). Returns streams aligned to the input, None for
        absent series."""
        out: list[bytes | None] = [None] * len(series_ids)
        if not series_ids or not self._summary_ids:
            return out
        # walk cost ~ n_series parses; per-id cost ~ bloom + up to
        # _SUMMARY_EVERY/2 parses each — walk only when it wins
        if len(series_ids) * (_SUMMARY_EVERY // 2) < self.n_series:
            for i, sid in enumerate(series_ids):
                out[i] = self.read(sid)
            return out
        order = sorted(range(len(series_ids)), key=lambda i: series_ids[i])
        data = self._data
        off, end = 0, len(self._index)
        k, n_req = 0, len(order)
        while k < n_req and off < end:
            sid, _tags, data_off, data_len, nxt = self._parse_entry(off)
            while series_ids[order[k]] < sid:
                k += 1  # requested id absent from this fileset
                if k == n_req:
                    return out
            if series_ids[order[k]] == sid:
                stream = bytes(data[data_off : data_off + data_len])
                while k < n_req and series_ids[order[k]] == sid:
                    out[order[k]] = stream  # duplicate requests share it
                    k += 1
            off = nxt
        return out

    # -- columnar gather (the pipelined read path's fetch rung) --

    def _row_index(self):
        """id -> row dict plus per-row (data_off, data_len) columns,
        built by ONE walk of the mapped index and cached on the
        immutable reader (like series_checksums). The legacy per-query
        merge-join walk re-parses every entry per read_many call; the
        pipelined dataflow's gather rung pays the walk once per volume
        and serves every later query with dict lookups + data slices.
        Concurrent first builds race benignly (idempotent, last wins)."""
        import numpy as np

        cached = getattr(self, "_rows", None)
        if cached is not None:
            return cached
        n = self.n_series
        rows: dict[bytes, int] = {}
        data_off = np.empty(n, np.int64)
        data_len = np.empty(n, np.int64)
        off = 0
        for i in range(n):
            sid, _tags, d_off, d_len, off = self._parse_entry(off)
            rows[sid] = i
            data_off[i] = d_off
            data_len[i] = d_len
        self._rows = (rows, data_off, data_len)
        return self._rows

    def gather_many(self, series_ids: list[bytes]) -> list[bytes | None]:
        """`read_many` semantics served from the cached row index: one
        dict lookup + one data slice per requested series (duplicates
        share the stream object), None for absent ids. Same results as
        read_many — the pipelined gather rung, tested for parity."""
        rows, data_off, data_len = self._row_index()
        data = self._data
        out: list[bytes | None] = [None] * len(series_ids)
        memo: dict[bytes, bytes] = {}
        for k, sid in enumerate(series_ids):
            hit = memo.get(sid)
            if hit is None:
                i = rows.get(sid)
                if i is None:
                    continue
                o = int(data_off[i])
                hit = memo[sid] = bytes(data[o:o + int(data_len[i])])
            out[k] = hit
        return out

    def read_at(self, i: int) -> tuple[bytes, bytes, bytes]:
        """(id, encoded_tags, stream) for index position i."""
        off = int(self._entry_offsets()[i])
        sid, tags, data_off, data_len, _ = self._parse_entry(off)
        return sid, tags, bytes(self._data[data_off : data_off + data_len])

    def entry_at(self, i: int) -> tuple[bytes, bytes]:
        """(id, encoded_tags) without touching the data file."""
        off = int(self._entry_offsets()[i])
        sid, tags, _do, _dl, _ = self._parse_entry(off)
        return sid, tags

    def tags_of(self, series_id: bytes) -> bytes | None:
        off = self._find(series_id)
        if off is None:
            return None
        return self._parse_entry(off)[1]

    # -- repair support: per-series checksums + rollup digest --

    def series_checksums(self):
        """uint64 adler32 of every series' stream, in index (sorted-id)
        order — the per-series halves of the repair comparison. One pass
        over the mapped index/data; cached, because a volume is immutable
        once its checkpoint exists."""
        import numpy as np

        cached = getattr(self, "_series_checksums", None)
        if cached is not None:
            return cached
        offs = self._entry_offsets()
        out = np.empty(len(offs), np.uint64)
        data = self._data
        for i, o in enumerate(offs):
            _sid, _tags, data_off, data_len, _ = self._parse_entry(int(o))
            out[i] = zlib.adler32(data[data_off:data_off + data_len])
        out.flags.writeable = False
        self._series_checksums = out
        return out

    def rollup_digest(self) -> int:
        """ONE aggregate checksum for the whole block volume: adler32 over
        the vector of sorted per-series adler32s (little-endian u64) plus
        the series count. Content-addressed — two replicas holding the
        same series/streams produce the same digest regardless of volume
        number — so an in-sync block costs O(1) on the repair wire instead
        of one metadata row per series."""
        cached = getattr(self, "_rollup_digest", None)
        if cached is not None:
            return cached
        sums = self.series_checksums()
        digest = zlib.adler32(sums.tobytes(),
                              zlib.adler32(struct.pack("<Q", len(sums))))
        self._rollup_digest = digest
        return digest

    def close(self) -> None:
        for m in (self._index, self._data):
            if not isinstance(m, bytes):
                m.close()
        self._index_file.close()
        self._data_file.close()


def list_filesets(root: str, namespace: str, shard: int,
                  all_volumes: bool = False) -> list[tuple[int, int]]:
    """Complete (block_start, volume) pairs for a shard, ascending. By
    default only the max volume per block_start; all_volumes=True lists
    every complete volume (snapshot reclamation)."""
    d = os.path.join(root, namespace, str(shard))
    if not os.path.isdir(d):
        return []
    found: list[tuple[int, int]] = []
    for name in os.listdir(d):
        if not name.startswith("fileset-") or not name.endswith("-checkpoint.db"):
            continue
        parts = name[len("fileset-") : -len(".db")].split("-")
        if len(parts) != 3:
            continue
        found.append((int(parts[0]), int(parts[1])))
    if all_volumes:
        return sorted(found)
    best: dict[int, int] = {}
    for bs, vol in found:
        best[bs] = max(best.get(bs, -1), vol)
    return sorted(best.items())
