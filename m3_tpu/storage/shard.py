"""Shard: columnar buffer + immutable fileset volumes for one virtual shard.

Role parity with the reference dbShard (write/read orchestration, flush,
retention expiry — /root/reference/src/dbnode/storage/shard.go:869-896,
1085); the per-series object tree is replaced by the columnar ShardBuffer
and batched device encodes (SURVEY.md §7.2).
"""

from __future__ import annotations

import threading

import numpy as np

from m3_tpu.storage.buffer import ShardBuffer, merge_dedup
from m3_tpu.storage.fileset import FilesetReader, FilesetWriter, list_filesets
from m3_tpu.storage.options import DatabaseOptions, NamespaceOptions
from m3_tpu.utils import faults


class _FilesetReadGroup:
    """One (shard, block, volume) group of a pipelined batched read.

    ``gather()`` is the worker-safe leg: cache probe + columnar stream
    gather off the immutable reader — nothing thread-local, nothing
    mutated outside the lock-guarded BlockCache. ``consume()`` runs on
    the calling thread in submission order: querystats accounting, the
    ONE batched decode dispatch per group (the dispatch-economy
    contract), the cache fill, and the per-series parts append — every
    thread-local seam (query record, decode-rung counters, trace spans)
    stays on the query's own thread."""

    __slots__ = ("shard", "block_start", "reader", "series_ids", "parts")

    def __init__(self, shard: "Shard", block_start: int, reader,
                 series_ids: list[bytes], parts: list[list]):
        self.shard = shard
        self.block_start = block_start
        self.reader = reader
        self.series_ids = series_ids
        self.parts = parts

    def _cache(self):
        """The block cache, or None when it cannot serve (capacity 0):
        a disabled cache still charges key construction + a locked probe
        per group on the serial path — the pipelined gather skips the
        whole bookkeeping (misses-by-construction carry no information)."""
        cache = self.shard.cache
        if cache is None or getattr(cache, "capacity", 1) <= 0:
            return None
        return cache

    def gather(self):
        shard = self.shard
        cache = self._cache()
        if cache is None:
            return None, None, range(len(self.series_ids)), \
                self.reader.gather_many(self.series_ids)
        keys = [(shard.namespace, shard.shard_id, self.block_start,
                 self.reader.volume, sid) for sid in self.series_ids]
        cached = cache.get_many(keys)
        miss_idx = [i for i, hit in enumerate(cached) if hit is None]
        streams = (self.reader.gather_many(
            [self.series_ids[i] for i in miss_idx]) if miss_idx else [])
        return keys, cached, miss_idx, streams

    def consume(self, payload) -> None:
        from m3_tpu.encoding.m3tsz import hostpath
        from m3_tpu.utils import querystats

        keys, cached, miss_idx, streams = payload
        shard = self.shard
        parts = self.parts
        querystats.record(
            cache_hits=len(self.series_ids) - len(miss_idx),
            cache_misses=len(miss_idx))
        if cached is not None:
            for i, hit in enumerate(cached):
                if hit is not None and len(hit[0]):
                    parts[i].append(hit)
        if not miss_idx:
            return
        decoded = hostpath.decode_streams_batch(
            streams, shard.opts.write_time_unit, shard.opts.int_optimized)
        if keys is not None:  # negative results cached too
            shard.cache.put_many(
                [(keys[i], r) for i, r in zip(miss_idx, decoded)])
        for i, (ct, cv) in zip(miss_idx, decoded):
            if len(ct):
                parts[i].append((ct, cv))


class Shard:
    def __init__(
        self,
        shard_id: int,
        namespace: str,
        opts: NamespaceOptions,
        db_opts: DatabaseOptions,
        fs_root: str,
    ):
        self.shard_id = shard_id
        self.namespace = namespace
        self.opts = opts
        self.db_opts = db_opts
        self.fs_root = fs_root
        self.buffer = ShardBuffer(opts.retention.block_size_ns)
        self._filesets: dict[int, FilesetReader] = {}  # block_start -> reader
        # readers swapped out by flush/expire/repair: concurrent reads may
        # still hold them from their list() snapshot, so closing immediately
        # would fail those reads on a dead mmap. Each is closed only after
        # RETIRE_GRACE_S (far longer than any single-series decode), and the
        # list is lock-guarded because repair retires from RPC threads while
        # the tick thread drains.
        self._retired: list[tuple[float, FilesetReader]] = []
        self._retired_lock = threading.Lock()
        # serializes volume assignment + fileset swap between the tick
        # thread's flush/expire and repair running on RPC threads: without
        # it two maintenance passes can both write volume v+1 for the same
        # block (interleaved files, shared cache key for divergent data)
        self._maint_lock = threading.RLock()
        self.bootstrapped = False
        self.cache = None  # decoded-block LRU, set by the owning Database
        # fileset write pacing, set by the owning Database (runtime options)
        self.persist_limiter = None
        # per-window write sequence vs last-snapshotted sequence: lets the
        # snapshot loop skip windows with no new writes (dirty tracking);
        # guarded by _seq_lock (lost increments would mark dirty windows
        # clean and skip their snapshots)
        self._write_seq: dict[int, int] = {}
        self._snap_seq: dict[int, int] = {}
        self._seq_lock = threading.Lock()
        # warm/cold write split (reference series/buffer.go:77-147
        # WriteType + storage/coldflush.go): a write landing in a block
        # that already has a flushed volume is COLD — it must not drag
        # that block back into the warm flush path (which would decode+
        # merge+rewrite the volume inside the latency-sensitive warm
        # pass). Cold-dirty blocks flush separately as version-bumped
        # volumes.
        self.warm_writes = 0
        self.cold_writes = 0
        # monotone data-content version: bumped by every mutation a read
        # could observe (writes, flush/volume swaps, bootstrap, repair,
        # expiry). The device-resident hot tier (storage/hottier.py) keys
        # prepared query slabs on it — an unchanged version means an
        # identical fetch, so warm device pages can serve without a
        # rebuild. Guarded by _seq_lock (a lost bump would serve stale
        # pages, the one unacceptable failure mode).
        self.data_version = 0

    # -- write --

    def write(self, series_id: bytes, t_ns: int, value_bits: int,
              encoded_tags: bytes = b"") -> int:
        bs = self.opts.retention.block_start(t_ns)
        idx = self.buffer.write(series_id, t_ns, value_bits, encoded_tags)
        if bs in self._filesets:
            self.cold_writes += 1
        else:
            self.warm_writes += 1
        # seq bumps AFTER the point is in the buffer: a snapshot racing in
        # between re-snapshots next pass instead of marking the window
        # clean without the point
        with self._seq_lock:
            self._write_seq[bs] = self._write_seq.get(bs, 0) + 1
            self.data_version += 1
        return idx

    def write_many(self, series_ids: list[bytes], times: np.ndarray,
                   vbits: np.ndarray, tags_list: list[bytes]) -> None:
        """Bulk write: one buffer lock for the whole shard-local batch
        (ShardBuffer.write_many) and one warm/cold + write-seq update per
        touched window instead of per point."""
        self.buffer.write_many(series_ids, times, vbits, tags_list)
        bs = times - (times % self.opts.retention.block_size_ns)
        uniq, counts = np.unique(bs, return_counts=True)
        for w, c in zip(uniq.tolist(), counts.tolist()):
            if w in self._filesets:
                self.cold_writes += c
            else:
                self.warm_writes += c
        # seq bumps AFTER the points are in the buffer: a snapshot racing
        # in between re-snapshots next pass instead of marking the window
        # clean without the points (same rule as the per-point write)
        with self._seq_lock:
            for w, c in zip(uniq.tolist(), counts.tolist()):
                self._write_seq[w] = self._write_seq.get(w, 0) + c
            self.data_version += 1

    def bump_data_version(self) -> None:
        """Mark the shard's readable content changed (volume swaps from
        flush/bootstrap/repair, expiry) — hot-tier entries keyed on the
        old version stop matching."""
        with self._seq_lock:
            self.data_version += 1

    def write_seq(self, block_start: int) -> int:
        return self._write_seq.get(block_start, 0)

    def snapshotted_seq(self, block_start: int) -> int | None:
        return self._snap_seq.get(block_start)

    def mark_snapshotted(self, block_start: int, seq: int) -> None:
        self._snap_seq[block_start] = seq

    # -- read --

    def read(self, series_id: bytes, start_ns: int, end_ns: int):
        """Merged (times, value_bits) from flushed volumes + buffer."""
        from m3_tpu.encoding.m3tsz import hostpath

        parts_t, parts_v = [], []
        # snapshot: the tick thread swaps fileset volumes concurrently
        for bs, reader in list(self._filesets.items()):
            if bs + reader.block_size_ns <= start_ns or bs >= end_ns:
                continue
            # volume in the key: a read racing a flush may put() a decode of
            # the OLD volume after the swap; under a versioned key that
            # stale entry lands where no future read (which uses the new
            # reader's volume) will find it
            key = (self.namespace, self.shard_id, bs, reader.volume, series_id)
            cached = self.cache.get(key) if self.cache is not None else None
            if cached is not None:
                ct, cv = cached
                if len(ct):
                    parts_t.append(ct)
                    parts_v.append(cv)
                continue
            stream = reader.read(series_id)
            ct = np.empty(0, np.int64)
            cv = np.empty(0, np.uint64)
            if stream:
                ct, cv = hostpath.decode_stream(
                    stream, self.opts.write_time_unit,
                    self.opts.int_optimized,
                )
            if self.cache is not None:  # negative results cached too
                self.cache.put(key, (ct, cv))
            if len(ct):
                parts_t.append(ct)
                parts_v.append(cv)
        bt, bv = self.buffer.read(series_id, start_ns, end_ns)
        if len(bt):
            parts_t.append(bt)
            parts_v.append(bv)
        if not parts_t:
            return np.empty(0, np.int64), np.empty(0, np.uint64)
        # buffer parts were appended last, so last-write-wins keeps them
        return merge_dedup(
            np.concatenate(parts_t), np.concatenate(parts_v), start_ns, end_ns
        )

    def read_many(self, series_ids: list[bytes], start_ns: int, end_ns: int
                  ) -> list[tuple[np.ndarray, np.ndarray]]:
        """Batched read: ONE fused fetch+decode dispatch per (block,
        volume) group instead of one per series. Cache hits are served
        without entering the batch; the whole group's misses fill the
        decoded-block LRU in one pass. Identical results to per-series
        read() — parts accumulate in the same (filesets-then-buffer) order
        so last-write-wins resolution is unchanged.

        Default path is the PIPELINED dataflow (storage/pipeline.py):
        per-(block, volume) gather legs run on the executor pool up to
        depth-N ahead of the caller's decode rung, and the gather itself
        is the reader's cached columnar row index instead of a per-query
        merge-join walk. ``M3_TPU_PIPELINE=0`` pins this serial body —
        the seed behavior, kept verbatim for bisection."""
        from m3_tpu.storage import pipeline

        if pipeline.active():
            from m3_tpu.utils import querystats

            parts: list[list] = [[] for _ in series_ids]
            groups = self.plan_read_groups(series_ids, start_ns, end_ns,
                                           parts)
            stats = pipeline.run_stages(
                groups, lambda g: g.gather(), lambda g, p: g.consume(p))
            # overlap accounting reaches ?explain=analyze from THIS
            # entry too (the namespace's limit-chunked loop and direct
            # shard callers), not just the flattened namespace schedule
            querystats.record_pipeline(stats.items, stats.wall_s,
                                       stats.stages)
            from m3_tpu.storage import pagepool

            if pagepool.active():
                t, v, offs = self.finish_read_many(series_ids, parts,
                                                   start_ns, end_ns)
                return [(t[offs[i]:offs[i + 1]], v[offs[i]:offs[i + 1]])
                        for i in range(len(series_ids))]
            return [self.finish_read(sid, pl, start_ns, end_ns)
                    for sid, pl in zip(series_ids, parts)]
        return self._read_many_serial(series_ids, start_ns, end_ns)

    def plan_read_groups(self, series_ids: list[bytes], start_ns: int,
                         end_ns: int, parts: list[list]
                         ) -> "list[_FilesetReadGroup]":
        """One `_FilesetReadGroup` per (block, volume) reader overlapping
        the range — the schedulable unit of the pipelined read path.
        Planning snapshots `_filesets` on the calling thread (the tick
        thread swaps volumes concurrently; the retire grace keeps any
        captured reader alive for the whole read)."""
        groups = []
        for bs, reader in list(self._filesets.items()):
            if bs + reader.block_size_ns <= start_ns or bs >= end_ns:
                continue
            groups.append(_FilesetReadGroup(self, bs, reader, series_ids,
                                            parts))
        return groups

    def finish_read_many(self, series_ids: list[bytes], parts: list[list],
                         start_ns: int, end_ns: int):
        """Batched RAGGED finalize (ROADMAP #3): the per-series
        ``np.concatenate`` + ``merge_dedup`` pass in finish_read —
        profiled at ~15% of the sparse read path — becomes ONE buffer
        CSR gather (`ShardBuffer.read_many_csr`), one preallocated fill
        and one vectorized merge over every series at once
        (`ops.ragged.assemble_rows`).  Returns the (times, vbits,
        offsets) CSR aligned to `series_ids`; per-row results are
        element-identical to finish_read (same part order, same
        keep-last dedup, same range filter)."""
        from m3_tpu.ops import ragged

        if len(set(series_ids)) != len(series_ids):
            # duplicate ids: the CSR position map is one row per id —
            # take the per-series seed finalize (correctness over speed
            # on a shape no production caller emits)
            pairs = [self.finish_read(sid, list(pl), start_ns, end_ns)
                     for sid, pl in zip(series_ids, parts)]
            return ragged.pairs_to_csr(pairs)
        bt, bv, boffs = self.buffer.read_many_csr(series_ids, start_ns,
                                                  end_ns)
        if len(bt):
            # buffer leg LAST: last-write-wins keeps buffered points,
            # exactly the finish_read append order (parts lists are
            # owned by this read — appending in place, like finish_read)
            for i, pl in enumerate(parts):
                a, b = boffs[i], boffs[i + 1]
                if b > a:
                    pl.append((bt[a:b], bv[a:b]))
        return ragged.assemble_rows(parts, start_ns, end_ns)

    def finish_read(self, series_id: bytes, parts: list, start_ns: int,
                    end_ns: int) -> tuple[np.ndarray, np.ndarray]:
        """Per-series finalize: buffer leg LAST (last-write-wins keeps
        buffered points, same as the serial path), then one merge."""
        bt, bv = self.buffer.read(series_id, start_ns, end_ns)
        if len(bt):
            parts.append((bt, bv))
        if not parts:
            return np.empty(0, np.int64), np.empty(0, np.uint64)
        return merge_dedup(
            np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts]),
            start_ns, end_ns,
        )

    def _read_many_serial(self, series_ids: list[bytes], start_ns: int,
                          end_ns: int) -> list[tuple[np.ndarray, np.ndarray]]:
        from m3_tpu.encoding.m3tsz import hostpath

        n = len(series_ids)
        parts: list[list] = [[] for _ in range(n)]
        # snapshot: the tick thread swaps fileset volumes concurrently
        for bs, reader in list(self._filesets.items()):
            if bs + reader.block_size_ns <= start_ns or bs >= end_ns:
                continue
            keys = [(self.namespace, self.shard_id, bs, reader.volume, sid)
                    for sid in series_ids]
            cached = (self.cache.get_many(keys) if self.cache is not None
                      else [None] * n)
            miss_idx: list[int] = []
            for i, hit in enumerate(cached):
                if hit is not None:
                    ct, cv = hit
                    if len(ct):
                        parts[i].append((ct, cv))
                    continue
                miss_idx.append(i)
            from m3_tpu.utils import querystats

            querystats.record(cache_hits=n - len(miss_idx),
                              cache_misses=len(miss_idx))
            if not miss_idx:
                continue
            # batched fetch: one merge-join walk of the volume's index for
            # the whole miss set, then one batched decode of its streams
            streams = reader.read_many([series_ids[i] for i in miss_idx])
            decoded = hostpath.decode_streams_batch(
                streams, self.opts.write_time_unit, self.opts.int_optimized)
            if self.cache is not None:  # negative results cached too
                self.cache.put_many(
                    [(keys[i], r) for i, r in zip(miss_idx, decoded)])
            for i, (ct, cv) in zip(miss_idx, decoded):
                if len(ct):
                    parts[i].append((ct, cv))
        out = []
        for i, sid in enumerate(series_ids):
            bt, bv = self.buffer.read(sid, start_ns, end_ns)
            if len(bt):  # buffer last, so last-write-wins keeps it
                parts[i].append((bt, bv))
            if not parts[i]:
                out.append((np.empty(0, np.int64), np.empty(0, np.uint64)))
                continue
            out.append(merge_dedup(
                np.concatenate([p[0] for p in parts[i]]),
                np.concatenate([p[1] for p in parts[i]]),
                start_ns, end_ns,
            ))
        return out

    def series_ids(self) -> set[bytes]:
        ids = set(self.buffer.series_ids)
        for reader in self._filesets.values():
            ids.update(reader.series_ids())
        return ids

    # -- snapshots --

    def snapshot(self, block_start: int, snapshot_root: str,
                 snapshot_id: int) -> bool:
        """Write the window's CURRENT buffer contents as a snapshot fileset
        under snapshot_root (volume = snapshot_id, monotonic). The buffer
        keeps the data — snapshots exist so commitlogs can retire early and
        restarts recover in-flight blocks without replaying the whole WAL
        (the flush-model snapshot role, reference storage/README.md,
        persist/fs/snapshot_metadata_{read,write}.go)."""
        from m3_tpu.encoding.m3tsz import hostpath

        faults.check("shard.snapshot", shard=self.shard_id,
                     block_start=block_start)
        from m3_tpu.storage import pagepool

        if pagepool.active():
            # ragged seal + length-bucketed encode: no [B, max_T]
            # rectangle for the snapshot either (byte-identical streams)
            sealed = self.buffer.seal_csr(block_start, drop=False)
            if sealed is None:
                return False
            ids = [self.buffer.series_ids[i] for i in sealed.series_indices]
            tags = [self.buffer.series_tags[i]
                    for i in sealed.series_indices]
            try:
                streams = hostpath.encode_blocks_ragged(
                    sealed.times, sealed.value_bits, sealed.offsets,
                    np.full(sealed.n_series, block_start, np.int64),
                    self.opts.write_time_unit, self.opts.int_optimized,
                )
            except OverflowError:
                return False
        else:
            sealed = self.buffer.seal(block_start, drop=False)
            if sealed is None:
                return False
            ids = [self.buffer.series_ids[i] for i in sealed.series_indices]
            tags = [self.buffer.series_tags[i]
                    for i in sealed.series_indices]
            try:
                streams = hostpath.encode_blocks(
                    sealed.times, sealed.value_bits, sealed.starts,
                    sealed.n_points, self.opts.write_time_unit,
                    self.opts.int_optimized,
                )
            except OverflowError:
                return False
        writer = FilesetWriter(
            snapshot_root, self.namespace, self.shard_id, block_start,
            self.opts.retention.block_size_ns, snapshot_id,
        )
        for sid, stags, stream in zip(ids, tags, streams):
            self._pace_persist(len(stream))
            writer.write_series(sid, stags, stream)
        writer.close()
        return True

    # -- flush --

    def flushable_block_starts(self, now_ns: int) -> list[int]:
        """WARM flush candidates: buffered windows past buffer_past that
        have no volume yet. Windows with an existing volume are cold-dirty
        (see cold_dirty_block_starts) — keeping them out of here is what
        keeps warm flush latency flat under backfill."""
        r = self.opts.retention
        out = []
        for bs in self.buffer.block_starts():
            if bs + r.block_size_ns + r.buffer_past_ns <= now_ns \
                    and bs not in self._filesets:
                out.append(bs)
        return out

    def cold_dirty_block_starts(self) -> list[int]:
        """Blocks holding buffered COLD writes: a flushed volume exists and
        the buffer has new points for the window (reference
        coldFlushReuseableResources.dirtySeriesToWrite role)."""
        return sorted(bs for bs in self.buffer.block_starts()
                      if bs in self._filesets)

    def cold_flush(self, block_start: int) -> bool:
        """Merge the window's buffered cold writes with its current volume
        into a version-bumped volume (reference storage/coldflush.go +
        persist/fs/merger.go). Runs on the cold cadence so backfill never
        blocks the warm pass."""
        from m3_tpu.utils import trace

        with trace.span(trace.SHARD_FLUSH, shard=self.shard_id,
                        block_start=block_start, cold=True):
            return self._flush_traced(block_start)

    def flush(self, block_start: int) -> bool:
        """Seal the window, batch-encode on device, write a fileset volume.

        If a volume already exists for the window (cold-path reflush), its
        series are decoded, merged with the buffer's, and a higher volume is
        written — the role of the reference's fs merger (persist/fs/merger.go).
        """
        from m3_tpu.utils import trace

        with trace.span(trace.SHARD_FLUSH, shard=self.shard_id,
                        block_start=block_start):
            return self._flush_traced(block_start)

    def _pace_persist(self, n_bytes: int) -> None:
        if self.persist_limiter is not None:
            self.persist_limiter.acquire(n_bytes)

    # grace before a swapped-out reader is really closed; class attribute so
    # tests can shrink it
    RETIRE_GRACE_S = 30.0

    def _retire(self, reader: FilesetReader) -> None:
        import time

        with self._retired_lock:
            self._retired.append((time.monotonic(), reader))

    def _drain_retired(self) -> None:
        """Close readers retired at least RETIRE_GRACE_S ago; any read that
        captured them in its snapshot has finished by now. A drained
        reader whose volume was SUPERSEDED (flush/cold-flush/repair wrote
        a higher volume for the block) also has its files deleted here —
        without this every repair cycle leaks a full volume on disk until
        retention expiry (continuous repair would leak without bound).
        Readers retired by expire() already had their files deleted; the
        per-volume remove is a no-op for them."""
        import time

        now = time.monotonic()
        doomed = []
        with self._retired_lock:
            keep = []
            for ts, r in self._retired:
                (doomed if now - ts >= self.RETIRE_GRACE_S else keep).append((ts, r))
            self._retired = keep
        for _, r in doomed:
            r.close()
            cur = self._filesets.get(r.block_start)
            if cur is not None and cur.volume > r.volume:
                self._delete_volume_files(r.block_start, r.volume)

    def _flush_traced(self, block_start: int) -> bool:
        from m3_tpu.utils.instrument import default_registry

        with default_registry().root_scope("db").histogram(
                "shard_flush_seconds"):
            with self._maint_lock:
                return self._flush_locked(block_start)

    def _flush_locked(self, block_start: int) -> bool:
        from m3_tpu.encoding.m3tsz import hostpath

        # the kill-mid-flush seam: a crash anywhere before the checkpoint
        # lands must leave the buffer window intact (seal below never
        # drops) and the old volume readable
        faults.check("shard.flush", shard=self.shard_id,
                     block_start=block_start)
        self._drain_retired()
        from m3_tpu.storage import pagepool

        if pagepool.active():
            return self._flush_ragged(block_start)

        # Seal WITHOUT dropping: the buffer window is the only copy until the
        # fileset volume is durably on disk; a failed flush must leave it
        # intact (and with it the retired-commitlog coverage check).
        sealed = self.buffer.seal(block_start, drop=False)
        if sealed is None:
            return False

        ids = [self.buffer.series_ids[i] for i in sealed.series_indices]
        tags = [self.buffer.series_tags[i] for i in sealed.series_indices]
        times = sealed.times
        vbits = sealed.value_bits
        n_points = sealed.n_points

        prev = self._filesets.get(block_start)
        volume = 0
        extra: list[tuple[bytes, bytes, bytes]] = []  # untouched old series
        if prev is not None:
            volume = prev.volume + 1
            merged_t, merged_v, merged_n = [], [], []
            new_ids = {sid: k for k, sid in enumerate(ids)}
            for i in range(prev.n_series):
                sid, stags, stream = prev.read_at(i)
                if sid not in new_ids:
                    extra.append((sid, stags, stream))
                    continue
                k = new_ids[sid]
                old_t, old_v = hostpath.decode_stream(
                    stream, self.opts.write_time_unit,
                    self.opts.int_optimized,
                )
                nt, nv = merge_dedup(
                    np.concatenate([old_t, times[k, : n_points[k]]]),
                    np.concatenate([old_v, vbits[k, : n_points[k]]]),
                )
                merged_t.append(nt)
                merged_v.append(nv)
                merged_n.append(k)
            if merged_n:
                width = max(times.shape[1], max(len(t) for t in merged_t))
                if width > times.shape[1]:
                    pad = width - times.shape[1]
                    times = np.pad(times, ((0, 0), (0, pad)), constant_values=block_start)
                    vbits = np.pad(vbits, ((0, 0), (0, pad)))
                for k, nt, nv in zip(merged_n, merged_t, merged_v):
                    times[k, : len(nt)] = nt
                    vbits[k, : len(nv)] = nv
                    times[k, len(nt):] = nt[-1]
                    n_points[k] = len(nt)

        try:
            streams = hostpath.encode_blocks(
                times, vbits, sealed.starts, n_points,
                self.opts.write_time_unit, self.opts.int_optimized,
            )
        except OverflowError:
            raise RuntimeError(
                f"flush encode overflow: shard={self.shard_id} bs={block_start}"
            )

        self._write_volume_and_swap(ids, tags, streams, extra,
                                    block_start, volume, prev,
                                    sealed.raw_count)
        return True

    def _write_volume_and_swap(self, ids, tags, streams, extra,
                               block_start: int, volume: int, prev,
                               raw_count: int) -> None:
        """The flush DURABILITY TAIL shared by the padded and ragged
        bodies (which only differ in how they seal/merge/encode): paced
        volume write + checkpoint, reader retire/swap, cache
        invalidation, and only THEN dropping exactly the sealed prefix —
        concurrent appends after the seal copy stay buffered."""
        writer = FilesetWriter(
            self.fs_root, self.namespace, self.shard_id, block_start,
            self.opts.retention.block_size_ns, volume,
        )
        for sid, stags, stream in zip(ids, tags, streams):
            self._pace_persist(len(stream))
            writer.write_series(sid, stags, stream)
        for sid, stags, stream in extra:
            self._pace_persist(len(stream))
            writer.write_series(sid, stags, stream)
        writer.close()

        if prev is not None:
            self._retire(prev)
        self._filesets[block_start] = FilesetReader(
            self.fs_root, self.namespace, self.shard_id, block_start, volume
        )
        if self.cache is not None:  # cached decodes are for the old volume
            self.cache.invalidate_block(self.namespace, self.shard_id,
                                        block_start)
        self.buffer.drop_window_prefix(block_start, raw_count)
        self.bump_data_version()

    def _flush_ragged(self, block_start: int) -> bool:
        """The paged-memory flush body (M3_TPU_PAGED=1): ragged seal
        (no [B, max_T] scatter), per-series merge against the previous
        volume on CSR slices, length-bucketed ragged encode — streams
        byte-identical to the padded body, volumes indistinguishable on
        disk.  Durability order is the seed body's: seal without drop,
        write + checkpoint, swap, only then drop the covered prefix."""
        from m3_tpu.encoding.m3tsz import hostpath
        from m3_tpu.ops import ragged

        sealed = self.buffer.seal_csr(block_start, drop=False)
        if sealed is None:
            return False
        ids = [self.buffer.series_ids[i] for i in sealed.series_indices]
        tags = [self.buffer.series_tags[i] for i in sealed.series_indices]
        times, vbits, offsets = (sealed.times, sealed.value_bits,
                                 sealed.offsets)

        prev = self._filesets.get(block_start)
        volume = 0
        extra: list[tuple[bytes, bytes, bytes]] = []  # untouched old series
        replaced: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        if prev is not None:
            volume = prev.volume + 1
            new_ids = {sid: k for k, sid in enumerate(ids)}
            for i in range(prev.n_series):
                sid, stags, stream = prev.read_at(i)
                if sid not in new_ids:
                    extra.append((sid, stags, stream))
                    continue
                k = new_ids[sid]
                old_t, old_v = hostpath.decode_stream(
                    stream, self.opts.write_time_unit,
                    self.opts.int_optimized,
                )
                a, b = int(offsets[k]), int(offsets[k + 1])
                replaced[k] = merge_dedup(
                    np.concatenate([old_t, times[a:b]]),
                    np.concatenate([old_v, vbits[a:b]]),
                )
        if replaced:
            rows = []
            for k in range(sealed.n_series):
                hit = replaced.get(k)
                if hit is None:
                    a, b = int(offsets[k]), int(offsets[k + 1])
                    hit = (times[a:b], vbits[a:b])
                rows.append([hit])
            times, vbits, offsets = ragged.assemble_rows(rows)

        try:
            streams = hostpath.encode_blocks_ragged(
                times, vbits, offsets,
                np.full(sealed.n_series, block_start, np.int64),
                self.opts.write_time_unit, self.opts.int_optimized,
            )
        except OverflowError:
            raise RuntimeError(
                f"flush encode overflow: shard={self.shard_id} bs={block_start}"
            )

        self._write_volume_and_swap(ids, tags, streams, extra,
                                    block_start, volume, prev,
                                    sealed.raw_count)
        return True

    # -- bootstrap --

    def bootstrap_from_fs(self, now_ns: int | None = None) -> int:
        """Load complete volumes; expired ones are skipped (never deleted
        here — open() must not be destructive; the explicit tick()/expire
        path reclaims disk)."""
        r = self.opts.retention
        cutoff = None
        if now_ns is not None:
            cutoff = r.block_start(now_ns - r.retention_ns)
        n = 0
        for block_start, volume in list_filesets(self.fs_root, self.namespace, self.shard_id):
            if cutoff is not None and block_start < cutoff:
                continue
            try:
                reader = FilesetReader(
                    self.fs_root, self.namespace, self.shard_id, block_start, volume
                )
            except (FileNotFoundError, ValueError):
                continue  # incomplete or corrupt volume: ignore
            # same guard as flush/seal: re-bootstrap (live tenant
            # namespace creation, PR 7) can race a maintenance pass
            with self._maint_lock:
                self._filesets[block_start] = reader
            n += 1
        if n:
            self.bump_data_version()
        return n

    # -- maintenance --

    def _delete_fileset_files(self, block_start: int) -> None:
        # every volume of the block (retention expiry)
        self._delete_matching(f"fileset-{block_start}-*.db")

    def _delete_volume_files(self, block_start: int, volume: int) -> None:
        """ONE superseded volume's files (repair/flush wrote a higher
        volume; this one is no longer the bootstrap choice). Readers
        still holding it keep reading through their open fds/mmaps."""
        self._delete_matching(f"fileset-{block_start}-{volume}-*.db")

    def _delete_matching(self, pattern: str) -> None:
        import glob
        import os

        d = os.path.join(self.fs_root, self.namespace, str(self.shard_id))
        # *.db.tmp: leftovers of a flush killed mid-write (atomic writers
        # never expose them under final names; reclaim them here)
        full = os.path.join(d, pattern)
        paths = glob.glob(full) + glob.glob(full + ".tmp")
        # checkpoint first so a crash mid-delete leaves an "incomplete"
        # (ignored) volume rather than a corrupt-looking one
        paths = sorted(paths, key=lambda p: "checkpoint" not in p)
        for p in paths:
            try:
                os.remove(p)
            except OSError:
                pass

    def expire(self, now_ns: int) -> int:
        """Drop + delete block volumes and buffered windows past retention.

        Also reclaims on-disk volumes that were skipped at bootstrap as
        already-expired (they were never loaded into _filesets)."""
        r = self.opts.retention
        cutoff = r.block_start(now_ns - r.retention_ns)
        dropped = 0
        with self._maint_lock:
            self._drain_retired()
            for bs in list(self._filesets):
                if bs < cutoff:
                    # retire, don't close: a concurrent read may hold this
                    # reader; its open fds/mmaps keep the unlinked files
                    # readable until the grace period closes it
                    self._retire(self._filesets[bs])
                    del self._filesets[bs]
                    self._delete_fileset_files(bs)
                    dropped += 1
            with self._retired_lock:
                in_grace = {(r.block_start, r.volume)
                            for _ts, r in self._retired}
            for bs, vol in list_filesets(self.fs_root, self.namespace,
                                         self.shard_id, all_volumes=True):
                if bs < cutoff and bs not in self._filesets:
                    self._delete_fileset_files(bs)
                    continue
                # superseded-volume sweep: a complete volume below the one
                # currently serving the block is a crash leftover (killed
                # between the swap and the retired-reader cleanup) — only
                # the max volume is ever bootstrapped, so reclaim the rest.
                # Volumes still inside the retire grace are skipped (their
                # readers drain first; the next expire pass gets them).
                cur = self._filesets.get(bs)
                if cur is not None and vol < cur.volume \
                        and (bs, vol) not in in_grace:
                    self._delete_volume_files(bs, vol)
        expired = self.buffer.expire_before(cutoff)
        if dropped or expired:
            self.bump_data_version()
        return dropped

    def close(self) -> None:
        """Release every fileset reader (current and retired, grace
        ignored): after close the shard serves no reads, so the deferred-
        close protection no longer applies and holding the fds/mmaps would
        leak them for the rest of the process."""
        with self._maint_lock:
            with self._retired_lock:
                retired, self._retired = self._retired, []
            for _, reader in retired:
                reader.close()
            for reader in self._filesets.values():
                reader.close()
            self._filesets.clear()

    @property
    def flushed_block_starts(self) -> list[int]:
        return sorted(self._filesets)
