"""Commit log: write-ahead durability for the in-memory buffer.

Role parity with the reference WAL (/root/reference/src/dbnode/persist/fs/
commitlog: batched writes drained by one writer, chunked format with
digests, rotation + snapshot-based truncation). Here the queue is a
host-side byte buffer flushed on size/explicit fsync; the chunk format is:

  chunk:  u32 magic, u32 payload_len, u32 adler32(payload), payload
  entry:  u8 kind
          kind 0 (register): u32 sidx, u32 id_len + id, u32 tags_len + tags
          kind 1 (write):    u32 sidx, i64 time_ns, u64 value_bits, u8 unit
Series are registered once per log file and then referenced by index,
mirroring the reference's commit-log series registry.

Recovery modes: `replay` is strict (corrupt interior chunks raise — the
inspector/verifier behavior), `replay_salvage` truncates at the first bad
chunk and reports what was dropped (the bootstrap behavior: a damaged log
must never brick a node; the reference's commitlog bootstrapper likewise
reads until the first unrecoverable error). Torn TRAILING chunks — the
tail of a crashed process — are skipped by both.

Fault points (utils/faults.py): commitlog.write, commitlog.flush (torn
writes land a prefix of the chunk, the kill-mid-flush case),
commitlog.fsync.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass

import numpy as np

from m3_tpu.utils import faults
from m3_tpu.utils.instrument import default_registry

_MAGIC = 0xC0881706

# one kind-1 (write) record, as a packed big-endian numpy dtype: a whole
# batch of datapoint records renders with four vectorized column stores +
# one tobytes() instead of one struct.pack per entry (write_many)
_WRITE_REC = np.dtype([("kind", "u1"), ("sidx", ">u4"), ("t", ">i8"),
                       ("v", ">u8"), ("unit", "u1")])
assert _WRITE_REC.itemsize == 22  # must match the ">BIqQB" wire layout

# fsync latency distribution — the durability seam whose p99 bounds write
# ack latency; exposed as db_commitlog_fsync_seconds_bucket on /metrics
_scope = default_registry().root_scope("db")


def _fsync_timed(fileno: int) -> None:
    import time as _time

    t0 = _time.perf_counter()
    os.fsync(fileno)
    _scope.observe("commitlog_fsync_seconds", _time.perf_counter() - t0)


@dataclass
class CommitLogEntry:
    series_id: bytes
    encoded_tags: bytes
    time_ns: int
    value_bits: int
    unit: int


@dataclass
class SalvageReport:
    """What a salvage replay recovered and what it gave up on."""
    entries: int = 0            # entries successfully recovered
    chunks: int = 0             # complete chunks replayed
    truncated_at: int | None = None  # byte offset of the first bad chunk
    dropped_bytes: int = 0      # bytes abandoned from truncated_at on
    torn_tail: bool = False     # ended at a torn trailing chunk (benign)
    reason: str = ""

    @property
    def clean(self) -> bool:
        return self.truncated_at is None


class CommitLogWriter:
    def __init__(self, path: str, flush_every_bytes: int = 1 << 20):
        import threading

        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = open(path, "ab")
        self._buf = bytearray()
        self._series: dict[bytes, int] = {}
        self._flush_every = flush_every_bytes
        self.path = path
        # writer lock: concurrent ingest threads interleaving the
        # multi-append register records (or racing the series registry)
        # would tear the entry framing inside a digest-valid chunk — an
        # undecodable-chunk salvage truncation with NO crash involved.
        # The pipelined write path routes appends through a per-namespace
        # FIFO lane (storage/pipeline.py) so in steady state exactly one
        # thread holds this; the lock is the correctness backstop (and
        # the measured WAL class in the lock-wait profile: serial-path
        # ingest threads contend here for the full flush+fsync I/O).
        self._lock = threading.Lock()
        # a failed flush POISONS the writer: the file may hold a torn
        # interior chunk, and salvage replay truncates everything after
        # the first bad chunk — so acking any later write on this file
        # would be a silent-loss lie. Callers that survive the error (a
        # request handler swallowing it) must rotate to a fresh log.
        self._failed: Exception | None = None
        # saturation plane: acked bytes sitting in the user-space buffer
        # (lost on SIGKILL until flushed) vs the flush threshold
        from m3_tpu.utils.instrument import monitor_queue

        self._unmonitor = monitor_queue(
            "commitlog_flush_backlog", lambda: len(self._buf),
            flush_every_bytes, owner=self,
            log=os.path.basename(os.path.dirname(path)))

    def write(self, series_id: bytes, encoded_tags: bytes, time_ns: int,
              value_bits: int, unit: int) -> None:
        faults.check("commitlog.write")
        with self._lock:
            # poison check INSIDE the lock: a writer blocked here while a
            # concurrent flush fails must not append (and so ack) onto a
            # poisoned log — salvage replay would truncate those bytes
            self._check_poisoned_locked()
            sidx = self._series.get(series_id)
            if sidx is None:
                sidx = len(self._series)
                self._series[series_id] = sidx
                self._buf += struct.pack(">BI", 0, sidx)
                self._buf += struct.pack(">I", len(series_id)) + series_id
                self._buf += struct.pack(">I", len(encoded_tags)) \
                    + encoded_tags
            self._buf += struct.pack(">BIqQB", 1, sidx, time_ns, value_bits,
                                     unit)
            if len(self._buf) >= self._flush_every:
                # the WAL write/fsync seam deliberately completes under
                # the writer lock: the lock IS the append/flush ordering
                # (same class as the raft persist-before-ack waivers)
                # m3lint: disable=lock-blocking-call
                self._flush_locked(fsync=False)

    def _check_poisoned_locked(self) -> None:
        if self._failed is not None:
            raise OSError(
                f"commitlog writer poisoned by earlier flush failure "
                f"({self.path})"
            ) from self._failed

    def write_many(self, series_ids: list[bytes], tags_list: list[bytes],
                   times: np.ndarray, value_bits: np.ndarray,
                   unit: int) -> None:
        """ONE commitlog append for a whole batch (columns: parallel
        series/tags lists + int64 time and uint64 value-bit arrays, all
        sharing the namespace's time unit). The datapoint records render
        as one vectorized pack (four column stores + tobytes) with
        new-series register records spliced in at each first occurrence,
        so the emitted byte stream is IDENTICAL to calling write() per
        entry — replay/replay_salvage and the poison/torn-chunk semantics
        see nothing new. One fault-point hit and one flush-threshold
        check per batch (the per-point path checks per entry, so chunk
        BOUNDARIES may differ once a batch crosses the threshold; the
        entry stream never does)."""
        # same semantic seam as the per-point write() above — one name, one
        # injection schedule, whichever path the caller took
        # m3lint: disable=inv-fault-point-unique
        faults.check("commitlog.write", batch=len(series_ids))
        n = len(series_ids)
        if n == 0:
            return
        with self._lock:
            # deliberate: the batched append (incl. a threshold flush)
            # completes under the writer lock — see write()
            # m3lint: disable=lock-blocking-call
            self._write_many_locked(series_ids, tags_list, times,
                                    value_bits, unit)

    def _write_many_locked(self, series_ids, tags_list, times, value_bits,
                           unit) -> None:
        # same poisoned-writer rule as write(): checked under the lock
        self._check_poisoned_locked()
        n = len(series_ids)
        series = self._series
        # register records for series this log hasn't seen, keyed by the
        # batch position they must precede
        registers: list[tuple[int, bytes]] = []
        sidx_l: list = [0] * n
        for i, sid in enumerate(series_ids):
            sidx = series.get(sid)
            if sidx is None:
                sidx = len(series)
                series[sid] = sidx
                tags = tags_list[i]
                registers.append((i, struct.pack(">BI", 0, sidx)
                                  + struct.pack(">I", len(sid)) + sid
                                  + struct.pack(">I", len(tags)) + tags))
            sidx_l[i] = sidx
        rec = np.empty(n, _WRITE_REC)
        rec["kind"] = 1
        rec["unit"] = unit
        rec["sidx"] = np.array(sidx_l, np.uint32)
        rec["t"] = times
        rec["v"] = value_bits
        blob = rec.tobytes()
        if not registers:
            self._buf += blob
        else:
            sz = _WRITE_REC.itemsize
            pieces: list[bytes] = []
            prev = 0
            for i, reg in registers:
                pieces.append(blob[prev * sz : i * sz])
                pieces.append(reg)
                prev = i
            pieces.append(blob[prev * sz :])
            self._buf += b"".join(pieces)
        if len(self._buf) >= self._flush_every:
            self._flush_locked(fsync=False)

    def flush(self, fsync: bool = False) -> None:
        with self._lock:
            # deliberate: the flush+fsync seam holds the writer lock so
            # no append can interleave a half-flushed chunk
            # m3lint: disable=lock-blocking-call
            self._flush_locked(fsync)

    def _flush_locked(self, fsync: bool) -> None:
        self._check_poisoned_locked()
        try:
            if not self._buf:
                if fsync:
                    faults.check("commitlog.fsync")
                    _fsync_timed(self._f.fileno())
                return
            payload = bytes(self._buf)
            self._buf.clear()
            header = struct.pack(">III", _MAGIC, len(payload),
                                 zlib.adler32(payload))
            # a crash here may land any byte prefix of the chunk — the
            # torn tail that replay/replay_salvage skip
            faults.torn_write(self._f, header + payload, "commitlog.flush")
            self._f.flush()
            if fsync:
                # same fsync seam as the empty-buffer branch above: one
                # name for "the WAL fsync", whichever branch ran
                # m3lint: disable=inv-fault-point-unique
                faults.check("commitlog.fsync")
                _fsync_timed(self._f.fileno())
        except BaseException as e:
            self._failed = e
            raise

    def close(self) -> None:
        self._unmonitor()
        if self._failed is None:
            self.flush(fsync=True)
        with self._lock:
            self._f.close()


def _decode_payload(payload: bytes, series: dict[int, tuple[bytes, bytes]],
                    entries: list[CommitLogEntry]) -> None:
    """Decode one chunk payload into `entries`, updating the series
    registry. Raises ValueError/struct.error on a malformed entry."""
    p = 0
    while p < len(payload):
        kind, sidx = struct.unpack_from(">BI", payload, p)
        p += 5
        if kind == 0:
            (idlen,) = struct.unpack_from(">I", payload, p)
            p += 4
            sid = payload[p : p + idlen]
            p += idlen
            (tlen,) = struct.unpack_from(">I", payload, p)
            p += 4
            tags = payload[p : p + tlen]
            p += tlen
            series[sidx] = (sid, tags)
        elif kind == 1:
            t_ns, vbits, unit = struct.unpack_from(">qQB", payload, p)
            p += 17
            sid, tags = series[sidx]
            entries.append(CommitLogEntry(sid, tags, t_ns, vbits, unit))
        else:
            raise ValueError(f"unknown commitlog entry kind {kind}")


def _replay(path: str, salvage: bool) -> tuple[list[CommitLogEntry], SalvageReport]:
    entries: list[CommitLogEntry] = []
    report = SalvageReport()
    if not os.path.exists(path):
        return entries, report
    with open(path, "rb") as f:
        raw = f.read()
    series: dict[int, tuple[bytes, bytes]] = {}
    off = 0

    def bad(reason: str) -> tuple[list[CommitLogEntry], SalvageReport]:
        if not salvage:
            raise ValueError(f"{reason} at {off}")
        report.truncated_at = off
        report.dropped_bytes = len(raw) - off
        report.reason = reason
        report.entries = len(entries)
        return entries, report

    while off + 12 <= len(raw):
        magic, plen, digest = struct.unpack_from(">III", raw, off)
        if magic != _MAGIC:
            return bad("bad commitlog chunk magic")
        if off + 12 + plen > len(raw):
            report.torn_tail = True
            break  # torn tail chunk from a crash: ignore
        payload = raw[off + 12 : off + 12 + plen]
        if zlib.adler32(payload) != digest:
            if off + 12 + plen == len(raw):
                report.torn_tail = True
                break  # torn tail
            return bad("corrupt commitlog chunk")
        mark = len(entries)
        try:
            _decode_payload(payload, series, entries)
        except (ValueError, KeyError, struct.error) as e:
            # digest-valid but undecodable (format bug / sidx from a
            # truncated registry): salvage keeps nothing of this chunk
            del entries[mark:]
            return bad(f"undecodable commitlog chunk ({e})")
        report.chunks += 1
        off += 12 + plen
    if off < len(raw) and not report.torn_tail:
        # trailing sub-header garbage (< 12 bytes): torn tail by definition
        report.torn_tail = True
    report.entries = len(entries)
    return entries, report


def replay(path: str) -> list[CommitLogEntry]:
    """Strict replay: torn trailing chunks are skipped (the tail of a
    crashed process), corrupt interior chunks raise."""
    entries, _report = _replay(path, salvage=False)
    return entries


def replay_salvage(path: str) -> tuple[list[CommitLogEntry], SalvageReport]:
    """Salvage replay: recover every entry up to the first bad chunk and
    report the truncation instead of raising — bootstrap must come up on
    a damaged log and say what it lost."""
    return _replay(path, salvage=True)


def log_files(directory: str) -> list[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, n)
        for n in os.listdir(directory)
        if n.startswith("commitlog-") and n.endswith(".db")
    )
