"""Commit log: write-ahead durability for the in-memory buffer.

Role parity with the reference WAL (/root/reference/src/dbnode/persist/fs/
commitlog: batched writes drained by one writer, chunked format with
digests, rotation + snapshot-based truncation). Here the queue is a
host-side byte buffer flushed on size/explicit fsync; the chunk format is:

  chunk:  u32 magic, u32 payload_len, u32 adler32(payload), payload
  entry:  u8 kind
          kind 0 (register): u32 sidx, u32 id_len + id, u32 tags_len + tags
          kind 1 (write):    u32 sidx, i64 time_ns, u64 value_bits, u8 unit
Series are registered once per log file and then referenced by index,
mirroring the reference's commit-log series registry.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass

_MAGIC = 0xC0881706


@dataclass
class CommitLogEntry:
    series_id: bytes
    encoded_tags: bytes
    time_ns: int
    value_bits: int
    unit: int


class CommitLogWriter:
    def __init__(self, path: str, flush_every_bytes: int = 1 << 20):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = open(path, "ab")
        self._buf = bytearray()
        self._series: dict[bytes, int] = {}
        self._flush_every = flush_every_bytes
        self.path = path

    def write(self, series_id: bytes, encoded_tags: bytes, time_ns: int,
              value_bits: int, unit: int) -> None:
        sidx = self._series.get(series_id)
        if sidx is None:
            sidx = len(self._series)
            self._series[series_id] = sidx
            self._buf += struct.pack(">BI", 0, sidx)
            self._buf += struct.pack(">I", len(series_id)) + series_id
            self._buf += struct.pack(">I", len(encoded_tags)) + encoded_tags
        self._buf += struct.pack(">BIqQB", 1, sidx, time_ns, value_bits, unit)
        if len(self._buf) >= self._flush_every:
            self.flush()

    def flush(self, fsync: bool = False) -> None:
        if not self._buf:
            return
        payload = bytes(self._buf)
        self._buf.clear()
        header = struct.pack(">III", _MAGIC, len(payload), zlib.adler32(payload))
        self._f.write(header + payload)
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        self.flush(fsync=True)
        self._f.close()


def replay(path: str) -> list[CommitLogEntry]:
    """Replay a commit log; torn trailing chunks are skipped (the tail of a
    crashed process), corrupt interior chunks raise."""
    entries: list[CommitLogEntry] = []
    if not os.path.exists(path):
        return entries
    with open(path, "rb") as f:
        raw = f.read()
    series: dict[int, tuple[bytes, bytes]] = {}
    off = 0
    while off + 12 <= len(raw):
        magic, plen, digest = struct.unpack_from(">III", raw, off)
        if magic != _MAGIC:
            raise ValueError(f"bad commitlog chunk magic at {off}")
        if off + 12 + plen > len(raw):
            break  # torn tail chunk from a crash: ignore
        payload = raw[off + 12 : off + 12 + plen]
        if zlib.adler32(payload) != digest:
            if off + 12 + plen == len(raw):
                break  # torn tail
            raise ValueError(f"corrupt commitlog chunk at {off}")
        off += 12 + plen
        p = 0
        while p < len(payload):
            kind, sidx = struct.unpack_from(">BI", payload, p)
            p += 5
            if kind == 0:
                (idlen,) = struct.unpack_from(">I", payload, p)
                p += 4
                sid = payload[p : p + idlen]
                p += idlen
                (tlen,) = struct.unpack_from(">I", payload, p)
                p += 4
                tags = payload[p : p + tlen]
                p += tlen
                series[sidx] = (sid, tags)
            elif kind == 1:
                t_ns, vbits, unit = struct.unpack_from(">qQB", payload, p)
                p += 17
                sid, tags = series[sidx]
                entries.append(CommitLogEntry(sid, tags, t_ns, vbits, unit))
            else:
                raise ValueError(f"unknown commitlog entry kind {kind}")
    return entries


def log_files(directory: str) -> list[str]:
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, n)
        for n in os.listdir(directory)
        if n.startswith("commitlog-") and n.endswith(".db")
    )
