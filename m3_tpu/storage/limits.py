"""Whole-query resource limits enforced at the storage layer.

Role parity with the reference storage/limits
(/root/reference/src/dbnode/storage/limits/types.go:37-57): budgets are
accounted where the data is read (Namespace.query_ids / Namespace.read),
so EVERY read path — PromQL, Graphite render, Prometheus remote read,
/api/v1/series — shares one per-request budget instead of each HTTP
handler opting in.
"""

from __future__ import annotations

import threading


class QueryLimitError(ValueError):
    """A query exceeded the configured resource limits."""


class QueryLimits:
    """Resource ceilings accumulated across a WHOLE query (every selector
    in the expression shares the budget); zero means unlimited. Accounting
    state is thread-local so one database can serve concurrent requests."""

    def __init__(self, max_series: int = 0, max_datapoints: int = 0,
                 max_steps: int = 0):
        self.max_series = max_series
        self.max_datapoints = max_datapoints
        self.max_steps = max_steps
        self._tl = threading.local()

    def start_query(self) -> None:
        self._tl.active = True
        self._tl.series = 0
        self._tl.datapoints = 0

    def end_query(self) -> None:
        self._tl.active = False

    def check_steps(self, n_steps: int) -> None:
        if self.max_steps and n_steps > self.max_steps:
            raise QueryLimitError(
                f"query spans {n_steps} steps, limit {self.max_steps}"
            )

    def add_series(self, n_series: int) -> None:
        # only count inside an active start_query..end_query scope: reads
        # from background work (repair, flush, direct library calls) are not
        # budgeted, and without the gate their counts would accumulate on a
        # long-lived thread until every read failed
        if not getattr(self._tl, "active", False):
            return
        total = getattr(self._tl, "series", 0) + n_series
        self._tl.series = total
        if self.max_series and total > self.max_series:
            raise QueryLimitError(
                f"query matched {total} series, limit {self.max_series}"
            )

    def add_datapoints(self, n: int) -> None:
        if not getattr(self._tl, "active", False):
            return
        total = getattr(self._tl, "datapoints", 0) + n
        self._tl.datapoints = total
        if self.max_datapoints and total > self.max_datapoints:
            raise QueryLimitError(
                f"query would read {total} datapoints, limit {self.max_datapoints}"
            )


def live_series(db, namespace: str) -> int | None:
    """Live (buffered) series count for one namespace — the storage-side
    source behind the per-tenant cardinality ceiling
    (utils/tenantlimits): the count is read where the series actually
    live, so the ceiling tracks reality instead of an ingest-side
    estimate. Returns None when the storage is remote (cluster facade:
    the nodes own the buffers) — the ceiling is then not enforceable
    from this process and the admission controller skips it."""
    ns = getattr(db, "namespaces", {}).get(namespace)
    shards = getattr(ns, "shards", None)
    if shards is None:
        return None
    return sum(s.buffer.n_series for s in shards.values())
