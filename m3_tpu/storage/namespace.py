"""Namespace: a retention tier owning all local shards.

Role parity with the reference dbNamespace
(/root/reference/src/dbnode/storage/namespace.go:702,736,800).
"""

from __future__ import annotations

import itertools

from m3_tpu.index.index import NamespaceIndex
from m3_tpu.index.query import Query
from m3_tpu.storage.options import DatabaseOptions, NamespaceOptions
from m3_tpu.storage.shard import Shard
from m3_tpu.storage.sharding import ShardSet


class Namespace:
    # batch size per decode dispatch when a datapoint limit is active:
    # large enough to keep the batched path's dispatch economy, small
    # enough that an over-limit query stops within one chunk
    READ_MANY_LIMIT_CHUNK = 4096

    # capability marker for resolver.fetch_tagged_ragged and the hot
    # tier's fetch-version keys: ONLY local storage namespaces qualify.
    # Facades that delegate unknown attributes to a local namespace
    # (fanout) must override this with a CLASS attribute set to False —
    # hasattr probes would otherwise resolve through their __getattr__
    # and silently bypass the facade's own read path
    supports_ragged_read = True
    # local version truth (ns_uid + shard data_version counters): the
    # hot tier's fetch keys and the standing engine's incremental skip
    # require it. Split from supports_ragged_read because cluster
    # facades DO serve ragged CSR reads (over the binary wire) while
    # holding no version truth of their own
    has_version_truth = True

    def __init__(
        self,
        name: str,
        opts: NamespaceOptions,
        db_opts: DatabaseOptions,
        shard_set: ShardSet,
        fs_root: str,
    ):
        self.name = name
        self.opts = opts
        self.db_opts = db_opts
        self.shard_set = shard_set
        self.fs_root = fs_root
        self.shards: dict[int, Shard] = {
            sid: Shard(sid, name, opts, db_opts, fs_root)
            for sid in shard_set.shard_ids
        }
        self.index = (
            NamespaceIndex(opts.index.block_size_ns) if opts.index.enabled else None
        )
        # set by Database.create_namespace; carries the shared QueryLimits
        self.database = None
        # process-unique instance id: hot-tier keys must never collide
        # across two Namespace objects that happen to share a name and
        # fresh data-version counters (test fixtures, re-created tenants)
        self.ns_uid = next(self._UID)
        # bumped by every shard add/remove (see data_version)
        self._placement_epoch = 0

    _UID = itertools.count()

    @property
    def limits(self):
        return getattr(self.database, "limits", None)

    def data_version(self) -> tuple:
        """Content-version fingerprint for the device-resident hot tier
        (storage/hottier.py): changes whenever any owned shard's readable
        content could have changed. The placement epoch (bumped by every
        add/remove_shard) rides along because a remove+add swap can
        return the version SUM to a previously-seen value with different
        readable content — a sum alone would alias and serve stale
        pages."""
        shards = list(self.shards.values())  # placement changes mutate
        # the dict concurrently; iterate a snapshot
        return (self._placement_epoch, len(shards),
                sum(s.data_version for s in shards))

    def add_shard(self, shard_id: int, now_ns: int | None = None) -> Shard:
        """Start owning a shard (placement assignment). Local fileset data
        for the shard is bootstrapped if present; peer bootstrap is the
        caller's job (services layer) since it needs the topology."""
        shard = self.shards.get(shard_id)
        if shard is None:
            shard = Shard(shard_id, self.name, self.opts, self.db_opts,
                          self.fs_root)
            if self.database is not None:
                shard.cache = self.database.block_cache
                shard.persist_limiter = self.database.persist_limiter
            self.shards[shard_id] = shard
            self._placement_epoch += 1
            shard.bootstrap_from_fs(now_ns)
            shard.bootstrapped = True
        return shard

    def remove_shard(self, shard_id: int) -> None:
        """Stop owning a shard. Buffered (unflushed) windows are force-
        flushed to fileset volumes first so a handoff never discards the
        only copy of recent writes — background repair can reconcile the
        new owner from disk later (reference keeps LEAVING donors serving
        until cutover for the same reason)."""
        shard = self.shards.pop(shard_id, None)
        if shard is None:
            return
        self._placement_epoch += 1
        for bs in shard.buffer.block_starts():
            try:
                shard.flush(bs)
            except Exception:  # noqa: BLE001 - best effort on the way out
                pass

    def shard_for(self, series_id: bytes) -> Shard:
        sid = self.shard_set.lookup(series_id)
        shard = self.shards.get(sid)
        if shard is None:
            raise KeyError(f"shard {sid} not owned by this node")
        return shard

    def write(self, series_id: bytes, t_ns: int, value_bits: int,
              encoded_tags: bytes = b"") -> None:
        self.shard_for(series_id).write(series_id, t_ns, value_bits, encoded_tags)

    def write_tagged(self, series_id: bytes, tags: list[tuple[bytes, bytes]],
                     t_ns: int, value_bits: int, encoded_tags: bytes = b"") -> None:
        """Write + reverse-index the series in the datapoint's index block
        (the writeAndIndex path, reference storage/shard.go:869-896)."""
        self.shard_for(series_id).write(series_id, t_ns, value_bits, encoded_tags)
        if self.index is not None:
            self.index.insert(series_id, tags, t_ns)

    def route_many(self, series_ids: list[bytes]
                   ) -> tuple[dict[int, "object"], dict[int, str]]:
        """Vectorized series->shard routing for a batch: one murmur3 pass
        (ShardSet.lookup_many), then one row-index gather per distinct
        shard — no per-row python loop. Returns ({owned shard id: row
        index ndarray}, {row index: error} for rows landing on unowned
        shards — sparse, so the clean path allocates nothing per row).
        Split from write_many so Database.write_batch can validate
        ownership BEFORE logging, the per-point write order."""
        import numpy as np

        shards_arr = np.asarray(self.shard_set.lookup_many(series_ids),
                                np.int64)
        by_shard: dict[int, object] = {}
        errors: dict[int, str] = {}
        for s in np.unique(shards_arr).tolist():
            rows = np.nonzero(shards_arr == s)[0]
            if s in self.shards:
                by_shard[s] = rows
            else:
                msg = f"shard {s} not owned by this node"
                for i in rows.tolist():
                    errors[i] = msg
        return by_shard, errors

    def write_many(self, series_ids: list[bytes], times, value_bits,
                   tags_list: list[bytes], fields_list: list | None = None,
                   routed: tuple | None = None,
                   only_rows: list | None = None) -> list[str | None]:
        """Storage-side batched writes (the write half of read_many's
        contract): rows route in one vectorized murmur3 pass
        (ShardSet.lookup_many — pass `routed` to reuse a route_many
        result), each owned shard takes its rows through ONE buffer lock
        per (shard, window) group (Shard.write_many), and the reverse
        index sees one pre-filtered insert_many pass. Rows landing on
        unowned shards degrade per entry — the batch never fails
        wholesale. Returns per-row error strings (None = written).

        ``only_rows`` (with ``routed``) restricts the pass to those row
        indices — the pipelined write path's per-WAL-chunk call shape:
        the routed dict is already chunk-filtered, and the index insert
        must not re-insert other chunks' rows."""
        import numpy as np

        n = len(series_ids)
        if routed is not None:
            by_shard, errors = routed
        else:
            by_shard, err_map = self.route_many(series_ids)
            errors = [err_map.get(i) for i in range(n)] if err_map \
                else [None] * n
        for shard_id, rows in by_shard.items():
            ridx = np.asarray(rows, np.intp)
            rows_l = rows.tolist() if hasattr(rows, "tolist") else list(rows)
            self.shards[shard_id].write_many(
                [series_ids[i] for i in rows_l], times[ridx],
                value_bits[ridx], [tags_list[i] for i in rows_l])
        if self.index is not None and fields_list is not None:
            cand = only_rows if only_rows is not None else range(n)
            ok = [i for i in cand
                  if errors[i] is None and fields_list[i] is not None]
            if ok:
                self.index.insert_many([series_ids[i] for i in ok],
                                       [fields_list[i] for i in ok],
                                       times[np.asarray(ok, np.intp)])
        return errors

    def query_ids(self, query: Query, start_ns: int, end_ns: int, limit=None):
        """Matched index docs for the time range (storage QueryIDs role).

        Limits are accounted HERE — the shared storage read path — so every
        caller (PromQL, Graphite, remote read) draws from one budget, the
        way the reference enforces storage/limits below the query engines
        (/root/reference/src/dbnode/storage/limits/types.go:37)."""
        if self.index is None:
            raise RuntimeError(f"namespace {self.name} has no index enabled")
        docs = self.index.query(query, start_ns, end_ns, limit)
        if self.limits is not None:
            self.limits.add_series(len(docs))
        return docs

    def read(self, series_id: bytes, start_ns: int, end_ns: int):
        times, vbits = self.shard_for(series_id).read(series_id, start_ns, end_ns)
        if self.limits is not None:
            self.limits.add_datapoints(len(times))
        return times, vbits

    def read_many(self, series_ids: list[bytes], start_ns: int, end_ns: int):
        """Batch-read surface shared with the cluster facade (which turns
        it into one request per storage node).

        First-class batched operation: series group by owning shard and
        each shard fuses fetch+decode into one dispatch per (block,
        volume) group (Shard.read_many) — cache hits never enter the
        batch. Limits accounting stays EXACT: one add_datapoints per
        series, same as the per-series path; with a datapoint limit
        configured, shard batches are chunked so the limit still bounds
        decode WORK (an over-limit query aborts after at most one chunk
        of extra decode, not after materializing the whole match set)."""
        from m3_tpu.utils import trace
        from m3_tpu.utils.instrument import default_registry

        with trace.span(trace.READ_MANY, namespace=self.name,
                        series=len(series_ids)), \
                default_registry().root_scope("db") \
                .histogram("read_many_seconds"):
            return self._read_many_traced(series_ids, start_ns, end_ns)

    def read_many_ragged(self, series_ids: list[bytes], start_ns: int,
                         end_ns: int):
        """Batch read returning the RAGGED (times, vbits, offsets) CSR
        aligned to `series_ids` (ROADMAP #3): the per-shard finalize
        hands its merged columns straight through — no per-series tuple
        materialization — and the resolver/engine feed the CSR directly
        into `RaggedSeries`, which is exactly what the whole-query
        compiler's `_slab_cuts`/`_fill_slabs` slab prep consumes.  Same
        results, limits accounting and warnings contract as read_many
        (per-row slices are element-identical); paths the paged finalize
        doesn't cover (M3_TPU_PAGED=0, datapoint-limit chunking, serial
        hatch) assemble the CSR from the per-series views in one pass."""
        from m3_tpu.ops import ragged
        from m3_tpu.utils import trace
        from m3_tpu.utils.instrument import default_registry

        with trace.span(trace.READ_MANY, namespace=self.name,
                        series=len(series_ids)), \
                default_registry().root_scope("db") \
                .histogram("read_many_seconds"):
            res = self._read_many_traced(series_ids, start_ns, end_ns,
                                         want_ragged=True)
        if isinstance(res, tuple):
            return res
        return ragged.pairs_to_csr(res)

    def _read_many_traced(self, series_ids, start_ns, end_ns,
                          want_ragged: bool = False):
        from m3_tpu.storage import pipeline

        by_shard: dict[int, list[int]] = {}
        for i, shard_id in enumerate(self.shard_set.lookup_many(series_ids)):
            if shard_id not in self.shards:
                raise KeyError(f"shard {shard_id} not owned by this node")
            by_shard.setdefault(shard_id, []).append(i)
        limits = self.limits
        chunk = len(series_ids) or 1
        if limits is not None and getattr(limits, "max_datapoints", 0):
            chunk = min(chunk, self.READ_MANY_LIMIT_CHUNK)
        out: list = [None] * len(series_ids)
        if pipeline.active() and chunk >= len(series_ids):
            # pipelined dataflow (no datapoint-limit chunking): ONE
            # flattened schedule of per-(shard, block) gather legs
            # across every shard, overlapping the caller's decode rung
            return self._read_many_pipelined(series_ids, by_shard,
                                             start_ns, end_ns, out,
                                             want_ragged=want_ragged)
        for shard_id, idxs in by_shard.items():
            shard = self.shards[shard_id]
            for lo in range(0, len(idxs), chunk):
                part = idxs[lo : lo + chunk]
                results = shard.read_many(
                    [series_ids[i] for i in part], start_ns, end_ns)
                for i, (times, vbits) in zip(part, results):
                    if limits is not None:
                        limits.add_datapoints(len(times))
                    out[i] = (times, vbits)
        return out

    def _read_many_pipelined(self, series_ids, by_shard, start_ns, end_ns,
                             out, want_ragged: bool = False):
        """Per-(shard, block) groups through the executor seam: group
        N+1's fileset gather runs on the pool while group N decodes on
        this thread, and a shard's series FINALIZE (buffer merge +
        limits accounting, the partial columns downstream host prep
        consumes) as soon as its last group decodes — while later
        shards' gathers are still in flight. Results are identical to
        the serial path: groups run in the same nested order, decode
        stays one dispatch per group, and per-series parts keep the
        filesets-then-buffer order merge_dedup resolves last-write-wins.
        """
        from m3_tpu.ops import ragged
        from m3_tpu.storage import pagepool, pipeline
        from m3_tpu.utils import querystats

        # paged: batched ragged finalize per shard; fragments of the
        # namespace-level ragged combine (one merged per-shard CSR each,
        # landed by the finalize callback mid-flight) are only tracked
        # when the caller asked for the CSR back
        paged = pagepool.active()
        frags: list | None = [] if (paged and want_ragged) else None
        groups = []
        last_group_of: dict[int, object] = {}
        for shard_id, idxs in by_shard.items():
            shard = self.shards[shard_id]
            sids = [series_ids[i] for i in idxs]
            parts: list[list] = [[] for _ in idxs]
            plan = (shard, idxs, sids, parts)
            shard_groups = shard.plan_read_groups(sids, start_ns, end_ns,
                                                  parts)
            groups.extend(shard_groups)
            if shard_groups:
                last_group_of[id(shard_groups[-1])] = plan
            else:
                self._finalize_shard_read(plan, start_ns, end_ns, out,
                                          paged, frags)

        def consume(g, payload):
            g.consume(payload)
            plan = last_group_of.get(id(g))
            if plan is not None:  # this shard's partial columns are
                # complete: hand them downstream now, mid-pipeline
                self._finalize_shard_read(plan, start_ns, end_ns, out,
                                          paged, frags)

        stats = pipeline.run_stages(groups, lambda g: g.gather(), consume)
        querystats.record_pipeline(stats.items, stats.wall_s, stats.stages)
        if want_ragged and frags is not None:
            # pure O(N) scatter: each fragment is already merged and
            # filtered, and every row lives in exactly one fragment —
            # the combine just lands rows at their query-order positions
            return ragged.combine_fragments(frags, len(series_ids))
        return out

    def _finalize_shard_read(self, plan, start_ns, end_ns, out,
                             paged: bool = False,
                             frags: list | None = None) -> None:
        shard, idxs, sids, parts = plan
        limits = self.limits
        if paged:
            # batched ragged finalize (ROADMAP #3): ONE merge pass over
            # the shard's series instead of per-series concatenates;
            # out[] carries zero-copy row slices of the shard CSR
            import numpy as np

            t, v, offs = shard.finish_read_many(sids, parts, start_ns,
                                                end_ns)
            for j, i in enumerate(idxs):
                a, b = int(offs[j]), int(offs[j + 1])
                if limits is not None:
                    limits.add_datapoints(b - a)
                out[i] = (t[a:b], v[a:b])
            if frags is not None:
                frags.append((np.asarray(idxs, np.int64), t, v, offs))
            return
        for i, sid, pl in zip(idxs, sids, parts):
            times, vbits = shard.finish_read(sid, pl, start_ns, end_ns)
            if limits is not None:
                limits.add_datapoints(len(times))
            out[i] = (times, vbits)

    def flush(self, now_ns: int) -> int:
        """WARM flush: first volume for aged-out buffered windows."""
        if not self.opts.flush_enabled:
            return 0
        n = 0
        for shard in self.shards.values():
            for bs in shard.flushable_block_starts(now_ns):
                if shard.flush(bs):
                    n += 1
        return n

    def cold_flush(self) -> int:
        """COLD flush: version-bumped volumes for blocks that took writes
        after their warm flush (backfill/out-of-retention-order ingest).
        Separate pass so its decode+merge cost never sits in the warm
        path (reference storage/coldflush.go)."""
        if not self.opts.flush_enabled:
            return 0
        n = 0
        for shard in self.shards.values():
            for bs in shard.cold_dirty_block_starts():
                if shard.cold_flush(bs):
                    n += 1
        return n

    def expire(self, now_ns: int) -> int:
        return sum(s.expire(now_ns) for s in self.shards.values())

    def _spanned_index_starts(self, data_block_start: int) -> range:
        """Index block starts a data block overlaps (single source of the
        spanning rule for insert AND bootstrap-skip checks)."""
        idx_bs = self.opts.index.block_size_ns
        data_bs = self.opts.retention.block_size_ns
        first = data_block_start - (data_block_start % idx_bs)
        return range(first, data_block_start + data_bs, idx_bs)

    def index_insert_spanning(self, series_id: bytes, fields,
                              data_block_start: int) -> None:
        """Insert a doc into EVERY index block its data block overlaps (a
        data block can span several smaller index blocks)."""
        if self.index is None:
            return
        for t in self._spanned_index_starts(data_block_start):
            self.index.insert(series_id, fields, t)

    def bootstrap_from_fs(self, now_ns: int | None = None,
                          skip_index_blocks: set[int] | None = None) -> int:
        from m3_tpu.utils.ident import decode_tags

        n = sum(s.bootstrap_from_fs(now_ns) for s in self.shards.values())
        if self.index is not None:
            # rebuild the reverse index from fileset tag blobs, EXCEPT for
            # index blocks already restored from persisted segments
            skip = skip_index_blocks or set()
            for s in self.shards.values():
                for bs, reader in s._filesets.items():
                    # skip only if every overlapping index block was restored
                    if set(self._spanned_index_starts(bs)) <= skip:
                        continue
                    for i in range(reader.n_series):
                        sid, tags_blob = reader.entry_at(i)
                        if tags_blob:
                            self.index_insert_spanning(sid, decode_tags(tags_blob), bs)
        for s in self.shards.values():
            s.bootstrapped = True
        return n

    def series_ids(self) -> set[bytes]:
        out: set[bytes] = set()
        for s in self.shards.values():
            out |= s.series_ids()
        return out
