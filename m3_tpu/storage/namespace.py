"""Namespace: a retention tier owning all local shards.

Role parity with the reference dbNamespace
(/root/reference/src/dbnode/storage/namespace.go:702,736,800).
"""

from __future__ import annotations

from m3_tpu.storage.options import DatabaseOptions, NamespaceOptions
from m3_tpu.storage.shard import Shard
from m3_tpu.storage.sharding import ShardSet


class Namespace:
    def __init__(
        self,
        name: str,
        opts: NamespaceOptions,
        db_opts: DatabaseOptions,
        shard_set: ShardSet,
        fs_root: str,
    ):
        self.name = name
        self.opts = opts
        self.db_opts = db_opts
        self.shard_set = shard_set
        self.shards: dict[int, Shard] = {
            sid: Shard(sid, name, opts, db_opts, fs_root)
            for sid in shard_set.shard_ids
        }

    def shard_for(self, series_id: bytes) -> Shard:
        sid = self.shard_set.lookup(series_id)
        shard = self.shards.get(sid)
        if shard is None:
            raise KeyError(f"shard {sid} not owned by this node")
        return shard

    def write(self, series_id: bytes, t_ns: int, value_bits: int,
              encoded_tags: bytes = b"") -> None:
        self.shard_for(series_id).write(series_id, t_ns, value_bits, encoded_tags)

    def read(self, series_id: bytes, start_ns: int, end_ns: int):
        return self.shard_for(series_id).read(series_id, start_ns, end_ns)

    def flush(self, now_ns: int) -> int:
        if not self.opts.flush_enabled:
            return 0
        n = 0
        for shard in self.shards.values():
            for bs in shard.flushable_block_starts(now_ns):
                if shard.flush(bs):
                    n += 1
        return n

    def expire(self, now_ns: int) -> int:
        return sum(s.expire(now_ns) for s in self.shards.values())

    def bootstrap_from_fs(self) -> int:
        n = sum(s.bootstrap_from_fs() for s in self.shards.values())
        for s in self.shards.values():
            s.bootstrapped = True
        return n

    def series_ids(self) -> set[bytes]:
        out: set[bytes] = set()
        for s in self.shards.values():
            out |= s.series_ids()
        return out
