"""Continuous anti-entropy repair plane: the RepairDaemon.

Role parity with the reference's background repairer
(/root/reference/src/dbnode/storage/repair.go — shard repairers compare
per-series block checksums across replicas on a schedule and stream +
merge differing blocks). PR 2/this repo's `storage/peers.py` had the
mechanism (`repair_shard_block`) but only tests ever invoked it, so a
replica that slept through writes stayed divergent forever; this daemon
makes RF=2 actually mean two copies.

Design:

- **Digest-first comparison.** Each cycle exchanges ONE packed rollup
  digest table per (namespace, shard) with every replica peer
  (`PeerSource.rollup_digests`, the lean inter-node wire format of
  ROADMAP #5(c)): an in-sync block costs 20 bytes on the wire and an
  O(1) cached-digest lookup locally. Only blocks whose digests differ —
  or blocks the read path flagged (see `enqueue_range`) — fall through
  to the per-series `block_metadata` + `repair_shard_block` merge.
- **Pacing.** Streamed repair bytes pay into a token bucket
  (`PersistRateLimiter` discipline, MiB/s) and every cycle honors a
  deadline, so a repair storm after an outage trickles behind the
  serving path (the T3 overlap discipline: repair hides behind serving
  ticks instead of competing with them). Both knobs are runtime-tunable
  via the ``m3_tpu.repair`` KV key.
- **Shedding.** Peers are reached through the shared per-host breaker
  (`peers.peer_policy`); a dead peer costs one BreakerOpen per cycle,
  counted in `peer_shed`, never a 10s timeout per block.
- **Jitter.** Cycle sleeps are jittered from a seeded RNG so a fleet
  restarted together does not run repair in lockstep.

The daemon is wired by `services/dbnode.py` (placement-driven peer
discovery, config + KV tuning, /debug/repair status ring) and audited
end to end by the rig's convergence phase (tools/rig.py).
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, replace

from m3_tpu.utils import faults, trace
from m3_tpu.utils.instrument import Logger, default_registry

# the kvconfig key operators write to retune a live cluster's repair
# plane (same discipline as cluster/runtime.RUNTIME_KEY)
REPAIR_KEY = "m3_tpu.repair"


@dataclass(frozen=True)
class RepairOptions:
    enabled: bool = True
    # seconds between cycle STARTS (a cycle that overruns re-arms from
    # its own end); jitter_frac spreads replicas out
    interval_s: float = 30.0
    jitter_frac: float = 0.25
    # streamed-byte budget in MiB/s (0 = unpaced)
    rate_mbps: float = 8.0
    # one peer RPC's timeout and the whole cycle's wall budget: one slow
    # peer must not wedge a round (0 = no deadline)
    peer_timeout_s: float = 5.0
    cycle_deadline_s: float = 30.0

    def to_json(self) -> bytes:
        return json.dumps(asdict(self), sort_keys=True).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "RepairOptions":
        """Strictly-typed parse (RuntimeOptions discipline): a mistyped
        KV payload must fail HERE, not inside the watch listener where
        errors are swallowed and the operator sees nothing applied."""
        doc = json.loads(raw)
        known = {}
        for k in doc:
            if k not in cls.__dataclass_fields__:
                continue
            v = doc[k]
            default = cls.__dataclass_fields__[k].default
            if isinstance(default, bool):
                if not isinstance(v, bool):
                    raise ValueError(f"{k} must be a boolean, got {v!r}")
            elif isinstance(default, float):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(f"{k} must be a number, got {v!r}")
                v = float(v)
            known[k] = v
        return cls(**known)

    @classmethod
    def from_config(cls, doc: dict | None) -> "RepairOptions":
        """dbnode config `repair:` section -> options (strict)."""
        return cls.from_json(json.dumps(doc or {}).encode())


class RepairDaemon:
    """Background anti-entropy loop over this node's owned shards.

    Pluggable topology half: `shards_fn() -> iterable[int]` names the
    owned shards and `peers_fn(shard_id) -> list[PeerSource]` the replica
    peers that can serve them (services/dbnode.py passes placement-driven
    implementations; tests pass closures over in-process Databases)."""

    STATUS_RING = 32

    def __init__(self, db, shards_fn, peers_fn,
                 opts: RepairOptions | None = None, seed: int = 0,
                 clock=time.monotonic):
        from m3_tpu.cluster.runtime import PersistRateLimiter

        self.db = db
        self.shards_fn = shards_fn
        self.peers_fn = peers_fn
        self.log = Logger("repair")
        self.clock = clock
        self._opts = opts or RepairOptions()
        self._opts_lock = threading.Lock()
        self._pacer = PersistRateLimiter(self._opts.rate_mbps)
        self._rng = random.Random(f"repair:{seed}")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._unwatch = None
        # read-path divergence hints: (namespace, shard, start_ns, end_ns)
        # ranges, deduped, expanded to flushed blocks at drain time (a
        # hinted block may not have flushed yet when the hint arrives)
        self._queue: deque = deque(maxlen=1024)
        self._queued: set = set()
        self._queue_lock = threading.Lock()
        self.hint_drops = 0  # hints evicted by the bounded deque
        from m3_tpu.utils.instrument import monitor_queue

        self._unmonitor = monitor_queue(
            "repair_hints", lambda: len(self._queue), self._queue.maxlen,
            drops_fn=lambda: self.hint_drops, owner=self)
        # last-cycles ring + lifetime totals for /debug/repair
        self._ring: deque = deque(maxlen=self.STATUS_RING)
        self._ring_lock = threading.Lock()
        self.totals = {"cycles": 0, "blocks_checked": 0, "blocks_diverged": 0,
                       "series_repaired": 0, "peer_shed": 0, "errors": 0}
        self._scope = default_registry().root_scope("repair")

    # -- options ------------------------------------------------------------

    @property
    def opts(self) -> RepairOptions:
        with self._opts_lock:
            return self._opts

    @property
    def pacer(self):
        """The repair plane's shared token bucket: the handoff controller
        pays its bootstrap streams into the SAME budget (one storm-safety
        rate for all background replication traffic)."""
        return self._pacer

    def set_opts(self, opts: RepairOptions) -> None:
        with self._opts_lock:
            self._opts = opts
        self._pacer.set_rate(opts.rate_mbps)

    def update_opts(self, **fields) -> RepairOptions:
        with self._opts_lock:
            self._opts = replace(self._opts, **fields)
            opts = self._opts
        self._pacer.set_rate(opts.rate_mbps)
        return opts

    def watch_kv(self, kv, key: str = REPAIR_KEY):
        """Follow the repair KV key: operators retune pacing/interval on
        a live cluster without restarts. Returns an unwatch callable."""

        def on_change(_key, vv):
            if vv is None:
                return  # deletion keeps the last applied options
            try:
                self.set_opts(RepairOptions.from_json(vv.data))
            except (ValueError, TypeError):
                pass  # malformed payloads must not kill the watch thread

        self._unwatch = kv.watch(key, on_change)
        return self._unwatch

    # -- read-path divergence queue -----------------------------------------

    def enqueue_range(self, namespace: str, shard_id: int,
                      start_ns: int, end_ns: int) -> bool:
        """Out-of-band repair hint from the read path (quorum fetch saw
        replica checksums disagree). Cheap and lossy by design: bounded,
        deduped, dropped-oldest — a hint lost here is found again by the
        next full digest sweep."""
        key = (namespace, int(shard_id), int(start_ns), int(end_ns))
        with self._queue_lock:
            if key in self._queued:
                return False
            if len(self._queue) == self._queue.maxlen:
                old = self._queue.popleft()
                self._queued.discard(old)
                self.hint_drops += 1
            self._queue.append(key)
            self._queued.add(key)
        self._scope.counter("enqueued")
        return True

    def _drain_queue(self) -> dict[tuple, set[int]]:
        """Hinted (namespace, shard) -> block starts, expanded against
        the CURRENT flushed volumes."""
        with self._queue_lock:
            hints, self._queue = (list(self._queue),
                                  deque(maxlen=self._queue.maxlen))
            self._queued = set()
        out: dict[tuple, set[int]] = {}
        for namespace, shard_id, start_ns, end_ns in hints:
            ns = self.db.namespaces.get(namespace)
            if ns is None or shard_id not in ns.shards:
                continue
            size = ns.opts.retention.block_size_ns
            for bs in ns.shards[shard_id].flushed_block_starts:
                if bs + size > start_ns and bs < end_ns:
                    out.setdefault((namespace, shard_id), set()).add(bs)
        return out

    # -- the cycle ----------------------------------------------------------

    def run_cycle(self) -> dict:
        """One full anti-entropy round. Digest-compare every owned
        (namespace, shard) against its peers; repair diverging blocks,
        hinted blocks first. Returns the cycle report (also pushed onto
        the status ring)."""
        opts = self.opts
        t0 = self.clock()
        report = {"started_monotonic": round(t0, 3), "blocks_checked": 0,
                  "blocks_diverged": 0, "series_repaired": 0,
                  "peer_shed": 0, "deadline_hit": False, "errors": 0,
                  "queue_hints": 0, "shards": 0}
        with trace.span(trace.REPAIR_CYCLE), \
                self._scope.histogram("cycle_seconds"):
            # the kill-mid-repair seam: the rig schedules crashes here so
            # a daemon dying between compare and swap is a covered case
            faults.check("repair.cycle")
            hinted = self._drain_queue()
            report["queue_hints"] = sum(len(v) for v in hinted.values())
            deadline = (t0 + opts.cycle_deadline_s
                        if opts.cycle_deadline_s > 0 else None)
            for shard_id in sorted(self.shards_fn()):
                for namespace in list(self.db.namespaces):
                    if deadline is not None and self.clock() > deadline:
                        report["deadline_hit"] = True
                        break
                    self._repair_shard(namespace, shard_id, hinted, report,
                                       deadline)
                else:
                    report["shards"] += 1
                    continue
                break
        report["duration_s"] = round(self.clock() - t0, 4)
        with self._ring_lock:
            self._ring.append(report)
            self.totals["cycles"] += 1
            for k in ("blocks_checked", "blocks_diverged", "series_repaired",
                      "peer_shed", "errors"):
                self.totals[k] += report[k]
        return report

    def _repair_shard(self, namespace: str, shard_id: int,
                      hinted: dict[tuple, set[int]], report: dict,
                      deadline: float | None) -> None:
        from m3_tpu.client.breaker import BreakerOpen
        from m3_tpu.storage.peers import (
            local_rollup_digests,
            repair_shard_block,
        )

        ns = self.db.namespaces.get(namespace)
        if ns is None or shard_id not in ns.shards:
            return
        peers = self.peers_fn(shard_id)
        if not peers:
            return
        local = local_rollup_digests(self.db, namespace, shard_id)
        divergent: set[int] = set(hinted.get((namespace, shard_id), ()))
        reachable = []
        for peer in peers:
            try:
                remote = peer.rollup_digests(namespace, shard_id)
            except faults.SimulatedCrash:
                faults.escalate()  # our own injected death mid-cycle
                raise
            except BreakerOpen:
                # dead peer shed by the shared circuit: one cheap local
                # rejection, not a timeout per block
                report["peer_shed"] += 1
                self._scope.counter("peer_shed")
                continue
            except Exception as e:  # noqa: BLE001 - peer unreachable
                report["errors"] += 1
                self._scope.counter("peer_errors")
                self.log.info("rollup exchange failed", peer=str(peer),
                              error=str(e))
                continue
            reachable.append(peer)
            # symmetric difference: blocks only one side has, or held
            # with different content, fall through to per-series repair
            for bs in set(local) | set(remote):
                if local.get(bs) != remote.get(bs):
                    divergent.add(bs)
        checked = len(set(local) | divergent)
        report["blocks_checked"] += checked
        self._scope.counter("blocks_checked", checked)
        if not reachable or not divergent:
            return
        for bs in sorted(divergent):
            if deadline is not None and self.clock() > deadline:
                report["deadline_hit"] = True
                return
            try:
                res = repair_shard_block(self.db, namespace, shard_id, bs,
                                         reachable, pacer=self._pacer)
            except faults.SimulatedCrash:
                faults.escalate()
                raise
            except Exception as e:  # noqa: BLE001 - one bad block must
                # not end the cycle for every other block/shard
                report["errors"] += 1
                self._scope.counter("block_errors")
                self.log.info("block repair failed", namespace=namespace,
                              shard=shard_id, block_start=bs, error=str(e))
                continue
            if res.diverged:
                report["blocks_diverged"] += 1
                self._scope.counter("blocks_diverged")
            report["series_repaired"] += res.repaired
            if res.repaired:
                self._scope.counter("series_repaired", res.repaired)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="repair-daemon")
        self._thread.start()

    def _sleep_s(self) -> float:
        opts = self.opts
        return opts.interval_s * (1.0 + opts.jitter_frac * self._rng.random())

    def _run(self) -> None:
        from m3_tpu.utils import profiler

        # stall watchdog: the repair loop beats once per cycle; a cycle
        # wedged past the (retunable) interval is flagged with its stack
        hb = profiler.register_heartbeat("repair.cycle", self.opts.interval_s)
        # jittered initial delay: a fleet booting together must not fire
        # its first repair wave in lockstep on top of bootstrap traffic
        self._stop.wait(self._sleep_s() * 0.5)
        while not self._stop.is_set():
            hb.interval_s = max(self.opts.interval_s,
                                self.opts.cycle_deadline_s)
            hb.beat()
            if self.opts.enabled:
                try:
                    self.run_cycle()
                except faults.SimulatedCrash:
                    # armed (rig): the whole process dies here, SIGKILL
                    # parity; unarmed in-process: die loudly (daemon
                    # thread death is the crash analogue)
                    faults.escalate()
                    raise
                except Exception as e:  # noqa: BLE001 - a failed cycle
                    # must not kill the long-running daemon
                    with self._ring_lock:
                        self.totals["errors"] += 1
                    self.log.info("repair cycle error; continuing",
                                  error=str(e))
            self._stop.wait(self._sleep_s())

    def stop(self) -> None:
        self._stop.set()
        self._unmonitor()
        from m3_tpu.utils import profiler

        profiler.default_watchdog().unregister("repair.cycle")
        if self._unwatch is not None:
            try:
                self._unwatch()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
            self._unwatch = None
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    # -- status (/debug/repair) ---------------------------------------------

    def status(self) -> dict:
        with self._ring_lock:
            ring = list(self._ring)
            totals = dict(self.totals)
        with self._queue_lock:
            depth = len(self._queue)
        return {"options": asdict(self.opts), "totals": totals,
                "queue_depth": depth, "last_cycles": ring}
