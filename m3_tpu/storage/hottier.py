"""Device-resident hot tier: a bounded, paged cache of PREPARED query
slabs pinned in device memory (ROADMAP #3).

The whole-query compiler's host prep (window bounds, per-device slab
fill, prefix sums) plus the host->device transfer of those slabs is
what a REPEATED dashboard query pays after the block cache has already
amortized the decode.  This tier keys the prepared slab set on the
fetch's content identity — (namespace data versions, selector, time
range, eval grid, plan base, precision) — so an unchanged repeat skips
`window_bounds_batch`, `_slab_cuts`/`_fill_slabs` and the transfer
entirely: the compiled program re-runs against warm device buffers.

On CPU backends the "device" is jax's host platform and the tier is an
ordinary arena of committed buffers; when a TPU tunnel is live the same
code pins the working set in device HBM (the serving-tier story).  A
``bf16`` mirror (half the bytes; EQuARX's reduced-precision argument)
is negotiated PER QUERY: the API layer's ``?precision=bf16`` opt-in
installs a thread-local grant, and only plan bases whose output
tolerance permits it (`compiler._BF16_OK_BASES`) quantize — the
precision rides the cache key, so full-precision queries can never read
a quantized entry.

Saturation plane: byte occupancy/entries/evictions ride the
``queue_*{queue=hot_tier}`` gauges (PR-11 snapshot-hook seam, m3lint
``inv-pagepool-gauge``); per-query hit/miss counters land under
``storage.hot_tier`` and the ``hot_tier`` block on ``?explain=analyze``.
``M3_TPU_HOT_TIER_MB=0`` disables the tier.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from contextlib import contextmanager

from m3_tpu.utils import compute_stats
from m3_tpu.utils.instrument import monitor_queue


class HotTier:
    """Bytes-bounded LRU of prepared slab entries (device arrays)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()  # key -> (entry, nbytes)
        self.bytes_used = 0
        # resident bytes of the reduced-precision mirror alone (entries
        # prepared under a bf16 grant) — the device-memory gauges split
        # it out so operators can see what the opt-in actually saves
        self.bytes_bf16 = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return hit[0]

    @staticmethod
    def _is_bf16(entry) -> bool:
        try:
            return entry.get("precision") == "bf16"
        except AttributeError:
            return False

    def put(self, key, entry: dict, nbytes: int) -> None:
        if nbytes > self.max_bytes:
            return  # one oversized query must not wipe the working set
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes_used -= old[1]
                if self._is_bf16(old[0]):
                    self.bytes_bf16 -= old[1]
            self._entries[key] = (entry, nbytes)
            self.bytes_used += nbytes
            if self._is_bf16(entry):
                self.bytes_bf16 += nbytes
            while self.bytes_used > self.max_bytes and self._entries:
                _k, (e, nb) = self._entries.popitem(last=False)
                self.bytes_used -= nb
                if self._is_bf16(e):
                    self.bytes_bf16 -= nb
                self.evictions += 1

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes_used = 0
            self.bytes_bf16 = 0

    def stats(self) -> dict:
        """Entries + device bytes (total and bf16-mirror share) for the
        compute_stats device-cache gauges and /debug/compute."""
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": self.bytes_used,
                    "bf16_bytes": self.bytes_bf16,
                    "evictions": self.evictions,
                    "hits": self.hits, "misses": self.misses}

    def __len__(self) -> int:
        return len(self._entries)


_lock = threading.Lock()
_default: HotTier | None = None
_default_built = False


def default() -> HotTier | None:
    """The process hot tier, sized by M3_TPU_HOT_TIER_MB (default 256;
    0 disables). Built lazily on the first compiled query; the built
    flag is read lock-free on the hot path (set LAST, after _default,
    so a racing reader sees either "not built" or the finished tier)."""
    global _default, _default_built
    if _default_built:
        return _default
    with _lock:
        if not _default_built:
            try:
                mb = int(os.environ.get("M3_TPU_HOT_TIER_MB", "256"))
            except ValueError:
                mb = 256
            _default = HotTier(mb << 20) if mb > 0 else None
            _default_built = True
        return _default


def reset_default() -> None:
    """Drop the process tier (tests re-read the env on next use)."""
    global _default, _default_built
    with _lock:
        _default = None
        _default_built = False


# saturation-plane registration: depth/capacity in BYTES, drops =
# LRU evictions (one module-level registration, label set bounded)
monitor_queue("hot_tier",
              lambda: _default.bytes_used if _default is not None else 0,
              capacity=lambda: _default.max_bytes
              if _default is not None else 0,
              drops_fn=lambda: _default.evictions
              if _default is not None else 0)

# device-cache ledger registration: entries + device bytes (incl. the
# bf16-mirror share) ride the compute.device_cache{cache=hot_tier}
# gauges and the /debug/compute payload (utils/compute_stats reads,
# never imports storage)
compute_stats.register_device_cache(
    "hot_tier",
    lambda: _default.stats() if _default is not None
    else {"entries": 0, "bytes": 0, "bf16_bytes": 0})


# ---------------------------------------------------------------------------
# per-query precision negotiation (the bf16 mirror opt-in)
# ---------------------------------------------------------------------------

_tl = threading.local()


@contextmanager
def negotiated_precision(precision: str | None):
    """Install the query's precision grant for this thread ("bf16" from
    the API layer's ?precision=bf16; None = full precision). The
    compiler honors it only for tolerance-permitting plan bases."""
    prev = getattr(_tl, "precision", None)
    _tl.precision = precision
    try:
        yield
    finally:
        _tl.precision = prev


def query_precision() -> str | None:
    return getattr(_tl, "precision", None)
