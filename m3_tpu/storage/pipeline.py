"""Bounded-depth pipelined dataflow executor (ROADMAP #2).

The T3 pattern (PAPERS.md "Transparent Tracking & Triggering for
Fine-grained Overlap of Compute & Collectives") applied to the storage
engine's host dataflow: instead of `fetch whole group -> decode whole
group -> next group`, a small fixed worker pool runs the NEXT group's
fetch/RPC leg while the CALLER decodes the current one, with a bounded
prefetch depth so memory stays flat. The same executor serves both hot
paths:

  read side   `Shard`/`Namespace.read_many` push per-(shard, block)
              gather legs through ``run_stages`` so group N+1's fileset
              gather overlaps group N's decode rung, and
              `Session.fetch_many` / the coordinator fanout put every
              node/zone RPC in flight at once instead of draining them
              serially.
  write side  `Database.write_batch` splits a big batch into WAL chunks
              on a per-namespace FIFO ``lane`` — the lane worker packs/
              flushes chunk N while the caller runs chunk N-1's buffer
              and index inserts. Ack (the call returning) still happens
              only after every chunk's WAL stage completed, so the
              acked => durably-logged contract is untouched.

Design rules (enforced by m3lint + the shadow-lock checker):

- every lock is taken through the standard ``with`` discipline;
- the task queue is bounded and registered with
  ``instrument.monitor_queue`` (inv-queue-gauge) — saturation is a
  gauge, not a mystery;
- the ``pipeline.task`` fault point fires at SUBMIT time on the caller
  thread, so injection schedules stay deterministic under the seeded
  chaos specs (worker-side execution order is not);
- a worker that catches ``SimulatedCrash`` escalates (armed chaos ==
  process death) before handing the exception to the consumer, which
  re-raises it in submission order — serial-path crash semantics.
- hand-rolled thread-pool/queue pipelines anywhere else in the tree are
  an m3lint finding (``conc-handrolled-pipeline``): one executor seam,
  one saturation story, one fault surface.

Hatches: ``M3_TPU_PIPELINE=0`` pins every caller to its serial path
(bisection); ``M3_TPU_PIPELINE_WORKERS`` / ``M3_TPU_PIPELINE_DEPTH`` /
``M3_TPU_PIPELINE_WAL_CHUNK`` size the pool, the prefetch depth and the
write-side WAL chunking. Tasks submitted FROM a pipeline worker run
inline (``active()`` is False there): a worker waiting on the pool that
must run its work is a deadlock, not a pipeline.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from m3_tpu.utils import faults
from m3_tpu.utils.instrument import default_registry, monitor_queue

_scope = default_registry().root_scope("pipeline")

# worker heartbeat cadence: long enough that a worker parked on a slow
# (but legitimate) RPC leg doesn't trip the stall watchdog, short enough
# that a genuinely wedged pool is flagged within a minute
_HEARTBEAT_S = 30.0
_IDLE_POLL_S = 1.0


# service-config overrides (configure()); env always wins, defaults last
_cfg: dict[str, int] = {}


def _env_int(name: str, default: int, floor: int = 1) -> int:
    raw = os.environ.get(name, "")
    if raw:
        try:
            return max(floor, int(raw))
        except ValueError:
            pass
    if name in _cfg:
        return max(floor, _cfg[name])
    return default


def enabled() -> bool:
    """The M3_TPU_PIPELINE hatch: unset/1 = on, 0 = serial everywhere."""
    return os.environ.get("M3_TPU_PIPELINE", "1") != "0"


_tl = threading.local()


def in_worker() -> bool:
    return getattr(_tl, "worker", False)


def active() -> bool:
    """True when callers should pipeline: the hatch is open AND this is
    not already a pipeline worker (nested submission would wait on the
    pool it occupies — run inline instead)."""
    return enabled() and not in_worker()


def wal_chunk_entries() -> int:
    """Write-side WAL chunk size: batches larger than this split into
    per-chunk lane appends so buffer/index inserts for chunk N-1 overlap
    the WAL pack/flush of chunk N."""
    return _env_int("M3_TPU_PIPELINE_WAL_CHUNK", 4096)


class _Future:
    """Single-shot result slot (Event-based; no cancellation races)."""

    __slots__ = ("_done", "_result", "_exc")

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result = None
        self._exc: BaseException | None = None

    def run(self, fn) -> None:
        """Execute fn() capturing its outcome for the consumer. A
        SimulatedCrash escalates HERE (armed chaos kills the process at
        the point of injury) and is still handed to the consumer, which
        re-raises it in submission order — the serial path's semantics."""
        try:
            self._result = fn()
        except faults.SimulatedCrash as e:
            faults.escalate()
            self._exc = e
        except BaseException as e:  # delivered to the consumer's result()
            self._exc = e
        finally:
            self._done.set()

    def result(self):
        self._done.wait()
        if self._exc is not None:
            raise self._exc
        return self._result


class SerialLane:
    """Strict-FIFO execution lane over the shared pool: at most one lane
    task runs at a time, in submission order — the WAL discipline (the
    emitted commitlog byte stream must equal the serial path's)."""

    def __init__(self, executor: "PipelineExecutor", name: str):
        self._executor = executor
        self.name = name
        self._lock = threading.Lock()
        self._pending: deque = deque()
        self._draining = False
        # lane backlog on the saturation plane (depth only: the lane is
        # deliberately unbounded — WAL appends must never drop)
        self._unmonitor = monitor_queue(
            f"pipeline_lane_{name}", lambda: len(self._pending), None,
            owner=self)

    def submit(self, fn) -> _Future:
        faults.check("pipeline.task", lane=self.name)
        fut = _Future()
        with self._lock:
            self._pending.append((fn, fut))
            kick = not self._draining
            if kick:
                self._draining = True
        if kick:
            self._executor._enqueue(self._drain)
        return fut

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    self._draining = False
                    return
                fn, fut = self._pending.popleft()
            fut.run(fn)


class PipelineExecutor:
    """Fixed worker pool + bounded task queue + named FIFO lanes."""

    def __init__(self, workers: int | None = None,
                 queue_cap: int | None = None, name: str = "storage"):
        self.workers = workers or _env_int(
            "M3_TPU_PIPELINE_WORKERS", min(8, max(2, os.cpu_count() or 2)))
        self.name = name
        cap = queue_cap or max(64, self.workers * 16)
        self._q: queue.Queue = queue.Queue(maxsize=cap)
        self._lanes: dict[str, SerialLane] = {}
        self._lock = threading.Lock()
        self._started = False
        self._heartbeat = None
        self._unmonitor = monitor_queue(
            f"pipeline_tasks_{name}", self._q.qsize, cap, owner=self)

    # -- pool plumbing --

    def _ensure_started(self) -> None:
        with self._lock:
            if self._started:
                return
            self._started = True
            from m3_tpu.utils import profiler

            self._heartbeat = profiler.register_heartbeat(
                f"pipeline.workers.{self.name}", _HEARTBEAT_S)
            for i in range(self.workers):
                t = threading.Thread(target=self._worker_loop,
                                     name=f"pipeline-{self.name}-{i}",
                                     daemon=True)
                t.start()

    def _worker_loop(self) -> None:
        _tl.worker = True
        hb = self._heartbeat
        while True:
            try:
                task = self._q.get(timeout=_IDLE_POLL_S)
            except queue.Empty:
                if hb is not None:
                    hb.beat()
                continue
            if hb is not None:
                hb.beat()
            fn, fut = task
            if fut is None:
                fn()  # lane drain: runs its own futures
            else:
                fut.run(fn)

    def _enqueue(self, drain_fn) -> None:
        self._ensure_started()
        self._q.put((drain_fn, None))

    def submit(self, fn, point_ctx: str = "") -> _Future:
        # same semantic seam as SerialLane.submit — ONE submit-time
        # injection schedule for "a pipeline task", whichever entry the
        # caller took (deterministic: both fire on the caller thread)
        # m3lint: disable=inv-fault-point-unique
        faults.check("pipeline.task", stage=point_ctx)
        self._ensure_started()
        fut = _Future()
        self._q.put((fn, fut))
        return fut

    def lane(self, name: str) -> SerialLane:
        with self._lock:
            ln = self._lanes.get(name)
            if ln is None:
                ln = self._lanes[name] = SerialLane(self, name)
            return ln

    def map_ordered(self, fns: list, depth: int):
        """Yield fn() results in input order with up to ``depth`` calls
        in flight ahead of the consumer — the bounded-depth prefetch the
        read path overlaps gather and decode through. Falls back to a
        plain inline loop from worker context (no nested waits)."""
        if in_worker() or len(fns) <= 1:
            for fn in fns:
                yield fn()
            return
        depth = max(1, depth)
        futs: deque = deque()
        it = iter(fns)
        for fn in it:
            futs.append(self.submit(fn, point_ctx="map"))
            if len(futs) >= depth:
                break
        while futs:
            fut = futs.popleft()
            nxt = next(it, None)
            if nxt is not None:
                futs.append(self.submit(nxt, point_ctx="map"))
            yield fut.result()


_default_lock = threading.Lock()
_default: PipelineExecutor | None = None
_client: PipelineExecutor | None = None


def default_executor() -> PipelineExecutor:
    """The STORAGE pool: fileset gathers and WAL-lane appends — leaf
    tasks that never wait on another pipeline task."""
    global _default
    with _default_lock:
        if _default is None:
            _default = PipelineExecutor()
        return _default


def client_executor() -> PipelineExecutor:
    """The CLIENT pool: session/fanout RPC legs. Deliberately separate
    from the storage pool — a leg blocks on a downstream node whose read
    path needs STORAGE workers, so colocated processes (tests, the
    in-process rig) sharing one pool would form a wait cycle: legs hold
    every worker while the gathers that would unblock them queue behind
    them. Two pools with a strict leg->storage dependency direction
    cannot cycle. Sized for I/O (legs park on sockets, not cores)."""
    global _client
    with _default_lock:
        if _client is None:
            _client = PipelineExecutor(
                workers=_env_int("M3_TPU_PIPELINE_CLIENT_WORKERS",
                                 min(16, max(4, 2 * (os.cpu_count() or 2)))),
                name="client")
        return _client


def started() -> bool:
    """Whether the default pool ever spawned workers (hatch tests)."""
    with _default_lock:
        return _default is not None and _default._started


def configure(workers: int | None = None, depth: int | None = None,
              wal_chunk: int | None = None) -> None:
    """Service-config knobs (dbnode `pipeline:` section), recorded as
    module state: an explicit M3_TPU_PIPELINE_* env var still wins, the
    built-in defaults lose, and repeated calls last-write-win (an
    in-process multi-service harness gets the LAST service's sizing —
    depth/wal_chunk take effect immediately; worker counts bind when a
    pool first starts, so configure before first pipelined use)."""
    for name, value in (("M3_TPU_PIPELINE_WORKERS", workers),
                        ("M3_TPU_PIPELINE_DEPTH", depth),
                        ("M3_TPU_PIPELINE_WAL_CHUNK", wal_chunk)):
        if value is not None:
            _cfg[name] = int(value)


def prefetch_depth() -> int:
    return _env_int("M3_TPU_PIPELINE_DEPTH", 2)


def submit_client_leg(fn, tracer, ctx, point_ctx: str) -> _Future:
    """Submit ONE fan-out RPC leg to the client pool with the shared leg
    policy (session fetch_many and the coordinator fanout both ride
    this): the caller's trace context is re-activated on the worker
    (header injection and exemplar capture are thread-local), the leg is
    timed, and the outcome comes back AS A VALUE — ``(result, err,
    seconds)`` — so the consumer applies its own per-host/per-zone
    failure policy in submission order. A SimulatedCrash escalates on
    the worker (armed chaos == process death at the point of injury) and
    is still returned as ``err`` for the consumer to re-raise."""

    def leg():
        t0 = time.perf_counter()
        try:
            with tracer.activate(ctx):
                return fn(), None, time.perf_counter() - t0
        except faults.SimulatedCrash as e:
            faults.escalate()
            return None, e, time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001 - delivered to the consumer
            return None, e, time.perf_counter() - t0

    return client_executor().submit(leg, point_ctx=point_ctx)


@dataclass
class StageStats:
    """Per-run overlap accounting: wall time vs sum-of-stage time. When
    ``sum(stages.values()) > wall_s`` the pipeline overlapped work; the
    ratio rides ``?explain=analyze`` via querystats.record_pipeline."""

    items: int = 0
    wall_s: float = 0.0
    stages: dict = field(default_factory=dict)

    def add_stage(self, name: str, dt: float) -> None:
        self.stages[name] = self.stages.get(name, 0.0) + dt


def run_stages(items: list, produce, consume, depth: int | None = None,
               produce_stage: str = "gather",
               consume_stage: str = "decode") -> StageStats:
    """The two-stage overlap primitive: ``produce(item)`` runs on the
    pool up to ``depth`` items ahead (thread-safe leg: fileset gather,
    node RPC) while ``consume(item, payload)`` runs on the CALLING
    thread in submission order (thread-local leg: decode rungs,
    querystats, cache fills). With the hatch closed (or from a worker)
    it degrades to the exact serial interleaving ``consume(produce())``
    — same work, same order, no threads."""
    stats = StageStats(items=len(items))
    t0 = time.perf_counter()

    def timed_produce(item):
        p0 = time.perf_counter()
        payload = produce(item)
        return item, payload, time.perf_counter() - p0

    if active() and len(items) > 1:
        ex = default_executor()
        results = ex.map_ordered(
            [lambda it=it: timed_produce(it) for it in items],
            depth or prefetch_depth())
    else:
        results = (timed_produce(it) for it in items)
    for item, payload, p_dt in results:
        stats.add_stage(produce_stage, p_dt)
        c0 = time.perf_counter()
        consume(item, payload)
        stats.add_stage(consume_stage, time.perf_counter() - c0)
    stats.wall_s = time.perf_counter() - t0
    if stats.items:
        _scope.subscope("stage", stage=produce_stage).observe(
            "stage_seconds", stats.stages.get(produce_stage, 0.0))
        _scope.subscope("stage", stage=consume_stage).observe(
            "stage_seconds", stats.stages.get(consume_stage, 0.0))
    return stats
