"""Decoded-block LRU cache — the WiredList role.

The reference keeps recently-read compressed blocks wired in memory with a
global LRU (/root/reference/src/dbnode/storage/block/wired_list.go:77-131);
here the cached unit is the DECODED (times, value_bits) pair per
(namespace, shard, block_start, series_id) — the expensive step on the
read path is the per-series stream decode, so that is what is amortized.
One instance per Database, shared by every shard; entries for a block are
invalidated when a flush writes a replacement volume.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class BlockCache:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return hit

    def put(self, key, value) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def get_many(self, keys) -> list:
        """Batched probe under ONE lock acquisition (the read_many hot
        path probes a whole (shard, block) group at once)."""
        out = []
        with self._lock:
            for key in keys:
                hit = self._entries.get(key)
                if hit is None:
                    self.misses += 1
                else:
                    self._entries.move_to_end(key)
                    self.hits += 1
                out.append(hit)
        return out

    def put_many(self, items) -> None:
        """Batched fill under one lock acquisition; items: [(key, value)]."""
        if self.capacity <= 0:
            return
        with self._lock:
            for key, value in items:
                self._entries[key] = value
                self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def invalidate_block(self, namespace: str, shard_id: int,
                         block_start: int) -> int:
        """Drop every cached series of one (shard, block) — called when a
        flush replaces the block's fileset volume."""
        prefix = (namespace, shard_id, block_start)
        with self._lock:
            doomed = [k for k in self._entries if k[:3] == prefix]
            for k in doomed:
                del self._entries[k]
        return len(doomed)

    def __len__(self) -> int:
        return len(self._entries)
