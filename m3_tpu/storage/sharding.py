"""Shard routing: murmur3(id) % N virtual shards.

Parity: /root/reference/src/dbnode/sharding/shardset.go:76,158-175.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from m3_tpu.utils.hash import murmur3_32, murmur3_32_batch

DEFAULT_SEED = 42

# below this, the vectorized path's setup (buffer join + pad) costs more
# than it saves over the scalar loop
_BATCH_MIN = 64


@dataclass(frozen=True)
class ShardSet:
    n_shards: int
    shard_ids: tuple[int, ...] = field(default=None)
    seed: int = DEFAULT_SEED

    def __post_init__(self):
        if self.shard_ids is None:
            object.__setattr__(self, "shard_ids", tuple(range(self.n_shards)))

    def lookup(self, series_id: bytes) -> int:
        return murmur3_32(series_id, self.seed) % self.n_shards

    def lookup_many(self, series_ids: list[bytes]) -> list[int]:
        """Batched series->shard routing (one vectorized murmur3 pass;
        read_many routes 10k+ ids per call through here)."""
        if len(series_ids) < _BATCH_MIN:
            return [self.lookup(sid) for sid in series_ids]
        return (murmur3_32_batch(series_ids, self.seed)
                % self.n_shards).tolist()

    def owns(self, shard: int) -> bool:
        return shard in self.shard_ids
