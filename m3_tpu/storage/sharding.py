"""Shard routing: murmur3(id) % N virtual shards.

Parity: /root/reference/src/dbnode/sharding/shardset.go:76,158-175.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from m3_tpu.utils.hash import murmur3_32

DEFAULT_SEED = 42


@dataclass(frozen=True)
class ShardSet:
    n_shards: int
    shard_ids: tuple[int, ...] = field(default=None)
    seed: int = DEFAULT_SEED

    def __post_init__(self):
        if self.shard_ids is None:
            object.__setattr__(self, "shard_ids", tuple(range(self.n_shards)))

    def lookup(self, series_id: bytes) -> int:
        return murmur3_32(series_id, self.seed) % self.n_shards

    def owns(self, shard: int) -> bool:
        return shard in self.shard_ids
