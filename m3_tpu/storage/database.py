"""Database: namespaces + commitlog + bootstrap + tick orchestration.

Role parity with the reference storage.Database
(/root/reference/src/dbnode/storage/database.go:99 — Write:795,
ReadEncoded:1068, Bootstrap:1140) and the mediator tick/flush loop
(storage/mediator.go:79-160), collapsed into explicit open/write/read/
tick calls driven by the host control plane.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass

import numpy as np

from m3_tpu.storage import commitlog
from m3_tpu.storage.namespace import Namespace
from m3_tpu.storage.options import DatabaseOptions, NamespaceOptions
from m3_tpu.storage.sharding import ShardSet
from m3_tpu.utils import faults
from m3_tpu.utils.instrument import default_registry

log = logging.getLogger(__name__)

# write-seam latency histogram (p50/p99 derivable from /metrics _bucket
# series); the handle pre-resolves the metric key so the per-datapoint
# hot path pays one lock + bisect per observation, nothing more
_scope = default_registry().root_scope("db")
_observe_write = _scope.histogram_handle("write_seconds")
# the batched seam observes ONCE per batch; the points counter keeps
# throughput accounting comparable with the per-point histogram's count
_observe_write_batch = _scope.histogram_handle("write_batch_seconds")
# batch-size distribution (count-shaped bounds): whether ingest batches
# amortize the columnar pass is invisible from latency alone
from m3_tpu.utils.instrument import COUNT_BUCKETS  # noqa: E402

_observe_write_batch_size = _scope.histogram_handle(
    "write_batch_size", bounds=COUNT_BUCKETS)


@dataclass
class Datapoint:
    timestamp_ns: int
    value: float


def _f64_to_bits(v: float) -> int:
    return int(np.float64(v).view(np.uint64))


class Database:
    """Single-node database ("local" topology mode of the reference)."""

    def __init__(self, path: str, db_opts: DatabaseOptions | None = None):
        self.path = path
        self.opts = db_opts or DatabaseOptions()
        self.namespaces: dict[str, Namespace] = {}
        self._commitlogs: dict[str, commitlog.CommitLogWriter] = {}
        # block windows logged into the ACTIVE commitlog, per namespace
        self._log_windows: dict[str, set[int]] = {}
        # rotated logs awaiting deletion:
        # ns -> [(path, windows-it-covers, retired_at_ns)]
        self._retired_logs: dict[str, list[tuple[str, set[int], int]]] = {}
        # (ns, window) -> time of the last snapshot covering every shard
        self._snapshot_times: dict[tuple[str, int], int] = {}
        self._open = False
        self._shard_set = ShardSet(self.opts.n_shards, self.opts.owned_shards)
        # optional storage-layer QueryLimits shared by all read paths
        self.limits = None
        from m3_tpu.storage.cache import BlockCache

        # decoded-block LRU shared by every shard (WiredList role)
        self.block_cache = BlockCache(self.opts.block_cache_entries)
        from m3_tpu.cluster.runtime import PersistRateLimiter

        # fileset write pacing shared by every shard (reference ratelimit
        # role); rate comes from runtime options (0 = unlimited)
        self.persist_limiter = PersistRateLimiter()
        # live-tunable options (set via apply_runtime; None = all defaults)
        self.runtime = None
        self._runtime_opts = None

    # -- lifecycle --

    @property
    def fs_root(self) -> str:
        return os.path.join(self.path, "data")

    @property
    def snapshots_root(self) -> str:
        return os.path.join(self.path, "snapshots")

    def commitlog_dir(self, namespace: str) -> str:
        return os.path.join(self.path, "commitlog", namespace)

    def create_namespace(self, name: str, opts: NamespaceOptions | None = None) -> Namespace:
        if name in self.namespaces:
            return self.namespaces[name]
        ns = Namespace(name, opts or NamespaceOptions(), self.opts, self._shard_set,
                       self.fs_root)
        ns.database = self
        for shard in ns.shards.values():
            shard.cache = self.block_cache
            shard.persist_limiter = self.persist_limiter
        self.namespaces[name] = ns
        if self._open:
            # a namespace created on a LIVE database (the registry-sync
            # path every cluster node takes for dynamically-added tenant
            # namespaces, and the admin API) must bootstrap its durable
            # state exactly as open() would have — filesets, snapshots,
            # then commitlog replay. Without this, a restarted node
            # re-creates the namespace EMPTY and silently abandons its
            # WAL: acked writes vanish once the other replicas restart
            # too (found by the chaos rig's zero-acked-write-loss audit).
            self._bootstrap_namespace(name, ns, time.time_ns())
        return ns

    def drop_namespace(self, name: str) -> None:
        """Remove a namespace, closing its commitlog writer and retiring
        its log-tracking state (files on disk are left for the operator)."""
        ns = self.namespaces.pop(name, None)
        if ns is None:
            return
        log = self._commitlogs.pop(name, None)
        if log is not None:
            log.close()
        self._log_windows.pop(name, None)
        self._retired_logs.pop(name, None)
        for key in [k for k in self._snapshot_times if k[0] == name]:
            del self._snapshot_times[key]

    def _open_commitlog(self, namespace: str) -> None:
        d = self.commitlog_dir(namespace)
        path = os.path.join(d, f"commitlog-{int(time.time()*1e9)}.db")
        self._commitlogs[namespace] = commitlog.CommitLogWriter(
            path, self.opts.commitlog_flush_every_bytes
        )
        self._log_windows[namespace] = set()

    def open(self, now_ns: int | None = None) -> None:
        """Open + bootstrap: filesets first, then commitlog replay on top
        (the fs -> commitlog bootstrapper order of the reference's default
        pipeline, storage/bootstrap/bootstrapper/README.md)."""
        self._open = True
        now_ns = now_ns if now_ns is not None else time.time_ns()
        for name, ns in self.namespaces.items():
            self._bootstrap_namespace(name, ns, now_ns)

    def _bootstrap_namespace(self, name: str, ns: Namespace,
                             now_ns: int) -> None:
        """One namespace's boot sequence (shared by open() and live
        create_namespace): index + fileset bootstrap, snapshot restore,
        commitlog replay, then a fresh commitlog writer."""
        if ns.opts.bootstrap_enabled:
            restored = set()
            if ns.index is not None:
                from m3_tpu.index import persist as index_persist

                r = ns.opts.retention
                restored = index_persist.load_index(
                    ns.index, self.fs_root, name,
                    cutoff_ns=r.block_start(now_ns - r.retention_ns),
                )
            ns.bootstrap_from_fs(now_ns, skip_index_blocks=restored)
            self._restore_snapshots(name, ns, now_ns)
            self._replay_commitlogs(name, ns, now_ns)
        if ns.opts.writes_to_commitlog:
            self._open_commitlog(name)

    def _replay_commitlogs(self, name: str, ns: Namespace,
                           now_ns: int | None = None) -> None:
        """Replay every surviving log entry into the buffers. Entries whose
        datapoints also live in a flushed volume are resolved by the normal
        last-write-wins merge (and re-merged into a higher volume on the
        next flush), so replay is safe to repeat; replayed files are retired
        and deleted once every window they cover has flushed.

        Replay runs in SALVAGE mode: a corrupt interior chunk truncates
        that log (dropping everything after it, with a warning naming the
        offset and byte count) instead of raising — a damaged WAL must
        degrade bootstrap, never brick it.

        Each surviving log replays as ONE columnar batch through
        Namespace.write_many (vectorized shard routing, one buffer lock
        per (shard, window) group, one index insert_many pass with the
        tag blobs decoded once per distinct series) instead of a
        per-point write loop; entry order is preserved per window, so
        seal-time last-write-wins resolves exactly as the per-point
        replay did. Unowned shards degrade per row (the old loop's
        silent skip)."""
        from m3_tpu.utils.ident import decode_tags

        retired = self._retired_logs.setdefault(name, [])
        cutoff = None
        r = ns.opts.retention
        if now_ns is not None:
            cutoff = r.block_start(now_ns - r.retention_ns)
        for path in commitlog.log_files(self.commitlog_dir(name)):
            entries, report = commitlog.replay_salvage(path)
            if not report.clean:
                log.warning(
                    "commitlog salvage: %s truncated at byte %d (%s): "
                    "replayed %d entries, dropped %d bytes",
                    path, report.truncated_at, report.reason,
                    report.entries, report.dropped_bytes,
                )
            sids: list[bytes] = []
            encs: list[bytes] = []
            fields_list: list = []
            t_list: list[int] = []
            v_list: list[int] = []
            tag_fields: dict[bytes, list | None] = {}  # decode once per blob
            for e in entries:
                if cutoff is not None and e.time_ns < cutoff:
                    continue  # past retention: don't resurrect
                sids.append(e.series_id)
                encs.append(e.encoded_tags)
                t_list.append(e.time_ns)
                v_list.append(e.value_bits)
                if e.encoded_tags:
                    fields = tag_fields.get(e.encoded_tags)
                    if fields is None:
                        fields = tag_fields[e.encoded_tags] = \
                            decode_tags(e.encoded_tags)
                    fields_list.append(fields)
                else:
                    fields_list.append(None)  # untagged: skip the index
            windows: set[int] = set()
            if sids:
                times = np.array(t_list, np.int64)
                vbits = np.array(v_list, np.uint64)
                errors = ns.write_many(sids, times, vbits, encs, fields_list)
                ok = np.array([err is None for err in errors], bool)
                if ok.any():  # unowned-shard rows don't pin their windows
                    t_ok = times[ok]
                    for w in np.unique(
                            t_ok - (t_ok % r.block_size_ns)).tolist():
                        windows.add(int(w))
            retired.append((path, windows, now_ns if now_ns is not None else 0))

    def _cleanup_retired_logs(self, name: str, ns: Namespace, now_ns: int) -> None:
        r = ns.opts.retention
        remaining = []
        for path, windows, retired_at in self._retired_logs.get(name, []):
            covered = all(
                (
                    w + r.block_size_ns + r.buffer_past_ns <= now_ns
                    and all(s.buffer.points_in(w) == 0 for s in ns.shards.values())
                )
                or w < r.block_start(now_ns - r.retention_ns)  # past retention
                # a snapshot taken STRICTLY after the log was retired holds
                # every datapoint the log did (same-instant snapshots race
                # concurrent writers; the next tick's snapshot covers them)
                or self._snapshot_times.get((name, w), -1) > retired_at
                for w in windows
            )
            if covered:
                try:
                    os.remove(path)
                except OSError:
                    pass
            else:
                remaining.append((path, windows, retired_at))
        self._retired_logs[name] = remaining

    # -- snapshots --

    def snapshot(self, now_ns: int) -> dict[str, int]:
        """Snapshot every open (unflushed) buffer window of every
        snapshot-enabled namespace. Returns windows snapshotted per ns."""
        from m3_tpu.storage.fileset import list_filesets

        snap_id = int(now_ns // 1_000_000)  # monotonic across restarts
        counts: dict[str, int] = {}
        for name, ns in self.namespaces.items():
            if not ns.opts.snapshot_enabled:
                continue
            # a window is COVERED only when every shard holding it either
            # snapshotted it now or was already clean since its last
            # successful snapshot — a single failed shard must not let the
            # commitlog (or that shard's previous snapshot) be reclaimed
            ok_windows: set[int] = set()
            failed_windows: set[int] = set()
            for shard in ns.shards.values():
                done_here: set[int] = set()
                for bs in shard.buffer.block_starts():
                    seq = shard.write_seq(bs)
                    if shard.snapshotted_seq(bs) == seq:
                        ok_windows.add(bs)  # unchanged since last snapshot
                        continue
                    if shard.snapshot(bs, self.snapshots_root, snap_id):
                        shard.mark_snapshotted(bs, seq)
                        ok_windows.add(bs)
                        done_here.add(bs)
                    else:
                        failed_windows.add(bs)
                # reclaim superseded volumes ONLY where this shard's new
                # snapshot landed
                for old_bs, old_vol in list_filesets(
                    self.snapshots_root, name, shard.shard_id,
                    all_volumes=True,
                ):
                    if old_bs in done_here and old_vol < snap_id:
                        self._remove_snapshot(name, shard.shard_id, old_bs,
                                              old_vol)
            covered = ok_windows - failed_windows
            for w in covered:
                self._snapshot_times[(name, w)] = now_ns
            counts[name] = len(covered)
        return counts

    def _remove_snapshot(self, name: str, shard_id: int, bs: int,
                         vol: int) -> None:
        from m3_tpu.storage.fileset import SUFFIXES, fileset_path

        # checkpoint first: a half-deleted snapshot must read as incomplete
        for suffix in ("checkpoint",) + tuple(s for s in SUFFIXES
                                              if s != "checkpoint"):
            try:
                os.remove(fileset_path(self.snapshots_root, name, shard_id,
                                       bs, vol, suffix))
            except OSError:
                pass

    def _restore_snapshots(self, name: str, ns: Namespace, now_ns: int) -> None:
        """Load the latest snapshot of each in-flight window into the
        buffers (before commitlog replay; duplicates dedup on merge)."""
        from m3_tpu.encoding.m3tsz import decode as scalar_decode
        from m3_tpu.storage.fileset import FilesetReader, list_filesets

        cutoff = ns.opts.retention.block_start(
            now_ns - ns.opts.retention.retention_ns)
        from m3_tpu.utils.ident import decode_tags

        for shard in ns.shards.values():
            for bs, vol in list_filesets(self.snapshots_root, name,
                                         shard.shard_id):
                if bs < cutoff:
                    continue
                try:
                    reader = FilesetReader(self.snapshots_root, name,
                                           shard.shard_id, bs, vol)
                except (FileNotFoundError, ValueError):
                    continue
                for i in range(reader.n_series):
                    sid, tags, stream = reader.read_at(i)
                    n_restored = 0
                    for d in scalar_decode(
                        stream, int_optimized=ns.opts.int_optimized,
                        default_time_unit=ns.opts.write_time_unit,
                    ):
                        shard.buffer.write(
                            sid, d.timestamp_ns,
                            int(np.float64(d.value).view(np.uint64)), tags,
                        )
                        n_restored += 1
                    # restored points count as writes (dirty tracking) and
                    # re-index like commitlog replay does — the persisted
                    # index segment may be corrupt/missing for this block
                    shard._write_seq[bs] = shard._write_seq.get(bs, 0) + n_restored
                    if tags and n_restored:
                        ns.index_insert_spanning(sid, decode_tags(tags), bs)
                reader.close()

    def _cleanup_snapshots(self, name: str, ns: Namespace, now_ns: int) -> None:
        """Drop snapshots whose window is flushed-and-drained or expired."""
        from m3_tpu.storage.fileset import list_filesets

        r = ns.opts.retention
        cutoff = r.block_start(now_ns - r.retention_ns)
        for shard in ns.shards.values():
            open_windows = set(shard.buffer.block_starts())
            for bs, vol in list_filesets(self.snapshots_root, name,
                                         shard.shard_id, all_volumes=True):
                if bs >= cutoff and bs in open_windows:
                    continue  # still in flight
                self._remove_snapshot(name, shard.shard_id, bs, vol)
                self._snapshot_times.pop((name, bs), None)

    def close(self) -> None:
        for log in self._commitlogs.values():
            log.close()
        self._commitlogs.clear()
        for ns in self.namespaces.values():
            for shard in ns.shards.values():
                shard.close()  # releases current + retired fileset readers
        self._open = False

    # -- shard assignment (placement-driven; storage/cluster role) --

    @property
    def owned_shards(self) -> set[int]:
        return set(self._shard_set.shard_ids)

    def assign_shards(self, shard_ids: set[int], now_ns: int | None = None) -> tuple[set[int], set[int]]:
        """Reconcile shard ownership with a placement: create newly-assigned
        shards in every namespace (bootstrapping them from local filesets if
        present) and drop unassigned ones. Returns (added, removed).

        The topology-watch -> shard-assignment flow of the reference
        (/root/reference/src/dbnode/storage/cluster/database.go)."""
        current = self.owned_shards
        added = set(shard_ids) - current
        removed = current - set(shard_ids)
        if not added and not removed:
            return added, removed
        # order matters under concurrent writes from the HTTP handlers:
        # materialize new shard objects BEFORE publishing the new shard set
        # (a routed write finds its shard), and drop old ones only after
        for ns in self.namespaces.values():
            for sid in added:
                ns.add_shard(sid, now_ns)
        new_set = ShardSet(self.opts.n_shards, tuple(sorted(shard_ids)))
        self._shard_set = new_set
        for ns in self.namespaces.values():
            ns.shard_set = new_set
            for sid in removed:
                ns.remove_shard(sid)
        return added, removed

    # -- write/read --

    def write(self, namespace: str, series_id: bytes, t_ns: int, value: float,
              encoded_tags: bytes = b"") -> None:
        t0 = time.perf_counter()
        ns = self.namespaces[namespace]
        shard = ns.shard_for(series_id)  # validate ownership BEFORE logging
        vbits = _f64_to_bits(value)
        log = self._commitlogs.get(namespace)
        if log is not None:
            log.write(series_id, encoded_tags, t_ns, vbits, int(ns.opts.write_time_unit))
            self._log_windows[namespace].add(ns.opts.retention.block_start(t_ns))
        shard.write(series_id, t_ns, vbits, encoded_tags)
        if ns.index is not None and encoded_tags:
            # tagged-at-the-wire writes are index-visible like write_tagged,
            # not dependent on the fileset rebuild at restart
            from m3_tpu.utils.ident import decode_tags

            ns.index.insert(series_id, decode_tags(encoded_tags), t_ns)
        _observe_write(time.perf_counter() - t0)

    def write_tagged(self, namespace: str, metric_name: bytes,
                     tags: list[tuple[bytes, bytes]], t_ns: int, value: float) -> bytes:
        """Write + index a datapoint; returns the canonical series id."""
        from m3_tpu.utils.ident import encode_tags, tags_to_id

        t0 = time.perf_counter()
        ns = self.namespaces[namespace]
        fields = [(b"__name__", metric_name), *tags] if metric_name else list(tags)
        series_id = tags_to_id(metric_name, tags)
        shard = ns.shard_for(series_id)  # validate ownership BEFORE logging
        enc = encode_tags(fields)
        vbits = _f64_to_bits(value)
        log = self._commitlogs.get(namespace)
        if log is not None:
            log.write(series_id, enc, t_ns, vbits, int(ns.opts.write_time_unit))
            self._log_windows[namespace].add(ns.opts.retention.block_start(t_ns))
        shard.write(series_id, t_ns, vbits, enc)
        if ns.index is not None:
            ns.index.insert(series_id, fields, t_ns)
        _observe_write(time.perf_counter() - t0)
        return series_id

    def write_batch(self, namespace: str, entries) -> list[str | None]:
        """Storage-side batched writes — the real surface behind dbnode
        /write_batch. entries = [(metric_name, tags, t_ns, value)], the
        session/HTTP batch shape. A batch is processed as COLUMNS, not a
        loop: one tags_to_id/encode_tags pass with a per-batch memo for
        repeated series, one vectorized shard-routing pass (ownership
        validated BEFORE logging, per-point order), ONE commitlog append
        (CommitLogWriter.write_many — byte-identical framing to the
        per-point path), one buffer lock per (shard, window) group, and
        one pre-filtered index insert_many pass. Per-entry error
        isolation: a bad entry (malformed, unowned shard) degrades that
        entry only; a commitlog failure degrades every un-acked entry in
        the batch (they were never durably logged) without touching the
        buffers. Returns per-entry error strings aligned to the input
        (None = written)."""
        from m3_tpu.utils import trace

        t0 = time.perf_counter()
        try:
            with trace.span(trace.DB_WRITE_BATCH, namespace=namespace,
                            entries=len(entries)):
                results, n_ok = self._write_batch_traced(namespace, entries)
        finally:
            _observe_write_batch(time.perf_counter() - t0)
            _observe_write_batch_size(float(len(entries)))
        _scope.counter("write_batch_points", n_ok)
        return results

    def _write_batch_traced(self, namespace, entries
                            ) -> tuple[list[str | None], int]:
        from m3_tpu.utils.ident import encode_tags, tags_to_id

        ns = self.namespaces[namespace]
        n = len(entries)
        results: list[str | None] = [None] * n
        if n == 0:
            return results, 0
        # one fault-point hit per BATCH (the per-point path hits db-level
        # seams per datapoint); an injected error fails the whole call,
        # exactly like the HTTP handler's node-level faults
        faults.check("db.write_batch", namespace=namespace, entries=n)
        # identity pass: one tags_to_id/encode_tags per DISTINCT series —
        # ingest batches repeat series heavily, the memo is the point.
        # Scalars accumulate in python lists (one vectorized np.array at
        # the end: per-element ndarray stores dominate the loop otherwise)
        memo: dict = {}
        series_ids: list = [None] * n
        encs: list = [None] * n
        fields_list: list = [None] * n
        t_list: list = [0] * n
        v_list: list = [0.0] * n
        for i, e in enumerate(entries):
            try:
                metric_name, tags, t_ns, value = e
                key = (metric_name, tuple(tags))
                try:
                    got = memo.get(key)
                except TypeError:  # tags arrived as [[k, v], ...]: the
                    # tuple holds unhashable lists — normalize
                    key = (metric_name, tuple(map(tuple, tags)))
                    got = memo.get(key)
                if got is None:
                    fields = [(b"__name__", metric_name), *tags] \
                        if metric_name else list(tags)
                    got = (tags_to_id(metric_name, tags),
                           encode_tags(fields), fields)
                    memo[key] = got
                series_ids[i], encs[i], fields_list[i] = got
                t_list[i] = int(t_ns)
                v_list[i] = float(value)
            except Exception as ex:  # noqa: BLE001 - per-entry isolation
                results[i] = str(ex)
        times = np.array(t_list, np.int64)
        vbits = np.array(v_list, np.float64).view(np.uint64)
        ok0 = [i for i in range(n) if results[i] is None]
        # vectorized shard routing; ownership errors recorded BEFORE any
        # logging so an unowned row never lands in the WAL. In the common
        # all-entries-clean case the routed rows ARE entry indices; a
        # degraded batch routes the ok subset and maps rows back through it
        clean = len(ok0) == n
        route_ids = series_ids if clean else [series_ids[i] for i in ok0]
        by_shard, route_errors = ns.route_many(route_ids)
        if not clean:  # routed positions index ok0, not the entry list
            ok0_arr = np.asarray(ok0, np.intp)
            by_shard = {s: ok0_arr[rows] for s, rows in by_shard.items()}
        for k, msg in route_errors.items():
            results[k if clean else ok0[k]] = msg
        ok = [i for i in ok0 if results[i] is None] if route_errors else ok0
        if not ok:
            return results, 0
        clog = self._commitlogs.get(namespace)
        from m3_tpu.storage import pipeline

        if clog is not None and pipeline.active() \
                and len(ok) > pipeline.wal_chunk_entries():
            # pipelined write dataflow: WAL pack/flush for chunk N runs
            # on the per-namespace FIFO lane while THIS thread runs
            # chunk N-1's buffer/index inserts. Ack (returning) happens
            # only after every chunk's WAL stage completed, and a chunk
            # is buffered only AFTER its own WAL append succeeded — the
            # acked => durably-logged contract and per-entry isolation
            # are exactly the serial path's (M3_TPU_PIPELINE=0 pins it).
            return self._write_batch_pipelined(
                ns, namespace, clog, entries, series_ids, encs,
                fields_list, times, vbits, by_shard, results, ok)
        if clog is not None:
            all_ok = len(ok) == n
            ok_idx = None if all_ok else np.asarray(ok, np.intp)
            try:
                clog.write_many(
                    series_ids if all_ok else [series_ids[i] for i in ok],
                    encs if all_ok else [encs[i] for i in ok],
                    times if all_ok else times[ok_idx],
                    vbits if all_ok else vbits[ok_idx],
                    int(ns.opts.write_time_unit))
            except faults.SimulatedCrash:
                raise  # no handler survives a kill
            except Exception as ex:  # noqa: BLE001 - WAL failure: nothing
                # past this point is acked; degrade every pending entry
                # and leave the buffers untouched (an un-logged buffered
                # write would be silently lost by a crash)
                for i in ok:
                    results[i] = str(ex)
                return results, 0
            r = ns.opts.retention
            windows = self._log_windows[namespace]
            t_ok = times if all_ok else times[ok_idx]
            for w in np.unique(t_ok - (t_ok % r.block_size_ns)).tolist():
                windows.add(int(w))
        # buffer + index: reuse the routing pass; `results` doubles as the
        # error vector so entries degraded above skip the index insert
        ns.write_many(series_ids, times, vbits, encs, fields_list,
                      routed=(by_shard, results))
        return results, len(ok)

    def _write_batch_pipelined(self, ns, namespace, clog, entries,
                               series_ids, encs, fields_list, times, vbits,
                               by_shard, results, ok
                               ) -> tuple[list[str | None], int]:
        """The overlapped tail of _write_batch_traced: the clean rows
        split into WAL chunks appended in order on the per-namespace
        lane; as each chunk's append completes (== its entries are in
        the WAL buffer/OS, the serial path's ack point), this thread
        runs its buffer + index inserts while the lane packs the next
        chunk. A chunk whose WAL append failed degrades exactly its own
        entries and never touches the buffers (buffered => logged); the
        emitted WAL entry stream is byte-identical to the serial path
        (chunk boundaries only move the flush-threshold checks, as the
        batched write_many already documents)."""
        from m3_tpu.storage import pipeline

        n = len(entries)
        chunk = pipeline.wal_chunk_entries()
        unit = int(ns.opts.write_time_unit)
        lane = pipeline.default_executor().lane(f"wal:{namespace}")
        chunks = [ok[lo:lo + chunk] for lo in range(0, len(ok), chunk)]
        futs = []
        for ch in chunks:
            idx = np.asarray(ch, np.intp)
            futs.append(lane.submit(
                lambda s=[series_ids[i] for i in ch],
                g=[encs[i] for i in ch], t=times[idx], v=vbits[idx]:
                clog.write_many(s, g, t, v, unit)))
        r = ns.opts.retention
        windows = self._log_windows[namespace]
        mask = np.zeros(n, bool)
        n_ok = 0
        for fut, ch in zip(futs, chunks):
            try:
                fut.result()
            except faults.SimulatedCrash:
                raise  # no handler survives a kill
            except Exception as ex:  # noqa: BLE001 - this chunk was never
                # durably logged: degrade exactly its entries, leave the
                # buffers untouched (the serial path's WAL-failure rule,
                # applied per chunk)
                for i in ch:
                    results[i] = str(ex)
                continue
            idx = np.asarray(ch, np.intp)
            t_ch = times[idx]
            for w in np.unique(t_ch - (t_ch % r.block_size_ns)).tolist():
                windows.add(int(w))
            mask[:] = False
            mask[idx] = True
            routed_chunk = {}
            for s, rows in by_shard.items():
                sub = rows[mask[np.asarray(rows, np.intp)]]
                if len(sub):
                    routed_chunk[s] = sub
            ns.write_many(series_ids, times, vbits, encs, fields_list,
                          routed=(routed_chunk, results), only_rows=ch)
            n_ok += len(ch)
        return results, n_ok

    def write_tagged_batch(self, namespace: str, entries) -> int:
        """The cluster-facade batch surface (ClusterDatabase parity) over
        write_batch: all-or-error semantics — raises naming the first
        failures instead of returning per-entry results. Lets the
        coordinator ingest path op-batch against a LOCAL database too."""
        results = self.write_batch(namespace, entries)
        bad = [r for r in results if r is not None]
        if bad:
            raise RuntimeError(
                f"write_batch: {len(bad)}/{len(results)} entries failed "
                f"(first: {bad[:3]})")
        return len(results)

    def query(self, namespace: str, matchers, start_ns: int, end_ns: int,
              limit: int | None = None):
        """Index query + per-series reads: [(series_id, fields, [Datapoint])].

        The QueryIDs -> ReadEncoded flow of the reference
        (storage/database.go:1005,1068) collapsed into one call.
        """
        from m3_tpu.utils import trace

        with trace.span(trace.DB_QUERY, namespace=namespace):
            return self._query_traced(namespace, matchers, start_ns, end_ns,
                                      limit)

    def _query_traced(self, namespace, matchers, start_ns, end_ns, limit):
        from m3_tpu.index.query import matchers_to_query

        ns = self.namespaces[namespace]
        docs = ns.query_ids(matchers_to_query(list(matchers)), start_ns, end_ns, limit)
        # one batched read for the whole match set: a single fused
        # fetch+decode dispatch per (shard, block, volume) group
        results = ns.read_many([d.series_id for d in docs], start_ns, end_ns)
        out = []
        for doc, (times, vbits) in zip(docs, results):
            dps = [
                Datapoint(int(t), float(v))
                for t, v in zip(times, vbits.view(np.float64))
            ]
            out.append((doc.series_id, doc.fields, dps))
        return out

    def read(self, namespace: str, series_id: bytes, start_ns: int, end_ns: int
             ) -> list[Datapoint]:
        ns = self.namespaces[namespace]
        times, vbits = ns.read(series_id, start_ns, end_ns)
        values = vbits.view(np.float64)
        return [Datapoint(int(t), float(v)) for t, v in zip(times, values)]

    def read_batch(self, namespace: str, series_ids: list[bytes],
                   start_ns: int, end_ns: int) -> list[list[Datapoint]]:
        """Batched node-API reads (the read_batch RPC shape): one fused
        fetch+decode per (shard, block, volume) group server-side, so a
        Session wired to in-process databases batches like the HTTP path."""
        ns = self.namespaces[namespace]
        results = ns.read_many(series_ids, start_ns, end_ns)
        return [
            [Datapoint(int(t), float(v))
             for t, v in zip(times, vbits.view(np.float64))]
            for times, vbits in results
        ]

    def read_batch_csr(self, namespace: str, series_ids: list[bytes],
                       start_ns: int, end_ns: int,
                       precision: str | None = None):
        """read_batch landing the ragged (times, vbits, offsets) CSR —
        the NodeConnection fast path a Session prefers over read_batch:
        an in-process leg never materializes per-sample Datapoints at
        all.  ``precision`` is the wire-quantization grant; in-process
        there is no wire, so results stay exact (quantization is a
        transport measure, not a rounding contract)."""
        del precision  # no wire to quantize in-process
        ns = self.namespaces[namespace]
        return ns.read_many_ragged(series_ids, start_ns, end_ns)

    # -- maintenance --

    def apply_runtime(self, manager) -> None:
        """Bind a RuntimeOptionsManager: query limits, tick switches, and
        persist pacing follow its updates live (kvconfig role)."""
        from m3_tpu.cluster.runtime import apply_to_query_limits
        from m3_tpu.storage.limits import QueryLimits

        self.runtime = manager

        def on_opts(opts):
            # mutate the CURRENTLY bound limits: engines rebind db.limits,
            # and storage accounting reads the binding at check time
            if self.limits is None:
                self.limits = QueryLimits()
            apply_to_query_limits(self.limits, opts)
            self.persist_limiter.set_rate(opts.persist_rate_mbps)
            self._runtime_opts = opts

        manager.register_listener(on_opts)

    def tick(self, now_ns: int | None = None) -> dict:
        """One mediator cycle: warm flush of aged-out windows, cold flush
        of backfilled (already-flushed) windows, snapshot of in-flight
        windows, retention expiry, commitlog rotation (a log retires once
        its windows are flushed OR snapshotted after it was rotated — the
        reference flush model, storage/README.md + coldflush.go)."""
        now_ns = now_ns if now_ns is not None else time.time_ns()
        flushed = cold_flushed = expired = 0
        ropts = self._runtime_opts
        snap_on = ropts is None or ropts.snapshot_enabled
        flush_on = ropts is None or ropts.flush_enabled
        snapped = self.snapshot(now_ns) if snap_on else {}
        for name, ns in self.namespaces.items():
            n = ns.flush(now_ns) if flush_on else 0
            # cold pass AFTER the warm pass (reference mediator ordering):
            # backfilled blocks merge into version-bumped volumes without
            # delaying first-volume warm flushes
            n_cold = ns.cold_flush() if flush_on else 0
            flushed += n
            cold_flushed += n_cold
            n += n_cold  # both make windows durable for commitlog retirement
            expired += ns.expire(now_ns)
            self._cleanup_snapshots(name, ns, now_ns)
            ns_snapped = snapped.get(name, 0)
            if ns.index is not None:
                from m3_tpu.index import persist as index_persist

                cutoff = ns.opts.retention.block_start(
                    now_ns - ns.opts.retention.retention_ns
                )
                ns.index.expire_before(cutoff)
                # sealed-by-time blocks persist as one artifact; ACTIVE
                # blocks instead get a background size-tiered compaction
                # pass (index/compaction.py planner) so per-block segment
                # count stays bounded without rewriting every doc per tick
                index_persist.persist_index(
                    ns.index, self.fs_root, name,
                    seal_before_ns=now_ns - ns.opts.retention.buffer_past_ns)
                ns.index.compact()
                index_persist.expire_index_files(
                    self.fs_root, name, cutoff, ns.opts.index.block_size_ns
                )
            if ((n or ns_snapped) and name in self._commitlogs
                    and self._log_windows.get(name)):
                # the active log's windows are durable (fileset volume or
                # snapshot): retire it (recording windows + when) and start
                # a new one; retirement completes in _cleanup_retired_logs
                old = self._commitlogs[name]
                old.close()
                self._retired_logs.setdefault(name, []).append(
                    (old.path, self._log_windows.get(name, set()), now_ns)
                )
                self._open_commitlog(name)
            if name in self._commitlogs:
                self._cleanup_retired_logs(name, ns, now_ns)
        return {"flushed": flushed, "cold_flushed": cold_flushed,
                "expired": expired, "snapshotted": sum(snapped.values())}

    def aggregate_tiles(self, source_ns: str, target_ns: str,
                        start_ns: int, end_ns: int, tile_ns: int,
                        agg: str = "last") -> int:
        """Server-side downsampling of historical data: re-aggregate the
        source namespace's datapoints into `tile_ns` tiles written to the
        target namespace (the AggregateTiles RPC role,
        reference storage/database.go:1284). Returns tiles written.

        The tile reduction runs as one batched pass per shard via the same
        windowed segment reductions the aggregator uses.
        """
        from m3_tpu.metrics.aggregation import AggregationType
        from m3_tpu.ops import windowed_agg

        agg_type = {
            "last": AggregationType.LAST,
            "sum": AggregationType.SUM,
            "min": AggregationType.MIN,
            "max": AggregationType.MAX,
            "mean": AggregationType.MEAN,
            "count": AggregationType.COUNT,
        }[agg]
        src = self.namespaces[source_ns]
        if target_ns not in self.namespaces:
            raise KeyError(f"target namespace {target_ns} not created")
        # align to tile boundaries: a partial boundary tile computed from a
        # sub-range would overwrite the full tile on incremental runs
        start_ns = start_ns - (start_ns % tile_ns)
        end_ns = end_ns + (-end_ns % tile_ns)
        written = 0
        for shard in src.shards.values():
            ids = sorted(shard.series_ids())
            elem_rows, t_rows, v_rows = [], [], []
            tags_by_idx = []
            for sid in ids:
                times, vbits = shard.read(sid, start_ns, end_ns)
                if len(times) == 0:
                    continue
                buf_idx = shard.buffer._series.get(sid)
                tags_blob = (
                    shard.buffer.series_tags[buf_idx] if buf_idx is not None else b""
                )
                if not tags_blob:
                    for reader in shard._filesets.values():
                        tags_blob = reader.tags_of(sid) or tags_blob
                        if tags_blob:
                            break
                elem_rows.append(np.full(len(times), len(tags_by_idx), np.int64))
                t_rows.append(times)
                v_rows.append(vbits.view(np.float64))
                tags_by_idx.append((sid, tags_blob))
            if not elem_rows:
                continue
            e = np.concatenate(elem_rows)
            t = np.concatenate(t_rows)
            v = np.concatenate(v_rows)
            w = t // tile_ns
            ge, gw, stats, vq, offsets = windowed_agg.aggregate_groups(
                e, w, v, times=t
            )
            values = windowed_agg.extract(agg_type, stats, vq, offsets)
            tgt = self.namespaces[target_ns]
            # tiles land as ONE columnar batch per source shard (the
            # write_batch shape: one commitlog append, one buffer lock per
            # (target shard, window) group, one index insert_many pass)
            # instead of a per-tile Database.write loop
            from m3_tpu.utils.ident import decode_tags

            n_tiles = len(ge)
            if n_tiles == 0:
                continue
            sids: list[bytes] = [b""] * n_tiles
            encs: list[bytes] = [b""] * n_tiles
            fields_list: list = [None] * n_tiles
            fields_of: dict[bytes, list] = {}  # decode once per tag blob
            t_arr = np.asarray(gw, np.int64) * tile_ns
            v_arr = np.asarray(values, np.float64).view(np.uint64)
            for g in range(n_tiles):
                sid, tags_blob = tags_by_idx[int(ge[g])]
                sids[g] = sid
                encs[g] = tags_blob
                if tags_blob:
                    fields = fields_of.get(tags_blob)
                    if fields is None:
                        fields = fields_of[tags_blob] = decode_tags(tags_blob)
                    fields_list[g] = fields
            clog = self._commitlogs.get(target_ns)
            if clog is not None:
                # tiles hit the commitlog like every other write into the
                # target namespace, one append for the whole shard's batch
                clog.write_many(sids, encs, t_arr, v_arr,
                                int(tgt.opts.write_time_unit))
                windows = self._log_windows[target_ns]
                bs = tgt.opts.retention.block_size_ns
                for win in np.unique(t_arr - (t_arr % bs)).tolist():
                    windows.add(int(win))
            errors = tgt.write_many(sids, t_arr, v_arr, encs, fields_list)
            written += sum(1 for err in errors if err is None)
        return written

    def flush_all(self, now_ns: int | None = None) -> int:
        """Force-flush every buffered window regardless of buffer_past."""
        flushed = 0
        for ns in self.namespaces.values():
            for shard in ns.shards.values():
                for bs in shard.buffer.block_starts():
                    if shard.flush(bs):
                        flushed += 1
        return flushed

    def flush_shard(self, shard_id: int) -> int:
        """Force-flush every buffered window of ONE shard across all
        namespaces — the donor half of shard handoff (tail handoff): the
        mutable window's acked writes become flushed volumes the target
        can stream and digest-verify before cutover."""
        flushed = 0
        for ns in self.namespaces.values():
            shard = ns.shards.get(shard_id)
            if shard is None:
                continue
            for bs in shard.buffer.block_starts():
                if shard.flush(bs):
                    flushed += 1
        return flushed
