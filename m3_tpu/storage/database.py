"""Database: namespaces + commitlog + bootstrap + tick orchestration.

Role parity with the reference storage.Database
(/root/reference/src/dbnode/storage/database.go:99 — Write:795,
ReadEncoded:1068, Bootstrap:1140) and the mediator tick/flush loop
(storage/mediator.go:79-160), collapsed into explicit open/write/read/
tick calls driven by the host control plane.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import numpy as np

from m3_tpu.storage import commitlog
from m3_tpu.storage.namespace import Namespace
from m3_tpu.storage.options import DatabaseOptions, NamespaceOptions
from m3_tpu.storage.sharding import ShardSet


@dataclass
class Datapoint:
    timestamp_ns: int
    value: float


def _f64_to_bits(v: float) -> int:
    return int(np.float64(v).view(np.uint64))


class Database:
    """Single-node database ("local" topology mode of the reference)."""

    def __init__(self, path: str, db_opts: DatabaseOptions | None = None):
        self.path = path
        self.opts = db_opts or DatabaseOptions()
        self.namespaces: dict[str, Namespace] = {}
        self._commitlogs: dict[str, commitlog.CommitLogWriter] = {}
        # block windows logged into the ACTIVE commitlog, per namespace
        self._log_windows: dict[str, set[int]] = {}
        # rotated logs awaiting deletion: ns -> [(path, windows-it-covers)]
        self._retired_logs: dict[str, list[tuple[str, set[int]]]] = {}
        self._open = False
        self._shard_set = ShardSet(self.opts.n_shards, self.opts.owned_shards)
        # optional storage-layer QueryLimits shared by all read paths
        self.limits = None

    # -- lifecycle --

    @property
    def fs_root(self) -> str:
        return os.path.join(self.path, "data")

    def commitlog_dir(self, namespace: str) -> str:
        return os.path.join(self.path, "commitlog", namespace)

    def create_namespace(self, name: str, opts: NamespaceOptions | None = None) -> Namespace:
        if name in self.namespaces:
            return self.namespaces[name]
        ns = Namespace(name, opts or NamespaceOptions(), self.opts, self._shard_set,
                       self.fs_root)
        ns.database = self
        self.namespaces[name] = ns
        if ns.opts.writes_to_commitlog and self._open:
            self._open_commitlog(name)
        return ns

    def _open_commitlog(self, namespace: str) -> None:
        d = self.commitlog_dir(namespace)
        path = os.path.join(d, f"commitlog-{int(time.time()*1e9)}.db")
        self._commitlogs[namespace] = commitlog.CommitLogWriter(
            path, self.opts.commitlog_flush_every_bytes
        )
        self._log_windows[namespace] = set()

    def open(self, now_ns: int | None = None) -> None:
        """Open + bootstrap: filesets first, then commitlog replay on top
        (the fs -> commitlog bootstrapper order of the reference's default
        pipeline, storage/bootstrap/bootstrapper/README.md)."""
        self._open = True
        now_ns = now_ns if now_ns is not None else time.time_ns()
        for name, ns in self.namespaces.items():
            if ns.opts.bootstrap_enabled:
                restored = set()
                if ns.index is not None:
                    from m3_tpu.index import persist as index_persist

                    r = ns.opts.retention
                    restored = index_persist.load_index(
                        ns.index, self.fs_root, name,
                        cutoff_ns=r.block_start(now_ns - r.retention_ns),
                    )
                ns.bootstrap_from_fs(now_ns, skip_index_blocks=restored)
                self._replay_commitlogs(name, ns, now_ns)
            if ns.opts.writes_to_commitlog:
                self._open_commitlog(name)

    def _replay_commitlogs(self, name: str, ns: Namespace,
                           now_ns: int | None = None) -> None:
        """Replay every surviving log entry into the buffers. Entries whose
        datapoints also live in a flushed volume are resolved by the normal
        last-write-wins merge (and re-merged into a higher volume on the
        next flush), so replay is safe to repeat; replayed files are retired
        and deleted once every window they cover has flushed."""
        from m3_tpu.utils.ident import decode_tags

        retired = self._retired_logs.setdefault(name, [])
        cutoff = None
        if now_ns is not None:
            r = ns.opts.retention
            cutoff = r.block_start(now_ns - r.retention_ns)
        for path in commitlog.log_files(self.commitlog_dir(name)):
            windows: set[int] = set()
            for e in commitlog.replay(path):
                if cutoff is not None and e.time_ns < cutoff:
                    continue  # past retention: don't resurrect
                try:
                    shard = ns.shard_for(e.series_id)
                except KeyError:
                    continue  # shard no longer owned by this node
                windows.add(ns.opts.retention.block_start(e.time_ns))
                shard.write(e.series_id, e.time_ns, e.value_bits, e.encoded_tags)
                if ns.index is not None and e.encoded_tags:
                    ns.index.insert(e.series_id, decode_tags(e.encoded_tags), e.time_ns)
            retired.append((path, windows))

    def _cleanup_retired_logs(self, name: str, ns: Namespace, now_ns: int) -> None:
        r = ns.opts.retention
        remaining = []
        for path, windows in self._retired_logs.get(name, []):
            covered = all(
                (
                    w + r.block_size_ns + r.buffer_past_ns <= now_ns
                    and all(s.buffer.points_in(w) == 0 for s in ns.shards.values())
                )
                or w < r.block_start(now_ns - r.retention_ns)  # past retention
                for w in windows
            )
            if covered:
                try:
                    os.remove(path)
                except OSError:
                    pass
            else:
                remaining.append((path, windows))
        self._retired_logs[name] = remaining

    def close(self) -> None:
        for log in self._commitlogs.values():
            log.close()
        self._commitlogs.clear()
        self._open = False

    # -- shard assignment (placement-driven; storage/cluster role) --

    @property
    def owned_shards(self) -> set[int]:
        return set(self._shard_set.shard_ids)

    def assign_shards(self, shard_ids: set[int], now_ns: int | None = None) -> tuple[set[int], set[int]]:
        """Reconcile shard ownership with a placement: create newly-assigned
        shards in every namespace (bootstrapping them from local filesets if
        present) and drop unassigned ones. Returns (added, removed).

        The topology-watch -> shard-assignment flow of the reference
        (/root/reference/src/dbnode/storage/cluster/database.go)."""
        current = self.owned_shards
        added = set(shard_ids) - current
        removed = current - set(shard_ids)
        if not added and not removed:
            return added, removed
        # order matters under concurrent writes from the HTTP handlers:
        # materialize new shard objects BEFORE publishing the new shard set
        # (a routed write finds its shard), and drop old ones only after
        for ns in self.namespaces.values():
            for sid in added:
                ns.add_shard(sid, now_ns)
        new_set = ShardSet(self.opts.n_shards, tuple(sorted(shard_ids)))
        self._shard_set = new_set
        for ns in self.namespaces.values():
            ns.shard_set = new_set
            for sid in removed:
                ns.remove_shard(sid)
        return added, removed

    # -- write/read --

    def write(self, namespace: str, series_id: bytes, t_ns: int, value: float,
              encoded_tags: bytes = b"") -> None:
        ns = self.namespaces[namespace]
        shard = ns.shard_for(series_id)  # validate ownership BEFORE logging
        vbits = _f64_to_bits(value)
        log = self._commitlogs.get(namespace)
        if log is not None:
            log.write(series_id, encoded_tags, t_ns, vbits, int(ns.opts.write_time_unit))
            self._log_windows[namespace].add(ns.opts.retention.block_start(t_ns))
        shard.write(series_id, t_ns, vbits, encoded_tags)
        if ns.index is not None and encoded_tags:
            # tagged-at-the-wire writes are index-visible like write_tagged,
            # not dependent on the fileset rebuild at restart
            from m3_tpu.utils.ident import decode_tags

            ns.index.insert(series_id, decode_tags(encoded_tags), t_ns)

    def write_tagged(self, namespace: str, metric_name: bytes,
                     tags: list[tuple[bytes, bytes]], t_ns: int, value: float) -> bytes:
        """Write + index a datapoint; returns the canonical series id."""
        from m3_tpu.utils.ident import encode_tags, tags_to_id

        ns = self.namespaces[namespace]
        fields = [(b"__name__", metric_name), *tags] if metric_name else list(tags)
        series_id = tags_to_id(metric_name, tags)
        shard = ns.shard_for(series_id)  # validate ownership BEFORE logging
        enc = encode_tags(fields)
        vbits = _f64_to_bits(value)
        log = self._commitlogs.get(namespace)
        if log is not None:
            log.write(series_id, enc, t_ns, vbits, int(ns.opts.write_time_unit))
            self._log_windows[namespace].add(ns.opts.retention.block_start(t_ns))
        shard.write(series_id, t_ns, vbits, enc)
        if ns.index is not None:
            ns.index.insert(series_id, fields, t_ns)
        return series_id

    def query(self, namespace: str, matchers, start_ns: int, end_ns: int,
              limit: int | None = None):
        """Index query + per-series reads: [(series_id, fields, [Datapoint])].

        The QueryIDs -> ReadEncoded flow of the reference
        (storage/database.go:1005,1068) collapsed into one call.
        """
        from m3_tpu.index.query import matchers_to_query

        ns = self.namespaces[namespace]
        docs = ns.query_ids(matchers_to_query(list(matchers)), start_ns, end_ns, limit)
        out = []
        for doc in docs:
            times, vbits = ns.read(doc.series_id, start_ns, end_ns)
            dps = [
                Datapoint(int(t), float(v))
                for t, v in zip(times, vbits.view(np.float64))
            ]
            out.append((doc.series_id, doc.fields, dps))
        return out

    def read(self, namespace: str, series_id: bytes, start_ns: int, end_ns: int
             ) -> list[Datapoint]:
        ns = self.namespaces[namespace]
        times, vbits = ns.read(series_id, start_ns, end_ns)
        values = vbits.view(np.float64)
        return [Datapoint(int(t), float(v)) for t, v in zip(times, values)]

    # -- maintenance --

    def tick(self, now_ns: int | None = None) -> dict:
        """One mediator cycle: warm flush of cold windows + retention expiry
        + commitlog rotation after a successful flush."""
        now_ns = now_ns if now_ns is not None else time.time_ns()
        flushed = expired = 0
        for name, ns in self.namespaces.items():
            n = ns.flush(now_ns)
            flushed += n
            expired += ns.expire(now_ns)
            if ns.index is not None:
                from m3_tpu.index import persist as index_persist

                cutoff = ns.opts.retention.block_start(
                    now_ns - ns.opts.retention.retention_ns
                )
                ns.index.expire_before(cutoff)
                index_persist.persist_index(ns.index, self.fs_root, name)
                index_persist.expire_index_files(
                    self.fs_root, name, cutoff, ns.opts.index.block_size_ns
                )
            if n and name in self._commitlogs:
                # flushed windows are durable in filesets: retire the active
                # log (recording the windows it covers) and start a new one;
                # retired logs are deleted once every window has flushed
                old = self._commitlogs[name]
                old.close()
                self._retired_logs.setdefault(name, []).append(
                    (old.path, self._log_windows.get(name, set()))
                )
                self._open_commitlog(name)
            if name in self._commitlogs:
                self._cleanup_retired_logs(name, ns, now_ns)
        return {"flushed": flushed, "expired": expired}

    def aggregate_tiles(self, source_ns: str, target_ns: str,
                        start_ns: int, end_ns: int, tile_ns: int,
                        agg: str = "last") -> int:
        """Server-side downsampling of historical data: re-aggregate the
        source namespace's datapoints into `tile_ns` tiles written to the
        target namespace (the AggregateTiles RPC role,
        reference storage/database.go:1284). Returns tiles written.

        The tile reduction runs as one batched pass per shard via the same
        windowed segment reductions the aggregator uses.
        """
        from m3_tpu.metrics.aggregation import AggregationType
        from m3_tpu.ops import windowed_agg

        agg_type = {
            "last": AggregationType.LAST,
            "sum": AggregationType.SUM,
            "min": AggregationType.MIN,
            "max": AggregationType.MAX,
            "mean": AggregationType.MEAN,
            "count": AggregationType.COUNT,
        }[agg]
        src = self.namespaces[source_ns]
        if target_ns not in self.namespaces:
            raise KeyError(f"target namespace {target_ns} not created")
        # align to tile boundaries: a partial boundary tile computed from a
        # sub-range would overwrite the full tile on incremental runs
        start_ns = start_ns - (start_ns % tile_ns)
        end_ns = end_ns + (-end_ns % tile_ns)
        written = 0
        for shard in src.shards.values():
            ids = sorted(shard.series_ids())
            elem_rows, t_rows, v_rows = [], [], []
            tags_by_idx = []
            for sid in ids:
                times, vbits = shard.read(sid, start_ns, end_ns)
                if len(times) == 0:
                    continue
                buf_idx = shard.buffer._series.get(sid)
                tags_blob = (
                    shard.buffer.series_tags[buf_idx] if buf_idx is not None else b""
                )
                if not tags_blob:
                    for reader in shard._filesets.values():
                        tags_blob = reader.tags_of(sid) or tags_blob
                        if tags_blob:
                            break
                elem_rows.append(np.full(len(times), len(tags_by_idx), np.int64))
                t_rows.append(times)
                v_rows.append(vbits.view(np.float64))
                tags_by_idx.append((sid, tags_blob))
            if not elem_rows:
                continue
            e = np.concatenate(elem_rows)
            t = np.concatenate(t_rows)
            v = np.concatenate(v_rows)
            w = t // tile_ns
            ge, gw, stats, vq, offsets = windowed_agg.aggregate_groups(
                e, w, v, times=t
            )
            values = windowed_agg.extract(agg_type, stats, vq, offsets)
            tgt = self.namespaces[target_ns]
            for g in range(len(ge)):
                sid, tags_blob = tags_by_idx[int(ge[g])]
                tile_start = int(gw[g]) * tile_ns
                # through Database.write so tiles hit the commitlog like
                # every other write into the target namespace
                self.write(target_ns, sid, tile_start, float(values[g]),
                           tags_blob)
                if tgt.index is not None and tags_blob:
                    from m3_tpu.utils.ident import decode_tags

                    tgt.index.insert(sid, decode_tags(tags_blob), tile_start)
                written += 1
        return written

    def flush_all(self, now_ns: int | None = None) -> int:
        """Force-flush every buffered window regardless of buffer_past."""
        flushed = 0
        for ns in self.namespaces.values():
            for shard in ns.shards.values():
                for bs in shard.buffer.block_starts():
                    if shard.flush(bs):
                        flushed += 1
        return flushed
