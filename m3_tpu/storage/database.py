"""Database: namespaces + commitlog + bootstrap + tick orchestration.

Role parity with the reference storage.Database
(/root/reference/src/dbnode/storage/database.go:99 — Write:795,
ReadEncoded:1068, Bootstrap:1140) and the mediator tick/flush loop
(storage/mediator.go:79-160), collapsed into explicit open/write/read/
tick calls driven by the host control plane.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import numpy as np

from m3_tpu.storage import commitlog
from m3_tpu.storage.namespace import Namespace
from m3_tpu.storage.options import DatabaseOptions, NamespaceOptions
from m3_tpu.storage.sharding import ShardSet
from m3_tpu.utils.xtime import TimeUnit


@dataclass
class Datapoint:
    timestamp_ns: int
    value: float


def _f64_to_bits(v: float) -> int:
    return int(np.float64(v).view(np.uint64))


class Database:
    """Single-node database ("local" topology mode of the reference)."""

    def __init__(self, path: str, db_opts: DatabaseOptions | None = None):
        self.path = path
        self.opts = db_opts or DatabaseOptions()
        self.namespaces: dict[str, Namespace] = {}
        self._commitlogs: dict[str, commitlog.CommitLogWriter] = {}
        self._open = False
        self._shard_set = ShardSet(self.opts.n_shards)

    # -- lifecycle --

    @property
    def fs_root(self) -> str:
        return os.path.join(self.path, "data")

    def commitlog_dir(self, namespace: str) -> str:
        return os.path.join(self.path, "commitlog", namespace)

    def create_namespace(self, name: str, opts: NamespaceOptions | None = None) -> Namespace:
        if name in self.namespaces:
            return self.namespaces[name]
        ns = Namespace(name, opts or NamespaceOptions(), self.opts, self._shard_set,
                       self.fs_root)
        self.namespaces[name] = ns
        if ns.opts.writes_to_commitlog and self._open:
            self._open_commitlog(name)
        return ns

    def _open_commitlog(self, namespace: str) -> None:
        d = self.commitlog_dir(namespace)
        path = os.path.join(d, f"commitlog-{int(time.time()*1e9)}.db")
        self._commitlogs[namespace] = commitlog.CommitLogWriter(
            path, self.opts.commitlog_flush_every_bytes
        )

    def open(self) -> None:
        """Open + bootstrap: filesets first, then commitlog replay on top
        (the fs -> commitlog bootstrapper order of the reference's default
        pipeline, storage/bootstrap/bootstrapper/README.md)."""
        self._open = True
        for name, ns in self.namespaces.items():
            if ns.opts.bootstrap_enabled:
                ns.bootstrap_from_fs()
                self._replay_commitlogs(name, ns)
            if ns.opts.writes_to_commitlog:
                self._open_commitlog(name)

    def _replay_commitlogs(self, name: str, ns: Namespace) -> None:
        for path in commitlog.log_files(self.commitlog_dir(name)):
            for e in commitlog.replay(path):
                # skip datapoints already covered by a flushed volume
                shard = ns.shard_for(e.series_id)
                bs = ns.opts.retention.block_start(e.time_ns)
                if bs in shard._filesets:
                    continue
                shard.write(e.series_id, e.time_ns, e.value_bits, e.encoded_tags)

    def close(self) -> None:
        for log in self._commitlogs.values():
            log.close()
        self._commitlogs.clear()
        self._open = False

    # -- write/read --

    def write(self, namespace: str, series_id: bytes, t_ns: int, value: float,
              encoded_tags: bytes = b"") -> None:
        ns = self.namespaces[namespace]
        vbits = _f64_to_bits(value)
        log = self._commitlogs.get(namespace)
        if log is not None:
            log.write(series_id, encoded_tags, t_ns, vbits, int(ns.opts.write_time_unit))
        ns.write(series_id, t_ns, vbits, encoded_tags)

    def read(self, namespace: str, series_id: bytes, start_ns: int, end_ns: int
             ) -> list[Datapoint]:
        ns = self.namespaces[namespace]
        times, vbits = ns.read(series_id, start_ns, end_ns)
        values = vbits.view(np.float64)
        return [Datapoint(int(t), float(v)) for t, v in zip(times, values)]

    # -- maintenance --

    def tick(self, now_ns: int | None = None) -> dict:
        """One mediator cycle: warm flush of cold windows + retention expiry
        + commitlog rotation after a successful flush."""
        now_ns = now_ns if now_ns is not None else time.time_ns()
        flushed = expired = 0
        for name, ns in self.namespaces.items():
            n = ns.flush(now_ns)
            flushed += n
            expired += ns.expire(now_ns)
            if n and name in self._commitlogs:
                # flushed windows are durable in filesets; rotate the log so
                # replay cost stays bounded (reference: snapshot + rotate)
                self._commitlogs[name].close()
                self._open_commitlog(name)
        return {"flushed": flushed, "expired": expired}

    def flush_all(self, now_ns: int | None = None) -> int:
        """Force-flush every buffered window regardless of buffer_past."""
        flushed = 0
        for ns in self.namespaces.values():
            for shard in ns.shards.values():
                for bs in shard.buffer.block_starts():
                    if shard.flush(bs):
                        flushed += 1
        return flushed
