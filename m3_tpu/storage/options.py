"""Storage engine options.

Parity targets: namespace retention/block-size options
(/root/reference/src/dbnode/namespace/types.go:36,215,254) and the
series-buffer past/future acceptance windows
(/root/reference/src/dbnode/storage/series/buffer.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from m3_tpu.utils.xtime import TimeUnit

NANOS_PER_SECOND = 1_000_000_000


@dataclass(frozen=True)
class RetentionOptions:
    retention_ns: int = 48 * 3600 * NANOS_PER_SECOND
    block_size_ns: int = 2 * 3600 * NANOS_PER_SECOND
    buffer_past_ns: int = 10 * 60 * NANOS_PER_SECOND
    buffer_future_ns: int = 2 * 60 * NANOS_PER_SECOND

    def block_start(self, t_ns: int) -> int:
        return t_ns - (t_ns % self.block_size_ns)


@dataclass(frozen=True)
class IndexOptions:
    enabled: bool = True
    block_size_ns: int = 2 * 3600 * NANOS_PER_SECOND


@dataclass(frozen=True)
class NamespaceOptions:
    retention: RetentionOptions = field(default_factory=RetentionOptions)
    index: IndexOptions = field(default_factory=IndexOptions)
    write_time_unit: TimeUnit = TimeUnit.SECOND
    # 0 = unaggregated (raw) namespace; >0 = this namespace holds
    # downsampled data at this resolution (the reference's namespace
    # "aggregated" attributes, namespace/types.go AggregationOptions —
    # what retention-tier read resolution keys on)
    aggregated_resolution_ns: int = 0
    # the aggregated namespace holds EVERY metric at its resolution (a
    # downsample-all mapping rule feeds it) — only complete tiers are
    # eligible for cheapest-tier read resolution, because routing a
    # query to a partial tier would silently drop the unmatched series
    # (the reference's AggregationsOptions.DownsampleOptions "all" bit)
    aggregated_complete: bool = False
    # encode value streams with the M3TSZ int optimization (the reference's
    # production default; float-XOR only when False)
    int_optimized: bool = False
    bootstrap_enabled: bool = True
    flush_enabled: bool = True
    writes_to_commitlog: bool = True
    cold_writes_enabled: bool = False
    snapshot_enabled: bool = True


@dataclass(frozen=True)
class DatabaseOptions:
    n_shards: int = 8
    # shard ids this node owns (None = all n_shards; a placement-driven
    # node passes its assigned subset, reference storage/cluster/database.go)
    owned_shards: tuple[int, ...] | None = None
    # device batch geometry for seal/flush encodes
    max_points_per_block: int = 4096
    commitlog_flush_every_bytes: int = 1 << 20
    # decoded-block LRU entries shared across shards (0 disables; the
    # WiredList role, reference block/wired_list.go)
    block_cache_entries: int = 8192
