"""Boolean query AST + tag matchers.

Role parity with the reference search AST
(/root/reference/src/m3ninx/search/types.go:43-58 and search/searcher/*):
term/regexp/field/all leaves composed by conjunction (with negation folded
into AND-NOT) and disjunction. Matchers carry the PromQL =, !=, =~, !~
semantics used by the query layer.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field


class Query:
    pass


@dataclass(frozen=True)
class TermQuery(Query):
    field_name: bytes
    value: bytes


@dataclass(frozen=True)
class RegexpQuery(Query):
    field_name: bytes
    pattern: str  # anchored full-match semantics

    def compiled(self) -> re.Pattern:
        return re.compile(self.pattern.encode() if isinstance(self.pattern, str) else self.pattern)


@dataclass(frozen=True)
class FieldQuery(Query):
    field_name: bytes


@dataclass(frozen=True)
class AllQuery(Query):
    pass


@dataclass(frozen=True)
class NegationQuery(Query):
    inner: Query


@dataclass(frozen=True)
class ConjunctionQuery(Query):
    queries: tuple = field(default_factory=tuple)


@dataclass(frozen=True)
class DisjunctionQuery(Query):
    queries: tuple = field(default_factory=tuple)


class MatchType(enum.Enum):
    EQUAL = "="
    NOT_EQUAL = "!="
    REGEXP = "=~"
    NOT_REGEXP = "!~"


@dataclass(frozen=True)
class Matcher:
    """One PromQL-style label matcher."""

    match_type: MatchType
    name: bytes
    value: bytes

    def to_query(self) -> Query:
        if self.match_type == MatchType.EQUAL:
            return TermQuery(self.name, self.value)
        if self.match_type == MatchType.NOT_EQUAL:
            return NegationQuery(TermQuery(self.name, self.value))
        if self.match_type == MatchType.REGEXP:
            return RegexpQuery(self.name, self.value.decode())
        return NegationQuery(RegexpQuery(self.name, self.value.decode()))


def matchers_to_query(matchers: list[Matcher]) -> Query:
    """PromQL vector selector -> conjunction query."""
    if not matchers:
        return AllQuery()
    qs = tuple(m.to_query() for m in matchers)
    if len(qs) == 1:
        return qs[0]
    return ConjunctionQuery(qs)


# -- wire codec (the search/query proto codecs role, search/query/*.go) --


def query_to_json(q: Query) -> dict:
    """JSON-able encoding for shipping a query AST to a storage node."""
    import base64

    def b64(b: bytes) -> str:
        return base64.b64encode(b).decode()

    if isinstance(q, AllQuery):
        return {"t": "all"}
    if isinstance(q, TermQuery):
        return {"t": "term", "f": b64(q.field_name), "v": b64(q.value)}
    if isinstance(q, RegexpQuery):
        pat = q.pattern.encode() if isinstance(q.pattern, str) else q.pattern
        return {"t": "regexp", "f": b64(q.field_name), "p": b64(pat)}
    if isinstance(q, FieldQuery):
        return {"t": "field", "f": b64(q.field_name)}
    if isinstance(q, NegationQuery):
        return {"t": "not", "q": query_to_json(q.inner)}
    if isinstance(q, ConjunctionQuery):
        return {"t": "and", "qs": [query_to_json(x) for x in q.queries]}
    if isinstance(q, DisjunctionQuery):
        return {"t": "or", "qs": [query_to_json(x) for x in q.queries]}
    raise TypeError(f"unknown query type {type(q)}")


def query_from_json(doc: dict) -> Query:
    import base64

    def b(s: str) -> bytes:
        return base64.b64decode(s)

    t = doc["t"]
    if t == "all":
        return AllQuery()
    if t == "term":
        return TermQuery(b(doc["f"]), b(doc["v"]))
    if t == "regexp":
        return RegexpQuery(b(doc["f"]), b(doc["p"]).decode())
    if t == "field":
        return FieldQuery(b(doc["f"]))
    if t == "not":
        return NegationQuery(query_from_json(doc["q"]))
    if t == "and":
        return ConjunctionQuery(tuple(query_from_json(x) for x in doc["qs"]))
    if t == "or":
        return DisjunctionQuery(tuple(query_from_json(x) for x in doc["qs"]))
    raise ValueError(f"unknown query kind {t}")
