"""Boolean query AST + tag matchers.

Role parity with the reference search AST
(/root/reference/src/m3ninx/search/types.go:43-58 and search/searcher/*):
term/regexp/field/all leaves composed by conjunction (with negation folded
into AND-NOT) and disjunction. Matchers carry the PromQL =, !=, =~, !~
semantics used by the query layer.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field


class Query:
    pass


@dataclass(frozen=True)
class TermQuery(Query):
    field_name: bytes
    value: bytes


@dataclass(frozen=True)
class RegexpQuery(Query):
    field_name: bytes
    pattern: str  # anchored full-match semantics

    def compiled(self) -> re.Pattern:
        return re.compile(self.pattern.encode() if isinstance(self.pattern, str) else self.pattern)


@dataclass(frozen=True)
class FieldQuery(Query):
    field_name: bytes


@dataclass(frozen=True)
class AllQuery(Query):
    pass


@dataclass(frozen=True)
class NegationQuery(Query):
    inner: Query


@dataclass(frozen=True)
class ConjunctionQuery(Query):
    queries: tuple = field(default_factory=tuple)


@dataclass(frozen=True)
class DisjunctionQuery(Query):
    queries: tuple = field(default_factory=tuple)


class MatchType(enum.Enum):
    EQUAL = "="
    NOT_EQUAL = "!="
    REGEXP = "=~"
    NOT_REGEXP = "!~"


@dataclass(frozen=True)
class Matcher:
    """One PromQL-style label matcher."""

    match_type: MatchType
    name: bytes
    value: bytes

    def to_query(self) -> Query:
        if self.match_type == MatchType.EQUAL:
            return TermQuery(self.name, self.value)
        if self.match_type == MatchType.NOT_EQUAL:
            return NegationQuery(TermQuery(self.name, self.value))
        if self.match_type == MatchType.REGEXP:
            return RegexpQuery(self.name, self.value.decode())
        return NegationQuery(RegexpQuery(self.name, self.value.decode()))


def matchers_to_query(matchers: list[Matcher]) -> Query:
    """PromQL vector selector -> conjunction query."""
    if not matchers:
        return AllQuery()
    qs = tuple(m.to_query() for m in matchers)
    if len(qs) == 1:
        return qs[0]
    return ConjunctionQuery(qs)
