"""Query executor over segments.

Role parity with the reference search executor + searchers
(/root/reference/src/m3ninx/search/executor/executor.go and
search/searcher/conjunction.go:78-111): leaves resolve to postings per
segment; conjunctions intersect (negations become AND-NOT), disjunctions
union; multi-segment results concatenate with per-segment doc-id bases.
"""

from __future__ import annotations

import numpy as np

from m3_tpu.index import device, postings as P
from m3_tpu.utils import dispatch, querystats
from m3_tpu.index.query import (
    AllQuery,
    ConjunctionQuery,
    DisjunctionQuery,
    FieldQuery,
    NegationQuery,
    Query,
    RegexpQuery,
    TermQuery,
)
from m3_tpu.index.segment import Segment

# device bitmap algebra pays off when (terms x doc-space) is large; below
# this the sorted-array set ops win
BITMAP_WORK_THRESHOLD = 1 << 17


def _bitmap_combine(seg: Segment, positives: list[np.ndarray],
                    negatives: list[np.ndarray], conjunction: bool) -> np.ndarray:
    """Dense-bitmap evaluation on device: one [Q, W] AND/OR reduction plus
    an AND-NOT, replacing the reference's roaring container loops
    (/root/reference/src/m3ninx/search/searcher/conjunction.go:78-111)."""
    from m3_tpu.ops import bitmaps

    n_docs = seg.n_docs
    # pad the word axis to a power of two (zero words beyond n_docs in every
    # input, so padded output bits stay zero) to bound XLA recompiles
    W = dispatch.next_pow2((n_docs + 63) // 64)

    def mask(p: np.ndarray) -> np.ndarray:
        m = P.to_bitmap(p, n_docs)
        return np.pad(m, (0, W - len(m)))

    if positives:
        masks = np.stack([mask(p) for p in positives])
        acc = bitmaps.conjunct(masks) if conjunction else bitmaps.disjunct(masks)
    else:
        acc = mask(seg.postings_all())
    if negatives:
        neg = bitmaps.disjunct(np.stack([mask(m) for m in negatives]))
        acc = bitmaps.and_not(acc, neg)
    return P.from_bitmap(np.asarray(acc))


def search_segment(seg: Segment, query: Query) -> np.ndarray:
    """Postings of one segment matching the query."""
    if isinstance(query, AllQuery):
        return seg.postings_all()
    if isinstance(query, TermQuery):
        return seg.postings_term(query.field_name, query.value)
    if isinstance(query, RegexpQuery):
        return seg.postings_regexp(query.field_name, query.compiled())
    if isinstance(query, FieldQuery):
        return seg.postings_field(query.field_name)
    if isinstance(query, NegationQuery):
        return P.difference(seg.postings_all(), search_segment(seg, query.inner))
    if isinstance(query, ConjunctionQuery):
        if not query.queries:
            # an empty conjunction would be the identity (match-all); that's
            # never intentional from the query layer — reject it
            raise ValueError("empty conjunction query")
        ids, reason = device.match(seg, query)
        if ids is not None:
            querystats.record_index(device_segments=1)
            return ids
        querystats.record_index(fallback=reason)
        positives: list[np.ndarray] = []
        negatives: list[np.ndarray] = []
        for q in query.queries:
            if isinstance(q, NegationQuery):
                negatives.append(search_segment(seg, q.inner))
            else:
                positives.append(search_segment(seg, q))
        n_terms = len(positives) + len(negatives)
        if n_terms >= 3 and dispatch.use_device(
            n_terms * seg.n_docs, BITMAP_WORK_THRESHOLD
        ):
            dispatch.record("bitmaps.conjunct", True)
            return _bitmap_combine(seg, positives, negatives, conjunction=True)
        dispatch.record("bitmaps.conjunct", False)
        if positives:
            positives.sort(key=len)
            acc = positives[0]
            for p in positives[1:]:
                if len(acc) == 0:
                    return P.EMPTY
                acc = P.intersect(acc, p)
        else:
            acc = seg.postings_all()
        for n in negatives:
            if len(acc) == 0:
                return P.EMPTY
            acc = P.difference(acc, n)
        return acc
    if isinstance(query, DisjunctionQuery):
        ids, reason = device.match(seg, query)
        if ids is not None:
            querystats.record_index(device_segments=1)
            return ids
        querystats.record_index(fallback=reason)
        parts = [search_segment(seg, q) for q in query.queries]
        if len(parts) >= 3 and dispatch.use_device(
            len(parts) * seg.n_docs, BITMAP_WORK_THRESHOLD
        ):
            dispatch.record("bitmaps.disjunct", True)
            return _bitmap_combine(seg, parts, [], conjunction=False)
        dispatch.record("bitmaps.disjunct", False)
        return P.union_many(parts)
    raise TypeError(f"unknown query type {type(query)}")


def search(segments: list[Segment], query: Query, limit: int | None = None):
    """Execute over segments; yields (series_id, fields) deduped by series
    (later segments win nothing — first hit is kept).

    Batched the way the data half of fetch_tagged is (read_many's
    "cache hits never enter the batch" discipline): per segment, the
    matched series ids come out of the id blob in bulk passes
    (series_ids_at — no Document construction), cross-segment duplicates
    are filtered on those cheap ids, and only the fresh winners pay
    Document materialization (docs_at — the tag decode). A series
    matched in B overlapping blocks costs one tag decode, not B. With a
    limit, the id passes are chunked to a multiple of the remaining
    budget so a limit-10 query over a million matches stays O(limit),
    not O(matches), like the per-doc loop it replaced."""
    seen: set[bytes] = set()
    out: list = []
    for seg in segments:
        querystats.record_index(segments=1)
        ids = search_segment(seg, query)
        ids_of = getattr(seg, "series_ids_at", None)
        docs_of = getattr(seg, "docs_at", None)
        pos = 0
        while pos < len(ids):
            if limit is None:
                chunk = ids[pos:]
            else:
                chunk = ids[pos:pos + max(64, 4 * (limit - len(out)))]
            pos += len(chunk)
            if ids_of is None:  # minimal test doubles: per-doc path
                docs = seg.docs
                sids = [docs[int(i)].series_id for i in chunk]
            else:
                sids = ids_of(chunk)
            fresh: list[int] = []
            for i, sid in enumerate(sids):
                if sid in seen:
                    continue
                seen.add(sid)
                fresh.append(i)
                if limit is not None and len(out) + len(fresh) >= limit:
                    break
            if not fresh:
                continue
            take = chunk[np.asarray(fresh, np.intp)]
            if docs_of is None:
                docs = seg.docs
                out.extend(docs[int(i)] for i in take)
            else:
                out.extend(docs_of(take))
            if limit is not None and len(out) >= limit:
                return out
    return out
