"""Packed immutable index segments — the FST-segment-equivalent tier.

Role parity with the reference's mmap-able FST segments
(/root/reference/src/m3ninx/index/segment/fst/segment.go:130-180, writer
fst/writer.go) and its regex-automaton term matching
(fst/regexp/regexp.go:33-44), redesigned host-columnar instead of
FST-shaped:

- One contiguous buffer holds every doc id, tag blob, field name, term and
  postings list as offset-indexed numpy views: loading a persisted segment
  is ``np.frombuffer`` over an mmap — no dict rebuilding, no per-term
  Python objects (the round-1 gap: sealed segments were Python dicts).
- Term lookup is binary search over the sorted per-field vocab
  (the FST's ordered-lookup role).
- Regex queries run ONE C-speed ``re.finditer`` pass over the
  newline-joined vocab blob with ``(?m)^(?:pat)$`` — the batched
  replacement for automaton-FST intersection — narrowed first to the
  vocab range sharing the pattern's literal prefix.
- Per-segment LRU caches memoize regex/term postings (the
  storage/index/postings_list_cache.go role).

Layout (little-endian, every array 8-byte aligned):
  magic "M3PKSG02" | header (9x u64): n_docs, sid_blob_len, tags_blob_len,
  n_fields, fname_blob_len, n_terms, term_blob_len, postings_len, flags
  sid_offsets u64[D+1] | sid_blob | tag_offsets u64[D+1] | tags_blob |
  fname_offsets u64[F+1] | fname_blob | field_term_start u64[F+1] |
  term_offsets u64[T+1] | term_blob (each term followed by \n) |
  postings_offsets u64[T+1] | postings u32[P]
"""

from __future__ import annotations

import re
import struct
import threading
import weakref
from collections import OrderedDict

import numpy as np

from m3_tpu.index import postings as P
from m3_tpu.index.segment import Document
from m3_tpu.metrics.filters import literal_prefix as _literal_prefix
from m3_tpu.metrics.filters import literal_suffix as _literal_suffix
from m3_tpu.metrics.filters import prefix_upper_bound as _prefix_upper_bound
from m3_tpu.utils import querystats
from m3_tpu.utils.ident import decode_tags, encode_tags

MAGIC = b"M3PKSG02"
_HDR = struct.Struct("<9Q")
_CACHE_CAP = 256

# below this many candidate terms a scalar byte-compare bisect beats
# building/consulting the vectorized 8-byte key column
_KEYED_LOOKUP_MIN = 1024


def _align8(n: int) -> int:
    return (n + 7) & ~7


class _LazyDocs:
    """Sequence facade building Document objects on demand from the blobs."""

    __slots__ = ("_seg",)

    def __init__(self, seg: "PackedSegment"):
        self._seg = seg

    def __len__(self) -> int:
        return self._seg.n_docs

    def __getitem__(self, doc_id: int) -> Document:
        s = self._seg
        sid = bytes(s._sid_blob[s._sid_off[doc_id]: s._sid_off[doc_id + 1]])
        tags = decode_tags(
            bytes(s._tag_blob[s._tag_off[doc_id]: s._tag_off[doc_id + 1]])
        )
        return Document(doc_id, sid, tags)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class PackedSegment:
    """Immutable segment over one contiguous (possibly mmap'd) buffer."""

    def __init__(self, buf):
        mv = memoryview(buf)
        if bytes(mv[:8]) != MAGIC:
            raise ValueError("not a packed segment (bad magic)")
        (n_docs, sid_len, tags_len, n_fields, fname_len, n_terms,
         term_len, post_len, _flags) = _HDR.unpack_from(mv, 8)
        self.n_docs = n_docs
        self.n_fields = n_fields
        self.n_terms = n_terms
        self._buf = buf  # keep mmap/bytes alive
        off = _align8(8 + _HDR.size)

        def u64(count):
            nonlocal off
            a = np.frombuffer(mv, dtype="<u8", count=count, offset=off)
            off += 8 * count
            return a

        def blob(length):
            nonlocal off
            b = mv[off: off + length]
            off = _align8(off + length)
            return b

        self._sid_off = u64(n_docs + 1)
        self._sid_blob = blob(sid_len)
        self._tag_off = u64(n_docs + 1)
        self._tag_blob = blob(tags_len)
        self._fname_off = u64(n_fields + 1)
        self._fname_blob = blob(fname_len)
        self._field_term_start = u64(n_fields + 1)
        self._term_off = u64(n_terms + 1)
        self._term_blob = blob(term_len)
        self._post_off = u64(n_terms + 1)
        self._postings = np.frombuffer(mv, dtype="<u4", count=post_len, offset=off)
        # payload ends after the postings array; anything beyond (e.g. the
        # persistence checksum trailer) is NOT part of this segment
        self._payload_len = off + 4 * post_len
        self.docs = _LazyDocs(self)
        self._regex_cache: OrderedDict = OrderedDict()
        self._term_idx_cache: OrderedDict = OrderedDict()
        self._vocab_clean_cache: bool | None = None
        self._term_keys_cache: np.ndarray | None = None
        self._device_postings = None

    def series_ids(self):
        """Every doc's series id, sliced straight out of the id blob —
        no Document construction, no tag decode. The write path's
        per-block membership set (IndexBlock.seen_series) builds from
        this; going through `docs` would decode every tag blob."""
        off = self._sid_off
        blob = self._sid_blob
        return [bytes(blob[off[i] : off[i + 1]]) for i in range(self.n_docs)]

    def series_ids_at(self, doc_ids) -> list[bytes]:
        """Series ids for many doc ids in one pass over the id blob — no
        Document construction, no tag decode. The executor's batched
        search dedups on these BEFORE paying any tag decode."""
        off = self._sid_off
        blob = self._sid_blob
        return [bytes(blob[off[i]: off[i + 1]])
                for i in np.asarray(doc_ids, np.int64).tolist()]

    def docs_at(self, doc_ids) -> list[Document]:
        """Documents for many doc ids in one pass (the batched twin of
        the per-doc _LazyDocs facade: local offset/blob bindings, one tag
        decode per requested doc)."""
        sid_off, sid_blob = self._sid_off, self._sid_blob
        tag_off, tag_blob = self._tag_off, self._tag_blob
        out = []
        for i in np.asarray(doc_ids, np.int64).tolist():
            sid = bytes(sid_blob[sid_off[i]: sid_off[i + 1]])
            tags = decode_tags(bytes(tag_blob[tag_off[i]: tag_off[i + 1]]))
            out.append(Document(i, sid, tags))
        return out

    @property
    def _vocab_clean(self) -> bool:
        """Vocab is regex-scannable iff no term contains a newline. Computed
        lazily on first regex (a bootstrap-time scan would page in the whole
        blob) and without copying the blob out of the mapping."""
        if self._vocab_clean_cache is None:
            newlines = int(
                (np.frombuffer(self._term_blob, np.uint8) == 0x0A).sum()
            )
            self._vocab_clean_cache = newlines == self.n_terms
        return self._vocab_clean_cache

    # -- field/term access --

    def field_names(self) -> list[bytes]:
        return [
            bytes(self._fname_blob[self._fname_off[i]: self._fname_off[i + 1]])
            for i in range(self.n_fields)
        ]

    def _field_index(self, name: bytes) -> int:
        lo, hi = 0, self.n_fields
        while lo < hi:
            mid = (lo + hi) // 2
            t = bytes(self._fname_blob[self._fname_off[mid]: self._fname_off[mid + 1]])
            if t < name:
                lo = mid + 1
            else:
                hi = mid
        if lo < self.n_fields:
            t = bytes(self._fname_blob[self._fname_off[lo]: self._fname_off[lo + 1]])
            if t == name:
                return lo
        return -1

    def _term_at(self, i: int) -> bytes:
        return bytes(self._term_blob[self._term_off[i]: self._term_off[i + 1] - 1])

    def _term_range(self, fi: int) -> tuple[int, int]:
        return int(self._field_term_start[fi]), int(self._field_term_start[fi + 1])

    @property
    def _term_keys(self) -> np.ndarray:
        """u64 key per term: the first 8 bytes big-endian, zero-padded.
        Key order agrees with byte order everywhere keys differ (zero-pad
        vs prefix-shorter both sort the shorter string first), so a
        vectorized ``searchsorted`` over this column replaces all but the
        tie-run tail of a Python byte-compare bisect. Built lazily in 8
        vectorized gathers over the term blob — no per-term slicing —
        and cached forever (the segment is immutable): ~8 bytes/term."""
        keys = self._term_keys_cache
        if keys is None:
            offs = self._term_off[:-1].astype(np.int64)
            lens = self._term_off[1:].astype(np.int64) - offs - 1
            blob = np.frombuffer(self._term_blob, np.uint8)
            keys = np.zeros(self.n_terms, np.uint64)
            limit = max(blob.size - 1, 0)
            for j in range(8):
                b = blob[np.minimum(offs + j, limit)]
                keys = (keys << np.uint64(8)) | np.where(
                    j < lens, b, 0).astype(np.uint64)
            self._term_keys_cache = keys
        return keys

    @staticmethod
    def _term_key(value: bytes) -> int:
        v = value[:8]
        return int.from_bytes(v + b"\0" * (8 - len(v)), "big")

    def _bisect_term(self, lo: int, hi: int, value: bytes) -> int:
        """First term index in [lo, hi) with term >= value. Wide ranges
        run ONE vectorized searchsorted over the 8-byte key column; the
        scalar byte-compare loop then only walks the (usually empty) run
        of terms sharing value's first 8 bytes. Strict key inequality
        implies the same byte inequality, so the narrowing is exact."""
        if hi - lo >= _KEYED_LOOKUP_MIN:
            keys = self._term_keys
            k = np.uint64(self._term_key(value))
            lo = lo + int(np.searchsorted(keys[lo:hi], k, side="left"))
            hi = lo + int(np.searchsorted(keys[lo:hi], k, side="right"))
        while lo < hi:
            mid = (lo + hi) // 2
            if self._term_at(mid) < value:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def terms(self, field: bytes) -> list[bytes]:
        fi = self._field_index(field)
        if fi < 0:
            return []
        lo, hi = self._term_range(fi)
        return [self._term_at(i) for i in range(lo, hi)]

    def _postings_at(self, i: int) -> np.ndarray:
        return self._postings[self._post_off[i]: self._post_off[i + 1]].astype(
            np.uint32, copy=False
        )

    # -- query surface (same contract as segment.Segment) --

    def postings_term(self, field: bytes, value: bytes) -> np.ndarray:
        fi = self._field_index(field)
        if fi < 0:
            return P.EMPTY
        lo, hi = self._term_range(fi)
        i = self._bisect_term(lo, hi, value)
        if i < hi and self._term_at(i) == value:
            return self._postings_at(i)
        return P.EMPTY

    def postings_regexp(self, field: bytes, pattern: re.Pattern) -> np.ndarray:
        src = pattern.pattern
        if isinstance(src, str):
            src = src.encode()
        key = (field, src, pattern.flags)
        cached = self._regex_cache.get(key)
        if cached is not None:
            self._regex_cache.move_to_end(key)
            return cached
        out = self._gather_postings(self.term_indices_regexp(field, pattern))
        self._regex_cache[key] = out
        if len(self._regex_cache) > _CACHE_CAP:
            self._regex_cache.popitem(last=False)
        return out

    def term_indices_regexp(self, field: bytes,
                            pattern: re.Pattern) -> np.ndarray:
        """Absolute term indices matching the pattern — the term-selection
        surface the device-compiled postings programs consume
        (index/device.py needs WHICH CSR rows to intersect, not the
        materialized host union). Same narrowing as postings_regexp
        (shared LRU cache, keyed on field+source+flags): literal-prefix
        binary search bounds the vocab range before any Python ``re``
        runs, then the batched blob scan picks the matches."""
        src = pattern.pattern
        if isinstance(src, str):
            src = src.encode()
        key = (field, src, pattern.flags)
        cached = self._term_idx_cache.get(key)
        if cached is not None:
            self._term_idx_cache.move_to_end(key)
            return cached
        fi = self._field_index(field)
        if fi < 0:
            idxs = np.empty(0, np.int64)
        else:
            lo0, hi0 = self._term_range(fi)
            if pattern.flags & (re.I | re.X | re.S | re.M):
                # compile-time flags change what the literals mean —
                # prefix narrowing and the batched blob rescan (which
                # recompiles from source, losing the flags) are both
                # unsound; match per-term with the caller's own pattern
                querystats.record_index(terms_scanned=hi0 - lo0)
                idxs = np.asarray([i for i in range(lo0, hi0)
                                   if pattern.fullmatch(self._term_at(i))],
                                  np.int64)
            else:
                lo, hi = self._narrow_by_prefix(src, lo0, hi0)
                querystats.record_index(
                    terms_scanned=hi - lo,
                    terms_prefiltered=(hi0 - lo0) - (hi - lo))
                idxs = np.asarray(self._scan_vocab(src, pattern, lo, hi),
                                  np.int64)
        self._term_idx_cache[key] = idxs
        if len(self._term_idx_cache) > _CACHE_CAP:
            self._term_idx_cache.popitem(last=False)
        return idxs

    def _gather_postings(self, term_idxs) -> np.ndarray:
        """Union of the postings of many terms, gathered vectorized (no
        per-term Python) — the multi-list OR of the searcher algebra."""
        term_idxs = np.asarray(term_idxs, np.int64)
        if len(term_idxs) == 0:
            return P.EMPTY
        starts = self._post_off[term_idxs].astype(np.int64)
        lens = self._post_off[term_idxs + 1].astype(np.int64) - starts
        total = int(lens.sum())
        if total == 0:
            return P.EMPTY
        base = np.repeat(starts - np.concatenate([[0], np.cumsum(lens)[:-1]]),
                         lens)
        flat = self._postings[np.arange(total) + base]
        return np.unique(flat).astype(np.uint32, copy=False)

    def _narrow_by_prefix(self, src: bytes, lo: int, hi: int) -> tuple[int, int]:
        """Binary-search the vocab range sharing the pattern's literal
        prefix (the automaton's prefix-pruning role)."""
        prefix = _literal_prefix(src)
        if not prefix:
            return lo, hi
        new_lo = self._bisect_term(lo, hi, prefix)
        upper = _prefix_upper_bound(prefix)
        new_hi = self._bisect_term(new_lo, hi, upper) if upper else hi
        return new_lo, new_hi

    def _scan_scalar(self, src: bytes, pattern: re.Pattern,
                     lo: int, hi: int) -> list[int]:
        """Per-term matching tail for ranges the batched blob scan cannot
        soundly cover. A literal suffix (filters.literal_suffix) gates
        each term with a C-speed ``endswith`` before the Python regex
        engine ever runs — on adversarial backtracking patterns the
        endswith reject is the common case."""
        sfx = _literal_suffix(src)
        if sfx:
            return [i for i in range(lo, hi)
                    if self._term_at(i).endswith(sfx)
                    and pattern.fullmatch(self._term_at(i))]
        return [i for i in range(lo, hi)
                if pattern.fullmatch(self._term_at(i))]

    def _scan_vocab(self, src: bytes, pattern: re.Pattern,
                    lo: int, hi: int) -> list[int]:
        """Term indices in [lo, hi) fully matching the pattern: one
        C-speed multiline pass over the newline-joined vocab blob."""
        if lo >= hi:
            return []
        if not self._vocab_clean:
            return self._scan_scalar(src, pattern, lo, hi)
        start = int(self._term_off[lo])
        end = int(self._term_off[hi])
        blob = self._term_blob[start:end]
        try:
            rx = re.compile(b"(?m)^(?:" + src + b")$")
        except re.error:
            return self._scan_scalar(src, pattern, lo, hi)
        spans = [(m.start(), m.end()) for m in rx.finditer(blob)]
        if not spans:
            return []
        arr = np.asarray(spans, np.int64) + start
        offs = self._term_off[lo: hi + 1].astype(np.int64)  # one cast, reused
        idx = np.searchsorted(offs, arr[:, 0], side="right") - 1
        # zero-width matches at the very end of the blob land past the last
        # term; clamp before indexing and drop them via in_range
        in_range = (idx >= 0) & (idx < hi - lo)
        idx = np.clip(idx, 0, hi - lo - 1)
        # a match that consumed a term's trailing \n (pattern can match
        # newline: [^c]*, \D, ...) may have swallowed FOLLOWING terms that
        # match individually — finditer never revisits them, so the batched
        # scan is unsound for this pattern; fall back to per-term matching
        if bool((in_range & (arr[:, 1] >= offs[idx + 1])).any()):
            return self._scan_scalar(src, pattern, lo, hi)
        # full-term matches only: begin at the term start (rejects mid-term
        # hits of patterns containing \n) and end at the term's own \n
        valid = (in_range & (arr[:, 0] == offs[idx])
                 & (arr[:, 1] == offs[idx + 1] - 1))
        return lo + idx[valid]

    def postings_field(self, field: bytes) -> np.ndarray:
        fi = self._field_index(field)
        if fi < 0:
            return P.EMPTY
        lo, hi = self._term_range(fi)
        sl = self._postings[self._post_off[lo]: self._post_off[hi]]
        return np.unique(sl).astype(np.uint32, copy=False)

    def postings_all(self) -> np.ndarray:
        return np.arange(self.n_docs, dtype=np.uint32)

    # -- device-resident ragged CSR (index/device.py consumes these) --

    def postings_csr(self, term_idxs) -> tuple[np.ndarray, np.ndarray]:
        """(starts, lens) int64 rows of the flat postings column for the
        given absolute term indices — the host half of the ragged CSR
        a device postings program consumes (the offsets stay host-side;
        only the doc-id column lives on device)."""
        term_idxs = np.asarray(term_idxs, np.int64)
        starts = self._post_off[term_idxs].astype(np.int64)
        lens = self._post_off[term_idxs + 1].astype(np.int64) - starts
        return starts, lens

    def device_postings(self):
        """The flat doc-id postings column committed to device as int32,
        built once per sealed segment and cached forever (the segment is
        immutable, so seal/compaction time is the only transfer). Padded
        to a half-octave bucket so similarly-sized segments share device
        buffer shapes; the pad cells are never addressed by a valid CSR
        row, and the fused program's gather clips into them only for
        lanes it masks out anyway."""
        col = self._device_postings
        if col is None:
            import jax.numpy as jnp

            from m3_tpu.utils import compute_stats, dispatch

            n = len(self._postings)
            host = np.zeros(dispatch.next_bucket(max(n, 64)), np.int32)
            host[:n] = self._postings
            col = self._device_postings = jnp.asarray(host)
            # device-cache ledger: committed column bytes live as long
            # as the segment; a GC'd segment releases its share
            _track_device_column(self, int(col.nbytes))
            compute_stats.record_waste("postings", "column", n, host.size)
        return col

    # -- persistence --

    def to_bytes(self) -> bytes:
        return bytes(memoryview(self._buf)[: self._payload_len])


# -- device postings-column ledger ------------------------------------------
#
# Committed columns are cached forever on their (immutable) segment, so
# the only honest byte accounting is segment-lifetime: commit adds,
# segment GC subtracts (weakref.finalize). Registered as a
# compute_stats device-cache provider so /debug/compute and the soak
# trajectory see index device-memory pressure next to the hot tier's.

_dev_cols_lock = threading.Lock()
_dev_cols = {"entries": 0, "bytes": 0}


def _untrack_device_column(nbytes: int) -> None:
    with _dev_cols_lock:
        _dev_cols["entries"] -= 1
        _dev_cols["bytes"] -= nbytes


def _track_device_column(seg, nbytes: int) -> None:
    from m3_tpu.utils import compute_stats

    with _dev_cols_lock:
        _dev_cols["entries"] += 1
        _dev_cols["bytes"] += nbytes
    weakref.finalize(seg, _untrack_device_column, nbytes)
    compute_stats.register_device_cache(
        "postings_columns", lambda: dict(_dev_cols))


def build(docs) -> PackedSegment:
    """Pack an iterable of Documents (doc ids must be 0..D-1 in order)."""
    docs = list(docs)
    terms: dict[bytes, dict[bytes, list[int]]] = {}
    sid_parts: list[bytes] = []
    tag_parts: list[bytes] = []
    for d in docs:
        sid_parts.append(d.series_id)
        tag_parts.append(encode_tags(d.fields))
        for name, value in d.fields:
            terms.setdefault(name, {}).setdefault(value, []).append(d.doc_id)

    field_names = sorted(terms)
    fname_blob = b"".join(field_names)
    fname_off = np.zeros(len(field_names) + 1, "<u8")
    fname_off[1:] = np.cumsum([len(n) for n in field_names])

    term_parts: list[bytes] = []
    post_parts: list[np.ndarray] = []
    field_term_start = np.zeros(len(field_names) + 1, "<u8")
    for i, name in enumerate(field_names):
        vals = terms[name]
        vocab = sorted(vals)
        field_term_start[i + 1] = field_term_start[i] + len(vocab)
        for v in vocab:
            term_parts.append(v + b"\n")
            post_parts.append(np.asarray(sorted(set(vals[v])), dtype="<u4"))

    term_blob = b"".join(term_parts)
    n_terms = len(term_parts)
    term_off = np.zeros(n_terms + 1, "<u8")
    term_off[1:] = np.cumsum([len(t) for t in term_parts])
    post_off = np.zeros(n_terms + 1, "<u8")
    post_off[1:] = np.cumsum([len(p) for p in post_parts])
    postings = (np.concatenate(post_parts) if post_parts
                else np.empty(0, "<u4")).astype("<u4", copy=False)

    sid_blob = b"".join(sid_parts)
    sid_off = np.zeros(len(docs) + 1, "<u8")
    sid_off[1:] = np.cumsum([len(s) for s in sid_parts])
    tag_blob = b"".join(tag_parts)
    tag_off = np.zeros(len(docs) + 1, "<u8")
    tag_off[1:] = np.cumsum([len(t) for t in tag_parts])

    header = _HDR.pack(len(docs), len(sid_blob), len(tag_blob),
                       len(field_names), len(fname_blob), n_terms,
                       len(term_blob), len(postings), 0)
    out = bytearray(MAGIC + header)

    def pad(b: bytearray) -> None:
        b.extend(b"\0" * (_align8(len(b)) - len(b)))

    pad(out)
    for arr, raw in (
        (sid_off, sid_blob), (tag_off, tag_blob), (fname_off, fname_blob),
    ):
        out += arr.tobytes()
        out += raw
        pad(out)
    out += field_term_start.tobytes()
    out += term_off.tobytes()
    out += term_blob
    pad(out)
    out += post_off.tobytes()
    out += postings.tobytes()
    return PackedSegment(bytes(out))


def merge(segments: list) -> PackedSegment:
    """Compaction merge: dedupe series across segments, re-base doc ids
    (the multi_segments_builder role,
    /root/reference/src/m3ninx/index/segment/builder/multi_segments_builder.go)."""
    seen: set[bytes] = set()
    out: list[Document] = []
    for seg in segments:
        for d in seg.docs:
            if d.series_id in seen:
                continue
            seen.add(d.series_id)
            out.append(Document(len(out), d.series_id, d.fields))
    return build(out)


