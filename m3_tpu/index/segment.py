"""Index segments: mutable (accepting inserts) and sealed (immutable).

Role parity with the reference's mem segment + FST segment pair
(/root/reference/src/m3ninx/index/segment/mem/segment.go,
segment/fst/segment.go:130-180): a mutable segment is a concurrent-insert
terms dictionary; sealing produces an immutable segment with sorted term
dictionaries per field (the FST's role — ordered term lookup + range scan)
and postings as sorted id arrays. Regex queries scan the sorted vocabulary
of one field (the automaton-intersection role) and union the matching
postings.
"""

from __future__ import annotations

import re
import struct
from bisect import bisect_left

import numpy as np

from m3_tpu.index import postings as P


class Document:
    """Indexed document: series id + (name, value) fields."""

    __slots__ = ("doc_id", "series_id", "fields")

    def __init__(self, doc_id: int, series_id: bytes, fields: list[tuple[bytes, bytes]]):
        self.doc_id = doc_id
        self.series_id = series_id
        self.fields = fields


class MutableSegment:
    """Insert-optimized segment: field -> value -> growable id list."""

    def __init__(self) -> None:
        self._terms: dict[bytes, dict[bytes, list[int]]] = {}
        self._docs: list[Document] = []
        self._by_series: dict[bytes, int] = {}

    def insert(self, series_id: bytes, fields: list[tuple[bytes, bytes]]) -> int:
        """Insert once per series id; returns the doc id."""
        existing = self._by_series.get(series_id)
        if existing is not None:
            return existing
        doc_id = len(self._docs)
        doc = Document(doc_id, series_id, list(fields))
        self._docs.append(doc)
        self._by_series[series_id] = doc_id
        for name, value in fields:
            self._terms.setdefault(name, {}).setdefault(value, []).append(doc_id)
        return doc_id

    @property
    def n_docs(self) -> int:
        return len(self._docs)

    def seal(self) -> "Segment":
        fields = {}
        for name, values in self._terms.items():
            vocab = sorted(values)
            plists = [P.from_list(values[v]) for v in vocab]
            fields[name] = (vocab, plists)
        return Segment(fields, list(self._docs))


class Segment:
    """Immutable sealed segment: sorted vocab + postings per field."""

    def __init__(self, fields: dict, docs: list[Document]):
        # fields: name -> (sorted [values], [postings arrays])
        self._fields = fields
        self.docs = docs

    @property
    def n_docs(self) -> int:
        return len(self.docs)

    def series_ids(self) -> list[bytes]:
        """Every doc's series id (membership-set building, no field walk)."""
        return [d.series_id for d in self.docs]

    # batched doc surfaces (executor.search): docs here are materialized
    # objects, so bulk access is plain indexing — the methods exist to
    # share one contract with PackedSegment, whose lazy docs make the
    # split (ids first, docs only for dedup winners) actually cheap

    def series_ids_at(self, doc_ids) -> list[bytes]:
        docs = self.docs
        return [docs[int(i)].series_id for i in doc_ids]

    def docs_at(self, doc_ids) -> list[Document]:
        docs = self.docs
        return [docs[int(i)] for i in doc_ids]

    def field_names(self) -> list[bytes]:
        return sorted(self._fields)

    def terms(self, field: bytes) -> list[bytes]:
        f = self._fields.get(field)
        return list(f[0]) if f else []

    def postings_term(self, field: bytes, value: bytes) -> np.ndarray:
        f = self._fields.get(field)
        if not f:
            return P.EMPTY
        vocab, plists = f
        i = bisect_left(vocab, value)
        if i < len(vocab) and vocab[i] == value:
            return plists[i]
        return P.EMPTY

    def postings_regexp(self, field: bytes, pattern: re.Pattern) -> np.ndarray:
        """Union of postings whose term fully matches the pattern — the
        vocabulary scan standing in for FST-automaton intersection,
        narrowed first to the sorted-vocab range sharing the pattern's
        anchored literal prefix (binary search, not a full scan) and then
        by its literal suffix; ``fullmatch`` still decides membership, so
        the narrowing can only skip terms that cannot match."""
        from m3_tpu.metrics import filters

        f = self._fields.get(field)
        if not f:
            return P.EMPTY
        vocab, plists = f
        src = pattern.pattern
        if isinstance(src, str):
            src = src.encode()
        if pattern.flags & (re.IGNORECASE | re.VERBOSE):
            # compile-time flags change what the literal bytes mean —
            # byte-wise range/suffix narrowing would be unsound
            src = b""
        lo, hi = 0, len(vocab)
        prefix = filters.literal_prefix(src)
        if prefix:
            lo = bisect_left(vocab, prefix)
            upper = filters.prefix_upper_bound(prefix)
            if upper:
                hi = bisect_left(vocab, upper, lo)
        suffix = filters.literal_suffix(src)
        hits = [plists[i] for i in range(lo, hi)
                if (not suffix or vocab[i].endswith(suffix))
                and pattern.fullmatch(vocab[i])]
        from m3_tpu.utils import querystats

        querystats.record_index(terms_scanned=hi - lo,
                                terms_prefiltered=len(vocab) - (hi - lo))
        return P.union_many(hits)

    def postings_field(self, field: bytes) -> np.ndarray:
        """All docs having the field at any value."""
        f = self._fields.get(field)
        if not f:
            return P.EMPTY
        return P.union_many(list(f[1]))

    def postings_all(self) -> np.ndarray:
        return np.arange(len(self.docs), dtype=np.uint32)

    # -- persistence (the persist/fst-segment-files role) --

    def to_bytes(self) -> bytes:
        """Compact flat encoding: docs then per-field vocab+postings."""
        out = bytearray(struct.pack(">I", len(self.docs)))
        for d in self.docs:
            out += struct.pack(">I", len(d.series_id)) + d.series_id
            out += struct.pack(">H", len(d.fields))
            for n, v in d.fields:
                out += struct.pack(">H", len(n)) + n
                out += struct.pack(">H", len(v)) + v
        out += struct.pack(">I", len(self._fields))
        for name in sorted(self._fields):
            vocab, plists = self._fields[name]
            out += struct.pack(">H", len(name)) + name
            out += struct.pack(">I", len(vocab))
            for v, pl in zip(vocab, plists):
                out += struct.pack(">H", len(v)) + v
                out += struct.pack(">I", len(pl)) + pl.astype(">u4").tobytes()
        return bytes(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Segment":
        off = 0
        (n_docs,) = struct.unpack_from(">I", raw, off)
        off += 4
        docs = []
        for i in range(n_docs):
            (idlen,) = struct.unpack_from(">I", raw, off)
            off += 4
            sid = raw[off : off + idlen]
            off += idlen
            (nf,) = struct.unpack_from(">H", raw, off)
            off += 2
            fields = []
            for _ in range(nf):
                (ln,) = struct.unpack_from(">H", raw, off)
                off += 2
                name = raw[off : off + ln]
                off += ln
                (lv,) = struct.unpack_from(">H", raw, off)
                off += 2
                value = raw[off : off + lv]
                off += lv
                fields.append((name, value))
            docs.append(Document(i, sid, fields))
        (n_fields,) = struct.unpack_from(">I", raw, off)
        off += 4
        fields_map = {}
        for _ in range(n_fields):
            (ln,) = struct.unpack_from(">H", raw, off)
            off += 2
            name = raw[off : off + ln]
            off += ln
            (nv,) = struct.unpack_from(">I", raw, off)
            off += 4
            vocab, plists = [], []
            for _ in range(nv):
                (lv,) = struct.unpack_from(">H", raw, off)
                off += 2
                vocab.append(raw[off : off + lv])
                off += lv
                (np_len,) = struct.unpack_from(">I", raw, off)
                off += 4
                pl = np.frombuffer(raw, dtype=">u4", count=np_len, offset=off).astype(
                    np.uint32
                )
                off += 4 * np_len
                plists.append(pl)
            fields_map[name] = (vocab, plists)
        return cls(fields_map, docs)


def merge_segments(segments: list[Segment]) -> Segment:
    """Compaction: merge immutable segments, re-basing doc ids and deduping
    series (the multi_segments_builder role,
    /root/reference/src/m3ninx/index/segment/builder/multi_segments_builder.go)."""
    out = MutableSegment()
    for seg in segments:
        for d in seg.docs:
            out.insert(d.series_id, d.fields)
    return out.seal()
