"""Device-compiled inverted index: postings algebra as fused ragged
tensor programs (ROADMAP #4).

The reference evaluates label matchers with per-segment searcher loops
(/root/reference/src/m3ninx/search/searcher/conjunction.go:78-111) and
the seed kept that shape: each matcher materializes a host postings
array, then sorted-array set ops (or, past a threshold, host-built
bitmaps shipped to `ops/bitmaps` kernels) combine them. At a million
series the materialize-then-combine walk IS the latency — every matcher
pays a host union, every combine pays a transfer.

This module lowers the whole boolean combine onto the compute plane:

- Each sealed ``PackedSegment`` already stores its postings as a ragged
  CSR (flat doc-id column + per-term offsets). ``device_postings()``
  commits the column once per segment; only the selected (starts, lens)
  rows cross per query — the paged-ragged layout argument of `ops/ragged`
  applied to the index.
- Matcher resolution stays host-side and cheap: term bisect, literal
  prefix/suffix narrowed regex scans (`metrics/filters`), all LRU-cached
  on the immutable segment.
- The AND/OR/NOT combine across matchers compiles to ONE fused jit
  program per (n_pos, n_neg, conjunction, mesh) signature: a vmapped
  ragged gather expands each matcher's CSR rows to doc-membership bits,
  `ops/bitmaps.words_from_bool` packs them to uint64 words, and the
  word-wise reductions produce the result mask — no intermediate
  postings arrays, no per-matcher transfers. Shape buckets (half-octave
  on the rows/postings axes, word-aligned on the doc axis) bound the
  compile count, `dispatch.jit_tracker` proves cache behaviour.
- On an active ``("series",)`` compute mesh (PR 12) the packed word
  tensor is sharding-constrained to ``P(None, "series")`` — each device
  scatters and intersects only its own slice of the doc space; the
  reduced mask is replicated. Pure boolean algebra, so results are
  bit-identical at any device count.

Dispatch doctrine: the executor's scalar walk stays the counted
fallback — unpacked segments, nested boolean shapes, small work and
cold-jax processes never pay device overhead, and every fallback is
recorded with a reason (`querystats` index block, `dispatch` counters).
"""

from __future__ import annotations

import functools

import numpy as np

from m3_tpu.index import postings as P
from m3_tpu.index.query import (
    AllQuery,
    ConjunctionQuery,
    DisjunctionQuery,
    FieldQuery,
    NegationQuery,
    RegexpQuery,
    TermQuery,
)
from m3_tpu.utils import dispatch, querystats

# same economics as the executor's bitmap threshold: below this many
# (selected postings + doc-space) elements the sorted-array walk wins
WORK_THRESHOLD = 1 << 17

# operator hatch accepting the jax import on a query thread (see
# dispatch.jax_ready: a query thread must never be the first importer)
FORCE_ENV = "M3_TPU_INDEX_COMPILE"

_LEAVES = (TermQuery, RegexpQuery, FieldQuery)


def _fallback(reason: str):
    """Counted and explained, never an error: dispatch tally, registry
    counter (compute.index fallback{reason=...}) — the querystats
    fallback record is the executor's (it owns per-segment accounting)."""
    from m3_tpu.utils.instrument import default_registry

    dispatch.record("index.postings", False)
    default_registry().root_scope("compute").subscope(
        "index", reason=reason).counter("fallback")
    return None, reason


def _classify(query):
    """(conjunction, positive_leaves, negative_leaves) for a covered
    boolean shape, or a fallback-reason string. Covered: one AND or OR
    level over term/regexp/field leaves, with negation (of a leaf) only
    under AND — exactly the shapes `query.matchers_to_query` emits."""
    if isinstance(query, ConjunctionQuery):
        pos, neg = [], []
        for q in query.queries:
            if isinstance(q, AllQuery):
                continue  # AND identity
            if isinstance(q, NegationQuery):
                if not isinstance(q.inner, _LEAVES):
                    return "nested_boolean"
                neg.append(q.inner)
            elif isinstance(q, _LEAVES):
                pos.append(q)
            else:
                return "nested_boolean"
        if not pos and not neg:
            return "trivial_query"  # pure match-all: host shortcut
        return True, pos, neg
    if isinstance(query, DisjunctionQuery):
        pos = []
        for q in query.queries:
            if isinstance(q, AllQuery):
                return "trivial_query"  # OR absorbs to match-all
            if isinstance(q, _LEAVES):
                pos.append(q)
            else:
                return "nested_boolean"
        if not pos:
            return "trivial_query"  # empty OR: host returns EMPTY
        return False, pos, []
    return "nested_boolean"


def _resolve(seg, leaf) -> np.ndarray:
    """Absolute term indices a leaf selects — the host half of matcher
    evaluation (bisect / narrowed regex scan, all cached on the
    immutable segment). The device program never sees terms, only the
    CSR rows these indices name."""
    if isinstance(leaf, TermQuery):
        fi = seg._field_index(leaf.field_name)
        if fi < 0:
            return np.empty(0, np.int64)
        lo, hi = seg._term_range(fi)
        i = seg._bisect_term(lo, hi, leaf.value)
        if i < hi and seg._term_at(i) == leaf.value:
            return np.asarray([i], np.int64)
        return np.empty(0, np.int64)
    if isinstance(leaf, RegexpQuery):
        return seg.term_indices_regexp(leaf.field_name, leaf.compiled())
    fi = seg._field_index(leaf.field_name)
    if fi < 0:
        return np.empty(0, np.int64)
    lo, hi = seg._term_range(fi)
    return np.arange(lo, hi, dtype=np.int64)


@functools.lru_cache(maxsize=None)
def _program(n_pos: int, n_neg: int, conjunction: bool, mesh):
    """ONE fused program per matcher-shape signature: ragged gather ->
    membership scatter -> word pack -> boolean reduce. Data shapes vary
    only through the static (lb, npad) buckets and the committed column
    length, so recompiles stay O(log) per axis."""
    import jax
    import jax.numpy as jnp

    from m3_tpu.ops import bitmaps

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        words_sharding = NamedSharding(mesh, PartitionSpec(None, "series"))

    def run(col, starts, lens, *, lb, npad):
        def member(starts_m, lens_m):
            # expand this matcher's CSR rows into flat column positions:
            # lane j of lb belongs to row rid[j] at row-local offset
            # (j - exclusive_prefix[rid[j]])
            k = starts_m.shape[0]
            rid = jnp.repeat(jnp.arange(k, dtype=jnp.int32), lens_m,
                             total_repeat_length=lb)
            lane = jnp.arange(lb, dtype=jnp.int32)
            valid = lane < lens_m.sum()
            cum = jnp.cumsum(lens_m) - lens_m  # exclusive prefix
            idx = starts_m[rid] + (lane - cum[rid])
            ids = col[jnp.clip(idx, 0, col.shape[0] - 1)]
            # invalid lanes (repeat padding) scatter into the dump slot
            # npad-1, which the host decode discards with ids >= n_docs
            tgt = jnp.where(valid, ids, npad - 1)
            return jnp.zeros(npad, jnp.bool_).at[tgt].set(True)

        bits = jax.vmap(member)(starts, lens)          # [M, npad] bool
        words = bitmaps.words_from_bool(bits)          # [M, W] uint64
        if mesh is not None:
            # each device owns a contiguous slice of the doc-space words:
            # scatter+reduce stay device-local, the result mask replicates
            words = jax.lax.with_sharding_constraint(words, words_sharding)
        if conjunction:
            acc = bitmaps.and_reduce_words(words[:n_pos])
        else:
            acc = bitmaps.or_reduce_words(words[:n_pos])
        if n_neg:
            acc = acc & ~bitmaps.or_reduce_words(words[n_pos:])
        return acc

    return jax.jit(run, static_argnames=("lb", "npad"))


def match(seg, query):
    """Evaluate one boolean query against one segment on the compute
    plane. Returns ``(doc_ids, None)`` on success — sorted unique
    uint32, bit-identical to the scalar walk — or ``(None, reason)``
    when this (segment, query, process) should take the counted
    fallback."""
    if not hasattr(seg, "postings_csr"):
        return _fallback("unpacked_segment")
    shape = _classify(query)
    if isinstance(shape, str):
        return _fallback(shape)
    if not dispatch.jax_ready(FORCE_ENV):
        return _fallback("jax_not_ready")
    conjunction, pos_leaves, neg_leaves = shape

    sels = [_resolve(seg, q) for q in pos_leaves + neg_leaves]
    n_pos = len(pos_leaves)
    if conjunction and any(len(s) == 0 for s in sels[:n_pos]):
        # a positive matcher selected no terms: AND is empty, no program
        dispatch.record("index.postings", True)
        querystats.record_index(postings_rows=sum(len(s) for s in sels))
        return P.EMPTY, None

    csrs = [seg.postings_csr(s) for s in sels]
    totals = [int(lens.sum()) for _, lens in csrs]
    if not dispatch.use_device(sum(totals) + seg.n_docs, WORK_THRESHOLD):
        return _fallback("small_work")

    from m3_tpu.parallel import mesh as mesh_mod

    mesh = mesh_mod.active_compute_mesh()
    n_dev = int(mesh.devices.size) if mesh is not None else 1

    import jax.numpy as jnp

    M = len(csrs)
    kb = dispatch.next_bucket(max(max(len(s) for s in sels), 1))
    lb = dispatch.next_bucket(max(max(totals), 64))
    npad = dispatch.next_bucket(seg.n_docs + 1, multiple=64 * n_dev)
    starts = np.zeros((M, kb), np.int32)
    lens = np.zeros((M, kb), np.int32)
    for m, (s, ln) in enumerate(csrs):
        starts[m, : len(s)] = s
        lens[m, : len(ln)] = ln

    import time

    from m3_tpu.utils.instrument import default_registry

    col = seg.device_postings()
    prog = _program(n_pos, M - n_pos, conjunction, mesh)
    # padding-waste ledger: selected CSR rows vs the kb bucket, postings
    # lanes vs lb, doc-space bits vs the word-aligned npad
    from m3_tpu.utils import compute_stats

    compute_stats.record_waste("postings", "terms",
                               sum(len(s) for s in sels), M * kb)
    compute_stats.record_waste("postings", "lanes", sum(totals), M * lb)
    compute_stats.record_waste("postings", "docs", seg.n_docs + 1, npad)
    sig = (f"P{n_pos}N{M - n_pos}{'&' if conjunction else '|'}"
           f"|K{kb}|L{lb}|D{npad}" + (f"|M{n_dev}" if mesh else ""))
    starts_d, lens_d = jnp.asarray(starts), jnp.asarray(lens)
    t0 = time.perf_counter()
    with dispatch.jit_tracker(
            "postings_program", prog, sig=sig,
            lower=lambda: prog.lower(col, starts_d, lens_d,
                                     lb=lb, npad=npad)):
        words = prog(col, starts_d, lens_d, lb=lb, npad=npad)
    dispatch.record("index.postings", True)
    sc = default_registry().root_scope("compute").subscope("index")
    sc.counter("device")
    # program wall time; on a shape-cache miss this includes the
    # trace+compile (compute.jit{op=postings_program} splits that out)
    sc.observe("postings_seconds", time.perf_counter() - t0)
    querystats.record_index(postings_rows=sum(len(s) for s in sels))

    w = np.asarray(words)
    bits = np.unpackbits(w.view(np.uint8), bitorder="little")
    ids = np.nonzero(bits)[0]
    return ids[ids < seg.n_docs].astype(np.uint32), None
