"""Namespace index: time-partitioned reverse index over segments.

Role parity with the reference nsIndex
(/root/reference/src/dbnode/storage/index.go:623,1482,1524): index blocks
partitioned by block start; inserts land in a mutable segment per block and
compact into sealed immutable segments (the mutable->FST compaction,
storage/index/mutable_segments.go); queries evaluate over every block
overlapping the time range and dedupe series; aggregate queries surface
field names/values for label APIs.
"""

from __future__ import annotations

import re

from m3_tpu.index import packed
from m3_tpu.index.executor import search
from m3_tpu.index.query import Query
from m3_tpu.index.segment import MutableSegment, Segment


class IndexBlock:
    def __init__(self) -> None:
        self.mutable = MutableSegment()
        self.sealed: list[Segment] = []
        self._cache: Segment | None = None  # sealed view of `mutable`
        self._cache_docs = 0
        self.persisted_docs = -1  # doc count at last persist (persist.py)
        # series ids present anywhere in this block (mutable OR sealed),
        # built lazily: the insert pre-filter. Without it a re-insert of a
        # series that compaction moved into a sealed segment lands a
        # duplicate doc in the fresh mutable segment — growing n_docs and
        # so invalidating the sealed-view cache (a re-seal on the next
        # query) for a series the block already serves. None = not built
        # yet (or invalidated by an external sealed-segment install).
        self._seen: set[bytes] | None = None

    def seen_series(self) -> set[bytes]:
        """The block's series membership set (built on first use). Sealed
        segments contribute via series_ids() — id-blob slices, NOT the
        docs facade, which would decode every tag blob just to read ids
        (a restored block's first write would stall on an O(docs) tag
        decode otherwise)."""
        if self._seen is None:
            seen = set(self.mutable._by_series)
            for seg in self.sealed:
                ids_of = getattr(seg, "series_ids", None)
                if ids_of is not None:
                    seen.update(ids_of())
                else:  # segment types without the cheap surface
                    for doc in seg.docs:
                        seen.add(doc.series_id)
            self._seen = seen
        return self._seen

    def insert(self, series_id: bytes, fields) -> None:
        seen = self.seen_series()
        if series_id in seen:
            return  # already present (mutable or sealed): nothing to add
        self.mutable.insert(series_id, fields)
        seen.add(series_id)

    def segments(self) -> list[Segment]:
        segs = list(self.sealed)
        if self.mutable.n_docs:
            # the doc-count check is the (single) cache invalidation: docs
            # are only ever appended to a mutable segment
            if self._cache is None or self._cache_docs != self.mutable.n_docs:
                self._cache = self.mutable.seal()
                self._cache_docs = self.mutable.n_docs
            segs.append(self._cache)
        return segs

    def compact(self, full: bool = False) -> None:
        """Compact this block's segments (the mutable->FST compaction,
        reference storage/index/mutable_segments.go).

        Default: SIZE-TIERED — seal the mutable segment into a packed one
        (mutable-first priority, reference plan.go OrderBy), then run the
        planner over the sealed set and merge only within-level groups.
        Per-block segment count stays bounded under churn without
        rewriting every doc each pass. ``full=True`` folds everything into
        ONE packed segment (the persist path wants a single artifact)."""
        if full:
            segs = self.segments()
            if not segs:
                return
            if len(segs) > 1 or not isinstance(segs[0], packed.PackedSegment):
                self.sealed = [packed.merge(segs)]
            self.mutable = MutableSegment()
            self._cache = None
            return
        from m3_tpu.index import compaction

        if self.mutable.n_docs:
            sealed_view = self.segments()[-1]  # cached sealed view
            self.sealed.append(packed.merge([sealed_view])
                               if not isinstance(sealed_view, packed.PackedSegment)
                               else sealed_view)
            self.mutable = MutableSegment()
            self._cache = None
        for task in compaction.plan(self.sealed):
            merged = packed.merge(task.segments)
            keep = [s for s in self.sealed if s not in task.segments]
            self.sealed = keep + [merged]


class NamespaceIndex:
    def __init__(self, block_size_ns: int):
        self.block_size_ns = block_size_ns
        self._blocks: dict[int, IndexBlock] = {}

    def _block_for(self, t_ns: int) -> IndexBlock:
        bs = t_ns - (t_ns % self.block_size_ns)
        blk = self._blocks.get(bs)
        if blk is None:
            blk = self._blocks[bs] = IndexBlock()
        return blk

    def insert(self, series_id: bytes, fields: list[tuple[bytes, bytes]], t_ns: int) -> None:
        self._block_for(t_ns).insert(series_id, fields)

    def insert_many(self, series_ids: list[bytes], fields_list: list,
                    ts_ns) -> int:
        """Batched insert with the per-block seen-set pre-filter applied
        up front: rows group by target index block, and series already
        present in their block never touch the mutable segment — so a
        steady-state write batch of existing series costs one set probe
        per row and leaves the sealed-view cache valid (no re-seal on the
        next query). Returns docs actually inserted."""
        import numpy as np

        ts = np.asarray(ts_ns, np.int64)
        bs_arr = ts - (ts % self.block_size_ns)
        inserted = 0
        # one row-index gather per distinct target block (batches land in
        # 1-2 blocks), then the per-row work is a single set probe
        for bs in np.unique(bs_arr).tolist():
            blk = self._block_for(bs)  # bs is already block-aligned
            seen = blk.seen_series()
            for i in np.nonzero(bs_arr == bs)[0].tolist():
                sid = series_ids[i]
                if sid in seen:
                    continue
                blk.mutable.insert(sid, fields_list[i])
                seen.add(sid)
                inserted += 1
        return inserted

    def _overlapping(self, start_ns: int, end_ns: int) -> list[IndexBlock]:
        out = []
        for bs, blk in sorted(self._blocks.items()):
            if bs + self.block_size_ns <= start_ns or bs >= end_ns:
                continue
            out.append(blk)
        return out

    def query(self, query: Query, start_ns: int, end_ns: int, limit: int | None = None):
        """Docs whose series matched in any overlapping index block."""
        from m3_tpu.utils import trace

        with trace.span(trace.INDEX_QUERY):
            segments = []
            for blk in self._overlapping(start_ns, end_ns):
                segments.extend(blk.segments())
            return search(segments, query, limit)

    def aggregate_field_names(self, start_ns: int, end_ns: int) -> list[bytes]:
        names: set[bytes] = set()
        for blk in self._overlapping(start_ns, end_ns):
            for seg in blk.segments():
                names.update(seg.field_names())
        return sorted(names)

    def aggregate_field_values(
        self, field: bytes, start_ns: int, end_ns: int,
        pattern: str | None = None,
    ) -> list[bytes]:
        rx = re.compile(pattern.encode()) if pattern else None
        values: set[bytes] = set()
        for blk in self._overlapping(start_ns, end_ns):
            for seg in blk.segments():
                for v in seg.terms(field):
                    if rx is None or rx.fullmatch(v):
                        values.add(v)
        return sorted(values)

    def compact(self, full: bool = False) -> None:
        for blk in self._blocks.values():
            blk.compact(full=full)

    def expire_before(self, cutoff_ns: int) -> int:
        dropped = 0
        for bs in list(self._blocks):
            if bs + self.block_size_ns <= cutoff_ns:
                del self._blocks[bs]
                dropped += 1
        return dropped

    @property
    def n_blocks(self) -> int:
        return len(self._blocks)
