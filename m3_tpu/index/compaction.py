"""Size-tiered compaction planning for index segments.

Role parity with the reference compaction planner
(/root/reference/src/dbnode/storage/index/compaction/plan.go): segments are
grouped into size levels; within a level, consecutive segments accumulate
into one merge task until the cumulative size crosses the level's max; the
(sealed view of the) mutable segment is always compacted first. Segments
larger than every level are left alone — they are the tier outputs.

Size here is DOCUMENT COUNT: the packed columnar segments (index/packed.py)
scale linearly in docs, and doc count is available without re-serializing,
so it plays the role byte-size plays for the reference's FST segments.

The payoff is the same as the reference's: per-block segment count stays
O(levels + 1) under continuous churn, and each doc is rewritten
O(#levels) times total instead of once per compaction pass.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class Level:
    min_size_inclusive: int
    max_size_exclusive: int


# geometric doc-count tiers; segments >= the last max are terminal outputs
DEFAULT_LEVELS = (
    Level(0, 1 << 14),
    Level(1 << 14, 1 << 17),
    Level(1 << 17, 1 << 20),
)


@dataclass
class Task:
    segments: list = field(default_factory=list)

    @property
    def size(self) -> int:
        return sum(s.n_docs for s in self.segments)


def plan(sealed_segments: list, levels=DEFAULT_LEVELS) -> list[Task]:
    """Merge tasks over sealed segments (each task's segments merge into
    one). Only tasks with >= 2 segments are returned — a lone segment in
    its level is already compact."""
    by_level: dict[Level, list] = {}
    for seg in sealed_segments:
        for lv in levels:
            if lv.min_size_inclusive <= seg.n_docs < lv.max_size_exclusive:
                by_level.setdefault(lv, []).append(seg)
                break
        # segments above every level are terminal: left unplanned
    tasks: list[Task] = []
    for lv in sorted(by_level, key=lambda l: l.min_size_inclusive):
        segs = sorted(by_level[lv], key=lambda s: s.n_docs)
        cur = Task()
        for seg in segs:
            cur.segments.append(seg)
            if cur.size >= lv.max_size_exclusive:
                tasks.append(cur)
                cur = Task()
        if len(cur.segments):
            tasks.append(cur)
    return [t for t in tasks if len(t.segments) >= 2]
