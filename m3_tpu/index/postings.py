"""Postings lists: sorted uint32 document-id arrays.

Role parity with the reference postings abstraction
(/root/reference/src/m3ninx/postings/types.go:46-109) and its roaring-bitmap
implementation. Host-side set algebra runs on sorted numpy arrays (the
control-plane path); large batched query evaluation lowers to dense bitmap
tensors on device (m3_tpu.ops.bitmaps) where AND/OR/ANDNOT become vectorized
word ops — the TPU replacement for roaring container loops.
"""

from __future__ import annotations

import numpy as np

EMPTY = np.empty(0, dtype=np.uint32)


def from_list(ids) -> np.ndarray:
    return np.unique(np.asarray(ids, dtype=np.uint32))


def intersect(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.intersect1d(a, b, assume_unique=True)


def union(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.union1d(a, b)


def difference(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.setdiff1d(a, b, assume_unique=True)


def union_many(lists: list[np.ndarray]) -> np.ndarray:
    """N-way union as ONE concatenate + unique pass: O(N log N) on the
    total element count, instead of the pairwise-reduce union's repeated
    merge allocations (each intermediate is re-sorted and re-scanned)."""
    lists = [p for p in lists if len(p)]
    if not lists:
        return EMPTY
    if len(lists) == 1:
        return lists[0].astype(np.uint32, copy=False)
    return np.unique(np.concatenate(lists)).astype(np.uint32, copy=False)


def to_bitmap(p: np.ndarray, n_docs: int) -> np.ndarray:
    """Dense u64 word bitmap [ceil(n/64)] for device algebra."""
    words = np.zeros((n_docs + 63) // 64, dtype=np.uint64)
    if len(p):
        w = p // 64
        bit = np.uint64(1) << (p % 64).astype(np.uint64)
        np.bitwise_or.at(words, w, bit)
    return words


def from_bitmap(words: np.ndarray) -> np.ndarray:
    """Sorted ids from a dense u64 word bitmap (little-endian hosts)."""
    bits = np.unpackbits(np.ascontiguousarray(words).view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint32)
