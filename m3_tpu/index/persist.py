"""Index segment persistence.

Role parity with the reference's per-index-block segment files
(/root/reference/src/dbnode/persist/fs/index_write.go + m3ninx/persist):
each index block's compacted immutable segment is written to
<root>/<namespace>/_index/segment-<blockstart>-v<version>.db with an
adler32 trailer; bootstrap loads persisted segments instead of rebuilding
the reverse index from fileset tag scans (which remains the fallback for
blocks without a persisted segment).

Current format: the packed-segment buffer (index/packed.py) written
verbatim + adler32 trailer, loaded back as ZERO-COPY views over an mmap —
no dict rebuilding, the fst-segment mmap model (segment/fst/segment.go:130).
Legacy "M3IXSEG1" files (round-1 dict segments) still load.
"""

from __future__ import annotations

import os
import struct
import zlib

from m3_tpu.index import packed
from m3_tpu.index.index import IndexBlock, NamespaceIndex
from m3_tpu.index.segment import Segment
from m3_tpu.utils import faults

_MAGIC = b"M3IXSEG1"


def _index_dir(root: str, namespace: str) -> str:
    return os.path.join(root, namespace, "_index")


def _path(root: str, namespace: str, block_start: int) -> str:
    return os.path.join(_index_dir(root, namespace), f"segment-{block_start}.db")


def persist_index(index: NamespaceIndex, root: str, namespace: str,
                  seal_before_ns: int | None = None) -> int:
    """Compact + write every index block that has new docs since the last
    persist. Returns blocks written.

    ``seal_before_ns`` limits persistence to blocks whose window has fully
    passed (the reference persists index segments per block volume at data
    flush time, not continuously); ACTIVE blocks are left to the
    background size-tiered compaction instead of being fully rewritten
    every tick."""
    os.makedirs(_index_dir(root, namespace), exist_ok=True)
    written = 0
    for bs, blk in list(index._blocks.items()):
        if seal_before_ns is not None and \
                bs + index.block_size_ns > seal_before_ns:
            continue  # still accepting writes: tiered compaction only
        n_docs = sum(s.n_docs for s in blk.segments())
        if blk.persisted_docs == n_docs:
            continue
        blk.compact(full=True)  # the fileset wants one segment artifact
        if not blk.sealed:
            continue
        payload = blk.sealed[0].to_bytes()
        # packed buffers are written verbatim (their own magic leads) so
        # the loader can mmap them in place; trailer guards torn writes.
        # Fault seams mirror the fileset's: index.persist fires BEFORE any
        # byte lands (per-block), index.persist.write can tear the tmp
        # file — either way the committed segment under the final name
        # stays intact and bootstrap falls back to the tag-scan rebuild.
        faults.check("index.persist", block=bs)
        from m3_tpu.utils.instrument import default_registry

        raw = payload + struct.pack(">I", zlib.adler32(payload))
        tmp = _path(root, namespace, bs) + ".tmp"
        with default_registry().root_scope("index").histogram(
                "persist_seconds"):
            with open(tmp, "wb") as f:
                faults.torn_write(f, raw, "index.persist.write")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, _path(root, namespace, bs))
        # record the POST-compact doc count: pre-compact sums double-count
        # series duplicated across segments and would mask later inserts
        blk.persisted_docs = blk.sealed[0].n_docs
        written += 1
    return written


def _load_packed(path: str) -> packed.PackedSegment:
    """mmap a packed segment file; views are zero-copy over the mapping."""
    import mmap as _mmap

    with open(path, "rb") as f:
        mm = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    mv = memoryview(mm)
    try:
        if zlib.adler32(mv[:-4]) != struct.unpack(">I", mv[-4:])[0]:
            raise ValueError(f"checksum mismatch in {path}")
        return packed.PackedSegment(mm)
    finally:
        mv.release()


def load_index(index: NamespaceIndex, root: str, namespace: str,
               cutoff_ns: int | None = None) -> set[int]:
    """Load persisted segments into the index; returns the block starts
    restored (corrupt files are skipped — callers fall back to the fileset
    tag-scan rebuild for those blocks). Blocks fully before cutoff_ns are
    not resurrected (retention parity with the other bootstrap paths)."""
    d = _index_dir(root, namespace)
    restored: set[int] = set()
    if not os.path.isdir(d):
        return restored
    for name in sorted(os.listdir(d)):
        if not (name.startswith("segment-") and name.endswith(".db")):
            continue
        try:
            bs = int(name[len("segment-") : -len(".db")])
        except ValueError:
            continue
        if cutoff_ns is not None and bs + index.block_size_ns <= cutoff_ns:
            continue  # expired: leave for expire_index_files to reclaim
        try:
            path = os.path.join(d, name)
            with open(path, "rb") as f:
                head = f.read(8)
            if head == packed.MAGIC:
                seg = _load_packed(path)
            else:
                with open(path, "rb") as f:
                    raw = f.read()
                if not raw.startswith(_MAGIC):
                    continue
                payload, trailer = raw[len(_MAGIC) : -4], raw[-4:]
                if zlib.adler32(payload) != struct.unpack(">I", trailer)[0]:
                    continue
                seg = Segment.from_bytes(payload)  # legacy round-1 format
        except Exception:
            continue
        blk = index._blocks.get(bs)
        if blk is None:
            blk = index._blocks[bs] = IndexBlock()
        blk.sealed.append(seg)
        blk._seen = None  # membership grew outside insert: rebuild lazily
        blk.persisted_docs = sum(s.n_docs for s in blk.segments())
        restored.add(bs)
    return restored


def expire_index_files(root: str, namespace: str, cutoff_ns: int,
                       block_size_ns: int) -> int:
    d = _index_dir(root, namespace)
    if not os.path.isdir(d):
        return 0
    removed = 0
    for name in list(os.listdir(d)):
        if not (name.startswith("segment-") and name.endswith(".db")):
            continue
        try:
            bs = int(name[len("segment-") : -len(".db")])
        except ValueError:
            continue
        if bs + block_size_ns <= cutoff_ns:
            try:
                os.remove(os.path.join(d, name))
                removed += 1
            except OSError:
                pass
    return removed
