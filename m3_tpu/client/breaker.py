"""Per-host circuit breaker + bounded retry for the cluster client.

Role parity with the reference client's resilience layer
(/root/reference/src/dbnode/client/circuitbreaker/circuit.go — a
closed/open/half-open breaker gating each host's requests — and the
retrier wiring in client/session.go): a flapping replica must shed load
fast instead of being hammered with doomed requests, and transient
failures get a few backed-off retries before feeding the consistency
accumulator.

Redesign notes (not a port): the reference's breaker is windowed-ratio
based with goroutine-driven state sweeps; here the breaker is a small
lock-free-enough state machine checked inline on each call (no background
threads — the client is often embedded in request handlers), using
consecutive-failure opening, monotonic-clock cooldown, and a bounded
number of half-open probes. The clock is injectable so failover tests run
in virtual time.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass


class BreakerOpen(Exception):
    """Request rejected locally: the host's circuit is open."""


class Backpressure(Exception):
    """The server answered 429: it is HEALTHY and explicitly asked this
    caller to slow down (per-tenant admission control shedding load).
    Carries the server's Retry-After hint. HostPolicy treats this as
    backpressure — honored wait + jittered retry, never a breaker
    failure: counting sheds as failures would convert per-tenant
    throttling into node-level circuit-opening, the exact cross-tenant
    blast radius admission control exists to prevent."""

    def __init__(self, msg: str, retry_after_s: float = 0.05):
        super().__init__(msg)
        self.retry_after_s = max(0.001, float(retry_after_s))


@dataclass(frozen=True)
class BreakerConfig:
    failure_threshold: int = 5      # consecutive failures that open the circuit
    open_timeout_s: float = 5.0     # cooldown before a half-open probe
    half_open_probes: int = 1       # concurrent trial requests when half-open
    retry_attempts: int = 2         # per-call attempts (1 = no retry)
    retry_backoff_s: float = 0.02   # first backoff; doubles per retry
    # multiplicative backoff jitter in [0, frac): many callers retrying a
    # recovered host must not stampede it in lockstep (0 = deterministic)
    retry_jitter_frac: float = 0.0
    # 429 backpressure handling: cap on how long one Retry-After hint may
    # stall a caller, and jitter applied ON TOP of the honored wait so
    # shed tenants don't re-arrive in lockstep when the window reopens
    backpressure_cap_s: float = 2.0
    backpressure_jitter_frac: float = 0.25


CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """closed → (threshold failures) → open → (cooldown) → half_open →
    success closes / failure reopens."""

    def __init__(self, config: BreakerConfig = BreakerConfig(),
                 clock=time.monotonic):
        self.config = config
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self.rejected = 0  # observability: calls shed while open

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if self._state == OPEN and \
                self.clock() - self._opened_at >= self.config.open_timeout_s:
            self._state = HALF_OPEN
            self._probes_in_flight = 0

    def allow(self) -> bool:
        """True if a request may go out now (reserves a probe slot when
        half-open)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and \
                    self._probes_in_flight < self.config.half_open_probes:
                self._probes_in_flight += 1
                return True
            self.rejected += 1
            return False

    def on_success(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probes_in_flight = 0

    def on_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # failed probe: back to cooldown
                self._state = OPEN
                self._opened_at = self.clock()
                self._probes_in_flight = 0
                return
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.config.failure_threshold:
                self._state = OPEN
                self._opened_at = self.clock()


class HostPolicy:
    """One host's breaker + retry policy; `call` wraps every request the
    session sends that host."""

    def __init__(self, host: str, config: BreakerConfig = BreakerConfig(),
                 clock=time.monotonic, sleep=time.sleep,
                 rng: random.Random | None = None,
                 no_count: tuple[type[BaseException], ...] = ()):
        self.host = host
        self.breaker = CircuitBreaker(config, clock)
        self.config = config
        self._sleep = sleep
        # seeded per-host so jittered schedules replay deterministically
        self._rng = rng if rng is not None else random.Random(host)
        # exception types that are the CALLER's fault (deterministic 4xx,
        # malformed request): re-raised without a retry and without
        # counting as a host failure — a healthy host must not have its
        # circuit opened by requests that can never succeed
        self._no_count = no_count

    def call(self, fn, *args, **kwargs):
        """Run fn through the breaker with bounded backed-off retries.
        Raises BreakerOpen without touching the network when the circuit
        is open; re-raises the last error when retries are exhausted
        (feeding the caller's consistency accounting either way)."""
        last_err: Exception | None = None
        for attempt in range(max(1, self.config.retry_attempts)):
            if not self.breaker.allow():
                if last_err is not None:
                    raise last_err  # breaker opened mid-retry: surface cause
                raise BreakerOpen(f"circuit open for host {self.host}")
            try:
                out = fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - every failure counts
                if getattr(e, "retry_after_s", None) is not None:
                    # 429 backpressure: the host answered, so the breaker
                    # records a SUCCESS (a shed tenant must never open
                    # the node's circuit), and the caller waits out the
                    # server's Retry-After hint (capped, jittered) before
                    # retrying within the normal attempts budget
                    self.breaker.on_success()
                    last_err = e
                    if attempt + 1 < self.config.retry_attempts:
                        delay = min(float(e.retry_after_s),
                                    self.config.backpressure_cap_s)
                        if self.config.backpressure_jitter_frac:
                            delay *= 1.0 + \
                                self.config.backpressure_jitter_frac \
                                * self._rng.random()
                        self._sleep(delay)
                        continue
                    raise
                if self._no_count and isinstance(e, self._no_count):
                    # the host ANSWERED (deterministic request error): for
                    # the breaker that is a healthy response — record
                    # success so a half-open probe ending in a 4xx closes
                    # the circuit (and releases its probe slot) instead of
                    # leaking it and shedding the host forever
                    self.breaker.on_success()
                    raise  # ...but the caller still sees their error
                self.breaker.on_failure()
                last_err = e
                if attempt + 1 < self.config.retry_attempts:
                    backoff = self.config.retry_backoff_s * (2 ** attempt)
                    if self.config.retry_jitter_frac:
                        backoff *= 1.0 + \
                            self.config.retry_jitter_frac * self._rng.random()
                    self._sleep(backoff)
                continue
            self.breaker.on_success()
            return out
        raise last_err  # type: ignore[misc]
