"""HTTP connection to a storage node's NodeAPI.

The host-queue/transport layer of the reference client
(/root/reference/src/dbnode/client/host_queue.go — TChannel connections per
host) becomes one persistent HTTP connection per (host, thread), reconnected
on failure. Implements the Session's NodeConnection protocol plus the index
query surface.
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
from urllib.parse import urlencode, urlparse

from m3_tpu.storage.database import Datapoint


class NodeUnavailableError(ConnectionError):
    pass


def _retry_after_s(raw: str | None) -> float:
    """Retry-After header -> seconds (integer-seconds form; a missing or
    malformed value falls back to a short default so backpressure still
    backs off)."""
    try:
        return max(0.001, float(raw))
    except (TypeError, ValueError):
        return 0.05


def parse_endpoint(endpoint: str) -> tuple[str, int]:
    """(host, port) of a node endpoint; single source of the scheme guard
    and default port for connections AND topology-change detection."""
    u = urlparse(endpoint if "//" in endpoint else f"http://{endpoint}")
    return u.hostname, u.port or 9000


class HTTPNodeConnection:
    def __init__(self, endpoint: str, timeout_s: float = 10.0):
        self.host, self.port = parse_endpoint(endpoint)
        self.timeout_s = timeout_s
        self._tl = threading.local()
        # every thread's socket, so close() can tear all of them down
        self._all_lock = threading.Lock()
        self._all: set[http.client.HTTPConnection] = set()

    def _conn(self) -> http.client.HTTPConnection:
        c = getattr(self._tl, "conn", None)
        if c is None:
            c = http.client.HTTPConnection(self.host, self.port,
                                           timeout=self.timeout_s)
            self._tl.conn = c
            with self._all_lock:
                self._all.add(c)
        return c

    def _request(self, method: str, path: str, body: bytes | None = None):
        _ctype, payload = self._request_raw(method, path, body)
        return json.loads(payload) if payload else None

    def _request_raw(self, method: str, path: str, body: bytes | None = None,
                     accept: str | None = None):
        """(content_type, payload) of one node RPC — the raw transport
        under _request, kept separate so binary-framed responses
        (utils/wire) never round-trip through a JSON parse."""
        from m3_tpu.utils import trace

        # the active trace context rides every node RPC as a W3C-style
        # traceparent header, so node-side spans join the caller's trace
        headers = trace.inject_headers({"Content-Type": "application/json"})
        if accept is not None:
            headers["Accept"] = accept
        last_err: Exception | None = None
        for attempt in range(2):  # one transparent reconnect for stale conns
            c = self._conn()
            try:
                c.request(method, path, body=body, headers=headers)
                r = c.getresponse()
                payload = r.read()
                if r.status == 429:
                    # per-tenant admission shed: backpressure, not a node
                    # failure — the breaker layer honors Retry-After
                    # instead of counting this against the host's circuit
                    from m3_tpu.client.breaker import Backpressure

                    raise Backpressure(
                        f"{self.host}:{self.port}{path} -> 429 "
                        f"{payload[:200]!r}",
                        retry_after_s=_retry_after_s(r.getheader("Retry-After")),
                    )
                if r.status >= 400:
                    raise NodeUnavailableError(
                        f"{self.host}:{self.port}{path} -> {r.status} "
                        f"{payload[:200]!r}"
                    )
                return r.getheader("Content-Type"), payload
            except NodeUnavailableError:
                raise
            except Exception as e:  # noqa: BLE001 - socket-level failure
                if getattr(e, "retry_after_s", None) is not None:
                    raise  # Backpressure: the connection is healthy
                last_err = e
                self._tl.conn = None
                with self._all_lock:
                    self._all.discard(c)
                try:
                    c.close()
                except Exception:
                    pass
        raise NodeUnavailableError(f"{self.host}:{self.port}: {last_err}")

    # -- NodeConnection protocol --

    def write_tagged(self, namespace: str, metric_name: bytes, tags,
                     t_ns: int, value: float) -> None:
        # base64 wire: tag bytes are not guaranteed UTF-8 anywhere else in
        # the stack, and a dict would collapse duplicate keys
        self._request("POST", "/write", json.dumps({
            "namespace": namespace,
            "metric_b64": base64.b64encode(metric_name).decode(),
            "tags_b64": [[base64.b64encode(k).decode(),
                          base64.b64encode(v).decode()] for k, v in tags],
            "timestamp_ns": int(t_ns),
            "value": float(value),
        }).encode())

    def read(self, namespace: str, series_id: bytes, start_ns: int,
             end_ns: int) -> list[Datapoint]:
        qs = urlencode({
            "namespace": namespace,
            "series_id": base64.b64encode(series_id).decode(),
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
        })
        rows = self._request("GET", f"/read?{qs}") or []
        return [Datapoint(int(t), float(v)) for t, v in rows]

    def write_batch(self, namespace: str, entries) -> list[str | None]:
        """entries: [(metric, tags, t_ns, value)]; returns per-entry error
        strings (None = ok). One round-trip for the whole batch."""
        doc = {
            "namespace": namespace,
            "entries": [
                {
                    "metric_b64": base64.b64encode(m).decode(),
                    "tags_b64": [[base64.b64encode(k).decode(),
                                  base64.b64encode(v).decode()]
                                 for k, v in tags],
                    "timestamp_ns": int(t),
                    "value": float(v),
                }
                for m, tags, t, v in entries
            ],
        }
        out = self._request("POST", "/write_batch", json.dumps(doc).encode())
        return out["results"]

    def read_batch(self, namespace: str, series_ids: list[bytes],
                   start_ns: int, end_ns: int) -> list[list[Datapoint]]:
        """One round-trip for many series (the host-queue batching role).
        The node's response envelope carries its storage-side QueryStats
        counters (blocks/bytes/cache/rungs), merged here onto the calling
        thread's active query record; a bare JSON list (a pre-envelope
        node) still parses."""
        doc = self._request("POST", "/read_batch", json.dumps({
            "namespace": namespace,
            "series_ids": [base64.b64encode(s).decode() for s in series_ids],
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
        }).encode()) or []
        if isinstance(doc, dict):
            from m3_tpu.utils import querystats

            querystats.merge_storage(doc.get("stats"))
            rows = doc.get("rows") or []
        else:
            rows = doc
        return [[Datapoint(int(t), float(v)) for t, v in row] for row in rows]

    def read_batch_csr(self, namespace: str, series_ids: list[bytes],
                       start_ns: int, end_ns: int,
                       precision: str | None = None):
        """read_batch landing a ragged (times, vbits, offsets) CSR — the
        binary wire fast path (utils/wire).  With the packed wire armed
        the request offers Accept: application/x-m3wire and a capable
        node answers a sample frame (m3tsz-re-encoded columns, or bf16
        value columns under the negotiated ?precision=bf16 grant); a
        JSON answer — mixed-version node, M3_TPU_WIRE=json on either
        side — parses transparently with the fallback counted, never an
        error.  Rows align to series_ids; node storage counters merge
        onto the calling thread's QueryStats record either way."""
        import numpy as np

        from m3_tpu.utils import querystats, wire

        packed = wire.packed_enabled()
        doc = {
            "namespace": namespace,
            "series_ids": [base64.b64encode(s).decode() for s in series_ids],
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
        }
        if precision is not None:
            doc["precision"] = precision
        body = json.dumps(doc).encode()
        ctype, payload = self._request_raw(
            "POST", "/read_batch", body,
            accept=wire.CONTENT_TYPE if packed else None)
        wire.account("read_batch", sent=len(body), recv=len(payload))
        if wire.is_packed(ctype):
            times, vbits, offsets, stats = wire.unpack_samples(payload)
            querystats.merge_storage(stats)
            return times, vbits, offsets
        if packed:
            # capability probe result: this node speaks JSON only
            wire.count_fallback("server_json")
        envelope = json.loads(payload) if payload else []
        if isinstance(envelope, dict):
            querystats.merge_storage(envelope.get("stats"))
            rows = envelope.get("rows") or []
        else:
            rows = envelope
        from m3_tpu.ops import ragged

        pairs = []
        for row in rows:
            # int(t) per element: a float64 lane would shave ns epochs
            n = len(row)
            t = np.fromiter((int(p[0]) for p in row), np.int64, n)
            v = np.fromiter((float(p[1]) for p in row), np.float64,
                            n).view(np.uint64)
            pairs.append((t, v))
        return ragged.pairs_to_csr(pairs)

    # -- index query surface --

    def query_ids(self, namespace: str, query_doc: dict, start_ns: int,
                  end_ns: int, limit: int | None = None):
        """[(series_id, fields)] from the node's reverse index."""
        out = self._request("POST", "/query_ids", json.dumps({
            "namespace": namespace,
            "query": query_doc,
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
            "limit": limit,
        }).encode()) or []
        return [
            (
                base64.b64decode(d["series_id"]),
                [(base64.b64decode(k), base64.b64decode(v))
                 for k, v in d["fields"]],
            )
            for d in out
        ]

    def label_names(self, namespace: str, start_ns: int, end_ns: int):
        qs = urlencode({"namespace": namespace, "start_ns": int(start_ns),
                        "end_ns": int(end_ns)})
        return [base64.b64decode(n)
                for n in self._request("GET", f"/label_names?{qs}") or []]

    def label_values(self, namespace: str, field: bytes, start_ns: int,
                     end_ns: int):
        qs = urlencode({
            "namespace": namespace,
            "field": base64.b64encode(field).decode(),
            "start_ns": int(start_ns), "end_ns": int(end_ns),
        })
        return [base64.b64decode(v)
                for v in self._request("GET", f"/label_values?{qs}") or []]

    def debug_traces(self, trace_id: str) -> list[dict]:
        """The node's spans for one trace (coordinator-side stitching)."""
        doc = self._request(
            "GET", f"/debug/traces?trace_id={trace_id}") or {}
        return doc.get("spans", [])

    def repair_enqueue(self, namespace: str, shard: int, start_ns: int,
                       end_ns: int) -> bool:
        """Hand the node's repair daemon an out-of-band divergence hint (a
        quorum read saw replica checksums disagree for this shard range).
        Best-effort by contract: callers drop failures — the daemon's own
        digest sweep re-finds anything a lost hint would have flagged."""
        doc = self._request("POST", "/repair/enqueue", json.dumps({
            "namespace": namespace,
            "shard": int(shard),
            "start_ns": int(start_ns),
            "end_ns": int(end_ns),
        }).encode()) or {}
        return bool(doc.get("queued"))

    def health(self) -> bool:
        try:
            return bool(self._request("GET", "/health"))
        except Exception:
            return False

    def close(self) -> None:
        """Close EVERY thread's socket to this node (called when topology
        removes the instance), not just the calling thread's."""
        with self._all_lock:
            conns, self._all = self._all, set()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        self._tl.conn = None
